"""Bounded explicit-state model checker for the wire connection machines.

The wire v2 protocol rests on three connection state machines whose
correctness arguments have so far lived in comments and chaos tests:

- **DepositStream** (runtime/window_server.py): stable stream id +
  epoch; STREAM_ATTACH replies the applied high-water mark; the client
  retires through the mark and replays unretired batches; the server
  dedups ``seq <= mark``.  Claimed invariant: every batch applies
  EXACTLY ONCE, no matter which frames die.
- **Subscriber** (serving/subscriber.py): resumable push cursor; the
  sender skips to latest; the receiver drops ``round <= cursor`` and
  never advances the cursor on a torn frame.  Claimed invariant:
  delivered rounds are STRICTLY INCREASING and the latest round always
  eventually lands.
- **DeltaEncoder/Applier** (runtime/delta.py): kind-10 frames encode
  against the last round SENT; the applier refuses a base that is not
  its reconstruction cursor (``ERR_DELTA_BASE``) and the resumed stream
  resyncs on a full anchor.  Claimed invariant: a delta NEVER applies
  on the wrong base — reconstruction equals the round it claims.

This module encodes each machine as a hand-written transition table
over small integer state tuples, composes it with an adversarial
network — drop, duplicate, truncate (torn frame), crash (connection
kill), restart, and optionally reorder — and exhaustively enumerates
EVERY interleaving by breadth-first search to a fixpoint (the state
spaces are finite by construction: bounded batch counts, rounds,
channel capacity, and capped apply counters).  BFS means a violating
trace is already shortest; a greedy event-deletion pass then minimizes
it further before it is printed as an event sequence.

Three kinds of verdict come out of :func:`explore`:

- **invariant violations** — a reachable state where the machine's
  invariant predicate names a broken property;
- **stuck states** — a reachable state from which NO accepting state
  is reachable (computed by reverse reachability over the explored
  graph, so it needs no fairness assumption: the adversary may drop
  forever, but from every healthy state there must EXIST a recovery
  path);
- **incompleteness** — the ``max_states`` guard tripped before the
  fixpoint (never expected at the shipped bounds; reported, not
  silently ignored).

Transport assumptions are explicit and faithful to TCP: channels are
FIFO, and loss is a PREFIX CUT — a live stream never loses a frame
from the middle; bytes vanish only when the connection dies, and then
everything after the cut dies with it.  The adversary therefore gets:
``truncate`` (tear the next frame; delivering a torn frame kills the
connection and everything queued behind it), ``kill`` (connection
dies; frames already buffered remain prefix-deliverable), ``lose_*``
(the cut: discard what a dead connection still had queued),
``restart``/``resubscribe``/``attach`` (reconnect + replay), and
``dup`` (duplicate a queued frame — the abstraction of every duplicate
source at once: zombie-epoch connections, attach replay overlap — so
the dedup discipline is checked against duplication from ANY origin).
``reorder=True`` additionally lifts the FIFO assumption, and the
checker then PROVES it is load-bearing — the deposit dedup discipline
loses a batch under reordering (see ``tests/test_wire_verify.py``) —
which is exactly why reorder is modeled but the healthy configurations
keep FIFO.  Cross-connection interleavings, where reordering genuinely
happens, are covered by the crash/restart events plus replay.

Each machine also ships seeded-violation variants (``bug=`` flags that
plant a real historical defect shape: retire-on-send, dedup-off,
cursor-advance-on-torn, apply-on-wrong-base) so the checker's teeth are
themselves regression-tested, and ``tests/test_wire_verify.py`` pins
the model to reality by driving the live code through modeled
transitions in lockstep.
"""

from __future__ import annotations

import dataclasses
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "CheckResult",
    "DeltaMachine",
    "DepositStreamMachine",
    "Machine",
    "SubscriberMachine",
    "Violation",
    "check_all",
    "explore",
    "minimize_trace",
    "replay",
    "to_dot",
]

State = Tuple
Event = Tuple[str, State]


class Machine:
    """A finite connection state machine composed with the adversary.

    Subclasses provide :meth:`initial`, :meth:`events` (the FULL list
    of enabled protocol + adversary transitions), :meth:`invariant`
    (name of the violated property, or None) and :meth:`is_accepting`
    (all modeled work delivered)."""

    name = "machine"

    def initial(self) -> State:
        raise NotImplementedError

    def events(self, state: State) -> List[Event]:
        raise NotImplementedError

    def invariant(self, state: State) -> Optional[str]:
        raise NotImplementedError

    def is_accepting(self, state: State) -> bool:
        raise NotImplementedError


@dataclasses.dataclass(frozen=True)
class Violation:
    invariant: str
    trace: Tuple[str, ...]        # minimized event sequence
    state: State

    def format(self) -> str:
        return "%s after [%s]" % (self.invariant,
                                  " -> ".join(self.trace) or "<init>")


@dataclasses.dataclass
class CheckResult:
    machine: str
    states: int
    transitions: int
    depth: int                    # max BFS level reached
    complete: bool                # explored to fixpoint under max_states
    violations: List[Violation]
    stuck: List[Tuple[Tuple[str, ...], State]]   # (shortest trace, state)
    accepting: int
    edges: Optional[List[Tuple[State, str, State]]] = None

    @property
    def ok(self) -> bool:
        return (self.complete and not self.violations
                and not self.stuck and self.accepting > 0)

    def format(self) -> str:
        head = ("%s: %d state(s), %d transition(s), depth %d, "
                "%d accepting%s" % (
                    self.machine, self.states, self.transitions,
                    self.depth, self.accepting,
                    "" if self.complete else ", INCOMPLETE"))
        lines = [head]
        for v in self.violations:
            lines.append("  VIOLATION %s" % v.format())
        for trace, st in self.stuck:
            lines.append("  STUCK after [%s]: %r"
                         % (" -> ".join(trace) or "<init>", st))
        return "\n".join(lines)


# --------------------------------------------------------------------------
# the checker
# --------------------------------------------------------------------------

def replay(machine: Machine,
           labels: Sequence[str]) -> Optional[List[State]]:
    """Replay an event-label sequence from the initial state; None if
    some label is not enabled where the replay stands.  Labels carry
    their operands (``deliver(2,torn)``) so replay is deterministic."""
    st = machine.initial()
    seq = [st]
    for lbl in labels:
        nxt = None
        for l, s in machine.events(st):
            if l == lbl:
                nxt = s
                break
        if nxt is None:
            return None
        st = nxt
        seq.append(st)
    return seq


def minimize_trace(machine: Machine, labels: Sequence[str],
                   offends: Callable[[List[State]], bool]
                   ) -> Tuple[str, ...]:
    """Greedy event deletion: drop any single event whose removal still
    replays to an offending run; repeat until no event is droppable."""
    cur = list(labels)
    changed = True
    while changed:
        changed = False
        for i in range(len(cur)):
            cand = cur[:i] + cur[i + 1:]
            seq = replay(machine, cand)
            if seq is not None and offends(seq):
                cur = cand
                changed = True
                break
    return tuple(cur)


def _trace_to(pred: Dict[State, Tuple[Optional[State], str]],
              state: State) -> Tuple[str, ...]:
    out: List[str] = []
    cur: Optional[State] = state
    while cur is not None:
        prev, lbl = pred[cur]
        if prev is None:
            break
        out.append(lbl)
        cur = prev
    return tuple(reversed(out))


def explore(machine: Machine, *, max_states: int = 400_000,
            keep_edges: bool = False) -> CheckResult:
    """Exhaustive BFS over the machine's reachable states (fixpoint),
    with invariant checking, stuck (accepting-unreachable) analysis,
    and auto-minimized violation traces."""
    init = machine.initial()
    pred: Dict[State, Tuple[Optional[State], str]] = {init: (None, "")}
    level: Dict[State, int] = {init: 0}
    frontier = deque([init])
    transitions = 0
    depth = 0
    complete = True
    violations: Dict[str, Tuple[Tuple[str, ...], State]] = {}
    adj: Dict[State, List[Tuple[str, State]]] = {}
    accepting: List[State] = []

    inv0 = machine.invariant(init)
    if inv0:
        violations[inv0] = ((), init)
    if machine.is_accepting(init):
        accepting.append(init)

    while frontier:
        st = frontier.popleft()
        if machine.invariant(st):
            # violating states are terminal: the run already failed
            adj[st] = []
            continue
        evs = machine.events(st)
        adj[st] = evs
        for lbl, nxt in evs:
            transitions += 1
            if nxt in pred:
                continue
            if len(pred) >= max_states:
                complete = False
                continue
            pred[nxt] = (st, lbl)
            level[nxt] = level[st] + 1
            depth = max(depth, level[nxt])
            inv = machine.invariant(nxt)
            if inv and inv not in violations:
                violations[inv] = (_trace_to(pred, nxt), nxt)
            if machine.is_accepting(nxt):
                accepting.append(nxt)
            frontier.append(nxt)

    min_violations: List[Violation] = []
    for inv, (trace, vstate) in sorted(violations.items()):
        def offends(seq: List[State], _inv: str = inv) -> bool:
            return any(machine.invariant(s) == _inv for s in seq)
        min_violations.append(Violation(
            inv, minimize_trace(machine, trace, offends), vstate))

    stuck: List[Tuple[Tuple[str, ...], State]] = []
    if not violations and complete:
        co = set(accepting)
        radj: Dict[State, List[State]] = {}
        for src, evs in adj.items():
            for _lbl, dst in evs:
                radj.setdefault(dst, []).append(src)
        work = deque(co)
        while work:
            cur = work.popleft()
            for prev in radj.get(cur, ()):
                if prev not in co:
                    co.add(prev)
                    work.append(prev)
        dead = sorted((level[s], s) for s in pred if s not in co)
        for _lvl, s in dead[:3]:
            stuck.append((_trace_to(pred, s), s))

    edges = None
    if keep_edges:
        edges = [(src, lbl, dst) for src, evs in adj.items()
                 for lbl, dst in evs]
    return CheckResult(machine.name, len(pred), transitions, depth,
                       complete, min_violations, stuck, len(accepting),
                       edges)


def to_dot(result: CheckResult, *, max_nodes: int = 400) -> str:
    """Render an explored state graph as DOT (explore with
    ``keep_edges=True``); large graphs degrade to a summary node."""
    name = result.machine.replace("-", "_")
    lines = ["digraph %s {" % name, '  rankdir=LR;',
             '  node [shape=box, fontsize=9];']
    if result.edges is None or result.states > max_nodes:
        lines.append('  summary [label="%s\\n%d states / %d transitions'
                     '\\n(graph elided)"];' % (
                         result.machine, result.states,
                         result.transitions))
        lines.append("}")
        return "\n".join(lines)
    ids: Dict[State, int] = {}

    def nid(s: State) -> int:
        if s not in ids:
            ids[s] = len(ids)
        return ids[s]

    for src, lbl, dst in result.edges:
        lines.append('  n%d -> n%d [label="%s", fontsize=8];'
                     % (nid(src), nid(dst), lbl))
    for s, i in ids.items():
        lines.append('  n%d [label="%s"];'
                     % (i, str(s).replace('"', "'")))
    lines.append("}")
    return "\n".join(lines)


# --------------------------------------------------------------------------
# machine 1: DepositStream seq/epoch/attach-replay
# --------------------------------------------------------------------------

class DepositStreamMachine(Machine):
    """Exactly-once batch application under the adversarial network.

    State: ``(sent, retired, mark, inflight, acks, applied, alive)``
    where ``inflight`` is the FIFO client->server channel of
    ``(seq, torn)`` frames, ``acks`` the FIFO server->client ack
    channel, ``applied[seq-1]`` a capped per-seq apply counter, and
    ``mark`` the server's applied high-water mark (what STREAM_ATTACH
    replies).

    ``bug="retire_on_send"`` plants the client treating a SEND as
    durable (retiring before the ack) — the trace
    ``send(1), kill, lose_frames`` then violates
    ``retired-implies-applied``.
    ``bug="dedup_off"`` removes the server's ``seq <= mark`` dedup —
    a duplicated frame then violates ``exactly-once-apply``.
    ``reorder=True`` lets the adversary swap in-flight frames, proving
    the FIFO (TCP) assumption is load-bearing."""

    def __init__(self, *, n_batches: int = 2, window: int = 2,
                 chan_cap: int = 2, bug: Optional[str] = None,
                 reorder: bool = False):
        self.n = n_batches
        self.window = window
        self.cap = chan_cap
        self.bug = bug
        self.reorder = reorder
        self.name = "deposit-stream" + (("!" + bug) if bug else "")

    def initial(self) -> State:
        return (0, 0, 0, (), (), (0,) * self.n, True)

    def _apply(self, seq: int, applied: Tuple[int, ...]
               ) -> Tuple[int, ...]:
        return tuple(min(c + (1 if i == seq - 1 else 0), 2)
                     for i, c in enumerate(applied))

    def events(self, state: State) -> List[Event]:
        sent, retired, mark, inflight, acks, applied, alive = state
        out: List[Event] = []
        # client: send the next unretired batch, window-bounded
        if alive and sent < self.n and sent - retired < self.window \
                and len(inflight) < self.cap:
            seq = sent + 1
            n_retired = seq if self.bug == "retire_on_send" else retired
            out.append(("send(%d)" % seq,
                        (seq, n_retired, mark,
                         inflight + ((seq, False),), acks, applied,
                         alive)))
        # server: process the head in-flight frame (FIFO).  On a live
        # connection the ack lands in the return channel; a dead
        # connection may still DRAIN frames it buffered before the
        # crash (late processing), but the acks it writes are born dead.
        if inflight:
            (seq, torn), rest = inflight[0], inflight[1:]
            if torn:
                # a torn frame desyncs the stream: the server kills the
                # connection; everything queued behind the tear is lost
                out.append(("deliver(%d,torn)" % seq,
                            (sent, retired, mark, (), acks, applied,
                             False)))
            elif seq <= mark and self.bug != "dedup_off":
                # duplicate: re-ack (if the conn lives), do NOT apply
                if not alive:
                    out.append(("deliver(%d,dedup)" % seq,
                                (sent, retired, mark, rest, acks,
                                 applied, alive)))
                elif len(acks) < self.cap:
                    out.append(("deliver(%d,dedup)" % seq,
                                (sent, retired, mark, rest,
                                 acks + (seq,), applied, alive)))
            else:
                n_applied = self._apply(seq, applied)
                if not alive:
                    out.append(("deliver(%d)" % seq,
                                (sent, retired, max(mark, seq), rest,
                                 acks, n_applied, alive)))
                elif len(acks) < self.cap:
                    out.append(("deliver(%d)" % seq,
                                (sent, retired, max(mark, seq), rest,
                                 acks + (seq,), n_applied, alive)))
            if not torn and alive:
                out.append(("truncate(%d)" % seq,
                            (sent, retired, mark,
                             ((seq, True),) + rest, acks, applied,
                             alive)))
                if len(inflight) < self.cap:
                    out.append(("dup(%d)" % seq,
                                (sent, retired, mark,
                                 inflight + ((seq, False),), acks,
                                 applied, alive)))
        if self.reorder and alive and len(inflight) >= 2:
            swapped = (inflight[1], inflight[0]) + inflight[2:]
            out.append(("reorder",
                        (sent, retired, mark, swapped, acks, applied,
                         alive)))
        # client: consume the head ack (retire through it)
        if acks:
            a, rest_a = acks[0], acks[1:]
            out.append(("ack(%d)" % a,
                        (sent, max(retired, a), mark, inflight, rest_a,
                         applied, alive)))
        # crash the connection at any step; what was queued stays
        # prefix-deliverable until the adversary cuts it
        if alive:
            out.append(("kill",
                        (sent, retired, mark, inflight, acks, applied,
                         False)))
        else:
            if inflight:
                out.append(("lose_frames",
                            (sent, retired, mark, (), acks, applied,
                             False)))
            if acks:
                out.append(("lose_acks",
                            (sent, retired, mark, inflight, (),
                             applied, False)))
            # STREAM_ATTACH: only once the dead connection quiesced
            # (the server joins the old generation's worker before
            # replying the mark — modeled as: the old channels fully
            # drained or cut first).  The client retires through the
            # replied mark and rewinds ``sent`` to replay every
            # unretired batch.
            if not inflight and not acks:
                n_retired = max(retired, mark)
                out.append(("attach(mark=%d)" % mark,
                            (n_retired, n_retired, mark, (), (),
                             applied, True)))
        return out

    def invariant(self, state: State) -> Optional[str]:
        sent, retired, mark, inflight, acks, applied, alive = state
        if any(c > 1 for c in applied):
            return "exactly-once-apply"
        live = {seq for seq, _torn in inflight}
        for seq in range(1, retired + 1):
            if applied[seq - 1] == 0 and seq not in live:
                return "retired-implies-applied"
        return None

    def is_accepting(self, state: State) -> bool:
        _sent, retired, _mark, _inf, _acks, applied, _alive = state
        return retired == self.n and all(c == 1 for c in applied)


# --------------------------------------------------------------------------
# machine 2: Subscriber cursor/epoch/resume
# --------------------------------------------------------------------------

class SubscriberMachine(Machine):
    """Strictly-increasing push delivery with torn-frame safety.

    State: ``(published, pushed, chan, cursor, last_delivered,
    alive)`` — ``chan`` is the FIFO server->client channel of
    ``(round, torn)`` push frames; ``pushed`` the sender's last pushed
    round on the current connection (skip-to-latest: it pushes
    ``published`` directly); ``cursor`` the receiver's resume cursor;
    ``last_delivered`` the last round actually handed to the consumer.

    The healthy receiver drops ``round <= cursor`` and advances cursor
    and delivery together, so ``cursor == last_delivered`` is the
    machine invariant; ``bug="advance_on_torn"`` plants the cursor
    advancing on a torn frame (the defect BF-WIRE's state layer exists
    to catch), which both breaks that equality immediately and — left
    unchecked — would silently drop the round on resume."""

    def __init__(self, *, rounds: int = 3, chan_cap: int = 2,
                 bug: Optional[str] = None):
        self.rounds = rounds
        self.cap = chan_cap
        self.bug = bug
        self.name = "subscriber" + (("!" + bug) if bug else "")

    def initial(self) -> State:
        return (0, 0, (), 0, 0, True)

    def events(self, state: State) -> List[Event]:
        published, pushed, chan, cursor, last, alive = state
        out: List[Event] = []
        if published < self.rounds:
            out.append(("publish(%d)" % (published + 1),
                        (published + 1, pushed, chan, cursor, last,
                         alive)))
        if alive and published > pushed and len(chan) < self.cap:
            out.append(("push(%d)" % published,
                        (published, published,
                         chan + ((published, False),), cursor, last,
                         alive)))
        if chan:
            (rnd, torn), rest = chan[0], chan[1:]
            if torn:
                # a torn push frame desyncs the read loop: the
                # connection dies, the queue behind the tear with it —
                # and the HEALTHY cursor does not move
                n_cursor = (max(cursor, rnd)
                            if self.bug == "advance_on_torn" else cursor)
                out.append(("deliver(%d,torn)" % rnd,
                            (published, pushed, (), n_cursor, last,
                             False)))
            else:
                # a dead connection still drains frames the client had
                # buffered before noticing the crash
                if rnd <= cursor:
                    out.append(("deliver(%d,dup)" % rnd,
                                (published, pushed, rest, cursor, last,
                                 alive)))
                else:
                    out.append(("deliver(%d)" % rnd,
                                (published, pushed, rest, rnd, rnd,
                                 alive)))
                if alive:
                    out.append(("truncate(%d)" % rnd,
                                (published, pushed,
                                 ((rnd, True),) + rest, cursor, last,
                                 alive)))
                    if len(chan) < self.cap:
                        out.append(("dup(%d)" % rnd,
                                    (published, pushed,
                                     chan + ((rnd, False),), cursor,
                                     last, alive)))
        if alive:
            out.append(("kill",
                        (published, pushed, chan, cursor, last, False)))
        else:
            if chan:
                out.append(("lose_frames",
                            (published, pushed, (), cursor, last,
                             False)))
            else:
                # resume: SUBSCRIBE carries the cursor; the sender
                # restarts skip-to-latest strictly above it
                out.append(("resubscribe(cursor=%d)" % cursor,
                            (published, cursor, (), cursor, last,
                             True)))
        return out

    def invariant(self, state: State) -> Optional[str]:
        _published, _pushed, _chan, cursor, last, _alive = state
        if cursor != last:
            return "cursor-advanced-without-delivery"
        return None

    def is_accepting(self, state: State) -> bool:
        published, _pushed, _chan, _cursor, last, _alive = state
        return published == self.rounds and last == self.rounds


# --------------------------------------------------------------------------
# machine 3: DeltaEncoder/Applier base/resync
# --------------------------------------------------------------------------

class DeltaMachine(Machine):
    """Delta frames never apply on the wrong base; desync resyncs.

    State: ``(published, enc_base, cadence, pushed, chan, cursor,
    content, alive)`` — the encoder deltas against the last round it
    SENT (``enc_base``; -1 forces a full anchor), emitting a full
    frame every ``full_every`` sends; ``chan`` carries
    ``(kind, base, round, torn)`` with kind 10 = delta, 0 = full;
    ``content`` is the round the receiver's reconstruction actually
    equals (the thing a wrong-base apply corrupts), ``CORRUPT`` once a
    bad apply happened.

    Healthy appliers refuse ``base != content`` (ERR_DELTA_BASE: the
    connection dies and the resumed encoder re-anchors with a full
    frame).  Under the faithful FIFO/prefix-loss transport a healthy
    SENDER can never put a wrong-base delta in front of the applier —
    the checker proves that — so the seeded variants plant the sender
    defect the base check defends against (an encoder that keeps its
    base across reconnect and never re-anchors):

    - ``bug="no_reanchor"`` — that sender against the HEALTHY applier:
      every delta after a reconnect desyncs, the connection dies, the
      resumed sender still refuses to anchor — a livelock the checker
      reports as STUCK states (acceptance unreachable);
    - ``bug="apply_wrong_base"`` — the same sender against an applier
      missing the base check: the reconstruction silently corrupts,
      caught by the ``delta-applied-on-wrong-base`` invariant."""

    CORRUPT = -99

    def __init__(self, *, rounds: int = 3, full_every: int = 2,
                 chan_cap: int = 2, bug: Optional[str] = None):
        self.rounds = rounds
        self.full_every = max(1, full_every)
        self.cap = chan_cap
        self.bug = bug
        self.name = "delta" + (("!" + bug) if bug else "")

    def initial(self) -> State:
        return (0, -1, 0, 0, (), 0, 0, True)

    def events(self, state: State) -> List[Event]:
        (published, enc_base, cadence, pushed, chan, cursor, content,
         alive) = state
        out: List[Event] = []
        if published < self.rounds:
            out.append(("publish(%d)" % (published + 1),
                        (published + 1, enc_base, cadence, pushed,
                         chan, cursor, content, alive)))
        if alive and published > pushed and len(chan) < self.cap:
            rnd = published
            if self.bug in ("no_reanchor", "apply_wrong_base"):
                # the seeded sender defect: anchor only the very first
                # frame ever, never on cadence or reconnect
                full = enc_base < 0
            else:
                full = enc_base < 0 or cadence % self.full_every == 0
            kind, base = (0, -1) if full else (10, enc_base)
            lbl = ("send_full(%d)" % rnd if full
                   else "send_delta(%d,base=%d)" % (rnd, base))
            out.append((lbl,
                        (published, rnd, cadence + 1, rnd,
                         chan + ((kind, base, rnd, False),), cursor,
                         content, alive)))
        if chan:
            (kind, base, rnd, torn), rest = chan[0], chan[1:]
            if torn:
                out.append(("deliver(%d,torn)" % rnd,
                            (published, enc_base, cadence, pushed,
                             (), cursor, content, False)))
            else:
                if rnd <= cursor:
                    out.append(("deliver(%d,dup)" % rnd,
                                (published, enc_base, cadence, pushed,
                                 rest, cursor, content, alive)))
                elif kind == 0:
                    out.append(("deliver_full(%d)" % rnd,
                                (published, enc_base, cadence, pushed,
                                 rest, rnd, rnd, alive)))
                elif base != content and self.bug != "apply_wrong_base":
                    # ERR_DELTA_BASE: refuse, drop the connection; the
                    # resumed stream re-anchors with a full frame
                    out.append(("deliver_delta(%d,desync)" % rnd,
                                (published, enc_base, cadence, pushed,
                                 rest, cursor, content, False)))
                else:
                    n_content = (rnd if base == content
                                 else self.CORRUPT)
                    out.append(("deliver_delta(%d)" % rnd,
                                (published, enc_base, cadence, pushed,
                                 rest, rnd, n_content, alive)))
                if alive:
                    out.append(("truncate(%d)" % rnd,
                                (published, enc_base, cadence, pushed,
                                 ((kind, base, rnd, True),) + rest,
                                 cursor, content, alive)))
                    if len(chan) < self.cap:
                        out.append(("dup(%d)" % rnd,
                                    (published, enc_base, cadence,
                                     pushed,
                                     chan + ((kind, base, rnd, False),),
                                     cursor, content, alive)))
        if alive:
            out.append(("kill",
                        (published, enc_base, cadence, pushed, chan,
                         cursor, content, False)))
        else:
            if chan:
                out.append(("lose_frames",
                            (published, enc_base, cadence, pushed, (),
                             cursor, content, False)))
            else:
                # resume: fresh per-connection encoder state -> the
                # first frame of the new connection is a full anchor
                # (the seeded sender defect keeps the stale base)
                n_base = (enc_base
                          if self.bug in ("no_reanchor",
                                          "apply_wrong_base") else -1)
                out.append(("resubscribe(cursor=%d)" % cursor,
                            (published, n_base, 0, cursor, (), cursor,
                             content, True)))
        return out

    def invariant(self, state: State) -> Optional[str]:
        (_published, _enc_base, _cadence, _pushed, _chan, cursor,
         content, _alive) = state
        if content == self.CORRUPT:
            return "delta-applied-on-wrong-base"
        if content != cursor:
            return "reconstruction-diverged-from-cursor"
        return None

    def is_accepting(self, state: State) -> bool:
        published = state[0]
        cursor = state[5]
        return published == self.rounds and cursor == self.rounds


# --------------------------------------------------------------------------
# the shipped healthy configurations
# --------------------------------------------------------------------------

def check_all(*, n_batches: int = 2, rounds: int = 3,
              keep_edges: bool = False) -> List[CheckResult]:
    """Explore the three healthy machines at the shipped bounds (the
    deposit replay window and both cursors fully covered)."""
    return [
        explore(DepositStreamMachine(n_batches=n_batches),
                keep_edges=keep_edges),
        explore(SubscriberMachine(rounds=rounds),
                keep_edges=keep_edges),
        explore(DeltaMachine(rounds=rounds), keep_edges=keep_edges),
    ]
