"""BF-WIN lint: pipelined window deposits must be fenced before barriers.

The pipelined DCN transport (:class:`bluefog_tpu.runtime.window_server.
PipelinedRemoteWindow`) makes ``deposit_async`` fire-and-forget: the
payload is still queued or on the wire when the call returns.  Every
correctness audit in the async runners leans on a "no rank deposits after
this barrier" line (the dsgd mass-conservation drain), which is only true
if the loop FENCES — calls ``flush()`` on its peer handles — before
entering that barrier.  Forgetting the fence is not a crash: it is a
silently leaky mass audit that fails rarely, under load, on the slowest
peer.  Exactly the kind of bug a lint should catch at review time.

This pass is a *source* lint (AST), not a jaxpr lint — the async loops are
host Python.  The rule, per function:

- **pipelined-deposit sites** are calls of an attribute named
  ``deposit_async``, plus ``.deposit(...)`` calls on names bound from a
  ``PipelinedRemoteWindow(...)`` construction in the same function;
- **final-barrier sites** are ``<x>.wait("<stage>")`` calls whose first
  argument is a string literal (the :class:`FileBarrier` idiom);
- **fences** are calls of an attribute named ``flush``.

BF-WIN001 (error): a function issues pipelined deposits and then reaches
a barrier with no fence between the first deposit and the first
subsequent barrier.  BF-WIN002 (warning): a function issues pipelined
deposits and never fences at all (no barrier either — the handle may
escape, but a loop-local handle that is never flushed usually means the
fence lives in no one's code).  BF-WIN100 (info): scan summary.

BF-WIN004 (error): the compute/gossip-overlap apply.  The
:class:`~bluefog_tpu.runtime.async_windows.DoubleBuffer` harvester
stages round-(k-1) deposits while round-k compute runs; the ONLY legal
place to fold that staged mass into ``(x, p)`` is a round boundary —
applying it mid-step mixes stale neighbor state into a half-finished
gradient update and silently breaks the byte-identity-with-serial
contract.  The rule is the BF-CTL001 / BF-RES002 discipline applied to
the overlap path: a call of ``apply_staged`` is legal only inside a
function whose NAME carries the round-boundary/quiesce vocabulary
(``_BOUNDARY_RE``, shared with the control lint), so the apply is
reachable only from boundary code.  ``close()`` is exempt — drains are
terminal, not mid-round.

Line numbers approximate dominance (Python source order); that is the
right fidelity for a lint — the seeded-violation test pins the contract.
"""

from __future__ import annotations

import ast
import os
from typing import List, Optional

from bluefog_tpu.analysis.control_lint import _BOUNDARY_RE
from bluefog_tpu.analysis.report import Diagnostic

__all__ = ["check_pipelined_flush", "check_file"]

_PIPELINED_CTORS = ("PipelinedRemoteWindow",)
_STAGED_APPLY = "apply_staged"


def _call_name(node: ast.Call) -> Optional[str]:
    f = node.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None


class _FuncScan(ast.NodeVisitor):
    """Collect deposit/fence/barrier call lines within ONE function body
    (nested defs are scanned separately — their fences do not fence us)."""

    def __init__(self):
        self.deposits: List[int] = []
        self.flushes: List[int] = []
        self.barriers: List[int] = []
        self.pipelined_names: set = set()

    def visit_Assign(self, node: ast.Assign):
        v = node.value
        if isinstance(v, ast.Call) and _call_name(v) in _PIPELINED_CTORS:
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    self.pipelined_names.add(tgt.id)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call):
        f = node.func
        if isinstance(f, ast.Attribute):
            if f.attr == "deposit_async":
                self.deposits.append(node.lineno)
            elif (f.attr == "deposit" and isinstance(f.value, ast.Name)
                  and f.value.id in self.pipelined_names):
                self.deposits.append(node.lineno)
            elif f.attr == "flush":
                self.flushes.append(node.lineno)
            elif (f.attr == "wait" and node.args
                  and isinstance(node.args[0], ast.Constant)
                  and isinstance(node.args[0].value, str)):
                self.barriers.append(node.lineno)
        self.generic_visit(node)

    def visit_FunctionDef(self, node):  # do not descend into nested defs
        pass

    visit_AsyncFunctionDef = visit_FunctionDef


def _scan_function(fn: ast.AST, name: str, filename: str, *,
                   nested: bool = False) -> List[Diagnostic]:
    scan = _FuncScan()
    for stmt in fn.body:  # type: ignore[attr-defined]
        scan.visit(stmt)
    if not scan.deposits:
        return []
    d0 = min(scan.deposits)
    barriers_after = sorted(b for b in scan.barriers if b > d0)
    diags: List[Diagnostic] = []
    if barriers_after:
        b0 = barriers_after[0]
        if not any(d0 < f <= b0 for f in scan.flushes):
            diags.append(Diagnostic(
                "error", "BF-WIN001",
                f"{name} (at {filename}:{d0}) issues pipelined window "
                f"deposits (deposit_async) but reaches its barrier at "
                f"line {b0} with no flush() fence in between — in-flight "
                "deposits can land after the owners' final drain and "
                "break the exactly-once mass audit",
                pass_name="window-lint", subject=name))
    elif not scan.flushes and not nested:
        # nested defs are exempt from the never-fenced warning: a
        # deposit closure whose CALLER fences (the bench's one_round
        # shape) is idiomatic, and the enclosing function is scanned in
        # its own right
        diags.append(Diagnostic(
            "warning", "BF-WIN002",
            f"{name} (at {filename}:{d0}) issues pipelined window "
            "deposits and never fences them (no flush() in the "
            "function) — if no caller flushes the handle, deposits may "
            "still be in flight when results are read",
            pass_name="window-lint", subject=name))
    return diags


def _scan_staged_applies(tree: ast.AST, short: str) -> List[Diagnostic]:
    """BF-WIN004: every ``apply_staged`` call site must sit inside a
    function whose NAME carries the round-boundary vocabulary (the
    innermost enclosing def decides — a boundary-named closure inside a
    hot loop is exactly the sanctioned shape)."""
    diags: List[Diagnostic] = []

    def walk(node: ast.AST, fn_name: Optional[str]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # the DoubleBuffer method definition itself is the
                # primitive, not a caller — descend with its name so a
                # self-call inside it is still judged against it
                walk(child, child.name)
                continue
            if (isinstance(child, ast.Call)
                    and _call_name(child) == _STAGED_APPLY
                    and not (fn_name == _STAGED_APPLY
                             or (fn_name is not None
                                 and _BOUNDARY_RE.search(fn_name.lower())))):
                where = fn_name if fn_name is not None else "<module>"
                diags.append(Diagnostic(
                    "error", "BF-WIN004",
                    f"apply_staged() at {short}:{child.lineno} inside "
                    f"{where!r} — folding the overlap buffer's staged "
                    "round-(k-1) mass is legal only at a round boundary; "
                    "call it from a function whose name carries the "
                    "boundary/quiesce vocabulary (round/boundary/barrier/"
                    "fence/flush/quiesce/...) so stale mixing can never "
                    "apply mid-step",
                    pass_name="window-lint",
                    subject=f"{short}:{child.lineno}"))
            walk(child, fn_name)

    walk(tree, None)
    return diags


def check_pipelined_flush(source: str, *, filename: str = "<source>"
                          ) -> List[Diagnostic]:
    """Lint one Python source blob for the fence-before-barrier rule."""
    try:
        tree = ast.parse(source, filename=filename)
    except SyntaxError as e:
        return [Diagnostic(
            "warning", "BF-WIN003",
            f"could not parse {filename}: {e}",
            pass_name="window-lint", subject=filename)]
    diags: List[Diagnostic] = []
    short = os.path.basename(filename)
    # nested defs (closures) are scanned too, but flagged differently —
    # collect which function nodes live inside another function
    nested_fns = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for sub in ast.walk(node):
                if sub is not node and isinstance(
                        sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    nested_fns.add(sub)
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            diags.extend(_scan_function(node, node.name, short,
                                        nested=node in nested_fns))
    # module level (scripts deposit at top level too)
    mod = ast.Module(body=[s for s in tree.body
                           if not isinstance(s, (ast.FunctionDef,
                                                 ast.AsyncFunctionDef,
                                                 ast.ClassDef))],
                     type_ignores=[])
    diags.extend(_scan_function(mod, "<module>", short))
    # methods live inside ClassDef bodies; walk covers them via the
    # FunctionDef case above
    diags.extend(_scan_staged_applies(tree, short))
    return diags


def check_file(path: str) -> List[Diagnostic]:
    try:
        with open(path, "r", encoding="utf-8") as f:
            src = f.read()
    except OSError as e:
        return [Diagnostic(
            "warning", "BF-WIN003", f"could not read {path}: {e}",
            pass_name="window-lint", subject=os.path.basename(path))]
    return check_pipelined_flush(src, filename=path)
