"""AST-extracted model of every wire v2 encode/decode site (Pass 13).

The wire protocol (docs/transport.md) is hand-encoded in five modules —
the server (:mod:`bluefog_tpu.runtime.window_server`), the delta codec
(:mod:`bluefog_tpu.runtime.delta`), the snapshot reader
(:mod:`bluefog_tpu.serving.client`), the push subscriber
(:mod:`bluefog_tpu.serving.subscriber`) and the relay
(:mod:`bluefog_tpu.relay.node`) — with the status registry in
:mod:`bluefog_tpu.runtime.wire_status` and the payload codecs in
:mod:`bluefog_tpu.runtime.wire_codec`.  Until this pass, the two sides
of each frame were checked against each other only dynamically (frame
fuzzers, chaos soaks).  This module extracts a static model of the
protocol so :mod:`bluefog_tpu.analysis.protocol_check` can cross-check
both sides of every frame at lint time, the way
:mod:`bluefog_tpu.analysis.lockmodel` does for locks.

What is extracted (all by :mod:`ast`, no protocol module is imported):

- **struct defs** — module-level ``NAME = struct.Struct("<fmt")``
  constants, the one sanctioned way to declare a frame layout;
- **struct uses** — every ``.pack``/``.pack_into`` and
  ``.unpack``/``.unpack_from`` of a struct constant, attributed to its
  enclosing function and, where derivable, to the wire **op** it
  belongs to.  Op attribution has three sources, in order: a header
  pack whose argument list names an ``_OP_*`` constant opens an op
  context for the rest of the enclosing block (the client-send idiom:
  ``_HDR.pack(_MAGIC, _OP_SNAPSHOT, n)`` followed by the op's body
  structs); a branch guarded by ``op == _OP_X`` / ``op in _TRACED_OPS``
  scopes its body to those ops (the server-dispatch idiom); and a
  one-level-plus call-graph fixpoint carries ops into helpers
  (``handle`` dispatches op 8 to ``_handle_snapshot``, which calls
  ``_leaf_views``);
- **status sites** — every emission of a negative status constant
  (``_STATUS.pack(_ERR_X)``, ``self._batch_ack(seq, _ERR_X)``,
  ``return _ERR_STALE_EPOCH``) and every match against one
  (``rc == wire_status.ERR_ROUND_ROLLED``), with the match's handling
  classified retriable/terminal by the exception the guarded branch
  raises;
- **gate sites** — every emission of a feature-gated op (6/7/8/9/10)
  or optional header (``_TRACE_HDR``/``_DELTA_HDR``), with the
  negotiated-bit evidence found in the enclosing scope;
- **bound sites** — every wire-claimed length (a variable bound from a
  >=32-bit unpack field) that flows into an allocation-shaped sink
  (``np.empty``/``bytearray``/``_recv_exact``/``sock.recv``), with any
  lexically-prior bound guard (``wire_bytes_bound``/``_MAX_*``)
  recorded — the PR-4 discipline, extracted for BF-WIRE004;
- **waivers** — ``# bfwire: layout-ok|gate-ok <reason>`` comments; a
  bare token without a reason waives nothing (the bfverify precedent).

The registry (legal status values, retriable subset) is read from the
scanned ``wire_status``-defining module when present, so the model is
self-contained on synthetic sources; :func:`build_package_model` always
includes the real registry.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
import struct as _structmod
from typing import Dict, List, Optional, Sequence, Set, Tuple

__all__ = [
    "BoundSite",
    "GateSite",
    "InlineSite",
    "PROTOCOL_FILES",
    "StatusSite",
    "StructDef",
    "StructUse",
    "WireModel",
    "build_model",
    "build_package_model",
    "package_root",
]

#: the protocol surface, relative to the ``bluefog_tpu`` package root —
#: every module that encodes, decodes, emits, or matches wire v2 bytes
PROTOCOL_FILES = (
    "runtime/wire_status.py",
    "runtime/wire_codec.py",
    "runtime/window_server.py",
    "runtime/delta.py",
    "serving/client.py",
    "serving/subscriber.py",
    "relay/node.py",
)

_WAIVER_RE = re.compile(r"#\s*bfwire:\s*(layout-ok|gate-ok)\b[ \t]*(.*)")
_OP_NAME_RE = re.compile(r"^_?OP_")
_ERR_NAME_RE = re.compile(r"^_?ERR_")
_MAX_NAME_RE = re.compile(r"(^|_)MAX_")

#: ops that may only be emitted on a connection whose HELLO negotiated
#: the matching feature bit (docs/transport.md feature-bit table)
GATED_OPS: Dict[int, str] = {
    6: "FEATURE_RESUME",      # STREAM_ATTACH
    7: "FEATURE_HEARTBEAT",
    8: "FEATURE_SNAPSHOT",
    9: "FEATURE_SUBSCRIBE",
    10: "FEATURE_DELTA",      # DELTA push-frame kind
}

#: optional per-frame headers gated by a feature bit (matched by struct
#: constant name suffix so client-side ``ws._TRACE_HDR`` resolves too)
GATED_HEADERS: Dict[str, str] = {
    "TRACE_HDR": "FEATURE_TRACE",
    "DELTA_HDR": "FEATURE_DELTA",
}

#: evidence vocabulary per feature: an identifier (or string literal) in
#: the emitting scope that names the negotiated state for this feature
_FEATURE_KEYS: Dict[str, Tuple[str, ...]] = {
    "FEATURE_RESUME": ("resume", "attach"),
    "FEATURE_HEARTBEAT": ("heartbeat", "hb"),
    "FEATURE_SNAPSHOT": ("snapshot", "snap"),
    "FEATURE_SUBSCRIBE": ("subscribe", "sub"),
    "FEATURE_TRACE": ("trace",),
    "FEATURE_DELTA": ("delta",),
}

#: struct format chars wide enough that a lying peer can claim an
#: allocation-breaking length (u16 ``H`` maxes out at 65535 and is
#: treated as inherently bounded)
_WIDE_LEN_CHARS = frozenset("iIlLqQnN")

#: allocation-shaped sinks a wire-claimed length must not reach unguarded
_ALLOC_SINKS = frozenset({"empty", "zeros", "bytearray", "_recv_exact",
                          "recv"})

#: exceptions whose raise marks a status branch as retriable handling
_RETRIABLE_EXC = frozenset({"ConnectionError", "BrokenPipeError",
                            "ConnectionResetError", "TimeoutError",
                            "OSError", "RoundRolled",
                            "SnapshotUnavailable", "DeltaDesync"})
#: ... and as terminal handling (anything else is unclassified)
_TERMINAL_EXC = frozenset({"RuntimeError", "ValueError", "TypeError",
                           "PermissionError"})


# --------------------------------------------------------------------------
# model records
# --------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class StructDef:
    """One module-level ``NAME = struct.Struct(fmt)`` declaration."""

    name: str
    fmt: str
    file: str
    line: int


@dataclasses.dataclass(frozen=True)
class StructUse:
    """One pack/unpack of a struct constant, op-attributed."""

    struct: str
    fmt: str
    action: str               # "pack" | "unpack"
    ops: Optional[Tuple[int, ...]]  # None = op-independent site
    func: str                 # enclosing qualname ("Class.method")
    file: str
    line: int
    header: bool = False      # this pack OPENED the op context (frame
    #                           header); exempt from per-op balance


@dataclasses.dataclass(frozen=True)
class InlineSite:
    """A hand-rolled ``struct.pack``/``struct.Struct`` inside a protocol
    function — a layout outside the shared-constant discipline."""

    fmt: Optional[str]
    func: str
    file: str
    line: int


@dataclasses.dataclass(frozen=True)
class StatusSite:
    """One emission of, or match against, a negative status constant."""

    value: int
    action: str               # "emit" | "match"
    handling: Optional[str]   # match only: "retriable" | "terminal" | None
    func: str
    file: str
    line: int


@dataclasses.dataclass(frozen=True)
class GateSite:
    """One emission of a feature-gated op or optional header."""

    feature: str
    subject: str              # "op 8 (_OP_SNAPSHOT)" | "header _TRACE_HDR"
    satisfied: bool
    evidence: str             # what satisfied the gate (or "")
    func: str
    file: str
    line: int


@dataclasses.dataclass(frozen=True)
class BoundSite:
    """A wire-claimed length flowing into an allocation-shaped sink."""

    var: str
    fmt_char: str
    sink: str
    guarded: bool
    guard: str                # description of the guard (or "")
    func: str
    file: str
    line: int


@dataclasses.dataclass
class WireModel:
    """The extracted protocol model (see module docstring)."""

    structs: Dict[str, List[StructDef]] = dataclasses.field(
        default_factory=dict)
    uses: List[StructUse] = dataclasses.field(default_factory=list)
    inline_sites: List[InlineSite] = dataclasses.field(default_factory=list)
    status_sites: List[StatusSite] = dataclasses.field(default_factory=list)
    gate_sites: List[GateSite] = dataclasses.field(default_factory=list)
    bound_sites: List[BoundSite] = dataclasses.field(default_factory=list)
    constants: Dict[str, int] = dataclasses.field(default_factory=dict)
    #: legal status values (registry constants + WIRE_V2_CODES)
    registry_values: Set[int] = dataclasses.field(default_factory=set)
    #: the retriable subset of the registry
    retriable_values: Set[int] = dataclasses.field(default_factory=set)
    #: (file, line) -> (token, reason) for reasoned ``# bfwire:`` waivers
    waivers: Dict[Tuple[str, int], Tuple[str, str]] = dataclasses.field(
        default_factory=dict)
    #: (file, line) of every comment-only source line — lets a waiver
    #: sit in a comment block directly above its site
    comment_lines: Set[Tuple[str, int]] = dataclasses.field(
        default_factory=set)
    files: List[str] = dataclasses.field(default_factory=list)
    parse_failures: List[str] = dataclasses.field(default_factory=list)

    # ---------------------------------------------------------------- query
    def op_buckets(self) -> Dict[int, Dict[str, Set[str]]]:
        """Per-op ``{"pack": {struct...}, "unpack": {...}}`` buckets
        (header-opening packs excluded — a header is by definition
        unpacked once, pre-dispatch, for every op)."""
        out: Dict[int, Dict[str, Set[str]]] = {}
        for use in self.uses:
            if use.header or use.ops is None:
                continue
            for op in use.ops:
                b = out.setdefault(op, {"pack": set(), "unpack": set()})
                b[use.action].add(use.struct)
        return out

    def opless_structs(self, action: str) -> Set[str]:
        """Structs packed/unpacked at op-independent sites (the shared
        ack/push loops) — the per-op balance check accepts these as the
        opposite side of any op."""
        return {u.struct for u in self.uses
                if u.ops is None and u.action == action}

    def waiver_at(self, file: str, line: int,
                  token: str) -> Optional[str]:
        """Reason of a matching reasoned waiver on the site line or in
        the contiguous comment block directly above it, else None (a
        bare token with no reason waives nothing)."""
        at = line
        while True:
            got = self.waivers.get((file, at))
            if got is not None:
                if got[0] == token and got[1]:
                    return got[1]
                return None
            if (file, at - 1) not in self.comment_lines:
                return None
            at -= 1

    # --------------------------------------------------------------- report
    def format_text(self) -> str:
        lines = ["wire model: %d file(s), %d struct(s), %d use(s), "
                 "%d status site(s), %d gate site(s), %d bound site(s)"
                 % (len(self.files), len(self.structs), len(self.uses),
                    len(self.status_sites), len(self.gate_sites),
                    len(self.bound_sites))]
        buckets = self.op_buckets()
        for op in sorted(buckets):
            b = buckets[op]
            lines.append("  op %-2d  pack {%s}  unpack {%s}" % (
                op, ", ".join(sorted(b["pack"])) or "-",
                ", ".join(sorted(b["unpack"])) or "-"))
        shared_p = self.opless_structs("pack")
        shared_u = self.opless_structs("unpack")
        if shared_p or shared_u:
            lines.append("  shared pack {%s}  unpack {%s}" % (
                ", ".join(sorted(shared_p)) or "-",
                ", ".join(sorted(shared_u)) or "-"))
        if self.parse_failures:
            lines.append("  PARSE FAILURES: " +
                         ", ".join(self.parse_failures))
        return "\n".join(lines)


# --------------------------------------------------------------------------
# format helpers
# --------------------------------------------------------------------------

def _fmt_chars(fmt: str) -> List[str]:
    """Expand a struct format string into one char per unpacked value."""
    out: List[str] = []
    count = ""
    for ch in fmt:
        if ch in "<>=!@ ":
            continue
        if ch.isdigit():
            count += ch
            continue
        n = int(count) if count else 1
        count = ""
        if ch == "x":
            continue
        if ch in "sp":
            out.append(ch)          # one bytes value regardless of count
        else:
            out.extend(ch * n)
    return out


# --------------------------------------------------------------------------
# per-module collection (phase A)
# --------------------------------------------------------------------------

class _Module:
    def __init__(self, rel: str, text: str, tree: ast.Module):
        self.rel = rel
        self.text = text
        self.tree = tree
        self.struct_defs: Dict[str, StructDef] = {}
        self.int_consts: Dict[str, int] = {}
        self.aliases: Dict[str, str] = {}          # NAME -> bare attr/name
        self.set_consts: Dict[str, List[ast.expr]] = {}
        self.tuple_consts: Dict[str, List[ast.expr]] = {}


def _is_struct_ctor(call: ast.Call) -> bool:
    f = call.func
    if isinstance(f, ast.Attribute) and f.attr == "Struct":
        return True
    return isinstance(f, ast.Name) and f.id == "Struct"


def _const_int(node: ast.expr) -> Optional[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return node.value
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        inner = _const_int(node.operand)
        if inner is not None:
            return -inner
    return None


def _collect_module(rel: str, text: str) -> Optional[_Module]:
    try:
        tree = ast.parse(text)
    except SyntaxError:
        return None
    mod = _Module(rel, text, tree)
    for node in tree.body:
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        tgt = node.targets[0]
        if not isinstance(tgt, ast.Name):
            continue
        name, val = tgt.id, node.value
        lit = _const_int(val)
        if lit is not None:
            mod.int_consts[name] = lit
        elif isinstance(val, ast.Call) and _is_struct_ctor(val) \
                and val.args and isinstance(val.args[0], ast.Constant) \
                and isinstance(val.args[0].value, str):
            mod.struct_defs[name] = StructDef(name, val.args[0].value,
                                              rel, node.lineno)
        elif isinstance(val, ast.Attribute):
            mod.aliases[name] = val.attr
        elif isinstance(val, ast.Name):
            mod.aliases[name] = val.id
        elif isinstance(val, ast.Call) and isinstance(val.func, ast.Name) \
                and val.func.id in ("frozenset", "set", "tuple") \
                and val.args \
                and isinstance(val.args[0], (ast.Tuple, ast.Set, ast.List)):
            mod.set_consts[name] = list(val.args[0].elts)
        elif isinstance(val, ast.Tuple):
            mod.tuple_consts[name] = list(val.elts)
    return mod


def _collect_waivers(rel: str, text: str,
                     out: Dict[Tuple[str, int], Tuple[str, str]],
                     comments: Set[Tuple[str, int]]) -> None:
    for i, line in enumerate(text.splitlines(), start=1):
        if line.lstrip().startswith("#"):
            comments.add((rel, i))
        m = _WAIVER_RE.search(line)
        if m:
            out[(rel, i)] = (m.group(1), m.group(2).strip())


# --------------------------------------------------------------------------
# global resolution
# --------------------------------------------------------------------------

class _Resolver:
    """Resolve names/attributes to ints, struct names, or set values
    across the whole scan set (bare-name matching: ``ws._HDR`` and
    ``_HDR`` are the same constant — wire names are globally unique)."""

    def __init__(self, mods: Sequence[_Module]):
        self.structs: Dict[str, StructDef] = {}
        self.consts: Dict[str, int] = {}
        aliases: Dict[str, str] = {}
        self._set_exprs: Dict[str, List[ast.expr]] = {}
        for m in mods:
            self.structs.update(m.struct_defs)
            self.consts.update(m.int_consts)
            aliases.update(m.aliases)
            self._set_exprs.update(m.set_consts)
            self._set_exprs.update(m.tuple_consts)
        for _ in range(len(aliases) + 1):        # alias-chain fixpoint
            changed = False
            for name, target in aliases.items():
                if name not in self.consts and target in self.consts:
                    self.consts[name] = self.consts[target]
                    changed = True
                if name not in self.structs and target in self.structs:
                    self.structs[name] = self.structs[target]
                    changed = True
            if not changed:
                break
        self.set_values: Dict[str, Tuple[int, ...]] = {}
        for name, elts in self._set_exprs.items():
            vals = [self.resolve_int(e) for e in elts]
            if vals and all(v is not None for v in vals):
                self.set_values[name] = tuple(v for v in vals
                                              if v is not None)

    def _ref_name(self, node: ast.expr) -> Optional[str]:
        if isinstance(node, ast.Name):
            return node.id
        if isinstance(node, ast.Attribute):
            return node.attr
        return None

    def resolve_int(self, node: ast.expr) -> Optional[int]:
        lit = _const_int(node)
        if lit is not None:
            return lit
        name = self._ref_name(node)
        return self.consts.get(name) if name else None

    def resolve_int_name(self, node: ast.expr
                         ) -> Optional[Tuple[str, int]]:
        """Resolve a NAMED constant reference (never a bare literal)."""
        name = self._ref_name(node)
        if name is not None and name in self.consts:
            return name, self.consts[name]
        return None

    def struct_of(self, node: ast.expr) -> Optional[StructDef]:
        name = self._ref_name(node)
        return self.structs.get(name) if name else None


# --------------------------------------------------------------------------
# registry extraction
# --------------------------------------------------------------------------

def _extract_registry(res: _Resolver, model: WireModel) -> None:
    vals: Set[int] = set()
    for name, v in res.consts.items():
        if _ERR_NAME_RE.match(name):
            vals.add(v)
    for key in ("WIRE_V2_CODES",):
        vals.update(res.set_values.get(key, ()))
    retri = set(res.set_values.get("_RETRIABLE", ()))
    if not vals:
        # synthetic sources without a registry module: fall back to the
        # live table so status checks still have ground truth
        try:
            from bluefog_tpu.runtime import wire_status as _wst
            vals = set(_wst.WIRE_V2_CODES) | {_wst.ERR_GEOMETRY,
                                              _wst.ERR_NO_WINDOW}
            retri = {c for c in vals if _wst.is_retriable(c)}
        except Exception:  # pragma: no cover - import cycle safety
            pass
    model.registry_values = vals
    model.retriable_values = retri


# --------------------------------------------------------------------------
# function-body scan (phase B)
# --------------------------------------------------------------------------

def _calls_in_order(node: ast.AST) -> List[ast.Call]:
    calls = [n for n in ast.walk(node) if isinstance(n, ast.Call)]
    calls.sort(key=lambda c: (c.lineno, c.col_offset))
    return calls


def _scope_idents(node: ast.AST) -> Set[str]:
    """Every identifier-ish string in a scope (names, attributes, str
    literals) — the haystack for feature-gate evidence."""
    out: Set[str] = set()
    for n in ast.walk(node):
        if isinstance(n, ast.Name):
            out.add(n.id)
        elif isinstance(n, ast.Attribute):
            out.add(n.attr)
        elif isinstance(n, ast.Constant) and isinstance(n.value, str):
            out.add(n.value)
    return out


def _gate_evidence(feature: str, idents: Set[str]) -> Optional[str]:
    if feature in idents:
        return feature
    keys = _FEATURE_KEYS.get(feature, ())
    for ident in idents:
        low = ident.lower()
        if any(k in low for k in keys) and (
                "granted" in low or "want" in low or low.endswith("_on")):
            return ident
    return None


class _FuncScan:
    """Scan one function body: op-context tracking plus all site kinds."""

    def __init__(self, res: _Resolver, model: WireModel, rel: str,
                 qualname: str, scope_idents: Set[str]):
        self.res = res
        self.model = model
        self.rel = rel
        self.qualname = qualname
        self.scope_idents = scope_idents
        self.len_vars: Dict[str, str] = {}      # wire-claimed var -> char
        self.guards: List[Tuple[int, str, str]] = []  # (line, var, desc)
        self.pending_sinks: List[Tuple[ast.Call, str, str]] = []
        self.calls_out: List[Tuple[str, Optional[Tuple[int, ...]]]] = []
        self.uses_tmp: List[StructUse] = []

    # ------------------------------------------------------------- helpers
    def _record_use(self, sd: StructDef, action: str,
                    ops: Optional[Tuple[int, ...]], line: int,
                    header: bool = False) -> None:
        self.uses_tmp.append(StructUse(sd.name, sd.fmt, action, ops,
                                       self.qualname, self.rel, line,
                                       header))

    def _emit_status(self, node: ast.expr, line: int) -> None:
        v = self.res.resolve_int(node)
        if v is not None and v <= -2:
            self.model.status_sites.append(StatusSite(
                v, "emit", None, self.qualname, self.rel, line))

    def _gate_site(self, feature: str, subject: str, line: int) -> None:
        ev = _gate_evidence(feature, self.scope_idents)
        self.model.gate_sites.append(GateSite(
            feature, subject, ev is not None, ev or "",
            self.qualname, self.rel, line))

    def _header_struct_feature(self, struct_name: str) -> Optional[str]:
        for suffix, feature in GATED_HEADERS.items():
            if struct_name.endswith(suffix):
                return feature
        return None

    # ------------------------------------------------------- call handling
    def _handle_call(self, call: ast.Call,
                     ctx: Optional[Tuple[int, ...]]
                     ) -> Optional[Tuple[int, ...]]:
        f = call.func
        # a) struct constant pack/unpack
        if isinstance(f, ast.Attribute) and f.attr in (
                "pack", "pack_into", "unpack", "unpack_from"):
            sd = self.res.struct_of(f.value)
            if sd is not None:
                action = "pack" if f.attr.startswith("pack") else "unpack"
                if action == "pack":
                    header_op = None
                    for arg in call.args:
                        named = self.res.resolve_int_name(arg)
                        if named and _OP_NAME_RE.match(named[0]):
                            header_op = named[1]
                            break
                    for arg in call.args:
                        self._emit_status(arg, call.lineno)
                    hfeat = self._header_struct_feature(sd.name)
                    if hfeat is not None:
                        self._gate_site(hfeat, "header %s" % sd.name,
                                        call.lineno)
                    if header_op is not None:
                        self._record_use(sd, "pack", (header_op,),
                                         call.lineno, header=True)
                        if header_op in GATED_OPS and hfeat is None:
                            self._gate_site(
                                GATED_OPS[header_op],
                                "op %d" % header_op, call.lineno)
                        return (header_op,)
                self._record_use(sd, action, ctx, call.lineno)
                return ctx
        # b) hand-rolled struct module use inside a function
        if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name) \
                and f.value.id == "struct" and f.attr in (
                    "pack", "pack_into", "unpack", "unpack_from",
                    "calcsize", "Struct"):
            fmt = None
            if call.args and isinstance(call.args[0], ast.Constant) \
                    and isinstance(call.args[0].value, str):
                fmt = call.args[0].value
            self.model.inline_sites.append(InlineSite(
                fmt, self.qualname, self.rel, call.lineno))
            return ctx
        # c) status constants handed to ack/send helpers
        callee = None
        if isinstance(f, ast.Attribute):
            callee = f.attr
        elif isinstance(f, ast.Name):
            callee = f.id
        if callee is not None:
            low = callee.lower()
            if "ack" in low or "send" in low or "status" in low:
                for arg in call.args:
                    self._emit_status(arg, call.lineno)
            # allocation-shaped sinks fed by wire-claimed lengths
            if callee in _ALLOC_SINKS:
                for n in ast.walk(call):
                    if isinstance(n, ast.Name) and n.id in self.len_vars:
                        self.pending_sinks.append((call, n.id, callee))
                        break
            # min()-capping counts as an inline guard
            if callee == "min" and len(call.args) >= 2:
                for n in ast.walk(call):
                    if isinstance(n, ast.Name) and n.id in self.len_vars:
                        self.guards.append((call.lineno, n.id,
                                            "min() cap"))
            self.calls_out.append((callee, ctx))
        return ctx

    # -------------------------------------------------- statement handling
    def _branch_ops(self, test: ast.expr) -> Optional[Tuple[int, ...]]:
        comps = [n for n in ast.walk(test) if isinstance(n, ast.Compare)]
        for cmp_ in comps:
            if len(cmp_.ops) != 1:
                continue
            op_node, rhs = cmp_.ops[0], cmp_.comparators[0]
            if isinstance(op_node, ast.Eq):
                for side in (cmp_.left, rhs):
                    named = self.res.resolve_int_name(side)
                    if named and _OP_NAME_RE.match(named[0]):
                        return (named[1],)
            elif isinstance(op_node, ast.In):
                name = None
                if isinstance(rhs, ast.Name):
                    name = rhs.id
                elif isinstance(rhs, ast.Attribute):
                    name = rhs.attr
                if name and name in self.res.set_values:
                    return self.res.set_values[name]
                if isinstance(rhs, (ast.Tuple, ast.Set)):
                    vals = []
                    for e in rhs.elts:
                        named = self.res.resolve_int_name(e)
                        if not (named and _OP_NAME_RE.match(named[0])):
                            vals = []
                            break
                        vals.append(named[1])
                    if vals:
                        return tuple(vals)
        return None

    def _match_handling(self, body: List[ast.stmt]) -> Optional[str]:
        for stmt in body:
            for n in ast.walk(stmt):
                if not isinstance(n, ast.Raise) or n.exc is None:
                    continue
                exc = n.exc
                name = None
                if isinstance(exc, ast.Call):
                    exc = exc.func
                if isinstance(exc, ast.Name):
                    name = exc.id
                elif isinstance(exc, ast.Attribute):
                    name = exc.attr
                if name in _RETRIABLE_EXC:
                    return "retriable"
                if name in _TERMINAL_EXC:
                    return "terminal"
        return None

    def _status_matches(self, test: ast.expr, body: List[ast.stmt],
                        line: int) -> None:
        for cmp_ in (n for n in ast.walk(test)
                     if isinstance(n, ast.Compare)):
            if len(cmp_.ops) != 1:
                continue
            vals: List[int] = []
            if isinstance(cmp_.ops[0], (ast.Eq, ast.NotEq)):
                for side in (cmp_.left, cmp_.comparators[0]):
                    named = self.res.resolve_int_name(side)
                    if named and _ERR_NAME_RE.match(named[0]):
                        vals.append(named[1])
            elif isinstance(cmp_.ops[0], ast.In) and isinstance(
                    cmp_.comparators[0], (ast.Tuple, ast.Set)):
                for e in cmp_.comparators[0].elts:
                    named = self.res.resolve_int_name(e)
                    if named and _ERR_NAME_RE.match(named[0]):
                        vals.append(named[1])
            handling = self._match_handling(body) if vals else None
            for v in vals:
                self.model.status_sites.append(StatusSite(
                    v, "match", handling, self.qualname, self.rel,
                    cmp_.lineno if hasattr(cmp_, "lineno") else line))

    def _note_unpack_targets(self, stmt: ast.Assign) -> None:
        call = stmt.value
        if not (isinstance(call, ast.Call)
                and isinstance(call.func, ast.Attribute)
                and call.func.attr in ("unpack", "unpack_from")):
            return
        sd = self.res.struct_of(call.func.value)
        if sd is None or len(stmt.targets) != 1:
            return
        tgt = stmt.targets[0]
        names: List[Optional[str]] = []
        if isinstance(tgt, ast.Tuple):
            names = [e.id if isinstance(e, ast.Name) else None
                     for e in tgt.elts]
        elif isinstance(tgt, ast.Name):
            names = [tgt.id]
        for name, ch in zip(names, _fmt_chars(sd.fmt)):
            if name is not None and ch in _WIDE_LEN_CHARS:
                self.len_vars[name] = ch

    def _note_guards(self, stmt: ast.stmt) -> None:
        for cmp_ in (n for n in ast.walk(stmt)
                     if isinstance(n, ast.Compare)):
            vars_here = {n.id for n in ast.walk(cmp_)
                         if isinstance(n, ast.Name)
                         and n.id in self.len_vars}
            if not vars_here:
                continue
            desc = None
            for n in ast.walk(cmp_):
                if isinstance(n, ast.Call):
                    cname = (n.func.attr if isinstance(n.func,
                                                       ast.Attribute)
                             else n.func.id if isinstance(n.func,
                                                          ast.Name)
                             else "")
                    if "bound" in cname:
                        desc = "%s()" % cname
                        break
                if isinstance(n, (ast.Name, ast.Attribute)):
                    ident = n.id if isinstance(n, ast.Name) else n.attr
                    if _MAX_NAME_RE.search(ident):
                        desc = ident
                        break
                if isinstance(n, ast.Constant) \
                        and isinstance(n.value, int) \
                        and not isinstance(n.value, bool) \
                        and n.value > 0:
                    desc = "literal %d" % n.value
            if desc is None:
                # a bound carried by a non-wire variable — e.g. the
                # reply length checked against the REQUEST's own
                # n_elems: any direct operand that is a bare name not
                # itself unpacked from the wire
                for n in [cmp_.left, *cmp_.comparators]:
                    if isinstance(n, ast.Name) \
                            and n.id not in self.len_vars:
                        desc = "vs %s" % n.id
                        break
            if desc:
                for var in vars_here:
                    self.guards.append((cmp_.lineno, var, desc))

    def scan_body(self, body: List[ast.stmt],
                  ctx: Optional[Tuple[int, ...]]) -> None:
        cur = ctx
        for stmt in body:
            if isinstance(stmt, ast.Assign):
                self._note_unpack_targets(stmt)
            self._note_guards(stmt)
            if isinstance(stmt, ast.Return) and stmt.value is not None:
                v = self.res.resolve_int(stmt.value)
                if v is not None and v <= -2:
                    self.model.status_sites.append(StatusSite(
                        v, "emit", None, self.qualname, self.rel,
                        stmt.lineno))
            if isinstance(stmt, ast.If):
                self._status_matches(stmt.test, stmt.body, stmt.lineno)
                for call in _calls_in_order(stmt.test):
                    cur = self._handle_call(call, cur)
                branch = self._branch_ops(stmt.test)
                self.scan_body(stmt.body, branch if branch else cur)
                self.scan_body(stmt.orelse, cur)
            elif isinstance(stmt, (ast.For, ast.While)):
                for call in _calls_in_order(stmt.iter if isinstance(
                        stmt, ast.For) else stmt.test):
                    cur = self._handle_call(call, cur)
                self.scan_body(stmt.body, cur)
                self.scan_body(stmt.orelse, cur)
            elif isinstance(stmt, ast.With):
                for item in stmt.items:
                    for call in _calls_in_order(item.context_expr):
                        cur = self._handle_call(call, cur)
                self.scan_body(stmt.body, cur)
            elif isinstance(stmt, ast.Try):
                self.scan_body(stmt.body, cur)
                for h in stmt.handlers:
                    self.scan_body(h.body, cur)
                self.scan_body(stmt.orelse, cur)
                self.scan_body(stmt.finalbody, cur)
            elif isinstance(stmt, (ast.FunctionDef,
                                   ast.AsyncFunctionDef, ast.ClassDef)):
                continue        # nested defs scanned separately
            else:
                for call in _calls_in_order(stmt):
                    cur = self._handle_call(call, cur)

    def finish(self) -> None:
        for call, var, sink in self.pending_sinks:
            hit = [g for g in self.guards
                   if g[1] == var and g[0] <= call.lineno]
            self.model.bound_sites.append(BoundSite(
                var, self.len_vars[var], sink, bool(hit),
                hit[0][2] if hit else "", self.qualname, self.rel,
                call.lineno))


# --------------------------------------------------------------------------
# assembly
# --------------------------------------------------------------------------

def _iter_functions(tree: ast.Module):
    """Yield (qualname, func_node, scope_node) for every function; the
    scope node (class body for methods, the function itself otherwise)
    is where feature-gate evidence is searched."""
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node.name, node, node
        elif isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                    yield "%s.%s" % (node.name, sub.name), sub, node


def build_model(sources: Sequence[Tuple[str, str]]) -> WireModel:
    """Extract the wire model from ``(relpath, source_text)`` pairs."""
    model = WireModel()
    mods: List[_Module] = []
    for rel, text in sources:
        model.files.append(rel)
        _collect_waivers(rel, text, model.waivers, model.comment_lines)
        mod = _collect_module(rel, text)
        if mod is None:
            model.parse_failures.append(rel)
            continue
        mods.append(mod)
    res = _Resolver(mods)
    model.constants = dict(res.consts)
    for name, sd in res.structs.items():
        if name == sd.name:         # skip alias entries
            model.structs.setdefault(name, [])
            if sd not in model.structs[name]:
                model.structs[name].append(sd)
    # same-named struct constants DEFINED in two modules (not aliases)
    for m in mods:
        for name, sd in m.struct_defs.items():
            lst = model.structs.setdefault(name, [])
            if sd not in lst:
                lst.append(sd)
    _extract_registry(res, model)

    scans: Dict[str, _FuncScan] = {}
    callgraph: Dict[str, List[Tuple[str, Optional[Tuple[int, ...]]]]] = {}
    for m in mods:
        for qual, fn, scope in _iter_functions(m.tree):
            idents = _scope_idents(scope)
            scan = _FuncScan(res, model, m.rel, qual, idents)
            scan.scan_body(fn.body, None)
            scans["%s:%s" % (m.rel, qual)] = scan
            callgraph["%s:%s" % (m.rel, qual)] = scan.calls_out

    # op-entry fixpoint: a helper inherits the union of the op contexts
    # at its call sites (one-level-plus: contexts flow transitively)
    by_bare: Dict[str, List[str]] = {}
    for key in scans:
        by_bare.setdefault(
            key.rsplit(":", 1)[1].rsplit(".", 1)[-1], []).append(key)
    entry: Dict[str, Set[int]] = {key: set() for key in scans}
    for _ in range(len(scans)):
        changed = False
        for key, calls in callgraph.items():
            for callee, ctx in calls:
                ops = set(ctx) if ctx else entry[key]
                if not ops:
                    continue
                for tgt in by_bare.get(callee, ()):
                    if not ops <= entry[tgt]:
                        entry[tgt] |= ops
                        changed = True
        if not changed:
            break

    for key, scan in scans.items():
        inherited = tuple(sorted(entry[key])) or None
        for use in scan.uses_tmp:
            if use.ops is None and inherited is not None:
                use = dataclasses.replace(use, ops=inherited)
            model.uses.append(use)
        scan.finish()
    return model


def package_root() -> str:
    """The ``bluefog_tpu`` package directory."""
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def build_package_model(root: Optional[str] = None) -> WireModel:
    """Extract the model from the repo's protocol surface
    (:data:`PROTOCOL_FILES`)."""
    root = root or package_root()
    sources: List[Tuple[str, str]] = []
    for rel in PROTOCOL_FILES:
        path = os.path.join(root, *rel.split("/"))
        try:
            with open(path, "r", encoding="utf-8") as fh:
                sources.append((rel, fh.read()))
        except OSError:
            continue
    return build_model(sources)
