"""``python -m bluefog_tpu.chaos`` == the ``bfchaos-tpu`` CLI."""

from bluefog_tpu.chaos.cli import main

if __name__ == "__main__":
    raise SystemExit(main())
