"""``bfchaos-tpu`` — run a command under a deterministic fault spec.

::

    bfchaos-tpu --spec "server:drop:after_frames=40" -- \\
        python examples/async_dsgd_mp.py
    bfchaos-tpu --spec "rank2:sigkill:at_step=8" --explain
    bfchaos-tpu --grammar

The spec is validated HERE (a typo fails fast with the offending rule,
not silently deep inside a worker), exported to the child as
``BLUEFOG_TPU_CHAOS``, and the child's transport/runner shims do the
injecting.  ``--explain`` prints the parsed rules without running
anything; ``--grammar`` prints the spec grammar.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
from typing import List, Optional

from bluefog_tpu.chaos.injector import ChaosSpecError, parse_spec

__all__ = ["main"]

_GRAMMAR = """\
spec  := rule (';' rule)*
rule  := site ':' fault (':' key '=' value)*
site  := 'server' | 'ack' | 'client' | 'read' | 'sub' | 'any' | 'rank<N>'
fault := drop | truncate | delay | stall            (socket sites)
       | sigkill | sigstop | die | stall            (rank sites)
       | leave | join                               (membership churn)

socket keys: after_frames=N  every=K  prob=P  rate=P  times=T  seed=S
             ms=M (delay)    s=S (stall)
             (rate= is the lossy-link spelling of prob=: a link that
             loses ~P of its frames, deterministic per seed)
rank keys:   at_step=N  after_s=T  for_s=T (sigstop thaw / stall length)
             (leave needs at_step=; join needs after_s=)

sites 'server'/'ack'/'client' are the deposit (write) path; 'read' cuts
or stalls sync-read/SNAPSHOT replies on the serving host, 'sub' the
subscription push sender — the read-path fault surface.

examples:
  server:drop:after_frames=40      cut a server connection at frame 40
  ack:drop:after_frames=3          apply batch 3, drop before the ack
  client:truncate:after_frames=5   send half a frame, then cut
  server:delay:ms=20:prob=0.1      delay 10%% of frames by 20 ms
  server:drop:rate=0.05:seed=3     a 5%%-loss lossy link (seeded)
  read:truncate:every=7            tear every 7th read reply mid-frame
  read:stall:s=2:prob=0.05         wedge 5%% of read replies for 2 s
  sub:drop:after_frames=10         cut a push subscription at frame 10
  sub:stall:s=1:every=13           stall every 13th snapshot push 1 s
  rank2:sigkill:at_step=8          rank 2 SIGKILLs itself at step 8
  rank1:sigstop:after_s=0.8:for_s=1  freeze rank 1 for 1 s, then thaw
  rank1:leave:at_step=20           graceful drain (mass handed off)
  rank3:join:after_s=0.5           rank 3 attaches to the running job
"""


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="bfchaos-tpu",
        description="Run a command under a deterministic "
                    "BLUEFOG_TPU_CHAOS fault spec.")
    ap.add_argument("--spec", default=None,
                    help="chaos spec (see --grammar)")
    ap.add_argument("--explain", action="store_true",
                    help="parse and print the rules, run nothing")
    ap.add_argument("--grammar", action="store_true",
                    help="print the spec grammar and exit")
    ap.add_argument("cmd", nargs=argparse.REMAINDER,
                    help="command to run (prefix with --)")
    args = ap.parse_args(argv)

    if args.grammar:
        print(_GRAMMAR)
        return 0
    if args.spec is None:
        ap.error("--spec is required (or use --grammar)")
    try:
        rules = parse_spec(args.spec)
    except ChaosSpecError as e:
        print(f"bfchaos-tpu: bad spec: {e}", file=sys.stderr)
        return 2
    if args.explain:
        for i, r in enumerate(rules):
            print(f"rule {i}: {r}")
        return 0
    cmd = list(args.cmd)
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        ap.error("no command given (bfchaos-tpu --spec ... -- cmd args)")
    env = dict(os.environ)
    env["BLUEFOG_TPU_CHAOS"] = args.spec
    return subprocess.call(cmd, env=env)


if __name__ == "__main__":
    raise SystemExit(main())
