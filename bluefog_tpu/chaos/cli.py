"""``bfchaos-tpu`` — run a command under a deterministic fault spec.

::

    bfchaos-tpu --spec "server:drop:after_frames=40" -- \\
        python examples/async_dsgd_mp.py
    bfchaos-tpu --spec "rank2:sigkill:at_step=8" --explain
    bfchaos-tpu --grammar

The spec is validated HERE (a typo fails fast with the offending rule,
not silently deep inside a worker), exported to the child as
``BLUEFOG_TPU_CHAOS``, and the child's transport/runner shims do the
injecting.  ``--explain`` prints the parsed rules without running
anything; ``--grammar`` prints the spec grammar.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
from typing import List, Optional

from bluefog_tpu.chaos.spec import (GRAMMAR, ChaosSpecError,
                                    parse_spec)

__all__ = ["main"]



def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="bfchaos-tpu",
        description="Run a command under a deterministic "
                    "BLUEFOG_TPU_CHAOS fault spec.")
    ap.add_argument("--spec", default=None,
                    help="chaos spec (see --grammar)")
    ap.add_argument("--explain", action="store_true",
                    help="parse and print the rules, run nothing")
    ap.add_argument("--grammar", action="store_true",
                    help="print the spec grammar and exit")
    ap.add_argument("cmd", nargs=argparse.REMAINDER,
                    help="command to run (prefix with --)")
    args = ap.parse_args(argv)

    if args.grammar:
        print(GRAMMAR)
        return 0
    if args.spec is None:
        ap.error("--spec is required (or use --grammar)")
    try:
        rules = parse_spec(args.spec)
    except ChaosSpecError as e:
        print(f"bfchaos-tpu: bad spec: {e}", file=sys.stderr)
        return 2
    if args.explain:
        for i, r in enumerate(rules):
            print(f"rule {i}: {r}")
        return 0
    cmd = list(args.cmd)
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        ap.error("no command given (bfchaos-tpu --spec ... -- cmd args)")
    env = dict(os.environ)
    env["BLUEFOG_TPU_CHAOS"] = args.spec
    return subprocess.call(cmd, env=env)


if __name__ == "__main__":
    raise SystemExit(main())
