"""Deterministic fault injection for peer-fault-tolerance testing.

The ROADMAP's north star ("heavy traffic, as many scenarios as you can
imagine") needs failures ON DEMAND, not by luck: this package is a
seedable, counter-driven fault injector whose shims live at the exact
choke points the resilience layer defends —

- **socket faults** at the DCN window transport
  (:mod:`bluefog_tpu.runtime.window_server`): drop or truncate a frame,
  delay or stall a connection, and — the nastiest case — drop the
  connection *after* a batch applied but *before* its ack left, which is
  precisely the ambiguity the stream-epoch replay protocol exists to
  resolve;
- **reader faults** at the serving read path (the same transport's
  ``SNAPSHOT``/``SUBSCRIBE`` ops and sync reads): ``read:*`` cuts,
  tears mid-frame, or stalls a read reply on the serving host;
  ``sub:*`` does the same to a subscription's push sender — the
  surfaces :mod:`bluefog_tpu.serving`'s retry/resume machinery defends;
- **process faults** for multi-process runs: SIGKILL / SIGSTOP a rank at
  a deterministic step or wall-clock offset (a SIGSTOPped process
  arranges its own SIGCONT through a tiny helper child, so one spec line
  expresses the full freeze/thaw round trip);
- **thread faults** for the in-process rank loops
  (:func:`~bluefog_tpu.runtime.async_windows.run_async_dsgd`): ``die``
  raises :class:`ChaosKill` inside the rank loop (the thread-model
  analog of SIGKILL) and ``stall`` freezes the loop for a fixed time
  (the analog of SIGSTOP/SIGCONT).

Faults are configured with ``BLUEFOG_TPU_CHAOS=<spec>`` (read lazily,
like the metrics/blackbox env vars), programmatically via
:func:`configure`, or by wrapping a command with the ``bfchaos-tpu``
CLI.  Everything is deterministic given the same traffic: triggers count
frames/steps, and probabilistic rules draw from a per-rule seeded RNG.

The spec grammar — sites, faults, trigger keys, and their validation —
is defined and documented exactly ONCE, in
:mod:`bluefog_tpu.chaos.spec` (``parse_spec`` / ``Rule``); this package
re-exports the parser, and the fleet simulator
(:mod:`bluefog_tpu.sim`) consumes the same parsed rules for its
declarative fault schedules, so a fault reproduced live at 3 ranks
replays unchanged at 1000 simulated ranks.

Examples::

    server:drop:after_frames=40        # cut the connection at frame 40
    ack:drop:after_frames=3            # apply batch 3, drop before ack
    client:truncate:after_frames=5     # send half a frame, then cut
    server:delay:ms=20:prob=0.1:seed=7 # 10% of frames delayed 20 ms
    server:drop:rate=0.05:seed=3       # a 5%-loss lossy link (seeded)
    read:truncate:every=7              # tear every 7th read reply mid-frame
    sub:stall:s=1:every=13             # stall every 13th snapshot push 1 s
    rank2:sigkill:at_step=8            # rank 2 SIGKILLs itself at step 8
    rank1:sigstop:after_s=0.8:for_s=1  # freeze rank 1 for 1 s
    rank2:die:at_step=8                # thread-mode death (ChaosKill)
    rank1:stall:at_step=6:s=1.5        # thread-mode freeze/thaw
    rank1:leave:at_step=20             # graceful drain at step 20 (ChaosLeave)
    rank3:join:after_s=0.5             # rank 3 attaches to the job at t=0.5s
    rank3:join:after_s=0.5;rank3:leave:at_step=10;rank3:join:after_s=2
                                       # a flapping joiner: join, drain, rejoin

The injector records every firing in the flight recorder
(``chaos_inject``) and the ``bf_chaos_injections_total`` counter, so an
incident dump shows the injected fault next to the failure it caused.
"""

from bluefog_tpu.chaos.injector import (
    ChaosKill,
    ChaosLeave,
    ChaosSpecError,
    Injector,
    Rule,
    arm,
    check_step,
    configure,
    enabled,
    fire,
    get,
    join_times,
    parse_spec,
    reset,
)

__all__ = [
    "ChaosKill",
    "ChaosLeave",
    "ChaosSpecError",
    "Injector",
    "Rule",
    "arm",
    "check_step",
    "configure",
    "enabled",
    "fire",
    "get",
    "join_times",
    "parse_spec",
    "reset",
]
