"""The chaos injector: trigger evaluation and fault execution.

Spec parsing lives in :mod:`bluefog_tpu.chaos.spec` — the ONE grammar
definition, shared with the fleet simulator's fault schedules
(:mod:`bluefog_tpu.sim`); this module re-exports ``Rule`` /
``parse_spec`` / ``ChaosSpecError`` so existing imports keep working.
See the package docstring for the grammar.  Design notes:

- **Cheap when off.**  ``fire()`` is one module-level call with a None
  check; the env var is read once and cached (``configure``/``reset``
  invalidate), so instrumented hot paths (one check per wire frame) cost
  nothing in production.
- **Deterministic.**  Triggers are per-rule counters over the traffic
  the site actually sees (``after_frames``/``every``), and ``prob``
  draws from ``random.Random(seed ^ rule_index)`` — the same run
  produces the same injection sequence.
- **Faults are executed where they are honest.**  Socket rules only
  *return an action*; the transport shim applies it (closing ITS socket,
  sleeping on ITS thread).  Process rules execute real signals on the
  current process — a SIGKILLed rank dies exactly as an OOM-killed one
  would.  ``sigstop`` with ``for_s`` spawns a detached helper child that
  sleeps and SIGCONTs the parent (a stopped process cannot resume
  itself).
"""

from __future__ import annotations

import os
import random
import signal
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

from bluefog_tpu.blackbox import recorder as _bb
from bluefog_tpu.chaos.spec import (ChaosSpecError, Rule, parse_spec)
from bluefog_tpu.utils import lockcheck as _lc
from bluefog_tpu.metrics import comm as _mt

__all__ = [
    "ChaosKill",
    "ChaosLeave",
    "ChaosSpecError",
    "Injector",
    "Rule",
    "arm",
    "check_step",
    "configure",
    "enabled",
    "fire",
    "get",
    "join_times",
    "parse_spec",
    "reset",
]

_ENV = "BLUEFOG_TPU_CHAOS"


class ChaosKill(Exception):
    """Raised by a ``die`` rule inside a rank loop — the thread-model
    analog of SIGKILL.  The resilient runners treat the raising thread
    as dead (no drain, no final publish); anything else propagating it
    is a test-harness bug, so it is a plain ``Exception``."""

    def __init__(self, rank: int, step: Optional[int] = None):
        super().__init__(f"chaos killed rank {rank} at step {step}")
        self.rank = rank
        self.step = step


class ChaosLeave(Exception):
    """Raised by a ``leave`` rule inside a rank loop — a *graceful drain*
    request, the membership-churn twin of :class:`ChaosKill`.  The
    elastic runners catch it and perform the full leave protocol (fence,
    hand push-sum mass to the out-neighbors, write the ``left`` record)
    instead of treating the rank as a corpse; anything else propagating
    it is a harness bug, so it is a plain ``Exception``."""

    def __init__(self, rank: int, step: Optional[int] = None):
        super().__init__(f"chaos drained rank {rank} at step {step}")
        self.rank = rank
        self.step = step


class Injector:
    """Evaluates the parsed rules against the traffic.  Thread-safe: the
    shims call in from server daemon threads, stream sender threads, and
    rank loops concurrently."""

    def __init__(self, spec: str):
        self.spec = spec
        self.rules = parse_spec(spec)
        self._mu = _lc.lock("chaos.injector.Injector._mu")
        self._counters: Dict[int, int] = {i: 0 for i in range(len(self.rules))}
        self._fired: Dict[int, int] = {i: 0 for i in range(len(self.rules))}
        self._rngs = [random.Random((r.seed << 8) ^ i)
                      for i, r in enumerate(self.rules)]
        self._armed: set = set()
        self._timers: List[threading.Timer] = []

    # ------------------------------------------------------------ triggers
    def _record(self, rule: Rule, idx: int, **ctx) -> None:
        self._fired[idx] += 1
        _bb.record("chaos_inject", site=rule.site, fault=rule.fault,
                   rule=idx, **{k: v for k, v in ctx.items()
                                if isinstance(v, (str, int, float))})
        _mt.inc("bf_chaos_injections_total", 1.0, fault=rule.fault,
                site=rule.site)

    def fire(self, site: str, **ctx) -> Optional[Tuple]:
        """Socket shim entry: count this frame for every matching rule
        and return the first triggered action —
        ``('drop',) | ('truncate',) | ('delay', s) | ('stall', s)`` —
        or None.  Called per wire frame; must stay cheap."""
        action: Optional[Tuple] = None
        with self._mu:
            for i, r in enumerate(self.rules):
                # rank rules never match here: fire() sites are the
                # socket shims, and 'any' is defined as any SOCKET site
                if r.site != site and r.site != "any":
                    continue
                self._counters[i] += 1
                if action is not None:
                    continue  # keep counting other rules
                mx = r.max_fires()
                if mx and self._fired[i] >= mx:
                    continue
                hit = True
                if r.after_frames is not None:
                    hit = self._counters[i] == r.after_frames
                elif r.every is not None:
                    hit = self._counters[i] % max(r.every, 1) == 0
                elif r.prob is not None:
                    hit = self._rngs[i].random() < r.prob
                elif r.rate is not None:
                    # lossy link: an independent seeded coin per frame
                    hit = self._rngs[i].random() < r.rate
                if not hit:
                    continue
                self._record(r, i, **ctx)
                if r.fault == "drop":
                    action = ("drop",)
                elif r.fault == "truncate":
                    action = ("truncate",)
                elif r.fault == "delay":
                    action = ("delay", r.ms / 1000.0)
                else:  # stall
                    action = ("stall", r.s)
        return action

    # ------------------------------------------------------- process faults
    def _execute_rank_fault(self, rule: Rule, idx: int, rank: int,
                            step: Optional[int]) -> None:
        self._record(rule, idx, rank=rank, step=step if step is not None
                     else -1)
        if rule.fault == "die":
            raise ChaosKill(rank, step)
        if rule.fault == "leave":
            raise ChaosLeave(rank, step)
        if rule.fault == "stall":
            time.sleep(rule.s if rule.s > 0 else (rule.for_s or 0.0))
            return
        if rule.fault == "sigkill":
            os.kill(os.getpid(), signal.SIGKILL)
            return  # unreachable
        if rule.fault == "sigstop":
            if rule.for_s:
                # a stopped process cannot SIGCONT itself: detach a tiny
                # helper that sleeps through the freeze and thaws us
                subprocess.Popen(
                    [sys.executable, "-c",
                     "import time,os,signal,sys;"
                     "time.sleep(float(sys.argv[1]));"
                     "os.kill(int(sys.argv[2]), signal.SIGCONT)",
                     str(rule.for_s), str(os.getpid())],
                    stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
            os.kill(os.getpid(), signal.SIGSTOP)

    def check_step(self, rank: int, step: int) -> None:
        """Rank-loop hook: execute any matured ``at_step`` fault for this
        rank.  ``die`` raises :class:`ChaosKill`; ``stall`` sleeps here;
        signals are delivered to the current process."""
        todo: List[Tuple[Rule, int]] = []
        with self._mu:
            for i, r in enumerate(self.rules):
                if r.site != "rank" or r.rank != rank or r.at_step is None:
                    continue
                mx = r.max_fires()
                if mx and self._fired[i] >= mx:
                    continue
                if step >= r.at_step:
                    todo.append((r, i))
        for r, i in todo:
            self._execute_rank_fault(r, i, rank, step)

    def arm(self, rank: int) -> None:
        """Arm wall-clock (``after_s``) faults for this rank.  Idempotent
        per rank; timers are daemon threads, so an armed fault cannot
        keep a finished process alive."""
        with self._mu:
            if rank in self._armed:
                return
            self._armed.add(rank)
            rules = [(r, i) for i, r in enumerate(self.rules)
                     if r.site == "rank" and r.rank == rank
                     and r.after_s is not None and r.fault != "join"]
        for r, i in rules:
            t = threading.Timer(
                r.after_s, self._execute_rank_fault, args=(r, i, rank, None))
            t.daemon = True
            t.start()
            with self._mu:
                self._timers.append(t)

    def join_times(self, rank: int) -> List[float]:
        """The ``after_s`` offsets of this rank's ``join`` rules, sorted —
        the elastic runners consult this ONCE at startup to schedule when
        the rank attaches (a flapping joiner is two+ join rules
        interleaved with leave rules).  Each call marks the rules fired,
        so the schedule is consumed exactly once per run."""
        out: List[float] = []
        with self._mu:
            for i, r in enumerate(self.rules):
                if (r.site == "rank" and r.rank == rank
                        and r.fault == "join" and r.after_s is not None):
                    mx = r.max_fires()
                    if mx and self._fired[i] >= mx:
                        continue
                    out.append(float(r.after_s))
                    self._record(r, i, rank=rank, step=-1)
        return sorted(out)

    def cancel(self) -> None:
        with self._mu:
            timers = list(self._timers)
            self._timers.clear()
        for t in timers:
            t.cancel()

    def stats(self) -> Dict[int, Tuple[int, int]]:
        """rule index -> (frames counted, times fired)."""
        with self._mu:
            return {i: (self._counters[i], self._fired[i])
                    for i in range(len(self.rules))}


# ---------------------------------------------------------------------------
# Process-global state (env-lazy, like metrics/blackbox)
# ---------------------------------------------------------------------------

_injector: Optional[Injector] = None
_resolved = False
_state_mu = _lc.lock("chaos.injector._state_mu")


def configure(spec: Optional[str]) -> Optional[Injector]:
    """Install an injector from ``spec`` (None disables chaos and stops
    consulting the env until :func:`reset`)."""
    global _injector, _resolved
    with _state_mu:
        if _injector is not None:
            _injector.cancel()
        _injector = Injector(spec) if spec else None
        _resolved = True
        return _injector


def reset() -> None:
    """Drop any configured injector and re-read the env next time."""
    global _injector, _resolved
    with _state_mu:
        if _injector is not None:
            _injector.cancel()
        _injector = None
        _resolved = False


def get() -> Optional[Injector]:
    global _injector, _resolved
    if _resolved:
        return _injector
    with _state_mu:
        if not _resolved:
            spec = os.environ.get(_ENV, "").strip()
            _injector = Injector(spec) if spec else None
            _resolved = True
    return _injector


def enabled() -> bool:
    return get() is not None


def fire(site: str, **ctx) -> Optional[Tuple]:
    """Module-level socket shim (no-op unless chaos is configured)."""
    inj = get()
    return None if inj is None else inj.fire(site, **ctx)


def check_step(rank: int, step: int) -> None:
    """Module-level rank-loop shim (no-op unless chaos is configured)."""
    inj = get()
    if inj is not None:
        inj.check_step(rank, step)


def arm(rank: int) -> None:
    """Arm wall-clock process faults for this rank (no-op when off)."""
    inj = get()
    if inj is not None:
        inj.arm(rank)


def join_times(rank: int) -> List[float]:
    """This rank's scheduled join offsets (empty when chaos is off)."""
    inj = get()
    return [] if inj is None else inj.join_times(rank)
