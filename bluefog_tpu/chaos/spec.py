"""The chaos spec grammar: ONE parser, two consumers.

This module is the single definition of the ``BLUEFOG_TPU_CHAOS`` rule
grammar.  Two subsystems consume the parsed :class:`Rule` objects:

- the **live injector** (:mod:`bluefog_tpu.chaos.injector`) executes
  them against real traffic — socket shims return actions, process
  faults deliver real signals;
- the **fleet simulator** (:mod:`bluefog_tpu.sim`) interprets the SAME
  rules against simulated traffic on a virtual clock — a scenario's
  fault schedule is a chaos spec, so a fault that was reproduced live
  at 3 ranks can be replayed at 1000 simulated ranks unchanged.

The grammar itself is the :data:`GRAMMAR` text below — the ONE place
it is written down; ``bfchaos-tpu --grammar`` prints it verbatim and
every doc refers here.  Validation lives here too, so both consumers
refuse the same malformed specs with the same :class:`ChaosSpecError`
— the injector adds no grammar of its own, and neither does the
simulator.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

__all__ = [
    "ChaosSpecError",
    "GRAMMAR",
    "Rule",
    "parse_spec",
    "SOCKET_FAULTS",
    "RANK_FAULTS",
    "SOCKET_SITES",
]

#: THE spec grammar, defined and documented exactly once (printed by
#: ``bfchaos-tpu --grammar``; the simulator's scenario docs link here).
GRAMMAR = """\
spec  := rule (';' rule)*
rule  := site ':' fault (':' key '=' value)*
site  := 'server' | 'ack' | 'client' | 'read' | 'sub' | 'relay' | 'any'
       | 'rank<N>'
fault := drop | truncate | delay | stall            (socket sites)
       | sigkill | sigstop | die | stall            (rank sites)
       | leave | join                               (membership churn)

socket keys: after_frames=N  every=K  prob=P  rate=P  times=T  seed=S
             ms=M (delay)    s=S (stall)
             (rate= is the lossy-link spelling of prob=: a link that
             loses ~P of its frames, deterministic per seed)
rank keys:   at_step=N  after_s=T  for_s=T (sigstop thaw / stall length)
             (leave needs at_step=; join needs after_s=)

sites 'server'/'ack'/'client' are the deposit (write) path; 'read' cuts
or stalls sync-read/SNAPSHOT replies on the serving host, 'sub' the
subscription push sender, 'relay' a relay node's re-publish of an
upstream snapshot (drop = the round is not re-published, children see
a skip; truncate additionally tears the relay's upstream link, forcing
a cursor-gap resync) — the read-path fault surface.  The fleet
simulator (bluefog_tpu.sim) interprets the same rules against virtual
traffic: socket rules hit the simulated host's transport, rank rules
schedule kills/drains/stalls/joins on the virtual clock.

examples:
  server:drop:after_frames=40      cut a server connection at frame 40
  ack:drop:after_frames=3          apply batch 3, drop before the ack
  client:truncate:after_frames=5   send half a frame, then cut
  server:delay:ms=20:prob=0.1      delay 10% of frames by 20 ms
  server:drop:rate=0.05:seed=3     a 5%-loss lossy link (seeded)
  read:truncate:every=7            tear every 7th read reply mid-frame
  read:stall:s=2:prob=0.05         wedge 5% of read replies for 2 s
  sub:drop:after_frames=10         cut a push subscription at frame 10
  sub:stall:s=1:every=13           stall every 13th snapshot push 1 s
  relay:drop:every=9               a relay skips every 9th re-publish
  relay:truncate:after_frames=20   tear a relay's uplink at land 20
  rank2:sigkill:at_step=8          rank 2 SIGKILLs itself at step 8
  rank1:sigstop:after_s=0.8:for_s=1  freeze rank 1 for 1 s, then thaw
  rank1:leave:at_step=20           graceful drain (mass handed off)
  rank3:join:after_s=0.5           rank 3 attaches to the job at t=0.5s
"""

SOCKET_FAULTS = ("drop", "truncate", "delay", "stall")
RANK_FAULTS = ("sigkill", "sigstop", "die", "stall", "leave", "join")
# 'read' fires where the server is about to send a sync-read / SNAPSHOT
# reply (drop = vanish, truncate = reply torn mid-frame, stall = wedged
# owner); 'sub' fires in the per-subscription push sender (stall = slow
# push channel, drop/truncate = the reader's connection cut, torn for
# truncate); 'relay' fires in a relay node's land/re-publish path
# (drop = the round is not re-published, truncate = that plus a torn
# uplink — the cursor-gap resync case).  Together they are the
# READ-path fault surface, the twin of the PR-5 deposit-path sites.
SOCKET_SITES = ("server", "ack", "client", "read", "sub", "relay", "any")

_INT_KEYS = ("after_frames", "every", "times", "seed", "at_step")
_FLOAT_KEYS = ("prob", "rate", "ms", "s", "after_s", "for_s")


class ChaosSpecError(ValueError):
    """Malformed ``BLUEFOG_TPU_CHAOS`` spec."""


@dataclasses.dataclass
class Rule:
    site: str                 # 'server' | 'ack' | 'client' | 'any' | 'rank'
    fault: str
    rank: Optional[int] = None
    after_frames: Optional[int] = None
    every: Optional[int] = None
    prob: Optional[float] = None
    # the LOSSY-LINK trigger: an independent seeded coin per frame, like
    # ``prob`` but named for what it models — a link that loses ~rate of
    # its frames, deterministically per seed.  One of prob/rate per rule.
    rate: Optional[float] = None
    times: Optional[int] = None      # None -> default per trigger kind
    seed: int = 0
    ms: float = 0.0                  # delay milliseconds
    s: float = 0.0                   # stall seconds
    at_step: Optional[int] = None
    after_s: Optional[float] = None
    for_s: Optional[float] = None

    def max_fires(self) -> int:
        """0 = unlimited."""
        if self.times is not None:
            return self.times
        # a one-shot by nature: counter threshold or a scheduled fault
        if (self.after_frames is not None or self.at_step is not None
                or self.after_s is not None):
            return 1
        return 0


def _parse_rule(text: str, index: int) -> Rule:
    parts = [p.strip() for p in text.split(":") if p.strip()]
    if len(parts) < 2:
        raise ChaosSpecError(
            f"rule {text!r}: need at least '<site>:<fault>'")
    site_raw, fault = parts[0].lower(), parts[1].lower()
    rank: Optional[int] = None
    if site_raw.startswith("rank"):
        try:
            rank = int(site_raw[4:])
        except ValueError:
            raise ChaosSpecError(
                f"rule {text!r}: bad rank site {site_raw!r} "
                "(want e.g. 'rank2')") from None
        site = "rank"
        if fault not in RANK_FAULTS:
            raise ChaosSpecError(
                f"rule {text!r}: fault {fault!r} is not a rank fault "
                f"{RANK_FAULTS}")
    elif site_raw in SOCKET_SITES:
        site = site_raw
        if fault not in SOCKET_FAULTS:
            raise ChaosSpecError(
                f"rule {text!r}: fault {fault!r} is not a socket fault "
                f"{SOCKET_FAULTS}")
    else:
        raise ChaosSpecError(
            f"rule {text!r}: unknown site {site_raw!r} (want one of "
            f"{SOCKET_SITES} or 'rank<N>')")
    kw: Dict[str, object] = {}
    for p in parts[2:]:
        if "=" not in p:
            raise ChaosSpecError(f"rule {text!r}: bad key=value {p!r}")
        k, v = p.split("=", 1)
        k = k.strip().lower()
        try:
            if k in _INT_KEYS:
                kw[k] = int(v)
            elif k in _FLOAT_KEYS:
                kw[k] = float(v)
            else:
                raise ChaosSpecError(
                    f"rule {text!r}: unknown key {k!r}")
        except ValueError:
            raise ChaosSpecError(
                f"rule {text!r}: bad value for {k!r}: {v!r}") from None
    rule = Rule(site=site, fault=fault, rank=rank,
                seed=int(kw.pop("seed", index)), **kw)  # type: ignore
    if rule.site == "rank" and rule.at_step is None and rule.after_s is None:
        raise ChaosSpecError(
            f"rule {text!r}: rank faults need at_step= or after_s=")
    if rule.fault == "die" and rule.at_step is None:
        raise ChaosSpecError(
            f"rule {text!r}: 'die' is a thread-loop fault and needs "
            "at_step= (a timer thread cannot kill another thread)")
    if rule.fault == "leave" and rule.at_step is None:
        raise ChaosSpecError(
            f"rule {text!r}: 'leave' is a graceful drain executed by the "
            "rank loop itself and needs at_step= (the leave protocol — "
            "fence, mass handoff, record — must run on the leaving "
            "rank's own thread at a round boundary)")
    if rule.fault == "join" and rule.after_s is None:
        raise ChaosSpecError(
            f"rule {text!r}: 'join' schedules when a rank ATTACHES to "
            "the running job and needs after_s= (queried by the elastic "
            "runner via join_times(), not executed as a fault)")
    if rule.prob is not None and rule.rate is not None:
        raise ChaosSpecError(
            f"rule {text!r}: prob= and rate= are the same trigger "
            "(a seeded per-frame coin); give one, not both")
    for k in ("prob", "rate"):
        v = getattr(rule, k)
        if v is not None and not (0.0 <= v <= 1.0):
            raise ChaosSpecError(f"rule {text!r}: {k} must be in [0, 1]")
    if rule.rate is not None and rule.site == "rank":
        raise ChaosSpecError(
            f"rule {text!r}: rate= is a socket-site trigger (a lossy "
            "link); rank faults are scheduled with at_step=/after_s=")
    return rule


def parse_spec(spec: str) -> List[Rule]:
    rules = [
        _parse_rule(part, i)
        for i, part in enumerate(p for p in spec.split(";") if p.strip())
    ]
    if not rules:
        raise ChaosSpecError(f"empty chaos spec {spec!r}")
    return rules
