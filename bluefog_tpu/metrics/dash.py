"""Metrics dashboard CLI: summarize a JSONL metrics file.

::

    python -m bluefog_tpu.metrics.dash /tmp/m.jsonl
    bfmetrics-tpu /tmp/m.jsonl --match bytes

Reads the per-step snapshot lines :func:`bluefog_tpu.metrics.export.step`
appends (plus the atexit summary line) and prints one row per series:

- counters (``*_total``): the cumulative total, per-step delta mean /
  p50 / p99, and — for byte counters — bytes/step;
- gauges: last value plus per-step mean / p50 / p99;
- histogram expansions (``*_count`` / ``_sum`` / ``_p50`` / ...): shown
  as gauges of their per-step values.

Percentiles are over the per-step series, which is what an operator
asking "what does a bad step cost" wants — the registry's own
reservoir quantiles (the ``_p50``/``_p99`` series) answer the
per-*observation* version of the question.
"""

from __future__ import annotations

import argparse
import json
import math
import sys
from typing import Dict, List, Optional

from bluefog_tpu.metrics.registry import quantile

__all__ = ["main", "load_series", "summarize"]


def load_series(path: str):
    """Parse a metrics JSONL file into ``(steps, series, summary)``:
    ``steps`` the step indices, ``series`` ``{name: [value per line]}``
    (missing values forward-filled with NaN), ``summary`` the final
    summary snapshot (or None)."""
    steps: List[int] = []
    rows: List[Dict[str, float]] = []
    summary: Optional[Dict[str, float]] = None
    with open(path) as f:
        for ln, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as e:
                raise SystemExit(f"{path}:{ln}: not JSON ({e})")
            if rec.get("summary"):
                summary = rec.get("metrics", {})
                continue
            rows.append(rec.get("metrics", {}))
            steps.append(int(rec.get("step", len(steps))))
    names = sorted({n for row in rows for n in row})
    series = {n: [row.get(n, math.nan) for row in rows] for n in names}
    return steps, series, summary


def _is_counter(name: str) -> bool:
    base = name.split("{", 1)[0]
    return base.endswith("_total")


def _deltas(values: List[float]) -> List[float]:
    out = []
    prev = 0.0
    for v in values:
        if math.isnan(v):
            continue
        out.append(max(0.0, v - prev))
        prev = v
    return out


def _fmt(v: Optional[float]) -> str:
    if v is None or (isinstance(v, float) and math.isnan(v)):
        return "-"
    if v and (abs(v) >= 1e6 or abs(v) < 1e-3):
        return f"{v:.3e}"
    if float(v).is_integer():
        return f"{int(v)}"
    return f"{v:.4g}"


def summarize(steps, series, summary=None, *, match: str = "") -> List[dict]:
    """One summary record per series (the dash table's rows)."""
    out = []
    final = summary or {}
    # a run that never called step() still writes the atexit summary —
    # its series must appear (with zero per-step points), not vanish
    series = dict(series)
    for name in final:
        series.setdefault(name, [])
    for name, values in series.items():
        if match and match not in name:
            continue
        clean = [v for v in values if not math.isnan(v)]
        if not clean and name not in final:
            continue
        if _is_counter(name):
            total = final.get(name, clean[-1] if clean else 0.0)
            per_step = _deltas(values)
            s = sorted(per_step)
            row = {
                "series": name, "type": "counter", "points": len(clean),
                "total": total,
                "per_step_mean": (sum(per_step) / len(per_step)
                                  if per_step else math.nan),
                "p50": quantile(s, 0.50), "p99": quantile(s, 0.99),
            }
        else:
            s = sorted(clean)
            row = {
                "series": name, "type": "gauge", "points": len(clean),
                "total": final.get(name, clean[-1] if clean else math.nan),
                "per_step_mean": (sum(clean) / len(clean)
                                  if clean else math.nan),
                "p50": quantile(s, 0.50), "p99": quantile(s, 0.99),
            }
        out.append(row)
    return out


def format_table(rows: List[dict]) -> str:
    headers = ("series", "type", "points", "total/last", "per-step mean",
               "p50", "p99")
    table = [headers]
    for r in rows:
        table.append((r["series"], r["type"], str(r["points"]),
                      _fmt(r["total"]), _fmt(r["per_step_mean"]),
                      _fmt(r["p50"]), _fmt(r["p99"])))
    widths = [max(len(row[i]) for row in table) for i in range(len(headers))]
    lines = []
    for i, row in enumerate(table):
        lines.append("  ".join(c.ljust(w) for c, w in zip(row, widths)))
        if i == 0:
            lines.append("  ".join("-" * w for w in widths))
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="bfmetrics-tpu",
        description="Summarize a bluefog_tpu metrics JSONL file "
                    "(per-metric totals, per-step p50/p99).")
    ap.add_argument("path", help="JSONL file written via "
                    "BLUEFOG_TPU_METRICS=<path> / metrics.export.step()")
    ap.add_argument("--match", default="",
                    help="only show series containing this substring")
    ap.add_argument("--json", action="store_true",
                    help="emit the summary rows as JSON instead of a table")
    args = ap.parse_args(argv)

    try:
        steps, series, summary = load_series(args.path)
    except OSError as e:
        print(f"bfmetrics-tpu: {e}", file=sys.stderr)
        return 2
    if not steps and summary is None:
        print(f"bfmetrics-tpu: {args.path} has no metric records "
              "(did the run call bluefog_tpu.metrics.step()?)",
              file=sys.stderr)
        return 1
    rows = summarize(steps, series, summary, match=args.match)
    if args.json:
        # strict JSON for machine consumers (jq chokes on bare NaN)
        clean = [{k: (None if isinstance(v, float) and math.isnan(v) else v)
                  for k, v in r.items()} for r in rows]
        print(json.dumps(clean, indent=2, allow_nan=False))
        return 0
    n_steps = len(steps)
    print(f"{args.path}: {n_steps} step record(s), {len(rows)} series"
          + (" (summary line present)" if summary is not None else ""))
    print(format_table(rows))
    return 0


if __name__ == "__main__":
    sys.exit(main())
