"""Metrics dashboard CLI: summarize a JSONL metrics file.

::

    python -m bluefog_tpu.metrics.dash /tmp/m.jsonl
    bfmetrics-tpu /tmp/m.jsonl --match bytes

Reads the per-step snapshot lines :func:`bluefog_tpu.metrics.export.step`
appends (plus the atexit summary line) and prints one row per series:

- counters (``*_total``): the cumulative total, per-step delta mean /
  p50 / p99, and — for byte counters — bytes/step;
- gauges: last value plus per-step mean / p50 / p99;
- histograms: the ``_count``/``_sum``/``_p50``/... expansion series are
  folded back into ONE ``hist`` row **per label set** (count, mean
  observation, reservoir p50/p99) — per-peer
  ``bf_tcp_ack_latency_seconds`` reads as one row per peer instead of
  six suffix rows scattered through the table.

``--since <step>`` restricts the window: counter deltas re-baseline
against the last snapshot BEFORE the window (so the first in-window
delta is honest, not the whole cumulative history), histogram counts
and sums are differenced the same way, and gauge statistics cover only
in-window points.  Reservoir quantiles remain whole-run values (the
registry keeps no per-window reservoir) — the rows mark them so.

``--follow`` is the LIVE half (the missing twin of ``--since``): the
dash re-reads the growing JSONL every ``--interval`` seconds and
re-renders, exiting cleanly when the run's atexit summary line appears
(the file is finished) or on Ctrl-C.  On a TTY each refresh repaints in
place; redirected output gets one frame per refresh (tail-able logs).

Percentiles are over the per-step series, which is what an operator
asking "what does a bad step cost" wants — the registry's own
reservoir quantiles (the ``_p50``/``_p99`` series) answer the
per-*observation* version of the question.
"""

from __future__ import annotations

import argparse
import json
import math
import sys
import time
from typing import Dict, List, Optional

from bluefog_tpu.metrics.registry import HIST_SUFFIXES, quantile

__all__ = ["main", "load_series", "summarize"]


def load_series(path: str):
    """Parse a metrics JSONL file into ``(steps, series, summary)``:
    ``steps`` the step indices, ``series`` ``{name: [value per line]}``
    (missing values forward-filled with NaN), ``summary`` the final
    summary snapshot (or None)."""
    steps: List[int] = []
    rows: List[Dict[str, float]] = []
    summary: Optional[Dict[str, float]] = None
    with open(path) as f:
        for ln, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as e:
                raise SystemExit(f"{path}:{ln}: not JSON ({e})")
            if rec.get("summary"):
                summary = rec.get("metrics", {})
                continue
            rows.append(rec.get("metrics", {}))
            steps.append(int(rec.get("step", len(steps))))
    names = sorted({n for row in rows for n in row})
    series = {n: [row.get(n, math.nan) for row in rows] for n in names}
    return steps, series, summary


def _is_counter(name: str) -> bool:
    base = name.split("{", 1)[0]
    return base.endswith("_total")


def _deltas(values: List[float], prev: float = 0.0) -> List[float]:
    out = []
    for v in values:
        if math.isnan(v):
            continue
        out.append(max(0.0, v - prev))
        prev = v
    return out


def _hist_parts(name: str):
    """``(base, labels, suffix)`` when ``name`` is one series of a
    histogram's snapshot expansion (``<base><suffix>{labels}``), else
    None.  The base+labels pair is the per-label-value grouping key."""
    bare, brace, labels = name.partition("{")
    for suf in HIST_SUFFIXES:
        if bare.endswith(suf) and len(bare) > len(suf):
            return bare[:-len(suf)], brace + labels, suf
    return None


def _last(values: List[float]) -> float:
    for v in reversed(values):
        if not math.isnan(v):
            return v
    return math.nan


def _fmt(v: Optional[float]) -> str:
    if v is None or (isinstance(v, float) and math.isnan(v)):
        return "-"
    if v and (abs(v) >= 1e6 or abs(v) < 1e-3):
        return f"{v:.3e}"
    if float(v).is_integer():
        return f"{int(v)}"
    return f"{v:.4g}"


def summarize(steps, series, summary=None, *, match: str = "",
              since: Optional[int] = None) -> List[dict]:
    """One summary record per series (the dash table's rows).

    ``since`` keeps only snapshots at step >= it; cumulative values
    (counter totals, histogram counts/sums) are re-baselined against
    the last snapshot BEFORE the window so in-window deltas are honest.
    Histogram expansion series are folded into one ``hist`` row per
    (metric, label set): count, total seconds, mean observation, and
    the reservoir p50/p99 — the per-label-value breakdown that makes
    per-peer latency histograms readable.
    """
    final = summary or {}
    # a run that never called step() still writes the atexit summary —
    # its series must appear (with zero per-step points), not vanish
    series = dict(series)
    for name in final:
        series.setdefault(name, [])
    baseline: Dict[str, float] = {}
    if since is not None:
        i0 = next((i for i, s in enumerate(steps) if s >= since),
                  len(steps))
        for name, values in series.items():
            pre = [v for v in values[:i0] if not math.isnan(v)]
            if pre:
                baseline[name] = pre[-1]
        series = {n: v[i0:] for n, v in series.items()}

    # fold histogram expansions back into per-label-set groups; only a
    # COMPLETE suffix family is a histogram (a freestanding gauge that
    # happens to end in _count must not be swallowed)
    groups: Dict[tuple, Dict[str, str]] = {}
    for name in series:
        parts = _hist_parts(name)
        if parts is not None:
            base, labels, suf = parts
            groups.setdefault((base, labels), {})[suf] = name
    hist_names = set()
    for key, sufs in list(groups.items()):
        if set(sufs) == set(HIST_SUFFIXES):
            hist_names.update(sufs.values())
        else:
            del groups[key]

    out = []
    for name, values in sorted(series.items()):
        if name in hist_names or (match and match not in name):
            continue
        clean = [v for v in values if not math.isnan(v)]
        if not clean and name not in final:
            continue
        if _is_counter(name):
            total = final.get(name, clean[-1] if clean else 0.0)
            per_step = _deltas(values, baseline.get(name, 0.0))
            s = sorted(per_step)
            row = {
                "series": name, "type": "counter", "points": len(clean),
                "total": total,
                "per_step_mean": (sum(per_step) / len(per_step)
                                  if per_step else math.nan),
                "p50": quantile(s, 0.50), "p99": quantile(s, 0.99),
            }
        else:
            s = sorted(clean)
            row = {
                "series": name, "type": "gauge", "points": len(clean),
                "total": final.get(name, clean[-1] if clean else math.nan),
                "per_step_mean": (sum(clean) / len(clean)
                                  if clean else math.nan),
                "p50": quantile(s, 0.50), "p99": quantile(s, 0.99),
            }
        out.append(row)

    for (base, labels), sufs in sorted(groups.items()):
        name = base + labels
        if match and match not in name:
            continue

        def last_of(suf: str) -> float:
            n = sufs[suf]
            v = _last(series[n])
            if math.isnan(v):
                v = final.get(n, math.nan)
            return v

        count = last_of("_count") - baseline.get(sufs["_count"], 0.0)
        total = last_of("_sum") - baseline.get(sufs["_sum"], 0.0)
        out.append({
            # observations + mean are windowed; the reservoir p50/p99
            # are whole-run (the registry keeps no per-window reservoir)
            "series": name, "type": "hist", "points": int(count)
            if not math.isnan(count) else 0,
            "total": total,
            "per_step_mean": total / count if count > 0 else math.nan,
            "p50": last_of("_p50"), "p99": last_of("_p99"),
        })
    return out


def format_table(rows: List[dict]) -> str:
    headers = ("series", "type", "points", "total/last", "per-step mean",
               "p50", "p99")
    table = [headers]
    for r in rows:
        table.append((r["series"], r["type"], str(r["points"]),
                      _fmt(r["total"]), _fmt(r["per_step_mean"]),
                      _fmt(r["p50"]), _fmt(r["p99"])))
    widths = [max(len(row[i]) for row in table) for i in range(len(headers))]
    lines = []
    for i, row in enumerate(table):
        lines.append("  ".join(c.ljust(w) for c, w in zip(row, widths)))
        if i == 0:
            lines.append("  ".join("-" * w for w in widths))
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="bfmetrics-tpu",
        description="Summarize a bluefog_tpu metrics JSONL file "
                    "(per-metric totals, per-step p50/p99).")
    ap.add_argument("path", help="JSONL file written via "
                    "BLUEFOG_TPU_METRICS=<path> / metrics.export.step()")
    ap.add_argument("--match", default="",
                    help="only show series containing this substring")
    ap.add_argument("--since", type=int, default=None, metavar="STEP",
                    help="only count snapshots from this step on "
                    "(counter/histogram deltas re-baseline against the "
                    "last earlier snapshot)")
    ap.add_argument("--json", action="store_true",
                    help="emit the summary rows as JSON instead of a table")
    ap.add_argument("--follow", action="store_true",
                    help="live tail mode: re-read and re-render every "
                    "--interval seconds until the run's summary line "
                    "lands (or Ctrl-C)")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="--follow refresh period in seconds (default 2)")
    args = ap.parse_args(argv)

    def render_once() -> int:
        try:
            steps, series, summary = load_series(args.path)
        except OSError as e:
            if args.follow:
                # the writer may simply not have created the file yet
                print(f"bfmetrics-tpu: waiting for {args.path} ({e})",
                      flush=True)
                return -1
            print(f"bfmetrics-tpu: {e}", file=sys.stderr)
            return 2
        if not steps and summary is None:
            if args.follow:
                return -1  # nothing yet; keep waiting
            print(f"bfmetrics-tpu: {args.path} has no metric records "
                  "(did the run call bluefog_tpu.metrics.step()?)",
                  file=sys.stderr)
            return 1
        rows = summarize(steps, series, summary, match=args.match,
                         since=args.since)
        if args.json:
            # strict JSON for machine consumers (jq chokes on bare NaN)
            clean = [{k: (None if isinstance(v, float) and math.isnan(v)
                          else v) for k, v in r.items()} for r in rows]
            print(json.dumps(clean, indent=2, allow_nan=False))
            return 0 if summary is not None or not args.follow else -1
        if args.follow and sys.stdout.isatty():
            sys.stdout.write("\x1b[2J\x1b[H")  # repaint in place
        n_steps = len(steps)
        print(f"{args.path}: {n_steps} step record(s), {len(rows)} series"
              + (" (summary line present)" if summary is not None
                 else ""), flush=True)
        print(format_table(rows), flush=True)
        # in follow mode the summary line is the writer's "finished"
        # marker (metrics_stop / atexit): render it one last time, stop
        return 0 if summary is not None or not args.follow else -1

    if not args.follow:
        return render_once()
    try:
        while True:
            rc = render_once()
            if rc >= 0:
                return rc
            time.sleep(max(0.05, args.interval))
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
