"""Communication instrumentation hooks.

Two kinds of hook, matching the hard constraint carried from PR 1 (no
ordered ``io_callback`` on jitted paths — XLA in this environment
CHECK-fails on the threaded effect token):

- **Jitted-path hooks** (:func:`record_collective`, :func:`count`):
  trace-time gated.  When no registry is active at trace time they are
  the identity with zero HLO footprint.  When active, the per-execution
  increments ride an *unordered* ``io_callback`` whose zero result is
  folded back into the instrumented tree — the proven ``device_stage``
  dataflow pattern — with the increment amounts passed as traced
  operands, so data-dependent costs (aperiodic gossip's active-rotation
  count, the dynamic switch's per-branch bytes) are recorded exactly.
  A ``custom_jvp`` shell keeps instrumented collectives differentiable
  (the callback fires on the primal; tangents pass through).
- **Host-path hooks** (:func:`inc` / :func:`observe` / :func:`set`):
  plain guarded registry calls for code that already runs on the host —
  the async window runtime, the TCP window server's daemon threads, the
  pipeline's trace-time bubble gauge.

Byte accounting convention: ``bytes`` is what *this rank* ships per
round (payload bytes x out-slots).  The callback fires once per local
device per execution, so the counter naturally sums to the global
gossip volume of the devices this process hosts.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Dict, Optional, Sequence, Tuple, Union

from bluefog_tpu.metrics import registry as _reg

__all__ = [
    "record_collective",
    "count",
    "inc",
    "observe",
    "set",
    "suppress_comm_metrics",
    "tree_bytes",
    "tree_leaf_count",
]

Number = Union[int, float]

_suppress = threading.local()


@contextlib.contextmanager
def suppress_comm_metrics():
    """Trace-time escape hatch: jitted hooks are the identity inside this
    block.  Control-flow wrappers compiling sub-computations into
    ``lax.switch``/``lax.cond`` branches use it to hoist the record
    OUTSIDE the branch (mirroring ``timeline.suppress_device_stage``), so
    one call site records one round — with the branch-dependent cost
    selected by a traced operand, not by duplicated callbacks."""
    prev = getattr(_suppress, "on", False)
    _suppress.on = True
    try:
        yield
    finally:
        _suppress.on = prev


def _suppressed() -> bool:
    return getattr(_suppress, "on", False)


def tree_bytes(x) -> int:
    """Static payload size of a pytree, from trace-time shape/dtype."""
    import jax

    total = 0
    for leaf in jax.tree_util.tree_leaves(x):
        size = getattr(leaf, "size", None)
        dtype = getattr(leaf, "dtype", None)
        if size is not None and dtype is not None:
            total += int(size) * int(dtype.itemsize)
    return total


def tree_leaf_count(x) -> int:
    import jax

    return len([l for l in jax.tree_util.tree_leaves(x)
                if getattr(l, "size", None) is not None])


def count(x, counters: Sequence[Tuple[str, object]],
          labels: Optional[Dict[str, object]] = None):
    """Increment ``counters`` (``(name, amount)`` pairs; amounts may be
    Python numbers or traced scalars) once per execution of the program
    position where this is traced, returning ``x`` unchanged.

    Trace-time gated: identity (zero HLO) when metrics are off or
    suppressed.  The callback keeps a reference to the registry active at
    trace time, so a compiled program keeps recording into the registry
    it was built against (and into nothing after ``metrics_stop``).
    """
    reg = _reg.current()
    if reg is None or _suppressed() or not counters:
        return x

    import jax.numpy as jnp
    import numpy as np

    from bluefog_tpu.utils.stamping import stamp

    lbls = {str(k): str(v) for k, v in (labels or {}).items()}
    # materialize the counter objects at trace time: name/kind conflicts
    # surface here (at the call site), not inside a device callback
    objs = [reg.counter(name) for name, _ in counters]
    amounts = [jnp.asarray(a, jnp.float32) for _, a in counters]

    def cb(_token, *vals):
        for obj, v in zip(objs, vals):
            obj.inc(float(v), **lbls)
        return np.float32(0.0)

    # fire-after-data, order-by-dataflow, custom_jvp differentiability:
    # the shared stamping shell (utils/stamping.py)
    return stamp(x, cb, *amounts)


def record_collective(x, *, op: str, bytes_per_round, messages_per_round,
                      schedule: str = "", backend: str = "",
                      chunks: int = 0,
                      extra: Optional[Dict[str, object]] = None):
    """Record one communication round at the program position where this
    is traced: ``bf_comm_rounds_total`` += 1, ``bf_comm_bytes_total`` +=
    ``bytes_per_round``, ``bf_comm_messages_total`` +=
    ``messages_per_round`` (amounts may be traced), labelled by
    ``op``/``schedule``/``backend``.  Returns ``x`` unchanged; identity
    when metrics are off."""
    reg = _reg.current()
    if reg is None or _suppressed():
        return x
    counters = [
        ("bf_comm_rounds_total", 1.0),
        ("bf_comm_bytes_total", bytes_per_round),
        ("bf_comm_messages_total", messages_per_round),
    ]
    if chunks:
        counters.append(("bf_comm_pallas_chunks_total", chunks))
    labels: Dict[str, object] = {"op": op}
    if schedule:
        labels["schedule"] = schedule
    if backend:
        labels["backend"] = backend
    if extra:
        labels.update(extra)
    return count(x, counters, labels)


# ---------------------------------------------------------------------------
# Host-path conveniences (no tracing involved)
# ---------------------------------------------------------------------------


def inc(name: str, amount: Number = 1.0, **labels) -> None:
    reg = _reg.current()
    if reg is not None:
        reg.counter(name).inc(amount, **labels)


def observe(name: str, value: Number, **labels) -> None:
    reg = _reg.current()
    if reg is not None:
        reg.histogram(name).observe(value, **labels)


def set(name: str, value: Number, **labels) -> None:  # noqa: A001 — mirrors Gauge.set
    reg = _reg.current()
    if reg is not None:
        reg.gauge(name).set(value, **labels)
