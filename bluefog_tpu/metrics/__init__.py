"""Metrics & health observability subsystem.

The third leg of the observability story (ROADMAP north star: production
serving needs to *see* the gossip trade-off the paper argues for):

- ``utils/timeline.py`` answers **when** (chrome-trace spans);
- ``bluefog_tpu.analysis`` answers **whether it can work at all**
  (static verification before the job launches);
- this package answers **how much** at runtime: bytes gossiped, messages
  per window op, achieved compression ratio, consensus distance
  ``||x_i - x_bar||``, measured per-step mixing contraction vs the
  spectral-gap prediction, deposit staleness, heartbeat age.

Reference analog: Horovod/Bluefog shipped a timeline; a production
deployment also needs counters an operator can scrape.  Design rules:

- **Off by default, zero cost when off.**  Every hook checks
  :func:`current` (a None test) at *trace* time; with no registry active
  the instrumented jitted programs contain zero extra HLO and host paths
  pay one attribute load (asserted in ``tests/test_metrics.py``).
- **Enable** via ``BLUEFOG_TPU_METRICS=<file.jsonl>`` (auto-start, JSONL
  per-step export, atexit summary) or programmatically with
  :func:`metrics_start`.
- **No ordered io_callbacks on jitted paths** — this environment's XLA
  CHECK-fails on the threaded effect token (the PR-1 abort class; the
  analysis lint now flags it as BF-COMM012).  Jitted instrumentation is
  either a trace-time record (static costs: pipeline bubble fraction,
  compression ratio) or an *unordered* callback whose zero result is
  folded into the dataflow (the proven ``device_stage`` pattern), with
  per-execution increments carried as traced operands.

Consume the output with ``python -m bluefog_tpu.metrics.dash m.jsonl``
(console script ``bfmetrics-tpu``) or scrape
:func:`~bluefog_tpu.metrics.export.prometheus_text`.
"""

from bluefog_tpu.metrics import comm, health
from bluefog_tpu.metrics.registry import (
    MetricsRegistry,
    current,
    metrics_active,
    metrics_start,
    metrics_stop,
)
from bluefog_tpu.metrics.export import (
    MetricsWriter,
    prometheus_text,
    snapshot,
    step,
    write_prometheus,
)

__all__ = [
    "MetricsRegistry",
    "MetricsWriter",
    "current",
    "metrics_active",
    "metrics_start",
    "metrics_stop",
    "prometheus_text",
    "snapshot",
    "step",
    "write_prometheus",
]
