"""Metric export: JSONL per-step append, Prometheus text, atexit summary.

Three consumers, three formats:

- ``bench.py`` / the dash CLI want a **per-step time series** — one JSON
  line per :func:`step` call, each a full registry snapshot (cumulative
  counters; the reader differentiates).  Append-only so a crash loses at
  most the last line, and the file is tail-able while training runs.
- An operator's scrape wants the **Prometheus text format** —
  :func:`prometheus_text` / :func:`write_prometheus` render the same
  snapshot with ``# TYPE`` headers.
- A human at the terminal wants the **atexit summary** — when the
  process exits with metrics enabled, the final snapshot is appended as
  a ``{"summary": ...}`` line and a compact table goes to the
  bluefog_tpu logger (visible even if nobody ever ran the dash).
"""

from __future__ import annotations

import atexit
import json
import os
import time
from typing import Optional

from bluefog_tpu.metrics import registry as _reg
from bluefog_tpu.utils import lockcheck as _lc

__all__ = [
    "MetricsWriter",
    "attach_writer",
    "detach_writer",
    "prometheus_text",
    "snapshot",
    "step",
    "write_prometheus",
]


_initialized_paths = set()


class MetricsWriter:
    """Append-only JSONL writer; one line per snapshot."""

    def __init__(self, path: str):
        self.path = path
        self._lock = _lc.lock("metrics.export.MetricsWriter._lock")
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        # truncate once per process per path: each run owns its file
        # (matching the timeline writer), but a stop/start cycle within
        # ONE process must append, not erase the data already recorded
        key = os.path.abspath(path)
        if key not in _initialized_paths:
            _initialized_paths.add(key)
            with open(path, "w"):
                pass

    def write(self, record: dict) -> None:
        line = json.dumps(record, allow_nan=True, sort_keys=True)
        with self._lock:
            with open(self.path, "a") as f:
                f.write(line + "\n")


_WRITER: Optional[MetricsWriter] = None
_writer_lock = _lc.lock("metrics.export._writer_lock")
_step_counter = 0
_atexit_armed = False


def attach_writer(path: str) -> MetricsWriter:
    global _WRITER, _atexit_armed
    old = None
    with _writer_lock:
        # compare normalized paths: a relative and absolute spelling of
        # the same file must not be mistaken for a writer switch (which
        # would append a premature mid-file summary)
        if (_WRITER is None
                or os.path.abspath(_WRITER.path) != os.path.abspath(path)):
            old, _WRITER = _WRITER, MetricsWriter(path)
        if not _atexit_armed:
            atexit.register(_finalize)
            _atexit_armed = True
        w = _WRITER
    if old is not None:
        # re-pointing the export must not orphan the previous file
        # without its summary line — every JSONL this subsystem writes
        # ends with the {"summary": ...} record the dash treats as the
        # authoritative totals
        _summarize(old)
    return w


def detach_writer() -> None:
    global _WRITER
    with _writer_lock:
        w, _WRITER = _WRITER, None
    if w is not None:
        _summarize(w)


def step(step: Optional[int] = None) -> Optional[dict]:
    """Record one per-step snapshot line.  Call once per training step
    (or epoch/iteration — whatever granularity the consumer wants the
    time series at).  No-op when metrics are off, so examples call it
    unconditionally.

    Drains in-flight device->host callback effects first
    (``jax.effects_barrier``) so the snapshot includes every collective
    the step actually executed — the callbacks are unordered and may
    otherwise still be in flight when the host reads the counters.
    """
    global _step_counter
    reg = _reg.current()
    if reg is None:
        return None
    _drain_effects()
    if step is None:
        step = _step_counter
    _step_counter = int(step) + 1
    record = {"step": int(step), "time": time.time(),
              "metrics": reg.snapshot()}
    with _writer_lock:
        w = _WRITER
    if w is not None:
        w.write(record)
    return record


def snapshot(*, drain: bool = True) -> Optional[dict]:
    """One-shot registry snapshot (``None`` when metrics are off) — the
    crash-dump API: ``bluefog_tpu.blackbox`` embeds it in each incident
    file so the counters at failure time survive without the writer
    machinery.  ``drain=True`` (default) waits out in-flight callback
    effects first, same as :func:`step`; the blackbox dump passes
    ``drain=False`` because a watchdog thread dumping while the main
    thread is wedged in a device collective must never block on that
    same device — a slightly stale counter beats no dump."""
    reg = _reg.current()
    if reg is None:
        return None
    if drain:
        _drain_effects()
    return reg.snapshot()


def prometheus_text(registry: Optional[_reg.MetricsRegistry] = None) -> str:
    """Render the current snapshot in the Prometheus exposition text
    format (``# HELP`` / ``# TYPE`` headers, one sample per series)."""
    reg = registry if registry is not None else _reg.current()
    if reg is None:
        return "# bluefog_tpu metrics disabled\n"
    snap = reg.snapshot()
    kinds = reg.kinds()
    helps = reg.helps()
    lines = []
    seen_headers = set()
    for series in sorted(snap):
        base = series.split("{", 1)[0]
        # histogram expansions (<name>_p50 etc.) inherit gauge typing
        family = base
        for suffix in _reg.HIST_SUFFIXES:
            if base.endswith(suffix) and base[: -len(suffix)] in kinds:
                family = base[: -len(suffix)]
                break
        if base not in seen_headers:
            seen_headers.add(base)
            if family in helps:
                lines.append(f"# HELP {base} {helps[family]}")
            kind = kinds.get(base)
            if kind is None:
                kind = "counter" if base.endswith("_total") else "gauge"
            lines.append(f"# TYPE {base} {kind}")
        val = snap[series]
        lines.append(f"{series} {val}")
    return "\n".join(lines) + "\n"


def write_prometheus(path: str,
                     registry: Optional[_reg.MetricsRegistry] = None) -> None:
    """Atomic-replace a Prometheus text snapshot at ``path`` (point a
    node_exporter textfile collector or a sidecar scraper at it)."""
    text = prometheus_text(registry)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(text)
    os.replace(tmp, path)


def _drain_effects() -> None:
    """Wait out in-flight unordered io_callback deliveries so a snapshot
    sees every increment the device work already issued.  Best-effort:
    jax may be absent (pure-host metric users) or the barrier may fail
    on a torn-down backend at exit."""
    try:
        import jax

        jax.effects_barrier()
    except Exception:
        pass


def _summarize(writer: MetricsWriter) -> None:
    reg = _reg.current()
    if reg is None:
        return
    _drain_effects()
    snap = reg.snapshot()
    writer.write({"summary": True, "time": time.time(), "metrics": snap})
    from bluefog_tpu.utils import log

    totals = {k: v for k, v in snap.items() if "_total" in k}
    if totals:
        head = ", ".join(f"{k}={v:g}" for k, v in sorted(totals.items())[:6])
        log.info("metrics summary (%d series; run "
                 "`bfmetrics-tpu %s` for the full table): %s",
                 len(snap), writer.path, head)


def _finalize() -> None:
    global _WRITER
    with _writer_lock:
        w, _WRITER = _WRITER, None
    if w is not None:
        _summarize(w)
