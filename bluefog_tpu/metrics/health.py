"""Decentralization-specific health gauges.

The paper's trade (arXiv:2111.04287) is wall-clock speed against a
bounded consensus error; these are the gauges that make both sides of
the trade observable at runtime:

- **Consensus distance** ``||x_i - x_bar||_2`` — how far ranks have
  drifted apart.  :func:`consensus_distance` is the in-SPMD (traced)
  form: a pure-dataflow scalar the host fetches *outside* jit (no
  callback involved — the jitted-path constraint).
  :func:`consensus_distance_stacked` is the host/numpy form over the
  framework's rank-stacked representation.
- **Mixing contraction** — :class:`MixingTracker` compares the measured
  per-round contraction ``d_t / d_{t-1}`` against the static
  spectral-gap prediction ``|lambda_2(W)|`` from
  :mod:`bluefog_tpu.analysis.topology_check`: a measured rate
  persistently ABOVE the prediction means gossip is not delivering the
  contraction the topology was provisioned for (skew, drops, a wedged
  transport) — the runtime symptom the static verifier cannot see.
- **Heartbeat age** — seconds since the training loop last beat the
  :class:`bluefog_tpu.utils.failure.Heartbeat`, exported as a callback
  gauge (evaluated at snapshot time) so a scrape sees staleness grow
  *during* a hang, before the watchdog fires.
"""

from __future__ import annotations

import math
import time
from typing import Optional

import numpy as np

from bluefog_tpu.metrics import registry as _reg

__all__ = [
    "consensus_distance",
    "consensus_distance_stacked",
    "record_consensus",
    "MixingTracker",
    "watch_heartbeat",
    "unwatch_heartbeat",
]


def consensus_distance(x, axis_name: str):
    """Traced per-rank consensus distance: ``||x_i - x_bar||_2`` over the
    full tree, where ``x_bar`` is the mean over ``axis_name``.

    Call inside ``shard_map`` and return it from the jitted step (or
    ``lax.pmean`` it first for the global RMS) — the host records it with
    :func:`record_consensus` after fetching, keeping the jitted program
    free of host callbacks for this gauge.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    sq = jnp.float32(0)
    for leaf in jax.tree_util.tree_leaves(x):
        if not hasattr(leaf, "dtype"):
            continue
        lf = leaf.astype(jnp.float32)
        mean = lax.pmean(lf, axis_name)
        sq = sq + jnp.sum((lf - mean) ** 2)
    return jnp.sqrt(sq)


def consensus_distance_stacked(tree) -> float:
    """Host-side max-over-ranks consensus distance of a rank-stacked tree
    (every array leaf carries a leading rank axis, the
    ``bf.rank_stack`` convention)."""
    sq: Optional[np.ndarray] = None
    for leaf in _leaves(tree):
        arr = np.asarray(leaf, dtype=np.float64)
        if arr.ndim < 1:
            continue
        n = arr.shape[0]
        flat = arr.reshape(n, -1)
        d = flat - flat.mean(axis=0, keepdims=True)
        contrib = np.sum(d * d, axis=1)
        sq = contrib if sq is None else sq + contrib
    if sq is None:
        return 0.0
    return float(np.sqrt(sq).max())


def _leaves(tree):
    import jax

    return [l for l in jax.tree_util.tree_leaves(tree)
            if hasattr(l, "dtype") or isinstance(l, np.ndarray)]


def record_consensus(value: float, **labels) -> float:
    """Record a consensus-distance sample (gauge holds the latest value;
    a histogram keeps the distribution for p50/p99).  Returns ``value``
    so it chains inside expressions; no-op when metrics are off."""
    reg = _reg.current()
    v = float(value)
    if reg is not None:
        reg.gauge(
            "bf_consensus_distance",
            "max over ranks of ||x_i - mean(x)||_2").set(v, **labels)
        reg.histogram("bf_consensus_distance_hist").observe(v, **labels)
    return v


class MixingTracker:
    """Measured vs predicted mixing contraction.

    Feed it the consensus distance once per gossip round
    (:meth:`update`); it records

    - ``bf_mixing_contraction_measured`` — ``d_t / d_{t-1}`` (gauge);
    - ``bf_mixing_contraction_predicted`` — ``|lambda_2(W)|`` from the
      schedule's mixing matrix via
      :func:`bluefog_tpu.analysis.topology_check.spectral_gap` (set
      once, at construction);
    - ``bf_mixing_excess`` — measured minus predicted: persistently
      positive means consensus is contracting slower than the topology's
      spectral gap promises.

    ``rounds_per_update``: feed cadence, in gossip rounds.  An epoch-level
    caller (e.g. ``examples/mnist_decentralized.py``, whose jitted epoch
    scans R gossip rounds) passes R and the prediction becomes
    ``|lambda_2|^R`` so measured and predicted stay on the same scale —
    comparing an epoch ratio against a per-round bound would make the
    ``bf_mixing_excess`` alarm systematically wrong.

    SGD caveat, stated plainly: during *training* the gradient step
    re-injects disagreement every round, so the measured ratio hovers at
    the gossip/gradient equilibrium rather than decaying at
    ``|lambda_2|``; the predicted line is the floor, and the gauge pair
    is still the right alarm (measured >> predicted + noise = gossip is
    broken).  Pure averaging runs (``average_consensus.py``) track the
    prediction tightly.
    """

    def __init__(self, schedule=None, *, rounds_per_update: int = 1,
                 **labels):
        self.labels = {str(k): str(v) for k, v in labels.items()}
        self.predicted: Optional[float] = None
        self._prev: Optional[float] = None
        if rounds_per_update < 1:
            raise ValueError(
                f"rounds_per_update must be >= 1, got {rounds_per_update}")
        self._rounds_per_update = int(rounds_per_update)
        if schedule is not None:
            self.rebase(schedule)

    def rebase(self, schedule, *,
               rounds_per_update: Optional[int] = None) -> Optional[float]:
        """Re-anchor the prediction to a NEW mixing schedule/matrix — the
        call every membership or control-plan boundary owes this tracker.

        The prediction is |lambda_2(W)| of the topology in effect; after
        a ``heal``/``replan``/penalized control rebuild the old matrix's
        eigenvalue is simply the wrong baseline, and the
        ``bf_mixing_excess`` alarm would compare measured contraction
        against a topology that no longer exists (a healed ring looks
        permanently broken; a densified plan looks spuriously healthy).
        ``rounds_per_update`` re-anchors the feed-cadence exponent too:
        a controller that stretches the gossip cadence halves the
        GOSSIP rounds per feed window, and a prediction still assuming
        gossip-every-step would read the stretch as a mixing failure.
        Returns the new predicted contraction (None when the schedule
        cannot be analyzed — the previous baseline is then kept)."""
        if rounds_per_update is not None:
            if rounds_per_update < 1:
                raise ValueError(
                    f"rounds_per_update must be >= 1, got "
                    f"{rounds_per_update}")
            self._rounds_per_update = int(rounds_per_update)
        per_round = self._predict(schedule)
        if per_round is not None:
            self.predicted = per_round ** self._rounds_per_update
        reg = _reg.current()
        if reg is not None and self.predicted is not None:
            reg.gauge(
                "bf_mixing_contraction_predicted",
                "|lambda_2(W)|^rounds_per_update — static "
                "spectral-gap bound at the feed cadence",
            ).set(self.predicted, **self.labels)
        return self.predicted

    def reset_measurement(self) -> None:
        """Drop the previous consensus-distance sample so the NEXT
        :meth:`update` yields no ratio — owed at every MEMBERSHIP
        boundary (join/leave/heal), where the previous distance was
        measured over a DIFFERENT member set: the cross-boundary ratio
        compares apples to oranges and reads as a mixing failure (a
        join widens disagreement) or a miracle (a corpse's outlier
        leaves).  :meth:`rebase` re-anchors the *prediction*; this
        re-anchors the *measurement* stream."""
        self._prev = None

    @staticmethod
    def _predict(schedule) -> Optional[float]:
        try:
            from bluefog_tpu.analysis.topology_check import spectral_gap

            if hasattr(schedule, "mixing_matrix"):
                matrix = schedule.mixing_matrix()
            elif hasattr(schedule, "weights"):
                # a Topology: a healed/replanned one carries inert
                # identity rows for its inactive ranks, whose eigenvalue
                # 1 would swamp |lambda_2| — the contraction the live
                # fleet actually gets is the ACTIVE submatrix's
                matrix = np.asarray(schedule.weights)
                inactive = getattr(schedule, "inactive", None)
                if inactive:
                    live = [r for r in range(matrix.shape[0])
                            if r not in inactive]
                    matrix = matrix[np.ix_(live, live)]
            else:
                matrix = schedule
            return float(1.0 - spectral_gap(matrix))
        except Exception:
            return None

    def update(self, distance: float) -> Optional[float]:
        """Record one round's consensus distance; returns the measured
        contraction ratio (None on the first sample or a zero
        predecessor)."""
        d = float(distance)
        record_consensus(d, **self.labels)
        measured: Optional[float] = None
        prev, self._prev = self._prev, d
        if prev is not None and prev > 0 and math.isfinite(prev):
            measured = d / prev
            reg = _reg.current()
            if reg is not None:
                reg.gauge(
                    "bf_mixing_contraction_measured",
                    "per-round consensus-distance ratio d_t / d_{t-1}",
                ).set(measured, **self.labels)
                if self.predicted is not None:
                    # (re-)export the baseline here too: metrics may have
                    # been enabled AFTER construction, and an excess alarm
                    # without its predicted companion reads as noise
                    reg.gauge(
                        "bf_mixing_contraction_predicted",
                        "|lambda_2(W)|^rounds_per_update — static "
                        "spectral-gap bound at the feed cadence",
                    ).set(self.predicted, **self.labels)
                    reg.gauge(
                        "bf_mixing_excess",
                        "measured minus predicted contraction",
                    ).set(measured - self.predicted, **self.labels)
        return measured


def watch_heartbeat(heartbeat, name: str = "train") -> None:
    """Export ``bf_heartbeat_age_seconds{thread=<name>}`` as a callback
    gauge reading the heartbeat's last-beat monotonic stamp at snapshot
    time.  No-op when metrics are off; safe to call again after a
    restart (same label set re-registers the callback)."""
    reg = _reg.current()
    if reg is None:
        return
    reg.gauge_fn(
        "bf_heartbeat_age_seconds",
        lambda: time.monotonic() - heartbeat._last,
        help="seconds since the training loop last beat the watchdog",
        thread=name)


def unwatch_heartbeat(name: str = "train") -> None:
    reg = _reg.current()
    if reg is not None:
        reg.remove_gauge_fn("bf_heartbeat_age_seconds", thread=name)
