"""Thread-safe labelled metric registry: counters, gauges, histograms.

Prometheus-shaped data model, deliberately minimal: a *metric* is a named
family of *series*, one per distinct label set.  Counters accumulate,
gauges hold the last value, histograms keep streaming aggregates
(count/sum/min/max) plus a bounded sample reservoir for p50/p99.

Everything is guarded by one reentrant lock — recorders include the
window server's daemon threads, async rank loops, and io_callback
runners, and metric updates are a few arithmetic ops, so one lock beats
per-series locks at every realistic rate.

The registry is OFF by default.  :func:`metrics_start` (or the
``BLUEFOG_TPU_METRICS=<path>`` env var, read lazily exactly like the
timeline's ``BLUEFOG_TPU_TIMELINE``) installs the process-global
registry that :func:`current` hands to the instrumentation hooks; hooks
treat ``current() is None`` as "do nothing", which keeps disabled-path
cost to one attribute load and makes the jitted hooks trace-time gated
(no extra HLO when off — asserted in tests).
"""

from __future__ import annotations

import collections
import math
import os
import threading

from bluefog_tpu.utils import lockcheck as _lc
import time
from typing import Callable, Dict, List, Optional, Tuple

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "current",
    "metrics_active",
    "metrics_start",
    "metrics_stop",
]

# label sets are stored as sorted (key, value) tuples so the same labels
# in any kwarg order address the same series
_LabelKey = Tuple[Tuple[str, str], ...]


def _label_key(labels: Dict[str, object]) -> _LabelKey:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


#: histogram snapshot expansions (one source of truth — export.py's
#: Prometheus family attribution imports this)
HIST_SUFFIXES = ("_count", "_sum", "_min", "_max", "_p50", "_p99")


def _escape_label(v: str) -> str:
    """Prometheus exposition-format label escaping (backslash, quote,
    newline) — an unescaped quote in a window/compressor name would make
    a scraper reject the WHOLE exposition, not just the bad series."""
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def format_series(name: str, key: _LabelKey) -> str:
    """Prometheus-style series name: ``name{k="v",...}`` (bare ``name``
    for the empty label set) — also the JSONL field name, so the dash CLI
    and a scrape see the same identifiers."""
    if not key:
        return name
    inner = ",".join(f'{k}="{_escape_label(v)}"' for k, v in key)
    return f"{name}{{{inner}}}"


class _Metric:
    kind = "untyped"

    def __init__(self, registry: "MetricsRegistry", name: str, help: str):
        self._registry = registry
        self.name = name
        self.help = help


class Counter(_Metric):
    """Monotonically accumulating value (bytes shipped, messages sent)."""

    kind = "counter"

    def inc(self, amount: float = 1.0, **labels) -> None:
        if amount < 0:
            raise ValueError(f"counter {self.name} cannot decrease "
                             f"(inc {amount})")
        reg = self._registry
        with reg._lock:
            series = reg._values.setdefault(self.name, {})
            key = _label_key(labels)
            series[key] = series.get(key, 0.0) + float(amount)


class Gauge(_Metric):
    """Last-value metric (consensus distance, mixing rate, bubble
    fraction)."""

    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        reg = self._registry
        with reg._lock:
            reg._values.setdefault(self.name, {})[_label_key(labels)] = \
                float(value)


class _HistState:
    __slots__ = ("count", "total", "min", "max", "samples")

    def __init__(self, reservoir: int):
        self.count = 0
        self.total = 0.0
        self.min = math.inf
        self.max = -math.inf
        # deque(maxlen): O(1) sliding window — hot host paths observe per
        # consume while holding the registry lock, so a list.pop(0)
        # memmove per observation would be contended O(RESERVOIR) cost
        self.samples = collections.deque(maxlen=reservoir)


class Histogram(_Metric):
    """Streaming distribution: exact count/sum/min/max plus a bounded
    reservoir (last ``RESERVOIR`` observations) for p50/p99 — per-step
    JSONL lines carry the aggregates, so the dash CLI can reconstruct
    per-step behavior without the registry holding unbounded state."""

    kind = "histogram"
    RESERVOIR = 2048

    def observe(self, value: float, **labels) -> None:
        v = float(value)
        reg = self._registry
        with reg._lock:
            series = reg._values.setdefault(self.name, {})
            key = _label_key(labels)
            st = series.get(key)
            if st is None:
                st = series[key] = _HistState(self.RESERVOIR)
            st.count += 1
            st.total += v
            st.min = min(st.min, v)
            st.max = max(st.max, v)
            # sliding window, not classic reservoir sampling: recent
            # behavior is what an operator's p99 question is about
            st.samples.append(v)


def quantile(sorted_samples: List[float], q: float) -> float:
    """Nearest-rank quantile over an already-sorted sample list."""
    if not sorted_samples:
        return math.nan
    idx = min(len(sorted_samples) - 1,
              max(0, math.ceil(q * len(sorted_samples)) - 1))
    return sorted_samples[idx]


def median(vals) -> float:
    """Plain interpolating median (NaN on empty) — the ONE shared
    implementation the control and fleet planes aggregate with."""
    s = sorted(vals)
    n = len(s)
    if n == 0:
        return math.nan
    if n % 2:
        return s[n // 2]
    return 0.5 * (s[n // 2 - 1] + s[n // 2])


class MetricsRegistry:
    """Holds every metric family and its series; snapshot-able.

    ``gauge_fn`` registers a *callback gauge*: a zero-arg callable
    evaluated at snapshot time (e.g. heartbeat age — the value is a
    property of "now", not of any recording event).
    """

    def __init__(self):
        self._lock = _lc.rlock("metrics.registry.MetricsRegistry._lock")
        self._metrics: Dict[str, _Metric] = {}
        # name -> {label_key: float | _HistState}
        self._values: Dict[str, Dict[_LabelKey, object]] = {}
        # (name, label_key) -> callable
        self._gauge_fns: Dict[Tuple[str, _LabelKey], Callable[[], float]] = {}
        self.created_at = time.time()

    # ----------------------------------------------------------- factories
    def _get(self, cls, name: str, help: str) -> _Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(self, name, help)
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as {m.kind}, "
                    f"requested {cls.kind}")
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get(Counter, name, help)  # type: ignore[return-value]

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get(Gauge, name, help)  # type: ignore[return-value]

    def histogram(self, name: str, help: str = "") -> Histogram:
        return self._get(Histogram, name, help)  # type: ignore[return-value]

    def gauge_fn(self, name: str, fn: Callable[[], float], *,
                 help: str = "", **labels) -> None:
        """Register a callback gauge evaluated at snapshot time."""
        self._get(Gauge, name, help)
        with self._lock:
            self._gauge_fns[(name, _label_key(labels))] = fn

    def remove_gauge_fn(self, name: str, **labels) -> None:
        with self._lock:
            key = _label_key(labels)
            self._gauge_fns.pop((name, key), None)
            # drop the last sampled value too: a retired callback gauge
            # frozen at its final reading would keep exporting it — a
            # heartbeat age that stops growing reads as HEALTHY, the
            # exact inversion the gauge exists to prevent
            series = self._values.get(name)
            if series is not None:
                series.pop(key, None)
                if not series:
                    self._values.pop(name, None)

    # ------------------------------------------------------------ snapshot
    def snapshot(self) -> Dict[str, float]:
        """Flat ``{series_name: value}`` view of every metric right now.

        Histograms expand into ``<name>_count`` / ``_sum`` / ``_min`` /
        ``_max`` / ``_p50`` / ``_p99`` series (reservoir quantiles).
        Callback gauges are evaluated here; a raising callback yields NaN
        rather than poisoning the whole snapshot.
        """
        out: Dict[str, float] = {}
        with self._lock:
            for (name, key), fn in list(self._gauge_fns.items()):
                try:
                    self._values.setdefault(name, {})[key] = float(fn())
                except Exception:
                    self._values.setdefault(name, {})[key] = math.nan
            for name, series in self._values.items():
                kind = self._metrics[name].kind if name in self._metrics \
                    else "untyped"
                for key, val in series.items():
                    if kind == "histogram":
                        st = val  # _HistState
                        samples = sorted(st.samples)
                        expand = {  # keys must mirror HIST_SUFFIXES
                            "_count": st.count, "_sum": st.total,
                            "_min": st.min, "_max": st.max,
                            "_p50": quantile(samples, 0.50),
                            "_p99": quantile(samples, 0.99),
                        }
                        for suffix in HIST_SUFFIXES:
                            out[format_series(name + suffix, key)] = \
                                expand[suffix]
                    else:
                        out[format_series(name, key)] = float(val)
        return out

    def counter_totals(self) -> Dict[str, float]:
        """Per-FAMILY totals of every counter (label sets summed away):
        the cheap aggregate the fleet telemetry publisher carries each
        round.  Unlike :meth:`snapshot` there is no series-name
        formatting, no histogram expansion, and no callback-gauge
        evaluation — one lock hold, one float sum per family."""
        out: Dict[str, float] = {}
        with self._lock:
            for name, series in self._values.items():
                m = self._metrics.get(name)
                if m is None or m.kind != "counter":
                    continue
                out[name] = float(sum(series.values()))
        return out

    def kinds(self) -> Dict[str, str]:
        """``{metric_name: kind}`` for export formatting."""
        with self._lock:
            return {n: m.kind for n, m in self._metrics.items()}

    def helps(self) -> Dict[str, str]:
        with self._lock:
            return {n: m.help for n, m in self._metrics.items() if m.help}


_REGISTRY: Optional[MetricsRegistry] = None
_state_lock = _lc.lock("metrics.registry._state_lock")
# set by metrics_stop(): an explicit stop must stick even when
# BLUEFOG_TPU_METRICS is set, or the next instrumented call would lazily
# resurrect the subsystem and re-attach the writer
_STOPPED = False


def metrics_start(path: Optional[str] = None) -> MetricsRegistry:
    """Install (or return) the process-global registry.

    ``path`` (or ``BLUEFOG_TPU_METRICS``) additionally attaches a JSONL
    writer — each :func:`bluefog_tpu.metrics.export.step` call appends
    one snapshot line, and an atexit hook writes the final summary.
    Idempotent: a second call returns the live registry.
    """
    global _REGISTRY, _STOPPED
    with _state_lock:
        _STOPPED = False
        if _REGISTRY is None:
            _REGISTRY = MetricsRegistry()
        reg = _REGISTRY
    path = path or os.environ.get("BLUEFOG_TPU_METRICS")
    if path:
        from bluefog_tpu.metrics import export

        export.attach_writer(path)
    return reg


def metrics_stop() -> None:
    """Tear down: flush/close the writer and drop the registry, so
    already-compiled instrumented programs (whose callbacks hold a
    reference) keep running but record into a detached registry.  Sticky
    even under ``BLUEFOG_TPU_METRICS``: later instrumented calls do NOT
    lazily restart (which would re-attach the writer and truncate the
    just-finalized JSONL) — only an explicit :func:`metrics_start` does."""
    global _REGISTRY, _STOPPED
    from bluefog_tpu.metrics import export

    export.detach_writer()
    with _state_lock:
        _REGISTRY = None
        _STOPPED = True


def current() -> Optional[MetricsRegistry]:
    """The active registry, or None when metrics are off.  Lazily honors
    ``BLUEFOG_TPU_METRICS`` exactly like the timeline env var: the first
    hook that runs after the env var is set activates the subsystem
    (unless :func:`metrics_stop` explicitly turned it off)."""
    global _REGISTRY
    if (_REGISTRY is None and not _STOPPED
            and os.environ.get("BLUEFOG_TPU_METRICS")):
        metrics_start()
    return _REGISTRY


def metrics_active() -> bool:
    return current() is not None
