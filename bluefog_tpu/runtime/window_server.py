"""Cross-HOST one-sided window transport: pipelined TCP deposits (wire v2).

The passive-target window story by deployment scope (upstream
``bluefog/common/mpi_controller.cc`` Win* — ``MPI_Put`` lands anywhere in
the job; SURVEY.md §3.4):

- same process / rank threads — the in-process native table
  (``csrc/windows.cc``, anonymous mapping);
- same host, separate OS processes — the named-shm backing
  (``AsyncWindow(shm=True)``);
- **separate hosts (DCN)** — THIS module: every process can run one
  :class:`WindowServer` exposing its windows on a TCP port; peers hold a
  :class:`RemoteWindow` (synchronous, one round-trip per op) or a
  :class:`PipelinedRemoteWindow` (a per-peer background sender — the
  userspace analog of the reference's MPI progress thread) and deposit
  with no receiver involvement beyond the server's daemon thread.

Wire protocol **v2** (little-endian).  Every frame starts
``magic u32 | op u8 | name_len u16``; per-op bodies follow:

  0 DEPOSIT      name | slot i32, flags u8, dtype u8, n_elems i64 | payload
                 flags bit0 = accumulate, bit1 = deferred-ack (no status
                 reply; errors latch per connection until FLUSH), bit2 =
                 drain (a graceful leaver's final mass handoff — still an
                 accumulate on the table; the owner records it so the
                 membership audit can prove the handoff landed).
                 reply (unless deferred): status i64.
  1 GET_SELF     as v1: reply status i64 | dtype u8, n_elems i64 | payload
  2 READ_SLOT    as v1 (flags bit0 = consume; status carries fresh-count)
  3 HELLO        name_len == 0 | version u16, features u32.
                 reply status i64 = negotiated feature mask (>= 0) or
                 a negative error (wrong version).
  4 DEPOSIT_BATCH  name_len == 0 | seq u32, count u32, then ``count``
                 items, each ``name_len u16, slot i32, flags u8,
                 dtype u8, codec u8, n_elems i64, wire_bytes i64, name,
                 payload[wire_bytes]`` — ONE framed message for every
                 slot/leaf bound for this peer in a round, ONE ack:
                 ``seq u32 | status i64`` (items applied, or the first
                 error; per-item ``wire_bytes`` keeps the stream
                 parseable past a bad item, so one rejected deposit
                 cannot desync its neighbors).
  5 FLUSH        name_len == 0, no body.  reply status i64 = deposits
                 applied on this connection since the last FLUSH, or the
                 first latched deferred error (then cleared).
  6 STREAM_ATTACH  name_len == 0 | stream_id u64, epoch u32.  Binds this
                 connection to a client :class:`DepositStream` lineage:
                 the server quiesces any older-epoch connection of the
                 same stream (drains its applier, so nothing of the old
                 generation can land afterwards), then replies
                 status i64 = the highest batch seq ALREADY APPLIED for
                 this stream — the client drops those from its replay
                 window and re-sends only the rest, which is what makes
                 reconnect replay idempotent: a batch that was applied
                 but un-acked when the connection died is acknowledged
                 by the attach reply instead of being applied twice.
                 An attach whose epoch is not strictly newer gets
                 ``-105`` (a zombie connection can never steal a live
                 stream).  Requires the RESUME feature bit.
  7 HEARTBEAT    name_len == 0 | seq u32.  Lightweight peer liveness
                 probe; reply is an ACK frame ``(seq | 0x80000000, 0)``
                 so heartbeat replies share the deposit stream's ack
                 channel without ambiguity.  Requires the HEARTBEAT
                 feature bit.
  8 SNAPSHOT     name = snapshot GROUP | want_round i64, count u16, then
                 ``count`` leaf names (``name_len u16, name``); count 0
                 requests every leaf.  Serves the process-global
                 round-stamped snapshot table
                 (:mod:`bluefog_tpu.serving.snapshots`).  reply
                 status i64 = the round served (>= 0), then ``count u16``
                 and per leaf ``name_len u16, dtype u8, n_elems i64,
                 name, payload`` — or a negative error: ``-107`` round
                 rolled (RETRIABLE: the pinned ``want_round`` is no
                 longer current — re-pin and retry) / ``-108`` no
                 snapshot published yet.  Every leaf in one reply is
                 from ONE round: the server copies them under the
                 table's swap lock, so a reader can never observe a
                 torn mix of rounds.
  9 SUBSCRIBE    name = snapshot GROUP | sub_id u64, epoch u32,
                 every u32, cursor i64.  Binds this connection as the
                 live push channel of subscriber lineage ``sub_id`` —
                 the STREAM_ATTACH epoch pattern on the read path: a
                 strictly-newer epoch quiesces the superseded
                 connection's sender, a stale one gets ``-105``.  reply
                 status i64 = 0 (accepted), after which the connection
                 is SERVER-PUSH: frames ``round i64, skipped u32,
                 count u16`` + leaves (encoded as in SNAPSHOT replies);
                 ``round = -1`` frames are idle keepalives.  The
                 per-subscription sender pushes the LATEST published
                 round whenever it is >= last_delivered + every —
                 slow-reader policy is SKIP-TO-LATEST (training is
                 never throttled by a reader; ``skipped`` counts the
                 due rounds the reader missed) — and resumes strictly
                 after ``cursor`` on reconnect, so a resumed subscriber
                 misses or duplicates nothing it was promised (the
                 client-held cursor is the delivery truth, exactly as
                 the applied high-water mark is for deposits).

Version negotiation is LOUD, never silent: a v2 server answers a v1-magic
frame with one ``status = -101`` reply and drops the connection (the v1
client surfaces it as a clear ``RuntimeError``), and rejects any HELLO
whose version is not 2 the same way.  A v2 client talking to an old
server gets its connection dropped at the first frame (the v1 server's
magic check) and reports the likely version skew.

Zero-copy discipline: clients send scatter-gather ``sendmsg`` from
memoryviews (no ``tobytes()``, no frame-assembly join); the server
receives payloads with ``recv_into`` into per-connection reusable numpy
buffers and deposits straight from them into the window table (no
intermediate ``bytes``), and reads are served from a reusable reply
buffer.  Optional wire compression (f32 downcast / top-k; negotiated via
the HELLO feature mask, selected per item) lives in
:mod:`bluefog_tpu.runtime.wire_codec`.

The server writes into the native window table when the native runtime is
available, and into the in-process pure-Python fallback table otherwise —
the same dispatch :class:`~bluefog_tpu.runtime.async_windows.AsyncWindow`
uses, so the TCP path (and its tests/bench) works on hosts without a C++
toolchain.

Trust model, stated plainly: the protocol is UNAUTHENTICATED (a magic
word rejects accidental cross-talk, nothing more) — the same posture as
the MPI/NCCL transports it replaces, which also trust the cluster
network.  Bind to a cluster-internal interface (``start(host=...)``);
never expose the port beyond the training fabric.  Malformed requests
cannot corrupt or OOM the owner (geometry is validated against the
window's actual shape, and claimed lengths are bounded before any
allocation), but a network-level writer CAN deposit garbage values, as
it can with MPI.
"""

from __future__ import annotations

import collections
import ctypes
import itertools
import os
import socket
import socketserver
import struct
import threading
import time
import zlib
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from bluefog_tpu import chaos as _chaos
from bluefog_tpu.blackbox import recorder as _bb
from bluefog_tpu.metrics import comm as _mt
from bluefog_tpu.runtime import (delta as _delta, native, resilience,
                                 wire_codec, wire_status)
from bluefog_tpu.runtime.async_windows import _DTYPES as _DTYPE_IDS, _fallback
from bluefog_tpu.serving import snapshots as _snap
from bluefog_tpu.tracing import recorder as _tr
from bluefog_tpu.utils import lockcheck as _lc

__all__ = ["WindowServer", "RemoteWindow", "PipelinedRemoteWindow",
           "DepositStream", "PROTOCOL_VERSION"]

_MAGIC = 0xBF_51_0E_02      # wire v2
_MAGIC_V1 = 0xBF_51_0E_01   # recognized only to reject it loudly
PROTOCOL_VERSION = wire_status.PROTOCOL_VERSION

_HDR = struct.Struct("<IBH")          # magic, op, name_len
_BODY = struct.Struct("<iBBq")        # slot, flags, dtype, n_elems
_STATUS = struct.Struct("<q")
_SELF_HDR = struct.Struct("<Bq")      # dtype, n_elems
_HELLO = struct.Struct("<HI")         # version, features
_BATCH_HDR = struct.Struct("<II")     # seq, count
_ITEM = struct.Struct("<HiBBBqq")     # name_len, slot, flags, dtype,
                                      # codec, n_elems, wire_bytes
_ACK = struct.Struct("<Iq")           # seq, status
_ATTACH = struct.Struct("<QI")        # stream_id, epoch
_HB = struct.Struct("<I")             # heartbeat seq
_HB_MARK = 0x8000_0000                # ack-frame seq bit: heartbeat reply
_SNAP_REQ = struct.Struct("<qH")      # want_round, requested-leaf count
_LEAF_NAME = struct.Struct("<H")      # one requested leaf name length
_SNAP_CNT = struct.Struct("<H")       # leaves in a snapshot reply
_SNAP_LEAF = struct.Struct("<HBq")    # name_len, dtype, n_elems
_SUB_REQ = struct.Struct("<QIIq")     # sub_id, epoch, every, cursor
_PUSH = struct.Struct("<qIH")         # round (-1 = keepalive), skipped,
                                      # leaf count
_DELTA_HDR = struct.Struct("<Bq")     # frame kind (0 full / 10 delta),
                                      # base_round — after _PUSH (and the
                                      # trace header) on EVERY push frame
                                      # of a FEATURE_DELTA connection,
                                      # keepalives included, so the frame
                                      # parse stays deterministic
_DELTA_LEAF = struct.Struct("<HBBqq")  # name_len, dtype, codec, n_elems,
                                       # wire_bytes — one delta leaf entry
_TRACE_HDR = struct.Struct("<QQI")    # trace_id, span_id, round — the
                                      # wire-propagated causal context
                                      # (FEATURE_TRACE connections only)
_ACK_TIMES = struct.Struct("<II")     # queue_us, apply_us appended to
                                      # batch acks on FEATURE_TRACE
                                      # connections (never to heartbeat
                                      # acks, which keep the bit31 mark)

_OP_DEPOSIT = 0
_OP_GET_SELF = 1
_OP_READ_SLOT = 2
_OP_HELLO = 3
_OP_DEPOSIT_BATCH = 4
_OP_FLUSH = 5
_OP_STREAM_ATTACH = 6
_OP_HEARTBEAT = 7
_OP_SNAPSHOT = 8
_OP_SUBSCRIBE = 9
#: not a request op: the frame-KIND marker of a delta push frame on the
#: SUBSCRIBE push channel (FEATURE_DELTA connections; kind 0 = full)
_OP_DELTA = 10

#: client->server ops whose frames carry the trace header on
#: FEATURE_TRACE connections (SUBSCRIBE propagates the other way: the
#: server's push frames carry it instead)
_TRACED_OPS = frozenset((_OP_DEPOSIT_BATCH, _OP_FLUSH, _OP_HEARTBEAT,
                         _OP_SNAPSHOT))

# subscription push cadence when nothing is being published: an idle
# server must look different from a wedged one to the reader's idle
# timeout (keepalive round = -1)
_SUB_KEEPALIVE_S = 1.0
# bounds a SNAPSHOT request can claim before any allocation happens
_MAX_SNAP_LEAVES = 4096
_MAX_LEAF_NAME = 4096
# largest dense payload a single snapshot/push leaf header may claim
# before the READER allocates (the deposit path's BF-WIRE004
# discipline, applied to the reply direction): real leaves are
# per-window shards far below this; a lying header must never choose
# the reader's allocation size
_MAX_LEAF_BYTES = 1 << 31

_FLAG_ACCUMULATE = 1
_FLAG_DEFERRED_ACK = 2
# bit2: this deposit is a LEAVER'S FINAL MASS HANDOFF (graceful drain).
# Semantically still an accumulate — the flag exists so the owner's
# forensics can tell a drain apart from ordinary gossip: the leaver's
# push-sum mass must be CONSERVED in the audit (unlike a corpse's, which
# is written off), and the flagged deposit is the wire evidence the
# handoff happened.  Recorded as a `drain_deposit` blackbox event and
# the `bf_drain_deposits_total` counter on the receiving host.
_FLAG_DRAIN = 4

# HELLO feature bits (server replies with the granted intersection)
FEATURE_BATCH = 1
FEATURE_CODEC_F32 = 2
FEATURE_CODEC_TOPK = 4
FEATURE_HEARTBEAT = 8
FEATURE_RESUME = 16   # STREAM_ATTACH + idempotent reconnect replay
FEATURE_SNAPSHOT = 32   # round-stamped consistent snapshot reads (op 8)
FEATURE_SUBSCRIBE = 64  # resumable push subscriptions (op 9)
#: wire-propagated trace context: client->server frames of the ops in
#: ``_TRACED_OPS`` carry a ``(trace_id u64, span_id u64, round u32)``
#: header right after the frame header, batch acks grow a
#: ``(queue_us, apply_us)`` tail, and SUBSCRIBE push frames carry the
#: header after ``_PUSH`` — all ONLY on connections whose HELLO
#: negotiated this bit, so presence is deterministic per connection and
#: a v-old peer (or a tracing-disabled client) degrades silently.
FEATURE_TRACE = 128
#: delta push frames on the SUBSCRIBE channel (wire op 10): every push
#: frame of a granting connection carries a ``(kind u8, base_round i64)``
#: header after ``_PUSH`` (and the trace header) — kind 0 = full-frame
#: anchor (leaves dense, the resync point), kind 10 = round-over-round
#: delta encoded per leaf with the wire_codec twins + sender-side error
#: feedback.  Optional want, like FEATURE_TRACE: a v-old server degrades
#: to dense pushes silently.
FEATURE_DELTA = 256
_SERVER_FEATURES = (FEATURE_BATCH | FEATURE_CODEC_F32 | FEATURE_CODEC_TOPK
                    | FEATURE_HEARTBEAT | FEATURE_RESUME
                    | FEATURE_SNAPSHOT | FEATURE_SUBSCRIBE
                    | FEATURE_TRACE | FEATURE_DELTA)

_CODEC_FEATURE = {wire_codec.CODEC_NONE: 0,
                  wire_codec.CODEC_F32: FEATURE_CODEC_F32,
                  wire_codec.CODEC_TOPK: FEATURE_CODEC_TOPK}

# the ONE dtype-id table (async_windows owns np.dtype -> id; invert here)
_DTYPES = {v: k for k, v in _DTYPE_IDS.items()}

# error statuses: ONE registry (runtime/wire_status.py) shared with the
# serving clients and checked against docs/transport.md by BF-DOC001 —
# the local _ERR_* names are aliases kept for this module's long-standing
# internal (and test-visible) spelling
_ERR_GEOMETRY = wire_status.ERR_GEOMETRY
_ERR_NO_WINDOW = wire_status.ERR_NO_WINDOW
_ERR_BAD_OP = wire_status.ERR_BAD_OP
_ERR_VERSION = wire_status.ERR_VERSION
_ERR_CODEC = wire_status.ERR_CODEC
_ERR_TOO_LARGE = wire_status.ERR_TOO_LARGE
_ERR_STALE_EPOCH = wire_status.ERR_STALE_EPOCH
_ERR_BUSY = wire_status.ERR_BUSY
_ERR_ROUND_ROLLED = wire_status.ERR_ROUND_ROLLED
_ERR_NO_SNAPSHOT = wire_status.ERR_NO_SNAPSHOT

_err_text = wire_status.err_text


def _routable_host() -> str:
    """Best-effort routable address of this host for wildcard binds.
    ``gethostbyname(gethostname())`` alone is a trap: stock Debian/Ubuntu
    /etc/hosts maps the hostname to 127.0.1.1, which would advertise a
    loopback to remote peers.  The outbound-UDP trick (connect() sends no
    packet; the kernel just picks the egress interface) gets the real
    address; loopback-resolving fallbacks are rejected in favor of the
    next method."""
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.connect(("10.255.255.255", 1))  # no packet is sent
            addr = s.getsockname()[0]
        if not addr.startswith("127."):
            return addr
    except OSError:
        pass
    try:
        addr = socket.gethostbyname(socket.gethostname())
        if not addr.startswith("127."):
            return addr
    except OSError:
        pass
    return "127.0.0.1"  # single-host fallback (tests, laptops)


def _recv_into(sock: socket.socket, view: memoryview) -> None:
    """Fill ``view`` exactly from the socket (no intermediate bytes)."""
    got, n = 0, len(view)
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if r == 0:
            raise ConnectionError("peer closed mid-message")
        got += r


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    """Small fixed-size header reads only — payloads go via _recv_into."""
    buf = bytearray(n)
    _recv_into(sock, memoryview(buf))
    return bytes(buf)


_IOV_CHUNK = 512  # comfortably under any platform's IOV_MAX


def _sendmsg_all(sock: socket.socket, views: List) -> int:
    """Scatter-gather send of the whole frame, handling partial sends and
    the kernel's iovec limit.  ``views`` are bytes / byte-cast
    memoryviews; nothing is ever joined into one buffer."""
    views = collections.deque(
        mv for mv in (v if isinstance(v, memoryview) else memoryview(v)
                      for v in views) if len(mv))
    total = sum(len(v) for v in views)
    if not hasattr(sock, "sendmsg"):  # exotic platforms: still no join
        for v in views:
            sock.sendall(v)
        return total
    sent_total = 0
    while views:
        batch = list(itertools.islice(views, _IOV_CHUNK))  # peek a prefix
        sent = sock.sendmsg(batch)
        sent_total += sent
        while sent:  # advance the deque by exactly the bytes accepted
            head = views[0]
            if sent >= len(head):
                sent -= len(head)
                views.popleft()
            else:
                views[0] = head[sent:]
                sent = 0
    return sent_total


# ---------------------------------------------------------------------------
# Window-table dispatch: native runtime when present, pure-Python otherwise
# ---------------------------------------------------------------------------


class _NativeOps:
    """Server-side window ops over the native table (csrc/windows.cc)."""

    def __init__(self, lib):
        self._lib = lib

    def info(self, name_b: bytes) -> Optional[Tuple[int, int, int]]:
        ns = ctypes.c_int()
        ne = ctypes.c_longlong()
        dt = ctypes.c_int()
        if self._lib.bf_win_info(name_b, ctypes.byref(ns), ctypes.byref(ne),
                                 ctypes.byref(dt)) != 0:
            return None
        return ns.value, int(ne.value), dt.value

    def deposit(self, name_b, slot, arr, accumulate) -> int:
        return self._lib.bf_win_deposit(name_b, slot, arr.ctypes.data,
                                        arr.size, 1 if accumulate else 0)

    def read(self, name_b, slot, out, consume) -> int:
        return self._lib.bf_win_read(name_b, slot, out.ctypes.data,
                                     out.size, 1 if consume else 0)

    def read_self(self, name_b, out) -> int:
        return self._lib.bf_win_read_self(name_b, out.ctypes.data, out.size)


class _PyOps:
    """Same ops over the in-process pure-Python fallback table — keeps the
    DCN transport (and its tests/bench) alive on hosts without a C++
    toolchain, with identical status conventions."""

    def __init__(self):
        self._table = _fallback()

    def info(self, name_b: bytes) -> Optional[Tuple[int, int, int]]:
        got = self._table.info(name_b.decode())
        if got is None:
            return None
        n_slots, n_elems, dtype = got
        return n_slots, n_elems, _DTYPE_IDS[np.dtype(dtype)]

    def deposit(self, name_b, slot, arr, accumulate) -> int:
        return self._table.deposit(name_b.decode(), slot, arr, accumulate)

    def read(self, name_b, slot, out, consume) -> int:
        buf, fresh = self._table.read(name_b.decode(), slot, consume)
        if buf is None:
            return -1
        out[:] = buf
        return fresh

    def read_self(self, name_b, out) -> int:
        buf = self._table.read_self(name_b.decode())
        if buf is None:
            return -1
        out[:] = buf
        return 0


def _table_ops():
    lib = native.load()
    return _NativeOps(lib) if lib is not None else _PyOps()


# ---------------------------------------------------------------------------
# Server
# ---------------------------------------------------------------------------


class _ApplyWorker:
    """Per-connection background applier for BATCHED deposits: the handler
    thread does nothing but ``recv_into`` free buffers and hand them over;
    this thread decodes + lands them in the window table and sends the
    batch ack when its last item applied.  Both halves release the GIL
    (socket reads, numpy copies/adds), so receive of item N+1 genuinely
    overlaps apply of item N — the server side of the progress-engine
    story, and where the pipelined transport's throughput edge over the
    sync wire comes from on the receiving host.

    Hand-off granularity is ONE WIRE BATCH, not one item: the handler
    accumulates a batch's jobs locally and posts them as a single list,
    so the two threads pay one queue wake-up per frame instead of one per
    leaf (per-item ping-pong costs hundreds of microseconds of scheduler
    latency — more than a small leaf's entire payload).  The bounded
    batch queue (2 frames) is the memory/backpressure bound: the recv
    loop blocks when the applier falls two frames behind.  The ack for
    seq S is sent ONLY after every item of S hit the table — that
    ordering is what makes the client's ``flush()`` a real fence."""

    _MAX_FREE = 256  # pooled payload buffers kept per connection

    def __init__(self, handler, sock, ops, write_lock, peer):
        self._handler = handler
        self._sock = sock
        self._ops = ops
        self._wlock = write_lock
        self._peer = peer
        import queue as _q

        self._jobs: "_q.Queue" = _q.Queue(maxsize=2)
        self._closed = False
        self._free: Dict[int, List[np.ndarray]] = {}
        self._free_mu = _lc.lock("runtime.window_server._ApplyWorker._free_mu")
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name=f"bf-win-apply:{peer}")
        self._thread.start()

    def take_buffer(self, nbytes: int) -> np.ndarray:
        with self._free_mu:
            free = self._free.get(nbytes)
            if free:
                return free.pop()
        return np.empty(max(nbytes, 1), np.uint8)

    def _give_buffer(self, buf: np.ndarray) -> None:
        with self._free_mu:
            free = self._free.setdefault(buf.nbytes, [])
            if len(free) < self._MAX_FREE:
                free.append(buf)

    def submit_batch(self, seq: int, jobs: List, tctx=None) -> None:
        """One wire batch's jobs (('item', …) / ('err', code) entries, in
        arrival order); blocks when the applier is two frames behind.
        ``tctx`` is the frame's wire-propagated trace context
        ``(trace_id, span_id, round)`` or None — the owner-side
        queue-wait/apply/ack spans parent to it."""
        self._jobs.put((seq, jobs, tctx, time.time(),
                        time.perf_counter()))

    def close(self) -> bool:
        """Stop the worker after it drains every queued batch; returns
        True iff the thread is provably finished (callers deciding
        whether an applied high-water mark is FINAL rely on this)."""
        import queue as _q

        self._closed = True  # the loop polls this, so no sentinel race
        try:
            # best effort wake-up; never block the handler's finish()
            self._jobs.put_nowait(None)
        except _q.Full:
            pass
        self._thread.join(timeout=5)
        return not self._thread.is_alive()

    def _loop(self) -> None:
        import queue as _q

        h = self._handler
        while True:
            try:
                batch = self._jobs.get(timeout=1.0)
            except _q.Empty:
                if self._closed:
                    return  # drained and told to stop — no thread leak
                continue
            if batch is None:
                return
            seq, jobs, tctx, t_sub_w, t_sub_p = batch
            t_deq_p = time.perf_counter()
            queue_s = t_deq_p - t_sub_p
            applied = 0
            first_err = 0
            for job in jobs:
                if job[0] == "err":
                    if not first_err:
                        first_err = job[1]
                    continue
                (_, name_b, slot, flags, dtype_id, codec, n_elems, buf,
                 nbytes) = job
                try:
                    rc = h._apply_deposit(self._ops, name_b, slot, flags,
                                          dtype_id, codec, n_elems,
                                          memoryview(buf)[:nbytes])
                except Exception:
                    # NOTHING a payload contains may kill the applier: a
                    # dead applier acks no one and wedges the connection,
                    # which is strictly worse than a rejected item
                    rc = _ERR_BAD_OP
                self._give_buffer(buf)
                if rc < 0:
                    if not first_err:
                        first_err = rc
                else:
                    applied += 1
            apply_s = time.perf_counter() - t_deq_p
            trec = _tr.get() if tctx is not None else None
            if trec is not None:
                tid_, psid, rnd_ = tctx
                trec.emit("queue_wait", "tcp_srv", t0=t_sub_w,
                          dur=queue_s, parent=psid, round_=rnd_,
                          trace_id=tid_, peer=self._peer, seq=seq)
                trec.emit("apply", "tcp_srv", t0=t_sub_w + queue_s,
                          dur=apply_s, parent=psid, round_=rnd_,
                          trace_id=tid_, peer=self._peer, seq=seq,
                          items=applied)
            _mt.inc("bf_tcp_batches_total", 1.0, peer=self._peer)
            _bb.record("tcp_batch_deposit", seq=seq, applied=applied,
                       err=first_err, peer=self._peer)
            # the stream's applied high-water mark moves BEFORE the ack
            # leaves: a reconnecting client must never learn (via
            # STREAM_ATTACH) that an already-applied batch is still
            # outstanding, or it would replay it into a double-apply.
            # The first ERROR is latched alongside — if this ack dies
            # with the connection, the reconnect attach reports the
            # error instead of silently retiring the batch as success.
            h._note_applied(seq, first_err)
            act = _chaos.fire("ack", peer=self._peer, seq=seq)
            if act is not None and act[0] == "drop":
                # injected applied-but-UNACKED failure: the exact
                # ambiguity the stream-epoch replay protocol resolves —
                # cut the connection instead of acking
                for fn in (lambda: self._sock.shutdown(socket.SHUT_RDWR),
                           self._sock.close):
                    try:
                        fn()
                    except OSError:
                        pass
                return
            if act is not None and act[0] in ("delay", "stall"):
                time.sleep(act[1])
            ack = _ACK.pack(seq, first_err or applied)
            if getattr(self._handler, "_trace_granted", False):
                # the extended batch ack: owner-side phase timings ride
                # back so the SENDER can attribute its ack latency to
                # queue-wait vs apply without the owner's trace file
                ack += _ACK_TIMES.pack(
                    min(0xFFFF_FFFF, int(queue_s * 1e6)),
                    min(0xFFFF_FFFF, int(apply_s * 1e6)))
            t_ack_w = time.time()
            try:
                # the ack-after-apply ordering under the per-connection
                # write mutex IS the client's flush fence; a peer that
                # stops draining wedges only its own connection
                with self._wlock:  # bfverify: holds-ok per-connection write mutex; ack ordering is the flush fence (reviewed PR 4/9)
                    self._sock.sendall(ack)
            except OSError:
                return  # peer gone; the recv loop will notice too
            if trec is not None:
                trec.emit("ack", "tcp_srv", t0=t_ack_w,
                          dur=time.time() - t_ack_w, parent=tctx[1],
                          round_=tctx[2], trace_id=tctx[0],
                          peer=self._peer, seq=seq)


def _leaf_views(leaves: List[Tuple[str, np.ndarray]]) -> List:
    """Encode ``[(name, array), ...]`` as SNAPSHOT/PUSH leaf entries
    (``_SNAP_LEAF`` + name + payload per leaf).  Callers prepend their
    own count — the snapshot table only ever holds wire-supported
    dtypes (publish validates f32/f64), so nothing is skipped here."""
    views: List = []
    for name, arr in leaves:
        nb = name.encode()
        views.append(_SNAP_LEAF.pack(len(nb), _DTYPE_IDS[arr.dtype],
                                     arr.size))
        views.append(nb)
        views.append(memoryview(arr).cast("B"))
    return views


def _recv_leaves(sock: socket.socket, count: int) -> Dict[str, np.ndarray]:
    """Decode ``count`` leaf entries (the :func:`_leaf_views` wire
    twin): the ONE reader for SNAPSHOT replies and subscription push
    frames, so the two clients cannot drift apart on the leaf format.
    Claimed lengths are bounded BEFORE any allocation (BF-WIRE004); a
    malformed header raises ``ValueError``, which both clients treat
    as a dead connection."""
    leaves: Dict[str, np.ndarray] = {}
    for _ in range(count):
        name_len, dtype_id, n_elems = _SNAP_LEAF.unpack(
            _recv_exact(sock, _SNAP_LEAF.size))
        if (dtype_id not in _DTYPES or n_elems < 0
                or name_len > _MAX_LEAF_NAME
                or n_elems * _DTYPES[dtype_id].itemsize
                > _MAX_LEAF_BYTES):
            raise ValueError("snapshot leaf header out of bounds")
        name = _recv_exact(sock, name_len).decode("utf-8", "replace")
        out = np.empty(n_elems, _DTYPES[dtype_id])
        _recv_into(sock, memoryview(out).cast("B"))
        leaves[name] = out
    return leaves


def _delta_leaf_views(items) -> List:
    """Encode a :meth:`DeltaEncoder.step` item list as op-10 delta leaf
    entries (``_DELTA_LEAF`` + name + codec payload per leaf)."""
    views: List = []
    for name, dtype, codec, n_elems, payload_views, wire_b in items:
        nb = name.encode()
        views.append(_DELTA_LEAF.pack(len(nb), _DTYPE_IDS[dtype], codec,
                                      n_elems, wire_b))
        views.append(nb)
        views.extend(payload_views)
    return views


def _recv_delta_leaves(sock: socket.socket, count: int) -> List:
    """Decode ``count`` op-10 delta leaf entries (the
    :func:`_delta_leaf_views` wire twin) into ``(name, dtype, codec,
    n_elems, payload)`` tuples for :meth:`DeltaApplier.apply`.  Claimed
    lengths are bounded BEFORE any allocation (the deposit path's
    discipline); a malformed entry raises ``ValueError``, which the
    subscriber treats as a dead connection — the cursor never moves on
    a frame that did not fully parse."""
    items: List = []
    for _ in range(count):
        name_len, dtype_id, codec, n_elems, wire_b = _DELTA_LEAF.unpack(
            _recv_exact(sock, _DELTA_LEAF.size))
        if (dtype_id not in _DTYPES or codec not in wire_codec.CODEC_NAMES
                or n_elems < 0 or wire_b < 0 or name_len > _MAX_LEAF_NAME
                or wire_b > wire_codec.wire_bytes_bound(
                    n_elems, _DTYPES[dtype_id].itemsize)):
            raise ValueError("delta leaf header out of bounds")
        name = _recv_exact(sock, name_len).decode("utf-8", "replace")
        payload = bytearray(wire_b)
        _recv_into(sock, memoryview(payload))
        items.append((name, _DTYPES[dtype_id], codec, n_elems,
                      memoryview(payload)))
    return items


class _SubSender:
    """Per-subscription background pusher: blocks in the snapshot
    table's publish wait and pushes the LATEST due round to its reader.

    Slow-reader policy is SKIP-TO-LATEST: the sender never queues more
    than the one snapshot it is currently serializing, so a reader that
    cannot keep up receives fewer, newer snapshots (``skipped`` counts
    the due rounds it missed) and NOTHING here can backpressure the
    training loop — publish never waits on any subscriber.  A reader
    that stops draining its socket eventually blocks this thread in
    ``sendall``; that wedges only this subscription (its own thread, no
    shared locks held across the send), and the next epoch's attach —
    or the reader's death reaching TCP — tears it down.  Keepalive
    frames (round = -1) flow when nothing is published, so a live-but-
    idle server never trips the reader's silence detector."""

    def __init__(self, handler, sock, wmu, group: str, every: int,
                 cursor: int, peer: str, sid: int, epoch: int):
        self._handler = handler
        self._sock = sock
        self._wmu = wmu
        self._group = group
        self._every = max(1, int(every))
        # the client-held cursor is the delivery truth: nothing at or
        # below it is ever pushed again, which is the no-duplicates half
        # of resumable subscriptions (the no-misses half is that pushes
        # always carry the latest round ABOVE it)
        self._last_round = int(cursor)
        self._peer = peer
        self.sid = sid
        self.epoch = epoch
        self._closed = threading.Event()
        # delta pushes (wire op 10) ride only connections whose HELLO
        # negotiated FEATURE_DELTA; the encoder is per-CONNECTION state
        # (a reconnect gets a fresh one, which is what forces the
        # full-frame resync anchor after every cursor gap)
        self._delta_on = bool(getattr(handler, "_delta_granted", False))
        self._enc = _delta.DeltaEncoder() if self._delta_on else None
        # start one generation BEHIND the table: a subscriber attaching
        # AFTER the latest publish (replica restart, converged trainer)
        # must still receive the current round if its cursor is below
        # it — the first wait_newer then returns immediately and the
        # due-ness rule decides, instead of waiting for a future
        # publish that may never come
        gen = handler.server.snap_table.generation(group)
        self._gen = gen - 1 if gen > 0 else 0
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name=f"bf-sub:{peer}")
        self._thread.start()

    def close(self) -> bool:
        """Stop the sender (idempotent; callable from any thread).
        Closing the socket kicks a sender blocked mid-``sendall``."""
        self._closed.set()
        for fn in (lambda: self._sock.shutdown(socket.SHUT_RDWR),
                   self._sock.close):
            try:
                fn()
            except OSError:
                pass
        if threading.current_thread() is not self._thread:
            self._thread.join(timeout=5)
            return not self._thread.is_alive()
        return True

    def _send(self, views) -> bool:
        try:
            # a reader that stops draining blocks only this subscription's
            # own sender thread; the next epoch's attach tears it down
            with self._wmu:  # bfverify: holds-ok per-connection write mutex; a stalled reader wedges only its own subscription (reviewed PR 7/9)
                _sendmsg_all(self._sock, views)
            return True
        except (OSError, ConnectionError):
            return False

    def _keepalive_due(self) -> bool:
        return time.monotonic() - self._last_send >= _SUB_KEEPALIVE_S

    def _traced(self) -> bool:
        return bool(getattr(self._handler, "_trace_granted", False))

    def _ka_views(self) -> List:
        """A keepalive frame (round = -1); carries an empty trace header
        on FEATURE_TRACE connections (and an empty delta header on
        FEATURE_DELTA connections) so every push frame parses the same
        way."""
        views: List = [_PUSH.pack(-1, 0, 0)]
        if self._traced():
            views.append(_TRACE_HDR.pack(0, 0, 0))
        if self._delta_on:
            views.append(_DELTA_HDR.pack(0, -1))
        return views

    def _payload_views(self, rnd: int, leaves) -> List:
        """The frame body after the trace header: dense leaves on plain
        connections; on FEATURE_DELTA connections the delta header plus
        either the full-frame anchor or the encoded round-over-round
        delta, per the server's live :class:`DeltaConfig` (read fresh
        each push, so a TreePlan actuation changes cadence without
        touching the sender)."""
        if not self._delta_on:
            return _leaf_views(leaves)
        cfg = self._handler.server.delta_cfg
        kind, base_rnd, items = self._enc.step(rnd, leaves, cfg)
        if kind == _OP_DELTA:
            views = [_DELTA_HDR.pack(_OP_DELTA, base_rnd)]
            views += _delta_leaf_views(items)
            wire_b = sum(w for *_x, w in items)
            _mt.inc("bf_push_bytes_total", float(wire_b), kind="delta",
                    group=self._group)
        else:
            views = [_DELTA_HDR.pack(0, -1)] + _leaf_views(leaves)
            _mt.inc("bf_push_bytes_total",
                    float(sum(a.size * a.dtype.itemsize
                              for _, a in leaves)),
                    kind="full", group=self._group)
        return views

    def _loop(self) -> None:
        tbl = self._handler.server.snap_table
        self._last_send = time.monotonic()
        while not self._closed.is_set():
            gen = tbl.wait_newer(self._group, self._gen,
                                 timeout_s=_SUB_KEEPALIVE_S)
            if self._closed.is_set():
                return
            if gen is None:
                if not self._send(self._ka_views()):
                    return
                self._last_send = time.monotonic()
                continue
            self._gen = gen
            try:
                rnd, leaves = tbl.read(self._group)
            except _snap.SnapshotUnavailable:
                continue  # dropped between notify and read
            if self._last_round >= 0 and rnd < self._last_round + self._every:
                # not due yet (every-Nth-round contract) — but a steady
                # stream of not-due publishes must not starve the
                # keepalive cadence, or a healthy connection trips the
                # reader's idle timeout (large strides make pushes
                # arbitrarily rarer than publishes)
                if self._keepalive_due():
                    if not self._send(self._ka_views()):
                        return
                    self._last_send = time.monotonic()
                continue
            skipped = (max(0, (rnd - self._last_round) - self._every)
                       if self._last_round >= 0 else 0)
            # push-frame trace context: the reader's consume span
            # parents to this push span, so a delivered snapshot links
            # causally back to the serving host
            thdr: List = []
            psp = None
            if self._traced():
                trec = _tr.get()
                if trec is not None:
                    # parent to the publish's stored trace context when
                    # the publisher carried one (a relay hop parents to
                    # the upstream push this way, so `bftrace-tpu` walks
                    # trainer -> relay -> leaf across the whole tree)
                    ptc = tbl.trace_ctx(self._group)
                    psp = trec.begin_span(
                        "push", "tcp_srv", round_=max(0, rnd),
                        parent=ptc[1] if ptc else 0,
                        trace_id=ptc[0] if ptc else None,
                        group=self._group, peer=self._peer,
                        skipped=skipped)
                thdr = [_TRACE_HDR.pack(
                    psp.tid if psp is not None else 0,
                    psp.sid if psp is not None else 0, max(0, rnd))]
            try:
                # the frame body is built ONCE (the delta encoder's
                # error-feedback state advances per push; building it
                # twice would double-apply the residual)
                views = ([_PUSH.pack(rnd, skipped, len(leaves))] + thdr
                         + self._payload_views(rnd, leaves))
                act = _chaos.fire("sub", peer=self._peer,
                                  group=self._group)
                if act is not None:
                    if act[0] in ("delay", "stall"):
                        time.sleep(act[1])
                    elif act[0] in ("drop", "truncate"):
                        # an injected reader-side outage: cut the push
                        # channel (after half a frame for 'truncate' —
                        # the torn-mid-frame case the resuming reader
                        # must survive without consuming the fragment)
                        if act[0] == "truncate":
                            self._send(views[:max(1, len(views) // 2)])
                        self.close()
                        return
                if not self._send(views):
                    return
            finally:
                if psp is not None:
                    psp.finish()
            self._last_send = time.monotonic()
            self._last_round = rnd
            if skipped:
                _mt.inc("bf_sub_skipped_rounds_total", float(skipped),
                        peer=self._peer, group=self._group)
            # how far the fleet moved while this reader consumed the
            # push: a persistently positive age is the slow-reader
            # signature (skip-to-latest keeps it bounded, not zero)
            _mt.set("bf_snapshot_age_rounds",
                    float(max(0, tbl.current_round(self._group) - rnd)),
                    peer=self._peer, group=self._group)


class _Handler(socketserver.BaseRequestHandler):
    def setup(self):
        self.server.track(self.request)  # type: ignore[attr-defined]
        # per-connection flight-recorder records (always-on host path): a
        # hang dump on the OWNER shows which peers were connected and
        # what their last deposits were — the receiving end of the
        # one-sided story that the peers' own dumps cannot show
        _bb.record("tcp_connect", peer=self.client_address[0])
        # reusable per-connection receive/decode scratch (grown once per
        # high-water mark, then zero allocation on the hot path)
        self._pay: Dict[int, np.ndarray] = {}     # dtype id -> raw buffer
        self._dense: Dict[int, np.ndarray] = {}   # dtype id -> decode dst
        self._out: Dict[int, np.ndarray] = {}     # dtype id -> reply buffer
        self._name = bytearray(256)
        self._discard = None  # allocated only if a bad item must be eaten
        self._deferred_applied = 0
        self._deferred_err = 0
        # replies can come from two threads once a batch stream starts
        # (handler: sync ops; apply worker: batch acks) — serialize writes
        self._wmu = _lc.lock("runtime.window_server._Handler._wmu")
        self._worker: Optional[_ApplyWorker] = None  # created on 1st batch
        # DepositStream lineage binding (STREAM_ATTACH); None = unbound
        self._stream_sid: Optional[int] = None
        self._stream_epoch = 0
        # FEATURE_TRACE negotiated on THIS connection: frames of the
        # _TRACED_OPS carry the trace header, batch acks grow the
        # timing tail, push frames carry the header (set at HELLO —
        # presence is deterministic per connection)
        self._trace_granted = False
        # FEATURE_DELTA negotiated on THIS connection: push frames carry
        # the delta header and may be op-10 deltas (set at HELLO — same
        # deterministic-per-connection discipline as the trace header)
        self._delta_granted = False
        # subscription push sender (SUBSCRIBE); None = plain connection
        self._sub: Optional[_SubSender] = None

    def _send(self, data) -> None:
        with self._wmu:  # bfverify: holds-ok per-connection write mutex; only this connection's handler+applier share it (reviewed PR 4/9)
            self.request.sendall(data)

    def _send_views(self, views) -> None:
        with self._wmu:  # bfverify: holds-ok per-connection write mutex; only this connection's handler+applier share it (reviewed PR 4/9)
            _sendmsg_all(self.request, views)

    def finish(self):
        if self._worker is not None:
            self._worker.close()
        if self._sub is not None:
            self._sub.close()
            self.server.note_sub(-1)  # type: ignore[attr-defined]
            self._sub = None
        self.server.untrack(self.request)  # type: ignore[attr-defined]
        _bb.record("tcp_disconnect", peer=self.client_address[0])

    def quiesce_sub(self) -> bool:
        """Fence a superseded SUBSCRIBE connection: close its socket and
        stop its push sender, so the old epoch can push nothing after
        the successor's accept reply.  Idempotent vs ``finish``."""
        for fn in (lambda: self.request.shutdown(socket.SHUT_RDWR),
                   self.request.close):
            try:
                fn()
            except OSError:
                pass
        s = self._sub
        if s is not None:
            return s.close()
        return True

    def quiesce(self) -> bool:
        """Fence a superseded connection: close its socket and DRAIN its
        apply worker, so nothing of the old stream generation can land
        after the successor's STREAM_ATTACH reply.  Called by the server
        when a newer epoch of the same stream attaches; safe to race
        with this handler's own ``finish`` (both paths are idempotent).
        Returns False when the worker could not be proven drained (the
        attach must then refuse rather than reply a non-final mark)."""
        for fn in (lambda: self.request.shutdown(socket.SHUT_RDWR),
                   self.request.close):
            try:
                fn()
            except OSError:
                pass
        w = self._worker
        if w is not None:
            return w.close()  # joins: the worker drains every batch
        return True

    def _note_applied(self, seq: int, err: int = 0) -> None:
        """Apply-worker callback: advance this stream's applied
        high-water mark and latch the first batch error (no-op for
        connections that never attached)."""
        if self._stream_sid is not None:
            self.server.note_applied(  # type: ignore[attr-defined]
                self._stream_sid, self._stream_epoch, seq, err)

    # ------------------------------------------------------------ plumbing
    def _geometry(self, ops, name_b):
        return ops.info(name_b)

    def _pay_buf(self, dtype_id: int, nbytes: int) -> np.ndarray:
        buf = self._pay.get(dtype_id)
        if buf is None or buf.nbytes < nbytes:
            buf = np.empty(max(nbytes, 1), np.uint8)
            self._pay[dtype_id] = buf
        return buf

    def _dense_buf(self, dtype_id: int, n_elems: int) -> np.ndarray:
        buf = self._dense.get(dtype_id)
        if buf is None or buf.size < n_elems:
            buf = np.empty(max(n_elems, 1), _DTYPES[dtype_id])
            self._dense[dtype_id] = buf
        return buf

    def _out_buf(self, dtype_id: int, n_elems: int) -> np.ndarray:
        buf = self._out.get(dtype_id)
        if buf is None or buf.size < n_elems:
            buf = np.empty(max(n_elems, 1), _DTYPES[dtype_id])
            self._out[dtype_id] = buf
        return buf

    def _eat(self, sock, nbytes: int) -> None:
        """Consume and discard ``nbytes`` of payload (bad item in a batch)
        without allocating proportionally to the claimed length."""
        if self._discard is None:
            self._discard = bytearray(1 << 20)
        mv = memoryview(self._discard)
        while nbytes > 0:  # strict: a negative count must never turn the
            # python slice below into a giant read of unsent bytes
            chunk = min(nbytes, len(mv))
            _recv_into(sock, mv[:chunk])
            nbytes -= chunk

    def _recv_name(self, sock, name_len: int) -> bytes:
        if name_len > len(self._name):
            self._name = bytearray(name_len)
        mv = memoryview(self._name)[:name_len]
        _recv_into(sock, mv)
        return bytes(mv)

    # ------------------------------------------------------------ handlers
    def _apply_deposit(self, ops, name_b, slot, flags, dtype_id, codec,
                       n_elems, payload_mv) -> int:
        """Decode (if needed) and land one validated deposit; returns the
        native status (deposit count) or a negative error."""
        if codec == wire_codec.CODEC_NONE:
            if len(payload_mv) != n_elems * _DTYPES[dtype_id].itemsize:
                return _ERR_CODEC  # belt-and-braces; validated upstream
            # zero-copy: a dtype view over the receive buffer
            arr = np.frombuffer(payload_mv, _DTYPES[dtype_id],
                                count=n_elems)
        else:
            try:
                # exact-size VIEW of the grown scratch: decode requires
                # out.size == n_elems, so handing it the whole buffer
                # would silently allocate fresh per item
                arr = wire_codec.decode(
                    codec, payload_mv, n_elems, _DTYPES[dtype_id],
                    out=self._dense_buf(dtype_id, n_elems)[:n_elems])
            except ValueError:
                return _ERR_CODEC
        rc = ops.deposit(name_b, slot, arr,
                         bool(flags & _FLAG_ACCUMULATE))
        if rc >= 0:
            nbytes = n_elems * _DTYPES[dtype_id].itemsize
            _mt.inc("bf_tcp_deposit_bytes_total", nbytes,
                    window=name_b.decode("utf-8", "replace"),
                    peer=self.client_address[0])
            _mt.inc("bf_tcp_deposits_total", 1.0,
                    peer=self.client_address[0])
            _bb.record("tcp_deposit", slot=slot, bytes=nbytes,
                       window=name_b.decode("utf-8", "replace"),
                       peer=self.client_address[0])
            if flags & _FLAG_DRAIN:
                # a graceful leaver handed its push-sum mass to this
                # owner: the audit-relevant membership event, recorded
                # where the receiving side's forensics will look
                _mt.inc("bf_drain_deposits_total", 1.0,
                        peer=self.client_address[0])
                _bb.record("drain_deposit", slot=slot,
                           window=name_b.decode("utf-8", "replace"),
                           peer=self.client_address[0])
        return rc

    def _batch_ack(self, seq: int, status: int) -> None:
        """Handler-thread batch ack (dedup / unparseable-stream paths):
        carries the timing tail on trace connections so the ack stream
        stays parseable regardless of which thread acked."""
        ack = _ACK.pack(seq, status)
        if self._trace_granted:
            ack += _ACK_TIMES.pack(0, 0)
        self._send(ack)

    def _handle_batch(self, ops, sock, tctx=None) -> bool:
        """One DEPOSIT_BATCH frame; returns False to drop the connection
        (only when the stream itself is unrecoverable).  The handler
        thread only validates headers and ``recv_into``s payloads; the
        per-connection :class:`_ApplyWorker` decodes and lands them, so
        receiving item N+1 overlaps applying item N.  The ack is emitted
        by the worker after the batch's last item applied.  ``tctx`` is
        the frame's trace context: the owner-side recv span is emitted
        here, the queue-wait/apply/ack spans by the worker."""
        if self._worker is None:
            self._worker = _ApplyWorker(
                self, sock, ops, self._wmu, self.client_address[0])
        worker = self._worker
        trec = _tr.get() if tctx is not None else None
        t_recv_w = time.time()
        t_recv_p = time.perf_counter()
        seq, count = _BATCH_HDR.unpack(_recv_exact(sock, _BATCH_HDR.size))
        if self._stream_sid is not None and seq <= self.server.stream_applied(  # type: ignore[attr-defined]
                self._stream_sid):
            # replayed duplicate of a batch this stream already applied
            # (it was in flight, applied, but un-acked when the previous
            # connection died): consume the frame WITHOUT touching the
            # window table, ack as applied — server-side exactly-once
            for _ in range(count):
                (name_len, _slot, _flags, dt, _codec, n_elems,
                 wire_bytes) = _ITEM.unpack(_recv_exact(sock, _ITEM.size))
                if (wire_bytes < 0 or n_elems < 0 or dt not in _DTYPES
                        or wire_bytes > wire_codec.wire_bytes_bound(
                            n_elems, _DTYPES[dt].itemsize)):
                    # same bound discipline as the fresh path: a lying
                    # duplicate cannot make the server consume unbounded
                    # claimed bytes
                    self._batch_ack(seq, _ERR_BAD_OP)
                    return False
                self._recv_name(sock, name_len)
                self._eat(sock, wire_bytes)
            _mt.inc("bf_tcp_deduped_batches_total", 1.0,
                    peer=self.client_address[0])
            _bb.record("tcp_dedup_batch", seq=seq, items=count,
                       peer=self.client_address[0])
            self._batch_ack(seq, count)
            return True
        jobs: List = []
        for _ in range(count):
            (name_len, slot, flags, dtype_id, codec, n_elems,
             wire_bytes) = _ITEM.unpack(_recv_exact(sock, _ITEM.size))
            if (dtype_id not in _DTYPES or n_elems < 0 or wire_bytes < 0
                    or codec not in wire_codec.CODEC_NAMES):
                # lengths are unparseable -> the stream cannot be resynced
                self._batch_ack(seq, _ERR_BAD_OP)
                return False
            name_b = self._recv_name(sock, name_len)
            err = 0
            itemsize = _DTYPES[dtype_id].itemsize
            if wire_bytes > wire_codec.wire_bytes_bound(n_elems, itemsize):
                err = _ERR_TOO_LARGE
            elif (codec == wire_codec.CODEC_NONE
                  and wire_bytes != n_elems * itemsize) or (
                      codec == wire_codec.CODEC_F32
                      and wire_bytes != n_elems * 4):
                # fixed-length codecs must claim EXACTLY their length: an
                # under-length dense payload would otherwise blow up in
                # the applier, and an over-length one smuggle trailing
                # garbage (topk is variable-length; decode validates it)
                err = _ERR_GEOMETRY
            elif not self.server.features_granted(  # type: ignore
                    self.request, _CODEC_FEATURE.get(codec, 0)):
                err = _ERR_CODEC
            else:
                info = self._geometry(ops, name_b)
                if info is None:
                    err = _ERR_NO_WINDOW
                elif info[2] != dtype_id or info[1] != n_elems:
                    err = _ERR_GEOMETRY
            if err:
                self._eat(sock, wire_bytes)
                jobs.append(("err", err))
                continue
            buf = worker.take_buffer(wire_bytes)
            _recv_into(sock, memoryview(buf)[:wire_bytes])
            jobs.append(("item", name_b, slot, flags, dtype_id, codec,
                         n_elems, buf, wire_bytes))
        if trec is not None:
            trec.emit("recv", "tcp_srv", t0=t_recv_w,
                      dur=time.perf_counter() - t_recv_p,
                      parent=tctx[1], round_=tctx[2], trace_id=tctx[0],
                      peer=self.client_address[0], seq=seq, items=count)
        worker.submit_batch(seq, jobs, tctx)
        return True

    def _handle_snapshot(self, sock, name_len: int, tctx=None) -> bool:
        """One SNAPSHOT request: all requested leaves from ONE round or
        a retriable negative status; returns False to drop the
        connection (unparseable request, or an injected read fault).
        ``tctx``: the reader's trace context — the serve span parents to
        it so the read links causally into the reader's trace."""
        t_serve_w = time.time()
        t_serve_p = time.perf_counter()
        group = self._recv_name(sock, name_len).decode("utf-8", "replace")
        want_round, count = _SNAP_REQ.unpack(
            _recv_exact(sock, _SNAP_REQ.size))
        if count > _MAX_SNAP_LEAVES:
            self._send(_STATUS.pack(_ERR_BAD_OP))
            return False
        names: List[str] = []
        for _ in range(count):
            (ln,) = _LEAF_NAME.unpack(_recv_exact(sock, _LEAF_NAME.size))
            if ln > _MAX_LEAF_NAME:
                self._send(_STATUS.pack(_ERR_BAD_OP))
                return False
            names.append(
                self._recv_name(sock, ln).decode("utf-8", "replace"))
        try:
            rnd, leaves = self.server.snap_table.read(  # type: ignore
                group, names or None,
                want_round=want_round if want_round >= 0 else -1)
        except _snap.RoundRolled:
            _mt.inc("bf_reads_total", 1.0, op="snapshot", status="rolled")
            self._send(_STATUS.pack(_ERR_ROUND_ROLLED))
            return True
        except _snap.SnapshotUnavailable:
            _mt.inc("bf_reads_total", 1.0, op="snapshot", status="none")
            self._send(_STATUS.pack(_ERR_NO_SNAPSHOT))
            return True
        views = ([_STATUS.pack(rnd), _SNAP_CNT.pack(len(leaves))]
                 + _leaf_views(leaves))
        act = _chaos.fire("read", op="snapshot",
                          peer=self.client_address[0])
        if act is not None:
            if act[0] in ("delay", "stall"):
                time.sleep(act[1])
            elif act[0] == "truncate":
                # a TORN reply frame, then the cut: the client must
                # detect it and retry a fresh read, never consume the
                # fragment as a snapshot
                self._send_views(views[:max(1, len(views) // 2)])
                return False
            elif act[0] == "drop":
                return False
        self._send_views(views)
        _mt.inc("bf_reads_total", 1.0, op="snapshot", status="ok")
        _bb.record("tcp_snapshot", group=group, round=rnd,
                   leaves=len(leaves), peer=self.client_address[0])
        trec = _tr.get() if tctx is not None else None
        if trec is not None:
            trec.emit("snapshot_serve", "tcp_srv", t0=t_serve_w,
                      dur=time.perf_counter() - t_serve_p,
                      parent=tctx[1], round_=tctx[2], trace_id=tctx[0],
                      peer=self.client_address[0], group=group,
                      served_round=rnd)
        return True

    def _handle_subscribe(self, sock, name_len: int) -> bool:
        """One SUBSCRIBE request: bind this connection as the push
        channel of a subscriber lineage and start its sender."""
        group = self._recv_name(sock, name_len).decode("utf-8", "replace")
        sid, epoch, every, cursor = _SUB_REQ.unpack(
            _recv_exact(sock, _SUB_REQ.size))
        if self._sub is not None:
            # one subscription per connection: a second SUBSCRIBE on the
            # same socket would interleave two push streams' framing
            self._send(_STATUS.pack(_ERR_BAD_OP))
            return False
        if not self.server.sub_reserve():  # type: ignore[attr-defined]
            # the tree plan's degree actuation: a relay at its fan-out
            # limit refuses RETRIABLY — the reader backs off and finds a
            # sibling (or the tree deepens at the next plan boundary)
            _mt.inc("bf_sub_rejected_total", 1.0, reason="fanout")
            _bb.record("sub_fanout_reject", group=group,
                       peer=self.client_address[0])
            self._send(_STATUS.pack(_ERR_BUSY))
            return False
        rc = self.server.attach_sub(sid, epoch, self)  # type: ignore
        if rc < 0:
            self.server.note_sub(-1)  # type: ignore[attr-defined]
            self._send(_STATUS.pack(rc))
            return False
        self._send(_STATUS.pack(0))
        self._sub = _SubSender(self, sock, self._wmu, group,
                               every, cursor, self.client_address[0],
                               sid=sid, epoch=epoch)
        ev = "sub_resume" if epoch > 1 else "sub_attach"
        _bb.record(ev, group=group, sub_id=sid, epoch=epoch,
                   cursor=cursor, every=max(1, every),
                   peer=self.client_address[0])
        return True

    def handle(self):
        ops = self.server.ops  # type: ignore[attr-defined]
        sock = self.request
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        try:
            while True:
                try:
                    hdr = _recv_exact(sock, _HDR.size)
                except ConnectionError:
                    return  # peer done
                magic, op, name_len = _HDR.unpack(hdr)
                if magic == _MAGIC_V1:
                    # an old client: reject LOUDLY (it is blocked on an
                    # 8-byte status right now), then drop — never try to
                    # parse a v1 stream as v2
                    self._send(_STATUS.pack(_ERR_VERSION))
                    return
                if magic != _MAGIC:
                    return  # not ours; drop the connection
                act = _chaos.fire("server", op=op,
                                  peer=self.client_address[0])
                if act is not None:
                    kind = act[0]
                    if kind in ("drop", "truncate"):
                        # 'truncate' differs from 'drop' only in where it
                        # cuts: the frame header was consumed, the body
                        # was not — the client observes a connection that
                        # died mid-frame either way
                        return
                    if kind in ("delay", "stall"):
                        time.sleep(act[1])
                # wire-propagated trace context: present iff this
                # connection's HELLO negotiated FEATURE_TRACE and the op
                # is one of the traced client->server frames (read AFTER
                # the chaos shim so an injected 'truncate' still models
                # "header consumed, body not").  span_id 0 = the sender
                # had no active span: parse, then ignore.
                tctx = None
                if self._trace_granted and op in _TRACED_OPS:
                    t_id, s_id, t_round = _TRACE_HDR.unpack(
                        _recv_exact(sock, _TRACE_HDR.size))
                    if s_id:
                        tctx = (t_id, s_id, t_round)
                if op == _OP_HEARTBEAT:
                    (hb_seq,) = _HB.unpack(_recv_exact(sock, _HB.size))
                    self._send(_ACK.pack((hb_seq & ~_HB_MARK) | _HB_MARK, 0))
                    continue
                if op == _OP_STREAM_ATTACH:
                    sid, epoch = _ATTACH.unpack(
                        _recv_exact(sock, _ATTACH.size))
                    rc = self.server.attach_stream(  # type: ignore
                        sid, epoch, self)
                    self._send(_STATUS.pack(rc))
                    if rc < 0:
                        return  # zombie generation; drop it
                    self._stream_sid = sid
                    self._stream_epoch = epoch
                    continue
                if op == _OP_HELLO:
                    body = _recv_exact(sock, _HELLO.size)
                    version, features = _HELLO.unpack(body)
                    if version != PROTOCOL_VERSION:
                        self._send(_STATUS.pack(_ERR_VERSION))
                        return
                    granted = features & _SERVER_FEATURES
                    self.server.set_features(self.request, granted)  # type: ignore
                    self._trace_granted = bool(granted & FEATURE_TRACE)
                    self._delta_granted = bool(granted & FEATURE_DELTA)
                    self._send(_STATUS.pack(granted))
                    continue
                if op == _OP_DEPOSIT_BATCH:
                    if not self._handle_batch(ops, sock, tctx):
                        return
                    continue
                if op == _OP_SNAPSHOT:
                    if not self._handle_snapshot(sock, name_len, tctx):
                        return
                    continue
                if op == _OP_SUBSCRIBE:
                    if not self._handle_subscribe(sock, name_len):
                        return
                    continue
                if op == _OP_FLUSH:
                    rc = self._deferred_err or self._deferred_applied
                    self._deferred_err = 0
                    self._deferred_applied = 0
                    _bb.record("tcp_flush", peer=self.client_address[0],
                               status=rc)
                    # bfwire: layout-ok no in-repo decoder for the op-5 reply
                    # (wire FLUSH is a bare status round-trip only the
                    # transport tests drive; the production stream
                    # fences on batch ACKs instead)
                    self._send(_STATUS.pack(rc))
                    continue
                name = self._recv_name(sock, name_len)
                slot, flags, dtype, n_elems = _BODY.unpack(
                    _recv_exact(sock, _BODY.size))
                if dtype not in _DTYPES or n_elems < 0 or op not in (
                        _OP_DEPOSIT, _OP_GET_SELF, _OP_READ_SLOT):
                    self._send(_STATUS.pack(_ERR_BAD_OP))
                    return  # cannot even parse the payload; drop
                info = self._geometry(ops, name)
                err = 0
                if info is None:
                    err = _ERR_NO_WINDOW
                elif info[2] != dtype or info[1] != n_elems:
                    # the client's claimed (dtype, n_elems) must MATCH the
                    # window's geometry before anything is allocated: the C
                    # entry points validate n_elems only and copy nbytes =
                    # n_elems * window_elem_size — a lying dtype would over-
                    # read the payload or overflow the reply buffer, and a
                    # huge n_elems would allocate unbounded owner memory
                    err = _ERR_GEOMETRY
                if op == _OP_DEPOSIT:
                    deferred = bool(flags & _FLAG_DEFERRED_ACK)
                    if err:
                        if deferred:
                            # the payload length is client-claimed but
                            # parseable (dense wire): eat it, latch, go on
                            self._eat(sock,
                                      n_elems * _DTYPES[dtype].itemsize)
                            if not self._deferred_err:
                                self._deferred_err = err
                            continue
                        # sync path keeps v1's posture: report and drop
                        self._send(_STATUS.pack(err))
                        return
                    nbytes = n_elems * _DTYPES[dtype].itemsize
                    buf = self._pay_buf(dtype, nbytes)
                    mv = memoryview(buf)[:nbytes]
                    _recv_into(sock, mv)
                    rc = self._apply_deposit(
                        ops, name, slot, flags, dtype,
                        wire_codec.CODEC_NONE, n_elems, mv)
                    if deferred:
                        if rc >= 0:
                            self._deferred_applied += 1
                        elif not self._deferred_err:
                            self._deferred_err = rc
                        continue
                    self._send(_STATUS.pack(rc))
                    continue
                if err:
                    self._send(_STATUS.pack(err))
                    continue
                out = self._out_buf(dtype, n_elems)[:n_elems]
                op_name = "get_self" if op == _OP_GET_SELF else "read_slot"
                if op == _OP_GET_SELF:
                    rc = ops.read_self(name, out)
                else:
                    rc = ops.read(name, slot, out, bool(flags & 1))
                if rc < 0:
                    self._send(_STATUS.pack(rc))
                    continue
                reply = [_STATUS.pack(rc), _SELF_HDR.pack(dtype, n_elems),
                         memoryview(out).cast("B")]
                act = _chaos.fire("read", op=op_name,
                                  peer=self.client_address[0])
                if act is not None:
                    if act[0] in ("delay", "stall"):
                        time.sleep(act[1])
                    elif act[0] == "truncate":
                        # status + a fragment of the payload, then the
                        # cut: the reader observes a reply torn mid-frame
                        self._send_views(reply[:2])
                        return
                    elif act[0] == "drop":
                        return
                self._send_views(reply)
                _mt.inc("bf_reads_total", 1.0, op=op_name, status="ok")
                _bb.record(
                    "tcp_read", op=op_name,
                    slot=slot, window=name.decode("utf-8", "replace"),
                    peer=self.client_address[0])
        except (ConnectionError, OSError):
            return


class _Server(socketserver.ThreadingTCPServer):
    daemon_threads = True
    allow_reuse_address = True

    _MAX_STREAMS = 512  # attach-state entries kept (oldest evicted)

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._conns: set = set()
        self._features: Dict[int, int] = {}  # id(sock) -> granted mask
        self._conns_mu = _lc.lock("runtime.window_server._Server._conns_mu")
        # DepositStream lineage state: stream_id -> [epoch, applied_seq,
        # handler, last_activity, first_err].  Survives connection churn
        # — that is the whole point: the applied high-water mark is what
        # makes replay after a reconnect idempotent, and the latched
        # first batch error is what keeps a rejected deposit LOUD even
        # when the connection died before its negative ack got out.
        self._streams: Dict[int, list] = {}
        self._streams_mu = _lc.lock(
            "runtime.window_server._Server._streams_mu")
        # Subscriber lineage state: sub_id -> [epoch, handler,
        # last_activity].  Same epoch discipline as deposit streams, on
        # the read path: a reconnecting subscriber's newer epoch
        # quiesces the superseded push sender, a zombie can never keep
        # pushing beside its successor.
        self._subs: Dict[int, list] = {}
        self._subs_mu = _lc.lock("runtime.window_server._Server._subs_mu")
        self._live_subs = 0
        # the snapshot table THIS server serves: the process-global one
        # by default (trainers), a private one for relay processes that
        # re-publish upstream groups (WindowServer(snapshots=...))
        self.snap_table: "_snap.SnapshotTable" = _snap.table()
        # the live delta cadence/codec of this server's push senders —
        # swapped whole (immutable dataclass) by the tree plan's
        # actuation at round boundaries; senders read it fresh per push
        self.delta_cfg: _delta.DeltaConfig = _delta.DeltaConfig()
        # fan-out admission limit (None = unlimited): the TreePlan's
        # degree knob
        self.sub_limit: Optional[int] = None

    def sub_reserve(self) -> bool:
        """Atomically claim one subscription slot under the fan-out
        limit (check-and-increment in ONE critical section: N children
        of a dead relay re-parenting simultaneously must not all pass a
        bare check and overshoot the degree the tree plan actuated).
        The claimer releases with ``note_sub(-1)`` on any later failure
        or teardown."""
        with self._subs_mu:
            if (self.sub_limit is not None
                    and self._live_subs >= self.sub_limit):
                return False
            self._live_subs += 1
            _mt.set("bf_subscribers", float(self._live_subs))
            return True

    # -------------------------------------------------- subscriber lineage
    def attach_sub(self, sid: int, epoch: int, handler) -> int:
        """Bind ``handler`` as the live push connection of subscriber
        ``sid`` at ``epoch``; quiesces the superseded connection before
        accepting.  0 on success, ``-105`` when the epoch is not
        strictly newer."""
        with self._subs_mu:
            st = self._subs.get(sid)
            if st is not None and epoch <= st[0]:
                return _ERR_STALE_EPOCH
            old = st[1] if st is not None else None
        if old is not None and old is not handler:
            # outside the lock: quiesce joins the old sender thread
            old.quiesce_sub()
        with self._subs_mu:
            st = self._subs.get(sid)
            if st is None:
                if len(self._subs) >= self._MAX_STREAMS:
                    oldest = min(self._subs,
                                 key=lambda k: self._subs[k][2])
                    del self._subs[oldest]
                st = self._subs[sid] = [0, None, time.monotonic()]
            if epoch <= st[0]:
                return _ERR_STALE_EPOCH  # lost an attach race
            st[0] = epoch
            st[1] = handler
            st[2] = time.monotonic()
        return 0

    def note_sub(self, delta: int) -> None:
        with self._subs_mu:
            self._live_subs = max(0, self._live_subs + delta)
            _mt.set("bf_subscribers", float(self._live_subs))

    # ------------------------------------------------------ stream lineage
    def attach_stream(self, sid: int, epoch: int, handler) -> int:
        """Bind ``handler`` as the live connection of stream ``sid`` at
        ``epoch``.  Quiesces the superseded connection (if any) BEFORE
        replying, so the returned applied-seq is final for everything the
        old generation received.  Returns the applied high-water mark;
        ``_ERR_STALE_EPOCH`` if ``epoch`` is not strictly newer;
        ``_ERR_BUSY`` (retryable) if the old generation could not be
        proven drained; or the stream's latched first batch error —
        errors must not be silently retired by a reconnect."""
        with self._streams_mu:
            st = self._streams.get(sid)
            if st is not None and epoch <= st[0]:
                return _ERR_STALE_EPOCH
            old_handler = st[2] if st is not None else None
        if old_handler is not None and old_handler is not handler:
            # outside the lock: quiesce JOINS the old apply worker (it
            # may be mid-deposit), and note_applied from that drain needs
            # the lock
            if not old_handler.quiesce():
                # a wedged old applier means the mark below could still
                # move AFTER our reply — refuse (retryably) rather than
                # hand out a non-final mark and risk a double apply
                return _ERR_BUSY
        with self._streams_mu:
            st = self._streams.get(sid)
            if st is None:
                if len(self._streams) >= self._MAX_STREAMS:
                    oldest = min(self._streams,
                                 key=lambda k: self._streams[k][3])
                    del self._streams[oldest]
                st = self._streams[sid] = [0, 0, None, time.monotonic(), 0]
            if epoch <= st[0]:
                return _ERR_STALE_EPOCH  # lost an attach race
            st[0] = epoch
            st[2] = handler
            st[3] = time.monotonic()
            if st[4]:
                # the stream already rejected a deposit (and the ack may
                # have died with the old connection): report THAT, not a
                # clean resume point — the client fails loudly exactly as
                # the lost ack would have made it
                return st[4]
            return st[1]

    def stream_applied(self, sid: int) -> int:
        with self._streams_mu:
            st = self._streams.get(sid)
            return st[1] if st is not None else 0

    def note_applied(self, sid: int, epoch: int, seq: int,
                     err: int = 0) -> None:
        """Advance the applied high-water mark (monotonic) and latch the
        stream's first batch error.  The epoch is deliberately NOT
        checked: a drained old-generation worker's applies are real
        applies, and recording them is exactly what keeps the successor's
        replay from repeating them.  Touching last_activity keeps busy
        lineages out of the eviction scan's reach."""
        with self._streams_mu:
            st = self._streams.get(sid)
            if st is not None:
                if seq > st[1]:
                    st[1] = seq
                if err and not st[4]:
                    st[4] = err
                st[3] = time.monotonic()

    def track(self, sock):
        with self._conns_mu:
            self._conns.add(sock)

    def untrack(self, sock):
        with self._conns_mu:
            self._conns.discard(sock)
            self._features.pop(id(sock), None)

    def set_features(self, sock, granted: int):
        with self._conns_mu:
            self._features[id(sock)] = granted

    def features_granted(self, sock, needed: int) -> bool:
        if not needed:
            return True
        with self._conns_mu:
            return bool(self._features.get(id(sock), 0) & needed)

    def close_connections(self):
        """stop() must QUIESCE: shutting down the accept loop alone leaves
        persistent handler connections serving deposits into windows the
        owner now believes are frozen."""
        with self._conns_mu:
            conns = list(self._conns)
        for s in conns:
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                s.close()
            except OSError:
                pass


class WindowServer:
    """Expose this process's windows for remote one-sided access.

    ``WindowServer().start()`` binds (default: an ephemeral port on all
    interfaces) and serves deposits/reads on daemon threads.  The address
    to hand to peers is ``.address``.  Serves the native runtime's window
    table when available, the in-process pure-Python table otherwise.

    ``snapshots`` selects the :class:`~bluefog_tpu.serving.snapshots.
    SnapshotTable` this server's SNAPSHOT/SUBSCRIBE ops serve — the
    process-global table by default; a relay passes its own, so one
    process can host a trainer's table AND a relay's re-published
    groups on separate ports without colliding.  ``delta`` configures
    the push senders' op-10 delta cadence (see
    :class:`~bluefog_tpu.runtime.delta.DeltaConfig`)."""

    def __init__(self, *, snapshots=None, delta=None):
        self._ops = _table_ops()
        self._server: Optional[_Server] = None
        self._thread: Optional[threading.Thread] = None
        self._snapshots = snapshots
        self._delta = delta

    def start(self, host: str = "0.0.0.0", port: int = 0) -> Tuple[str, int]:
        if self._server is not None:
            raise RuntimeError("server already running")
        self._server = _Server((host, port), _Handler)
        self._server.ops = self._ops  # type: ignore[attr-defined]
        if self._snapshots is not None:
            self._server.snap_table = self._snapshots
        if self._delta is not None:
            self._server.delta_cfg = self._delta
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True)
        self._thread.start()
        return self.address

    @property
    def snapshots(self):
        """The snapshot table this server serves."""
        if self._server is not None:
            return self._server.snap_table
        return self._snapshots if self._snapshots is not None \
            else _snap.table()

    def set_delta(self, cfg) -> None:
        """Install a new delta cadence (whole-config swap; push senders
        read it fresh per push).  The tree control plane calls this from
        its round-boundary actuation."""
        self._delta = cfg
        if self._server is not None:
            self._server.delta_cfg = cfg

    def set_fanout_limit(self, limit: Optional[int]) -> None:
        """Cap live subscriptions (None = unlimited) — the TreePlan's
        degree knob; over-limit SUBSCRIBEs are refused retriably
        (``ERR_BUSY``)."""
        if self._server is not None:
            self._server.sub_limit = (None if limit is None
                                      else max(1, int(limit)))

    @property
    def address(self) -> Tuple[str, int]:
        """``(host, port)`` for peers.  A wildcard bind is substituted with
        a routable address of this host (peers cannot connect to
        ``0.0.0.0``); pass an explicit ``host`` to ``start`` to control
        exactly what is advertised."""
        assert self._server is not None, "server not started"
        host, port = self._server.server_address[:2]
        if host in ("0.0.0.0", "::"):
            host = _routable_host()
        return host, port

    def stop(self) -> None:
        """Quiesce: stop accepting AND close live peer connections, so no
        deposit can land after stop() returns."""
        if self._server is not None:
            self._server.shutdown()
            self._server.close_connections()
            self._server.server_close()
            if self._thread is not None:
                self._thread.join(timeout=10)
            self._server = None
            self._thread = None


# ---------------------------------------------------------------------------
# Clients
# ---------------------------------------------------------------------------


class RemoteWindow:
    """Synchronous client handle to a window served by another host's
    :class:`WindowServer` — ``deposit`` is ``MPI_Put``/``MPI_Accumulate``
    across the DCN, ``read_self`` the passive ``win_get``.  One persistent
    connection per handle; NOT thread-safe (one handle per rank thread,
    like an MPI endpoint).  For hot deposit paths prefer
    :class:`PipelinedRemoteWindow`, which overlaps the wire with compute.

    Every operation runs under a per-op DEADLINE (``timeout_s``): a
    wedged owner surfaces as a loud :class:`TimeoutError` naming the op,
    never an indefinitely hung reader thread.  ``retry=`` (``True`` for
    the defaults, or a dict of :class:`~bluefog_tpu.runtime.resilience.
    Backoff` kwargs) additionally reconnects and retries *idempotent
    reads* — ``read_self`` and non-consuming ``read`` — under a bounded
    backoff; a consuming ``read`` and ``deposit`` are never silently
    re-issued (re-running them is not idempotent: a retried consume
    whose first reply died would silently drop the consumed mass, and a
    retried accumulate would double-apply).  When the budget exhausts
    (or a non-retriable op fails), the error LATCHES like a
    :class:`DepositStream`'s: every later call on this handle raises it
    immediately instead of re-hammering a dead owner."""

    def __init__(self, address: Tuple[str, int], name: str,
                 timeout_s: float = 30.0, *, retry=None):
        self.name = name
        self._name_b = name.encode()
        self._addr = (address[0], int(address[1]))
        self._timeout_s = float(timeout_s)
        self._retry_cfg = (dict(retry) if isinstance(retry, dict)
                           else ({} if retry else None))
        self._err: Optional[str] = None
        self._sock = self._connect()

    def _connect(self) -> socket.socket:
        sock = socket.create_connection(self._addr,
                                        timeout=self._timeout_s)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        # the connect timeout persists as the PER-OP deadline: recv on a
        # wedged owner raises instead of parking this thread forever
        sock.settimeout(self._timeout_s)
        return sock

    def _reconnect(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass
        self._sock = self._connect()

    def _fail(self, msg: str) -> None:
        if self._err is None:
            self._err = msg
        _bb.record("tcp_sync_error", window=self.name, error=msg[:200])

    def _raise_if_err(self) -> None:
        if self._err is not None:
            raise RuntimeError(
                f"sync window client for {self.name!r} failed earlier "
                f"and is latched: {self._err}")

    def _roundtrip(self, op: int, slot: int, flags: int, dtype_id: int,
                   n_elems: int, payload_view=None, *,
                   recv_array: bool = False):
        pre = (_HDR.pack(_MAGIC, op, len(self._name_b)) + self._name_b +
               _BODY.pack(slot, flags, dtype_id, n_elems))
        views = [pre] if payload_view is None else [pre, payload_view]
        _sendmsg_all(self._sock, views)
        (rc,) = _STATUS.unpack(_recv_exact(self._sock, _STATUS.size))
        if rc < 0 or not recv_array:
            return rc, None
        dtype, got = _SELF_HDR.unpack(
            _recv_exact(self._sock, _SELF_HDR.size))
        # the reply's claimed geometry is bounded by the REQUEST's own
        # n_elems before anything is allocated (BF-WIRE004): a lying
        # owner must not choose this client's allocation size
        if dtype not in _DTYPES or got < 0 or got > n_elems:
            raise ConnectionError(
                f"reply header out of bounds (dtype id {dtype}, "
                f"{got} elems vs {n_elems} requested)")
        # single-allocation receive: the destination array IS the receive
        # buffer (no intermediate bytes + frombuffer().copy())
        out = np.empty(got, _DTYPES[dtype])
        _recv_into(self._sock, memoryview(out).cast("B"))
        return rc, out

    def _request(self, op: int, slot: int, flags: int, dtype_id: int,
                 n_elems: int, payload_view=None, *,
                 recv_array: bool = False, idempotent: bool = False):
        self._raise_if_err()
        op_desc = {_OP_DEPOSIT: "deposit", _OP_GET_SELF: "read_self",
                   _OP_READ_SLOT: "read"}.get(op, f"op{op}")
        try:
            return self._roundtrip(op, slot, flags, dtype_id, n_elems,
                                   payload_view, recv_array=recv_array)
        except (TimeoutError, ConnectionError, OSError) as e:
            first = e
        if idempotent and self._retry_cfg is not None:
            # a timed-out or torn reply leaves the connection desynced:
            # every retry starts from a FRESH connection, under the
            # bounded backoff — reads are pure, so re-issuing is safe
            bo = resilience.read_backoff(self._retry_cfg)
            last: BaseException = first
            for delay in bo:
                _bb.record("torn_read_retry", window=self.name,
                           op=op_desc, error=str(last)[:200])
                _mt.inc("bf_read_retries_total", 1.0, op=op_desc)
                time.sleep(delay)
                try:
                    self._reconnect()
                    return self._roundtrip(op, slot, flags, dtype_id,
                                           n_elems, payload_view,
                                           recv_array=recv_array)
                except (TimeoutError, ConnectionError, OSError) as e2:
                    last = e2
            self._fail(f"{op_desc} retry budget exhausted after "
                       f"{bo.attempts} attempt(s): {last}")
            self._raise_if_err()
        if isinstance(first, TimeoutError):
            self._fail(f"{op_desc} deadline ({self._timeout_s}s) "
                       "expired — the owner is wedged or unreachable")
            raise TimeoutError(
                f"remote {op_desc} of {self.name!r} timed out after "
                f"{self._timeout_s}s (wedged owner?)") from first
        self._fail(f"connection lost mid-{op_desc}: {first}")
        raise ConnectionError(
            f"window server for {self.name!r} closed the connection "
            "mid-request (server stopped, or a protocol version "
            "mismatch — v1 servers drop unrecognized v2 frames)"
        ) from first

    def deposit(self, slot: int, arr: np.ndarray, *,
                accumulate: bool = True) -> int:
        a = np.ascontiguousarray(arr)
        if a.dtype not in _DTYPE_IDS:
            raise TypeError(f"RemoteWindow supports f32/f64, got {a.dtype}")
        rc, _ = self._request(_OP_DEPOSIT, slot,
                              _FLAG_ACCUMULATE if accumulate else 0,
                              _DTYPE_IDS[a.dtype], a.size,
                              memoryview(a).cast("B"))
        if rc < 0:
            raise RuntimeError(
                f"remote deposit into {self.name!r}[{slot}] failed ({rc}): "
                + _err_text(rc))
        _mt.inc("bf_tcp_single_deposits_total", 1.0)
        return rc

    def read_self(self, n_elems: int, dtype=np.float64) -> np.ndarray:
        rc, out = self._request(_OP_GET_SELF, 0, 0,
                                _DTYPE_IDS[np.dtype(dtype)], n_elems,
                                recv_array=True, idempotent=True)
        if rc < 0:
            raise RuntimeError(
                f"remote read_self of {self.name!r} failed ({rc}): "
                + _err_text(rc))
        return out

    def read(self, slot: int, n_elems: int, dtype=np.float64, *,
             consume: bool = True) -> Tuple[np.ndarray, int]:
        rc, out = self._request(_OP_READ_SLOT, slot, 1 if consume else 0,
                                _DTYPE_IDS[np.dtype(dtype)], n_elems,
                                recv_array=True, idempotent=not consume)
        if rc < 0:
            raise RuntimeError(
                f"remote read of {self.name!r}[{slot}] failed ({rc}): "
                + _err_text(rc))
        return out, rc

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


class _Item:
    __slots__ = ("name_b", "slot", "flags", "dtype_id", "codec", "n_elems",
                 "views", "wire_bytes", "dense_bytes", "pooled", "tctx",
                 "t_enq")

    def __init__(self, name_b, slot, flags, dtype_id, codec, n_elems,
                 views, wire_bytes, dense_bytes, pooled, tctx=None,
                 t_enq=0.0):
        self.name_b = name_b
        self.slot = slot
        self.flags = flags
        self.dtype_id = dtype_id
        self.codec = codec
        self.n_elems = n_elems
        self.views = views
        self.wire_bytes = wire_bytes
        self.dense_bytes = dense_bytes
        self.pooled = pooled  # buffer to return to the pool after send
        self.tctx = tctx      # (trace_id, span_id, round) of the caller
        self.t_enq = t_enq    # perf_counter at enqueue (enqueue span)


_LOOPBACK_HOSTS = ("127.0.0.1", "::1", "localhost")


def _is_local_host(host: str) -> bool:
    """True when ``host`` names THIS machine — the co-location test of
    the same-host shm fast path.  Loopback spellings are local by
    definition; otherwise the host must equal this machine's hostname or
    one of its resolved addresses.  Resolution failures return False
    (detection failure = TCP, never an error)."""
    if host in _LOOPBACK_HOSTS or host.startswith("127."):
        return True
    try:
        names = {socket.gethostname(), socket.getfqdn()}
        if host in names:
            return True
        addrs = set()
        for n in names:
            try:
                addrs.update(info[4][0]
                             for info in socket.getaddrinfo(n, None))
            except OSError:
                pass
        if host in addrs:
            return True
    except OSError:
        return False
    # hostname resolution often maps only to loopback while the server
    # publishes its interface address: the authoritative test is a bind
    # probe — an OS will only bind a socket to one of ITS OWN addresses
    try:
        infos = socket.getaddrinfo(host, None, type=socket.SOCK_DGRAM)
    except OSError:
        return False
    for family, stype, proto, _, sockaddr in infos[:4]:
        try:
            s = socket.socket(family, stype, proto)
        except OSError:
            continue
        try:
            s.bind((sockaddr[0], 0))
            return True
        except OSError:
            continue
        finally:
            s.close()
    return False


class _LocalWindowRef:
    """Same-process twin of an shm attach: the target window already
    lives in THIS process's native table (the owner and the depositor
    are the same process — unit tests, single-host self-loops), where a
    second ``bf_win_attach_shm`` mapping is refused.  Deposits go
    straight through the table by name; geometry comes from
    ``bf_win_info`` so the fast path keeps the same dtype/size guard as
    the attached case."""

    def __init__(self, name: str):
        lib = native.load()
        if lib is None:
            raise RuntimeError("native runtime unavailable")
        ns = ctypes.c_int()
        ne = ctypes.c_longlong()
        dt = ctypes.c_int()
        if lib.bf_win_info(name.encode(), ctypes.byref(ns),
                           ctypes.byref(ne), ctypes.byref(dt)) != 0:
            raise RuntimeError(f"window {name!r} not in the local table")
        self._lib = lib
        self.name = name
        self.n_slots = ns.value
        self.n_elems = int(ne.value)
        self.dtype = np.dtype(np.float64 if dt.value == 1 else np.float32)

    def deposit_async(self, slot: int, arr: np.ndarray, *,
                      accumulate: bool = True, copy: bool = True,
                      drain: bool = False) -> int:
        del copy  # applied before return, signature parity only
        a = np.ascontiguousarray(arr, dtype=self.dtype).ravel()
        v = self._lib.bf_win_deposit(
            self.name.encode(), slot, a.ctypes.data, self.n_elems,
            1 if accumulate else 0)
        if v < 0:
            raise RuntimeError(
                f"deposit into {self.name!r}[{slot}] failed")
        if drain:
            _mt.inc("bf_drain_deposits_total", 1.0, peer="local")
            _bb.record("drain_deposit", window=self.name, slot=slot,
                       peer="local")
        return int(v)


class DepositStream:
    """Per-PEER pipelined deposit engine: fire-and-forget deposits into any
    of a peer's windows through one background sender with a bounded
    in-flight window — the userspace analog of the reference's MPI
    progress thread servicing ``win_put``/``win_accumulate`` while the
    training thread computes.

    - :meth:`deposit_async` enqueues and returns immediately (by default it
      snapshots the payload, so callers may reuse their buffer — the
      async-DSGD hot loop does).  The sender thread coalesces everything
      queued — across windows/leaves — into ONE batched wire frame per
      send (one ack), keeps at most ``max_in_flight`` batches
      unacknowledged, and reports transport errors at the next call or at
      :meth:`flush`.
    - :meth:`flush` is the FENCE: it returns only when every enqueued
      deposit has been acknowledged as applied by the serving host.  Any
      loop whose correctness audit assumes "no deposit lands after X"
      (the async-DSGD mass audit barrier) MUST flush before X — the
      BF-WIN lint rule checks exactly this.

    One stream per (client process, peer host): every window bound for the
    same peer should share it, so a round's leaves ride one frame.
    Optional wire compression (``codec="f32"`` / ``"topk"``) is negotiated
    at connect; lossy codecs are opt-in and must NOT be used on payloads
    whose exact mass matters (push-sum ``p``).  NOT thread-safe for
    concurrent producers (one stream per rank thread).

    Fault tolerance (``reconnect=``): when enabled, a broken connection
    does not latch a terminal error immediately — the sender reconnects
    with exponential backoff + jitter under a RETRY BUDGET
    (:class:`~bluefog_tpu.runtime.resilience.Backoff`; pass ``True`` for
    the defaults or a dict of Backoff kwargs), re-attaches its stream
    lineage (STREAM_ATTACH carries a stable stream id + a fresh epoch),
    and REPLAYS the unacked in-flight batches.  The attach reply is the
    server's applied high-water mark, so a batch that was applied but
    un-acked when the connection died is retired instead of re-sent —
    and the server dedups by the same mark, making replay idempotent
    end to end.  Only when the budget is exhausted does the stream latch
    the error and mark the peer DEAD (:attr:`health`).
    ``heartbeat_interval_s > 0`` additionally probes an *idle* stream
    with the lightweight HEARTBEAT wire op, so peer health does not go
    stale between deposits.

    Same-host shm fast path (``shm=True``): when the peer address names
    THIS machine, deposits are routed through the named-shm window table
    (``AsyncWindow(attach=True)`` — or the process-local table when
    owner and depositor share a process) instead of the TCP wire: one
    mutex-guarded memory accumulate, no frame, no ack.  Detection is
    per stream and transparent: the first attach failure (no native
    runtime, non-shm windows on the owner, remote host) records a
    ``shm_fallback`` blackbox event and routes everything over TCP; a
    per-window geometry/dtype mismatch or a mid-run shm fault falls
    back for that window only.  Routing is sticky per window name, so
    a window's deposits never reorder across transports.  Fence
    semantics are unchanged — an shm deposit is APPLIED when
    :meth:`deposit_async` returns (the slot flip is atomic under the
    window mutex: a torn write is absent, never half-applied), so
    :meth:`flush` still fences exactly the deposits still on the wire.
    Health/heartbeats keep riding TCP: liveness of the peer *process*
    is a wire question even when payloads take the table."""

    def __init__(self, address: Tuple[str, int],
                 timeout_s: float = 30.0, *, codec: Optional[str] = None,
                 topk_ratio: float = 0.1, max_in_flight: int = 4,
                 max_queue_items: int = 1024,
                 max_batch_bytes: int = 16 << 20,
                 reconnect=None,
                 heartbeat_interval_s: float = 0.0,
                 suspect_after_s: float = 2.0,
                 dead_after_s: float = 20.0,
                 shm: bool = False,
                 on_ewma: Optional[Callable[[float], None]] = None):
        self._addr = (address[0], int(address[1]))
        self._peer = f"{address[0]}:{address[1]}"
        self._timeout_s = float(timeout_s)
        self._codec = wire_codec.CODEC_IDS[codec or "none"]
        # the negotiation ceiling: HELLO requests this codec's feature
        # bit plus every less aggressive one, so set_codec() can walk
        # the whole ladder at or below it after connect
        self._codec_max = self._codec
        self._topk_ratio = float(topk_ratio)
        self._max_in_flight = max(1, int(max_in_flight))
        self._max_queue = max(1, int(max_queue_items))
        # coalescing cap: without it a fast producer collapses the whole
        # queue into one mega-frame and the pipeline degenerates to
        # stop-and-wait at frame granularity — several bounded frames in
        # flight is what keeps client send, server recv, and server apply
        # continuously overlapped
        self._max_batch_bytes = max(1 << 16, int(max_batch_bytes))
        # ------------------------------------------------------ tracing
        # the arming decision is taken ONCE, at construction (the same
        # moment the codec ceiling is fixed): a stream built while
        # tracing is armed asks for FEATURE_TRACE at every HELLO it
        # ever sends, so reconnect replay frames parse identically to
        # first-sends.  Non-grant (a v-old server) degrades silently —
        # per-connection, never a handshake failure.
        self._trace_want = _tr.get() is not None
        self._trace_on = False
        # --------------------------------------------------- resilience
        self._resume = bool(reconnect)
        self._reconnect_cfg = (dict(reconnect)
                               if isinstance(reconnect, dict) else {})
        self._hb_interval = float(heartbeat_interval_s)
        self._hb_last = time.monotonic()
        self._hb_seq = 0
        self._hb_sent: Dict[int, float] = {}
        self.health: Optional[resilience.PeerHealth] = (
            resilience.PeerHealth(self._peer,
                                  suspect_after_s=suspect_after_s,
                                  dead_after_s=dead_after_s)
            if (self._resume or self._hb_interval > 0) else None)
        # stable lineage id + per-connection epoch (see STREAM_ATTACH)
        self._stream_id = int.from_bytes(os.urandom(8), "little") or 1
        self._epoch = 0
        self._sock_gen = 0
        self._conn_broken = False
        self._wake = threading.Event()  # interrupts backoff sleeps on close
        self._cv = _lc.condition("runtime.window_server.DepositStream._cv")
        self._queue: collections.deque = collections.deque()
        # seq -> (t_send, retained items | None, n_items, wire, dense);
        # items are retained until the ack ONLY when reconnect is on —
        # they are the replay window
        self._inflight: Dict[int, Tuple] = {}
        self._seq = 0
        self._err: Optional[str] = None
        self._closed = False
        self._pool: Dict[Tuple[int, int], List[np.ndarray]] = {}
        self._flushes = 0
        # bench/observability: recent (send -> ack) latencies in seconds
        self.ack_latencies: collections.deque = collections.deque(
            maxlen=4096)
        # per-peer ack-latency EWMA — the slow-peer evidence the
        # communication controller consumes programmatically (a gauge a
        # decision loop can read without parsing histogram buckets).
        # Heartbeat RTTs fold into the SAME average, so an idle stream's
        # evidence does not go stale between deposits (the heartbeat
        # piggyback half of evidence collection).  Written by the ack
        # thread, read by the producer thread: a single float store is
        # atomic under the GIL.
        self._ack_ewma: Optional[float] = None
        self._ack_ewma_alpha = 0.2
        # per-peer PHASE EWMAs (net / owner-queue / owner-apply seconds)
        # from the extended batch acks of FEATURE_TRACE connections: the
        # evidence that lets the control plane tell a slow LINK from a
        # slow HOST.  None until the first timed ack (or forever, when
        # tracing is off).  Written by the ack thread only; readers take
        # a GIL-atomic tuple-ref snapshot.
        self._phase_ewma: Optional[Tuple[float, float, float]] = None
        # striping hook: when set, EWMA updates go to the callback
        # INSTEAD of the per-peer gauge — a StripedDepositStream rolls
        # its stripes up into one bf_peer_ack_ewma_seconds{peer=} value
        # (max-of-stripes) so the slow-peer detector sees one peer, not
        # one gauge per stripe
        self._on_ewma = on_ewma
        self._reconnects = 0
        # ------------------------------------------------ shm fast path
        # co-location is decided once per stream (cheap address test);
        # capability (native runtime + shm-backed windows on the owner)
        # is probed at the first deposit and latched — see _shm_window
        self._shm_ok = bool(shm) and _is_local_host(self._addr[0])
        if shm and not self._shm_ok:
            _bb.record("shm_fallback", peer=self._peer, window="*",
                       reason="peer host is not local")
        self._shm_wins: Dict[bytes, Optional[object]] = {}
        self._shm_deposits = 0
        self._sock = self._connect_once(self._timeout_s)
        self._sender = threading.Thread(
            target=self._send_loop, daemon=True,
            name=f"bf-win-send:{self._peer}")
        self._acker = threading.Thread(
            target=self._ack_loop, daemon=True,
            name=f"bf-win-ack:{self._peer}")
        self._sender.start()
        self._acker.start()

    # --------------------------------------------------------- connection
    def _connect_once(self, timeout_s: float) -> socket.socket:
        """One connect + HELLO (+ STREAM_ATTACH when resuming).  Raises
        on any failure.  On a resumed stream the attach reply retires
        every in-flight batch the server already applied, which is the
        idempotence half of reconnect replay."""
        sock = socket.create_connection(self._addr, timeout=timeout_s)
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            # request the CEILING codec's feature bit and every rung
            # below it, so a mid-run set_codec() step-down (and back up
            # to the ceiling) never needs a renegotiation — and the
            # want is stable across reconnects regardless of the codec
            # in effect when the connection died
            want = FEATURE_BATCH
            for cid, bit in _CODEC_FEATURE.items():
                if cid <= self._codec_max:
                    want |= bit
            if self._resume:
                want |= FEATURE_RESUME
            if self._hb_interval > 0:
                want |= FEATURE_HEARTBEAT
            if self._trace_want:
                want |= FEATURE_TRACE
            _sendmsg_all(sock, [
                _HDR.pack(_MAGIC, _OP_HELLO, 0),
                _HELLO.pack(PROTOCOL_VERSION, want)])
            (granted,) = _STATUS.unpack(_recv_exact(sock, _STATUS.size))
            if granted < 0:
                raise RuntimeError(
                    f"window server at {self._peer} rejected the v"
                    f"{PROTOCOL_VERSION} handshake ({granted}): "
                    + _err_text(int(granted)))
            if want & ~granted & ~FEATURE_TRACE:
                # FEATURE_TRACE is the one OPTIONAL want: a v-old server
                # that cannot carry trace headers still serves deposits
                # — tracing degrades silently on this connection
                raise RuntimeError(
                    f"window server at {self._peer} does not support the "
                    f"requested transport features (want {want:#x}, "
                    f"granted {int(granted):#x})")
            self._trace_on = bool(self._trace_want
                                  and granted & FEATURE_TRACE)
            if self._resume:
                self._epoch += 1
                _sendmsg_all(sock, [
                    _HDR.pack(_MAGIC, _OP_STREAM_ATTACH, 0),
                    _ATTACH.pack(self._stream_id, self._epoch)])
                (applied,) = _STATUS.unpack(
                    _recv_exact(sock, _STATUS.size))
                if applied == _ERR_BUSY:
                    # old generation still draining: retryable — surface
                    # as a connection-level condition so the backoff
                    # loop tries again
                    raise ConnectionError(
                        f"stream attach to {self._peer} deferred: "
                        + _err_text(_ERR_BUSY))
                if applied < 0:
                    # terminal: a latched batch error (a deposit this
                    # stream sent WAS rejected; the ack died with the
                    # old connection) or a superseded epoch — retrying
                    # cannot fix either
                    raise RuntimeError(
                        f"stream attach to {self._peer} rejected "
                        f"({int(applied)}): " + _err_text(int(applied)))
                self._retire_through(int(applied))
            # connect/HELLO/attach honored timeout_s; the steady-state
            # stream must NOT — the ack reader is a free-running
            # background thread whose recv legitimately sits idle for as
            # long as the training loop goes without depositing
            sock.settimeout(None)
        except BaseException:
            try:
                sock.close()
            except OSError:
                pass
            raise
        return sock

    def _retire_through(self, applied_seq: int) -> None:
        """Drop in-flight batches the server reports as applied (they
        were applied-but-unacked when the old connection died)."""
        with self._cv:
            for s in [s for s in self._inflight if s <= applied_seq]:
                entry = self._inflight.pop(s)
                for it in entry[1] or ():
                    if it.pooled is not None:
                        self._give(it.pooled)
                if entry[5] is not None:
                    # applied-but-unacked, resolved by the attach mark:
                    # the wire span ends here, marked so the analyzer
                    # knows its duration includes the outage
                    entry[5].finish(retired=True)
            self._cv.notify_all()

    def _frame_views(self, seq: int, items: List["_Item"],
                     tctx=None) -> List:
        views: List = [_HDR.pack(_MAGIC, _OP_DEPOSIT_BATCH, 0),
                       _BATCH_HDR.pack(seq, len(items))]
        if self._trace_on:
            # the wire-propagated causal context: present on EVERY batch
            # frame of a FEATURE_TRACE connection (span_id 0 = no active
            # span — the server parses, then ignores), inserted right
            # after the frame header, before the batch header
            tid, sid, rnd = tctx or (0, 0, 0)
            views.insert(1, _TRACE_HDR.pack(tid, sid, rnd))
        for it in items:
            views.append(_ITEM.pack(
                len(it.name_b), it.slot, it.flags, it.dtype_id,
                it.codec, it.n_elems, it.wire_bytes))
            views.append(it.name_b)
            views.extend(it.views)
        return views

    def _recover(self, reason: str) -> bool:
        """Reconnect with bounded backoff + jitter and replay the unacked
        in-flight window.  True when the stream is live again; False
        after latching the terminal error (budget exhausted or the
        stream is closing) — the peer is then DEAD."""
        if not self._resume or self._closed:
            return False
        h = self.health
        if h is not None:
            h.note_failure()
        _bb.record("tcp_reconnect", peer=self._peer, reason=reason[:200],
                   inflight=len(self._inflight))
        try:
            self._sock.close()
        except OSError:
            pass
        bo = resilience.Backoff(**{**dict(base_s=0.05, cap_s=2.0,
                                          factor=2.0, jitter=0.5,
                                          budget=8),
                                   **self._reconnect_cfg})
        for delay in bo:
            _mt.observe("bf_reconnect_backoff_seconds", delay,
                        peer=self._peer)
            if self._wake.wait(delay) or self._closed:
                return False
            try:
                sock = self._connect_once(self._timeout_s)
            except (OSError, ConnectionError):
                if h is not None:
                    h.note_failure()
                continue
            except RuntimeError as e:
                # handshake/attach REJECTION (version, features, a
                # latched batch error, a superseded epoch): terminal —
                # burning the rest of the budget would only relabel the
                # real error as "peer unreachable"
                self._fail(str(e))
                return False
            # replay what the attach reply left outstanding, in seq
            # order; the server dedups anything a zombie raced in
            with self._cv:
                pending = sorted(self._inflight.items())
            replayed = 0
            try:
                for seq, entry in pending:
                    wsp = entry[5]
                    _sendmsg_all(sock, self._frame_views(
                        seq, entry[1],
                        wsp.ctx if wsp is not None else None))
                    replayed += 1
            except (OSError, ConnectionError):
                try:
                    sock.close()
                except OSError:
                    pass
                if h is not None:
                    h.note_failure()
                continue
            with self._cv:
                if self._closed:
                    # close() won the race while we were connecting: it
                    # already closed (or is about to close) the OLD
                    # socket it read — installing the fresh one here
                    # would leak it and leave the ack thread parked in
                    # recv on a socket nobody will ever close (found by
                    # the BF-CONC003 thread-shared-state audit)
                    try:
                        sock.close()
                    except OSError:
                        pass
                    return False
                self._sock = sock
                self._sock_gen += 1
                self._conn_broken = False
                self._cv.notify_all()
            self._hb_last = time.monotonic()
            self._reconnects += 1  # bfverify: shared-ok written only by the sender thread; readers take a GIL-atomic int snapshot
            _mt.inc("bf_reconnects_total", 1.0, peer=self._peer)
            if replayed:
                _mt.inc("bf_replayed_batches_total", float(replayed),
                        peer=self._peer)
            _bb.record("tcp_reconnected", peer=self._peer,
                       epoch=self._epoch, replayed=replayed)
            if h is not None:
                h.note_ok()
            return True
        if h is not None:
            h.mark_dead("reconnect budget exhausted")
        self._fail(f"peer unreachable ({reason}); reconnect budget "
                   f"exhausted after {bo.attempts} attempt(s)")
        return False

    def _heartbeat(self) -> bool:
        """Probe an idle stream's liveness (HEARTBEAT wire op); the reply
        rides the ack channel with the high bit set."""
        self._hb_seq = (self._hb_seq + 1) & 0x7FFF_FFFF
        seq = self._hb_seq
        self._hb_sent[seq] = time.perf_counter()
        while len(self._hb_sent) > 64:
            self._hb_sent.pop(next(iter(self._hb_sent)))
        self._hb_last = time.monotonic()
        views: List = [_HDR.pack(_MAGIC, _OP_HEARTBEAT, 0), _HB.pack(seq)]
        if self._trace_on:
            # HEARTBEAT is a traced op: the header rides along (empty —
            # an idle probe has no active span) so the server's frame
            # parse stays deterministic per connection
            views.insert(1, _TRACE_HDR.pack(0, 0, 0))
        try:
            _sendmsg_all(self._sock, views)
        except (OSError, ConnectionError) as e:
            if self._resume:
                return self._recover(f"heartbeat send failed: {e}")
            self._fail(f"heartbeat send failed: {e}")
            return False
        return True

    # ------------------------------------------------------------ producer
    def _take(self, dtype: np.dtype, n: int) -> np.ndarray:
        key = (_DTYPE_IDS[dtype], n)
        free = self._pool.get(key)
        if free:
            return free.pop()
        return np.empty(n, dtype)

    def _give(self, arr: np.ndarray) -> None:
        key = (_DTYPE_IDS[arr.dtype], arr.size)
        free = self._pool.setdefault(key, [])
        if len(free) < self._max_in_flight * 2 + 2:
            free.append(arr)

    def _raise_if_err(self) -> None:
        if self._err is not None:  # bfverify: shared-ok latch-once str ref; _fail() writes under _cv, a GIL-atomic read here can only be early, never torn
            raise RuntimeError(
                f"pipelined deposits to {self._peer} failed: {self._err}")

    # ------------------------------------------------------ wire telemetry
    def _note_latency(self, seconds: float) -> None:
        prev = self._ack_ewma
        a = self._ack_ewma_alpha
        ewma = seconds if prev is None else (a * seconds + (1.0 - a) * prev)
        self._ack_ewma = ewma  # bfverify: shared-ok single float-ref store, atomic under the GIL; only the ack thread writes
        if self._on_ewma is not None:
            self._on_ewma(ewma)
        else:
            _mt.set("bf_peer_ack_ewma_seconds", ewma, peer=self._peer)

    def ack_ewma(self) -> Optional[float]:
        """EWMA (seconds) over this peer's deposit-ack latencies and
        heartbeat RTTs — the programmatic slow-peer signal (the gauge
        twin is ``bf_peer_ack_ewma_seconds{peer=}``).  None until the
        first ack/heartbeat reply arrives."""
        return self._ack_ewma

    def _note_phases(self, wsp, times, lat: float, seq: int) -> None:
        """Ack-thread bookkeeping for one traced batch: finish the wire
        span (folding in the owner-side ``queue_s``/``apply_s`` the
        extended ack carried), emit the ``ack_wait`` child span, and
        fold the (net, queue, apply) split into the per-peer phase EWMA
        the control plane reads through :meth:`phase_ewma`."""
        extra = {}
        if times is not None:
            queue_s, apply_s = times[0] / 1e6, times[1] / 1e6
            extra = {"queue_s": queue_s, "apply_s": apply_s}
            net = max(0.0, lat - queue_s - apply_s)
            prev = self._phase_ewma
            a = self._ack_ewma_alpha
            if prev is None:
                self._phase_ewma = (net, queue_s, apply_s)  # bfverify: shared-ok single tuple-ref store, atomic under the GIL; only the ack thread writes
            else:
                self._phase_ewma = (
                    a * net + (1.0 - a) * prev[0],
                    a * queue_s + (1.0 - a) * prev[1],
                    a * apply_s + (1.0 - a) * prev[2])
        trec = _tr.get()
        if trec is not None:
            # send_s may not be written yet when the ack beat the
            # sender's post-sendall bookkeeping (see the benign-race
            # note at the write site); clamp into [0, lat]
            send_s = min(lat, float(wsp.fields.get("send_s", 0.0)
                                    or 0.0))
            trec.emit("ack_wait", "tcp", t0=wsp.t0 + send_s,
                      dur=max(0.0, lat - send_s), parent=wsp.sid,
                      round_=wsp.round, trace_id=wsp.tid,
                      peer=self._peer, seq=seq)
        wsp.finish(**extra)

    def phase_ewma(self) -> Optional[Dict[str, float]]:
        """Per-peer wire-phase decomposition EWMA: ``{"net": s,
        "queue": s, "apply": s}`` splitting this peer's ack latency into
        network+frontend residue vs owner-side queue-wait vs apply —
        the slow-link-vs-slow-host evidence
        (:class:`bluefog_tpu.control.evidence.Evidence` ``phase_s``).
        None until a FEATURE_TRACE connection delivered a timed ack."""
        p = self._phase_ewma
        if p is None:
            return None
        return {"net": p[0], "queue": p[1], "apply": p[2]}

    @property
    def reconnects(self) -> int:
        """Completed reconnect+replay cycles on this stream (the
        programmatic twin of ``bf_reconnects_total{peer=}`` — lossy-link
        evidence for the communication controller)."""
        return self._reconnects

    def set_codec(self, codec: Optional[str]) -> None:
        """Retune wire-compression aggressiveness at a ROUND BOUNDARY:
        subsequent :meth:`deposit_async` calls encode with ``codec``
        (``None``/``"none"``, ``"f32"``, ``"topk"``).  The stream
        negotiates feature bits for its CONSTRUCTION codec and every
        less aggressive one at HELLO, so the controller can step
        anywhere at or below that ceiling — but never above it (the
        server was never asked for the capability; open the stream with
        the most aggressive codec the run may ever use and back OFF
        from there).  Call from the producer thread only (the same
        thread that deposits), so no in-flight item changes encoding
        under its ack."""
        want = wire_codec.CODEC_IDS[codec or "none"]
        if want > self._codec_max:
            raise ValueError(
                f"codec {codec!r} exceeds the ceiling negotiated at "
                f"connect ({wire_codec.CODEC_NAMES[self._codec_max]!r}); "
                "open the stream with the most aggressive codec the run "
                "may ever use — the controller backs OFF from there")
        self._codec = want

    def set_max_batch_bytes(self, n: int) -> None:
        """Retune the coalescing cap at a ROUND BOUNDARY (the autotune
        knob: smaller frames = more frames in flight = deeper pipeline;
        larger frames = fewer acks).  A single int store read by the
        sender thread at its next coalesce — call from the producer
        thread, ideally fenced, like :meth:`set_codec`."""
        self._max_batch_bytes = max(1 << 16, int(n))

    # ----------------------------------------------------- shm fast path
    def _shm_window(self, name: bytes):
        """Resolve the shm route for one window name, caching the
        verdict: an attached/local window handle, or None (permanent TCP
        for this name).  The FIRST attach failure latches shm off for
        the whole stream — windows of one owner are homogeneous, and a
        per-name probe against a non-shm owner would pay the attach
        timeout once per leaf."""
        if name in self._shm_wins:
            return self._shm_wins[name]
        win = None
        try:
            from bluefog_tpu.runtime.async_windows import AsyncWindow
            try:
                win = AsyncWindow(name.decode(), attach=True,
                                  attach_timeout_s=1.0)
            except ValueError:
                # already open in THIS process: owner and depositor
                # share a table — deposit through it directly
                win = _LocalWindowRef(name.decode())
        except Exception as e:  # noqa: BLE001 — any capability failure
            # (no native runtime, owner's windows not shm-backed, stale
            # geometry) means TCP, never an error
            self._shm_ok = False
            _bb.record("shm_fallback", peer=self._peer,
                       window=name.decode("utf-8", "replace"),
                       reason=f"{type(e).__name__}: {e}"[:200])
        self._shm_wins[name] = win
        return win

    def _try_shm_deposit(self, name: bytes, slot: int, arr: np.ndarray,
                         *, accumulate: bool, drain: bool) -> bool:
        """Apply one deposit through the same-host shm table.  True =
        applied (exactly once, atomically under the window mutex);
        False = route this deposit over TCP instead.  A chaos 'client'
        fault or a real shm failure here models the TORN-WRITE case:
        the fault fires BEFORE the atomic table accumulate, so a torn
        shm write is never half-applied — it is absent, and recovery is
        re-delivery over the TCP wire (still exactly once)."""
        win = self._shm_window(name)
        if win is None:
            return False
        if win.dtype != arr.dtype or win.n_elems != arr.size:
            # geometry mismatch: the wire path's per-item dtype/size
            # negotiation handles it; the table route cannot
            self._shm_wins[name] = None
            _bb.record("shm_fallback", peer=self._peer,
                       window=name.decode("utf-8", "replace"),
                       reason="dtype/size mismatch")
            return False
        act = _chaos.fire("client", peer=self._peer, seq=-1, shm=1)
        if act is not None:
            if act[0] in ("delay", "stall"):
                time.sleep(act[1])
            else:  # drop/truncate: the shm write tore before the flip
                self._shm_wins[name] = None
                _bb.record("shm_fallback", peer=self._peer,
                           window=name.decode("utf-8", "replace"),
                           reason=f"chaos:{act[0]}")
                return False
        try:
            win.deposit_async(slot, arr, accumulate=accumulate,
                              drain=drain)
        except Exception as e:  # noqa: BLE001 — fall back, exactly once:
            # the native deposit applies fully or returns an error
            self._shm_wins[name] = None
            _bb.record("shm_fallback", peer=self._peer,
                       window=name.decode("utf-8", "replace"),
                       reason=f"{type(e).__name__}: {e}"[:200])
            return False
        self._shm_deposits += 1
        _mt.inc("bf_shm_deposits_total", 1.0, peer=self._peer)
        return True

    @property
    def shm_deposits(self) -> int:
        """Deposits this stream routed through the same-host shm table
        (the programmatic twin of ``bf_shm_deposits_total{peer=}``)."""
        return self._shm_deposits

    def deposit_async(self, name: bytes, slot: int, arr: np.ndarray, *,
                      accumulate: bool = True, copy: bool = True,
                      drain: bool = False) -> None:
        """Enqueue one deposit into the peer's window ``name`` (bytes);
        returns immediately.  ``copy=True`` (default) snapshots ``arr``
        into a pooled buffer so the caller may overwrite it right away;
        pass ``copy=False`` only when the buffer is immutable until
        :meth:`flush` returns.  ``drain=True`` marks the deposit as a
        graceful leaver's final mass handoff (wire flag bit2 — the owner
        records it for the membership audit; the value semantics are
        unchanged).  Errors (including those from earlier
        fire-and-forget deposits) raise here or at flush."""
        a = np.ascontiguousarray(arr)
        if a.dtype not in _DTYPE_IDS:
            raise TypeError(
                f"pipelined deposits support f32/f64, got {a.dtype}")
        a = a.reshape(-1)
        self._raise_if_err()
        if self._shm_ok and self._try_shm_deposit(
                name, slot, a, accumulate=accumulate, drain=drain):
            return  # applied: nothing in flight, nothing to fence
        # tracing: capture the CALLER's active span context here, on the
        # producer thread — round/parentage then ride the item into the
        # sender thread and onto the wire with zero API churn
        trec = _tr.get()
        tctx = _tr.current_ctx() if trec is not None else None
        t_snap_w = time.time() if trec is not None else 0.0
        t_snap_p = time.perf_counter() if trec is not None else 0.0
        dense_bytes = a.nbytes
        pooled = None
        if self._codec == wire_codec.CODEC_NONE:
            if copy:
                pooled = self._take(a.dtype, a.size)
                np.copyto(pooled, a)
                a = pooled
            views = [memoryview(a).cast("B")]
            wire = dense_bytes
        else:
            # lossy codecs allocate fresh wire arrays; the source is free
            views, wire = wire_codec.encode(
                a, self._codec, topk_ratio=self._topk_ratio)
        flags = (_FLAG_ACCUMULATE if accumulate else 0) | (
            _FLAG_DRAIN if drain else 0)
        item = _Item(name, slot, flags,
                     _DTYPE_IDS[a.dtype], self._codec, a.size, views,
                     wire, dense_bytes, pooled, tctx=tctx)
        if trec is not None:
            item.t_enq = time.perf_counter()
            trec.emit("snapshot", "tcp", t0=t_snap_w,
                      dur=item.t_enq - t_snap_p,
                      parent=tctx[1] if tctx else None,
                      round_=tctx[2] if tctx else None,
                      trace_id=tctx[0] if tctx else None,
                      peer=self._peer, bytes=wire)
        t0 = time.perf_counter()
        with self._cv:
            while (len(self._queue) >= self._max_queue
                   and self._err is None and not self._closed):
                self._cv.wait(timeout=1.0)
            self._raise_if_err()
            if self._closed:
                raise RuntimeError(
                    f"DepositStream to {self._peer} is closed")
            self._queue.append(item)
            self._cv.notify_all()
        stalled = time.perf_counter() - t0
        if trec is not None:
            # the enqueue phase: zero when the queue had room, the
            # backpressure wait when it did not — the FIRST place a slow
            # peer steals training-thread time, so it gets its own span
            trec.emit("enqueue", "tcp", t0=time.time() - stalled,
                      dur=stalled,
                      parent=tctx[1] if tctx else None,
                      round_=tctx[2] if tctx else None,
                      trace_id=tctx[0] if tctx else None,
                      peer=self._peer)
        if stalled > 0.005:
            # backpressure made the TRAINING thread wait: that is exactly
            # the signal a wedged/slow peer gives first — record it where
            # forensics will look
            _mt.inc("bf_tcp_queue_stalls_total", 1.0, peer=self._peer)
            _bb.record("tcp_queue_stall", peer=self._peer,
                       waited_s=round(stalled, 6))

    def flush(self, timeout_s: Optional[float] = None) -> None:
        """Fence: block until every enqueued deposit is acknowledged as
        APPLIED by the serving host (or raise the transport error).  After
        ``flush`` returns, an owner-side read observes all of this
        handle's prior deposits — the pipelined path's replacement for the
        per-deposit round-trip the synchronous client pays."""
        self._flushes += 1
        key = (self._peer, self._flushes)
        _bb.begin("tcp_flush", key=key, peer=self._peer)
        t0 = time.perf_counter()
        with self._cv:
            ok = self._cv.wait_for(
                lambda: self._err is not None or (
                    not self._queue and not self._inflight),
                timeout=timeout_s)
        waited = time.perf_counter() - t0
        _bb.end("tcp_flush", key=key, peer=self._peer,
                waited_s=round(waited, 6))
        _mt.observe("bf_tcp_flush_seconds", waited, peer=self._peer)
        self._raise_if_err()
        if not ok:
            raise TimeoutError(
                f"flush of pipelined deposits to {self._peer} timed out "
                f"after {timeout_s}s ({len(self._queue)} queued, "
                f"{len(self._inflight)} in flight)")

    # ------------------------------------------------------------- threads
    def _send_loop(self) -> None:
        # idle polling only exists for the resilience features: without
        # them the wait is unbounded, exactly the pre-resilience shape
        poll = None
        if self._hb_interval > 0:
            poll = min(self._hb_interval / 2.0, 1.0)
        elif self.health is not None:
            poll = 1.0
        try:
            while True:
                with self._cv:
                    self._cv.wait_for(
                        lambda: self._queue or self._closed
                        or self._err is not None or self._conn_broken,
                        timeout=poll)
                    if self._err is not None:
                        return
                    broken = self._conn_broken
                    if not self._queue and not broken:
                        if self._closed:
                            return
                        idle = True
                    else:
                        idle = False
                if broken:
                    # the ack reader saw the connection die first
                    if not self._recover("connection lost"):
                        return
                    continue
                if idle:
                    if self.health is not None:
                        self.health.poll()
                    if (self._hb_interval > 0 and
                            time.monotonic() - self._hb_last
                            >= self._hb_interval):
                        if not self._heartbeat():
                            return
                    continue
                with self._cv:
                    t0 = time.perf_counter()
                    while (len(self._inflight) >= self._max_in_flight
                           and self._err is None and not self._closed
                           and not self._conn_broken):
                        self._cv.wait(timeout=1.0)
                        if self.health is not None:
                            self.health.poll()
                    if self._err is not None:
                        return
                    if self._conn_broken:
                        continue  # the outer loop recovers first
                    stalled = time.perf_counter() - t0
                    items = []
                    nbytes = 0
                    while self._queue and (
                            not items
                            or nbytes < self._max_batch_bytes):
                        it = self._queue.popleft()
                        items.append(it)
                        nbytes += it.wire_bytes
                    self._seq += 1
                    seq = self._seq
                    wire_total = sum(i.wire_bytes for i in items)
                    dense_total = sum(i.dense_bytes for i in items)
                    wsp = None
                    trec = _tr.get() if self._trace_on else None
                    if trec is not None:
                        # the wire span: begun HERE on the sender thread,
                        # finished by the ack reader when the owner's ack
                        # lands — its sid is what the trace header
                        # carries, so the owner-side recv/queue/apply/ack
                        # spans parent to it across the rank boundary
                        ictx = next((i.tctx for i in items
                                     if i.tctx is not None), None)
                        t_oldest = min(i.t_enq for i in items)
                        wsp = trec.begin_span(  # bftrace: cross-thread ack reader finishes it; an unacked batch must show an OPEN wire span
                            "wire", "tcp",
                            parent=ictx[1] if ictx else None,
                            round_=ictx[2] if ictx else None,
                            trace_id=ictx[0] if ictx else None,
                            peer=self._peer, seq=seq, items=len(items),
                            bytes=wire_total,
                            dst=items[0].name_b.decode("utf-8", "replace"))
                        trec.emit("coalesce", "tcp",
                                  t0=time.time() -
                                  (time.perf_counter() - t_oldest),
                                  dur=time.perf_counter() - t_oldest,
                                  parent=wsp.sid, round_=wsp.round,
                                  trace_id=wsp.tid, peer=self._peer,
                                  seq=seq, items=len(items))
                    # items are retained until the ack when reconnect is
                    # on: they ARE the replay window
                    self._inflight[seq] = (
                        time.perf_counter(),
                        items if self._resume else None,
                        len(items), wire_total, dense_total, wsp)
                    self._cv.notify_all()
                if stalled > 0.005:
                    _mt.inc("bf_tcp_window_stalls_total", 1.0,
                            peer=self._peer)
                    _bb.record("tcp_window_stall", peer=self._peer,
                               waited_s=round(stalled, 6))
                views = self._frame_views(
                    seq, items, wsp.ctx if wsp is not None else None)
                t_send0 = time.perf_counter()
                try:
                    act = _chaos.fire("client", peer=self._peer, seq=seq)
                    if act is not None:
                        if act[0] in ("delay", "stall"):
                            time.sleep(act[1])
                        elif act[0] == "truncate":
                            # a TORN frame on the wire, then the cut: the
                            # server must discard the partial batch and
                            # the replay must deliver it exactly once
                            _sendmsg_all(self._sock,
                                         views[:max(2, len(views) // 2)])
                            raise ConnectionError("chaos: truncated frame")
                        elif act[0] == "drop":
                            raise ConnectionError("chaos: dropped "
                                                  "connection")
                    _sendmsg_all(self._sock, views)
                except (OSError, ConnectionError) as e:
                    if self._resume:
                        if self._recover(
                                f"send failed: {type(e).__name__}: {e}"):
                            continue
                        return  # _recover latched the terminal error
                    raise
                if wsp is not None:
                    # socket-buffer occupancy of this frame: lets the
                    # ack reader split the wire span into send vs
                    # ack_wait.  BENIGN RACE: the server can ack while
                    # sendall's final syscall is still returning, so
                    # the ack reader may observe this field as absent
                    # (it then folds the whole latency into ack_wait —
                    # a sub-microsecond mis-split on loopback, never a
                    # crash; _note_phases clamps)
                    wsp.fields["send_s"] = round(
                        time.perf_counter() - t_send0, 9)
                if not self._resume:
                    # without a replay window the snapshots are recycled
                    # as soon as the kernel took them (pre-resilience
                    # memory profile); with one, the ack reader recycles
                    with self._cv:
                        for it in items:
                            if it.pooled is not None:
                                self._give(it.pooled)
                _mt.inc("bf_tcp_pipelined_batches_total", 1.0,
                        peer=self._peer)
                _mt.inc("bf_tcp_pipelined_items_total", float(len(items)),
                        peer=self._peer)
                _mt.inc("bf_tcp_wire_bytes_total", wire_total,
                        peer=self._peer,
                        codec=wire_codec.CODEC_NAMES[self._codec])
                _mt.inc("bf_tcp_dense_bytes_total", dense_total,
                        peer=self._peer)
                _mt.set("bf_tcp_inflight_batches",
                        float(len(self._inflight)), peer=self._peer)
                if dense_total and self._codec != wire_codec.CODEC_NONE:
                    _mt.set(
                        "bf_compression_ratio", wire_total / dense_total,
                        compressor="wire_"
                        + wire_codec.CODEC_NAMES[self._codec],
                        transport="tcp")
        except Exception as e:  # noqa: BLE001 — NOTHING may kill the
            # sender silently: a dead sender with _err unset means every
            # later flush() blocks forever at the audit fence with no
            # diagnostic (struct.error from an out-of-range slot is just
            # as fatal to the stream as a socket error)
            self._fail(f"send failed: {type(e).__name__}: {e}")

    def _ack_loop(self) -> None:
        buf = bytearray(_ACK.size)
        mv = memoryview(buf)
        tbuf = bytearray(_ACK_TIMES.size)
        tmv = memoryview(tbuf)
        while True:
            with self._cv:
                sock = self._sock
                gen = self._sock_gen
                # per-connection negotiation decides the ack frame size;
                # snapshot it WITH the socket so a reconnect cannot
                # desync this reader's framing mid-generation
                t_on = self._trace_on
            try:
                _recv_into(sock, mv)
                seq, status = _ACK.unpack(buf)
                times = None
                if t_on and not seq & _HB_MARK:
                    # batch acks on FEATURE_TRACE connections carry the
                    # owner-side (queue_us, apply_us) tail — heartbeat
                    # acks never do (they keep the bit31 mark alone)
                    _recv_into(sock, tmv)
                    times = _ACK_TIMES.unpack(tbuf)
            except (OSError, ConnectionError, ValueError):
                if self._closed:
                    return
                if self._resume:
                    # flag the outage and wait for the sender to swap in
                    # a reconnected socket (or give up); only the CURRENT
                    # generation's failure counts — a socket the sender
                    # already replaced is stale news
                    with self._cv:
                        if self._sock_gen == gen:
                            self._conn_broken = True
                        self._cv.notify_all()
                        self._cv.wait_for(
                            lambda: self._sock_gen != gen or self._closed
                            or self._err is not None)
                        if self._closed or self._err is not None:
                            return
                    continue
                self._fail("connection lost before all deposits "
                           "were acknowledged")
                return
            if seq & _HB_MARK:
                t0 = self._hb_sent.pop(seq & ~_HB_MARK, None)
                if t0 is not None:
                    rtt = time.perf_counter() - t0
                    _mt.observe("bf_peer_heartbeat_rtt_seconds",
                                rtt, peer=self._peer)
                    self._note_latency(rtt)
                if self.health is not None:
                    self.health.note_ok()
                continue
            with self._cv:
                entry = self._inflight.pop(seq, None)
                if entry is not None:
                    for it in entry[1] or ():
                        if it.pooled is not None:
                            self._give(it.pooled)
                self._cv.notify_all()
            if entry is not None:
                lat = time.perf_counter() - entry[0]
                wsp = entry[5]
                if wsp is not None:
                    self._note_phases(wsp, times, lat, seq)
                self.ack_latencies.append(lat)
                self._note_latency(lat)
                _mt.observe("bf_tcp_ack_latency_seconds", lat,
                            peer=self._peer)
                _mt.set("bf_tcp_inflight_batches",
                        float(len(self._inflight)), peer=self._peer)
            if status < 0:
                self._fail(f"peer rejected a batched deposit ({status}): "
                           + _err_text(int(status)))
                return
            if self.health is not None:
                self.health.note_ok()

    def _fail(self, msg: str) -> None:
        with self._cv:
            if self._err is None:
                self._err = msg
            self._queue.clear()
            self._cv.notify_all()
        _bb.record("tcp_pipeline_error", peer=self._peer, error=msg)

    def close(self) -> None:
        """Close the stream.  Does NOT flush: callers owning an exactness
        invariant must :meth:`flush` first (the BF-WIN lint enforces this
        for the dsgd loops)."""
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        self._wake.set()  # interrupt a mid-backoff reconnect sleep
        self._sender.join(timeout=5)
        # read the socket under the lock: a reconnect mid-close must not
        # swap in a fresh socket between our read and our close (the
        # _recover() side refuses the swap once _closed is set)
        with self._cv:
            sock = self._sock
        # shutdown BEFORE close: closing an fd does not wake a thread
        # blocked in recv() on it, so without this the acker sits in
        # recv until the join times out (5 s per stream — N stripes pay
        # it N times over)
        try:
            sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            sock.close()
        except OSError:
            pass
        self._acker.join(timeout=5)


def stripe_of(name: bytes, n_stripes: int) -> int:
    """The stable stripe routing function: crc32 of the window name
    modulo the active stripe count.  Deterministic across processes and
    runs (no Python hash randomization), so the sharded path's
    per-coordinate ``name:r:ci`` windows spread over stripes the same
    way on every rank."""
    return zlib.crc32(name) % max(1, int(n_stripes))


class StripedDepositStream:
    """N parallel :class:`DepositStream` connections to ONE peer, striped
    by window name — the line-rate DCN shape: one TCP stream serializes
    every frame through one sender thread and one server-side applier,
    while N stripes give the peer N senders, N connections, and N
    concurrent appliers.  The sharded path's per-coordinate
    ``name:r:ci`` windows are the natural stripe unit (:func:`stripe_of`
    spreads coordinates deterministically); a dense run's per-leaf
    window names spread the same way.

    Duck-types the :class:`DepositStream` surface
    (``deposit_async``/``flush``/``close``/``ack_ewma``/``phase_ewma``/
    ``health``/``reconnects``/``set_codec``), so it drops into
    ``PipelinedRemoteWindow(stream=...)`` unchanged.  Routing is sticky
    per window name at a given stripe count, so one window's deposits
    never reorder; :meth:`flush` fences EVERY stripe, preserving the
    round-boundary audit discipline.

    The stripe count and per-stripe coalescing cap are the autotuner's
    knobs: :meth:`apply_plan` actuates a
    :class:`~bluefog_tpu.control.transport.TransportPlan` at a ROUND
    BOUNDARY (the BF-CTL001 lint holds call sites to round-boundary
    vocabulary, like every other plan).  Growing opens fresh stripes
    (``stripe_open`` blackbox event); shrinking fences the closing
    stripes first, so no deposit strands (``stripe_close``).

    Per-stripe ack-latency EWMAs roll up into the ONE existing
    ``bf_peer_ack_ewma_seconds{peer=}`` gauge as the max over live
    stripes — the slow-peer detector (PR 8) keeps working unchanged:
    a peer is as slow as its slowest stripe.  NOT thread-safe for
    concurrent producers (same contract as one stream)."""

    def __init__(self, address: Tuple[str, int],
                 timeout_s: float = 30.0, *, n_stripes: int = 2,
                 max_stripes: int = 16, **stream_kwargs):
        if "on_ewma" in stream_kwargs:
            raise ValueError("on_ewma is owned by the striping rollup")
        self._addr = (address[0], int(address[1]))
        self._peer = f"{address[0]}:{address[1]}"
        self._timeout_s = float(timeout_s)
        self._kw = dict(stream_kwargs)
        self._max_stripes = max(1, int(max_stripes))
        self._plan_version = 0
        # written by each stripe's ack thread, read anywhere: per-slot
        # float stores + a max over a snapshot — GIL-atomic, worst case
        # a reader sees a value one update stale
        self._ewmas: List[Optional[float]] = []
        self._ack_ewma: Optional[float] = None
        self._stripes: List[DepositStream] = []
        try:
            for _ in range(max(1, min(int(n_stripes), self._max_stripes))):
                self._open_stripe()
        except BaseException:
            self.close()
            raise

    # ----------------------------------------------------- stripe pool
    def _open_stripe(self) -> None:
        i = len(self._stripes)
        self._ewmas.append(None)
        self._stripes.append(DepositStream(
            self._addr, self._timeout_s,
            on_ewma=(lambda e, i=i: self._roll_up(i, e)), **self._kw))
        _bb.record("stripe_open", peer=self._peer, stripe=i)
        _mt.set("bf_stripe_streams", float(len(self._stripes)),
                peer=self._peer)

    def _roll_up(self, i: int, ewma: float) -> None:
        # max-of-stripes: the peer's effective ack latency is its
        # slowest stripe's — an optimistic mean would hide exactly the
        # stripe a slow-peer detector needs to see
        self._ewmas[i] = ewma
        vals = [v for v in self._ewmas[:len(self._stripes)]
                if v is not None]
        if vals:
            mx = max(vals)
            self._ack_ewma = mx  # bfverify: shared-ok single float-ref store under the GIL; ack threads race benignly (last writer wins a max over the same snapshot)
            _mt.set("bf_peer_ack_ewma_seconds", mx, peer=self._peer)

    @property
    def n_stripes(self) -> int:
        """Live stripe connections (gauge twin:
        ``bf_stripe_streams{peer=}``)."""
        return len(self._stripes)

    def apply_plan(self, plan) -> None:
        """Actuate a :class:`~bluefog_tpu.control.transport.
        TransportPlan` at a ROUND BOUNDARY: resize the stripe pool and
        retune every stripe's coalescing cap.  Shrinking fences the
        closing stripes before closing them, so actuation never strands
        a deposit — the exact-mass audit holds through every retune."""
        want = max(1, min(int(plan.stripes), self._max_stripes))
        while len(self._stripes) < want:
            self._open_stripe()
        if want < len(self._stripes):
            for s in self._stripes[want:]:
                s.flush()
            for i in range(len(self._stripes) - 1, want - 1, -1):
                self._stripes[i].close()
                self._ewmas[i] = None
                _bb.record("stripe_close", peer=self._peer, stripe=i)
            del self._stripes[want:]
            _mt.set("bf_stripe_streams", float(len(self._stripes)),
                    peer=self._peer)
        for s in self._stripes:
            s.set_max_batch_bytes(plan.coalesce_bytes)
        self._plan_version = int(plan.version)

    @property
    def plan_version(self) -> int:
        """Version of the TransportPlan last actuated (0 = launch)."""
        return self._plan_version

    # ------------------------------------------- DepositStream surface
    def deposit_async(self, name: bytes, slot: int, arr: np.ndarray, *,
                      accumulate: bool = True, copy: bool = True,
                      drain: bool = False) -> None:
        self._stripes[stripe_of(name, len(self._stripes))].deposit_async(
            name, slot, arr, accumulate=accumulate, copy=copy,
            drain=drain)

    def flush(self, timeout_s: Optional[float] = None) -> None:
        """Fence across ALL stripes: every prior deposit on every stripe
        is applied when this returns — the audit sees one quiesced peer,
        however many connections carried it."""
        for s in self._stripes:
            s.flush(timeout_s)

    def set_codec(self, codec: Optional[str]) -> None:
        for s in self._stripes:
            s.set_codec(codec)

    def set_max_batch_bytes(self, n: int) -> None:
        for s in self._stripes:
            s.set_max_batch_bytes(n)

    def ack_ewma(self) -> Optional[float]:
        """Max-of-stripes ack-latency EWMA (see class docstring)."""
        return self._ack_ewma

    def phase_ewma(self) -> Optional[Dict[str, float]]:
        """Elementwise MAX over stripes' {net, queue, apply} EWMAs —
        conservative: the phase split of the peer's worst case."""
        out: Optional[Dict[str, float]] = None
        for s in self._stripes:
            p = s.phase_ewma()
            if p is None:
                continue
            if out is None:
                out = dict(p)
            else:
                for k, v in p.items():
                    out[k] = max(out[k], v)
        return out

    @property
    def health(self):
        """Peer health of stripe 0 (all stripes share the peer; one
        health machine is the peer's — extra stripes carry payload,
        not liveness)."""
        return self._stripes[0].health if self._stripes else None

    @property
    def reconnects(self) -> int:
        """Sum of completed reconnect cycles across stripes."""
        return sum(s.reconnects for s in self._stripes)

    @property
    def shm_deposits(self) -> int:
        return sum(s.shm_deposits for s in self._stripes)

    @property
    def ack_latencies(self):
        """Stripe 0's recent ack latencies (bench/observability parity;
        per-stripe deques stay accessible via the stripes themselves)."""
        return self._stripes[0].ack_latencies

    def close(self) -> None:
        """Close every stripe.  Does NOT flush — fence first when
        exactness matters (same contract as one stream)."""
        for i in range(len(self._stripes) - 1, -1, -1):
            try:
                self._stripes[i].close()
            finally:
                _bb.record("stripe_close", peer=self._peer, stripe=i)
        self._stripes = []
        _mt.set("bf_stripe_streams", 0.0, peer=self._peer)


class PipelinedRemoteWindow:
    """Per-window client handle over a per-peer :class:`DepositStream`:
    fire-and-forget :meth:`deposit_async` + :meth:`flush` fence, with
    synchronous ops (:meth:`read`, :meth:`read_self`, :meth:`deposit`)
    riding a separate request/response connection so they never interleave
    with the deposit stream's framing.

    ``stream=`` shares an existing peer stream across several windows of
    the SAME peer (a round's leaves then coalesce into one wire frame —
    the batched multi-deposit op; :func:`DepositStream.flush` on the
    shared stream fences all of them at once).  Without it the handle owns
    a private stream and :meth:`close` tears it down."""

    def __init__(self, address: Tuple[str, int], name: str,
                 timeout_s: float = 30.0, *, codec: Optional[str] = None,
                 topk_ratio: Optional[float] = None,
                 max_in_flight: Optional[int] = None,
                 max_queue_items: Optional[int] = None,
                 max_batch_bytes: Optional[int] = None,
                 reconnect=None,
                 heartbeat_interval_s: Optional[float] = None,
                 suspect_after_s: Optional[float] = None,
                 dead_after_s: Optional[float] = None,
                 shm: Optional[bool] = None,
                 stream: Optional[DepositStream] = None,
                 sync_retry=None):
        """``sync_retry`` configures the SYNC connection's bounded
        retry for idempotent reads (see :class:`RemoteWindow`); it is
        independent of ``stream=`` because every handle owns its sync
        connection even when the deposit stream is shared.  ``shm=True``
        opts the owned stream into the same-host shared-memory fast
        path (see :class:`DepositStream`)."""
        self.name = name
        self._name_b = name.encode()
        if stream is not None and any(
                v is not None for v in (codec, topk_ratio, max_in_flight,
                                        max_queue_items, max_batch_bytes,
                                        reconnect,
                                        heartbeat_interval_s,
                                        suspect_after_s, dead_after_s,
                                        shm)):
            # a shared stream carries ITS configuration; accepting these
            # kwargs here would silently ignore them (e.g. codec='f32'
            # riding an uncompressed stream)
            raise ValueError(
                "stream= is mutually exclusive with codec/topk_ratio/"
                "max_in_flight/max_queue_items/max_batch_bytes/reconnect/"
                "heartbeat_interval_s/suspect_after_s/dead_after_s/shm — "
                "configure the shared DepositStream itself")
        self._sync = RemoteWindow(address, name, timeout_s,
                                  retry=sync_retry)
        self._owns_stream = stream is None
        if stream is not None:
            self.stream = stream
            return
        try:
            self.stream = DepositStream(
                address, timeout_s, codec=codec,
                topk_ratio=0.1 if topk_ratio is None else topk_ratio,
                max_in_flight=4 if max_in_flight is None else max_in_flight,
                max_queue_items=(1024 if max_queue_items is None
                                 else max_queue_items),
                max_batch_bytes=(16 << 20 if max_batch_bytes is None
                                 else max_batch_bytes),
                reconnect=reconnect,
                heartbeat_interval_s=(0.0 if heartbeat_interval_s is None
                                      else heartbeat_interval_s),
                suspect_after_s=(2.0 if suspect_after_s is None
                                 else suspect_after_s),
                dead_after_s=(20.0 if dead_after_s is None
                              else dead_after_s),
                shm=bool(shm))
        except BaseException:
            # a rejected handshake (version/feature) must not leak the
            # already-open sync connection and its server handler thread
            self._sync.close()
            raise

    @property
    def health(self):
        """Per-peer :class:`~bluefog_tpu.runtime.resilience.PeerHealth`
        of the underlying stream (None when resilience is off)."""
        return self.stream.health

    @property
    def ack_latencies(self):
        return self.stream.ack_latencies

    def ack_ewma(self) -> Optional[float]:
        """The stream's per-peer ack-latency EWMA (seconds; None before
        the first ack) — see :meth:`DepositStream.ack_ewma`."""
        return self.stream.ack_ewma()

    def phase_ewma(self) -> Optional[Dict[str, float]]:
        """The stream's per-peer wire-phase EWMA (net/queue/apply; None
        until a timed ack) — see :meth:`DepositStream.phase_ewma`."""
        return self.stream.phase_ewma()

    @property
    def reconnects(self) -> int:
        """Completed reconnect+replay cycles on the underlying stream."""
        return self.stream.reconnects

    def set_codec(self, codec: Optional[str]) -> None:
        """Retune the stream's wire-codec aggressiveness (round-boundary
        actuation; see :meth:`DepositStream.set_codec`)."""
        self.stream.set_codec(codec)

    def deposit_async(self, slot: int, arr: np.ndarray, *,
                      accumulate: bool = True, copy: bool = True,
                      drain: bool = False) -> None:
        """Fire-and-forget deposit (see :meth:`DepositStream.
        deposit_async`); fence with :meth:`flush`."""
        self.stream.deposit_async(self._name_b, slot, arr,
                                  accumulate=accumulate, copy=copy,
                                  drain=drain)

    def flush(self, timeout_s: Optional[float] = None) -> None:
        """Fence: every prior :meth:`deposit_async` is applied on the
        owner when this returns.  On a shared stream this fences the whole
        peer (all windows), which is what the dsgd audit needs."""
        self.stream.flush(timeout_s)

    def deposit(self, slot: int, arr: np.ndarray, *,
                accumulate: bool = True) -> int:
        """Synchronous deposit (own round-trip; callers needing ordering
        vs the async stream must flush first)."""
        return self._sync.deposit(slot, arr, accumulate=accumulate)

    def read(self, slot: int, n_elems: int, dtype=np.float64, *,
             consume: bool = True) -> Tuple[np.ndarray, int]:
        return self._sync.read(slot, n_elems, dtype, consume=consume)

    def read_self(self, n_elems: int, dtype=np.float64) -> np.ndarray:
        return self._sync.read_self(n_elems, dtype)

    def close(self) -> None:
        """Close the handle (and its stream, when privately owned).  Does
        NOT flush — fence first when exactness matters."""
        if self._owns_stream:
            self.stream.close()
        self._sync.close()
