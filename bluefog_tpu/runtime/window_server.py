"""Cross-HOST one-sided window transport: TCP deposits into the native table.

The passive-target window story by deployment scope (upstream
``bluefog/common/mpi_controller.cc`` Win* — ``MPI_Put`` lands anywhere in
the job; SURVEY.md §3.4):

- same process / rank threads — the in-process native table
  (``csrc/windows.cc``, anonymous mapping);
- same host, separate OS processes — the named-shm backing
  (``AsyncWindow(shm=True)``);
- **separate hosts (DCN)** — THIS module: every process can run one
  :class:`WindowServer` exposing its windows on a TCP port; peers hold a
  :class:`RemoteWindow` and deposit/read with no receiver involvement
  beyond the server's daemon thread (the MPI progress-thread analog).
  Within a TPU slice the device-side transport remains the Pallas RDMA
  kernels; this is the host path that crosses slice/DCN boundaries, where
  the reference used MPI over the cluster fabric.

Wire protocol (little-endian, one request per round-trip):

  request:  magic u32 | op u8 | name_len u16 | name utf-8 |
            slot i32 | flags u8 | dtype u8 | n_elems i64 | payload
  response: status i64 (>=0 ok / deposit-count; <0 error) |
            [GET_SELF only: dtype u8 | n_elems i64 | payload]

ops: 0 = DEPOSIT (flags bit0 = accumulate), 1 = GET_SELF, 2 = READ_SLOT
(flags bit0 = consume; response carries the fresh-count as status and the
slot payload).  dtype: 0 = f32, 1 = f64 (the native table's types).

Connections are persistent (a peer ranks' deposit stream reuses one
socket); the server is a daemon ``ThreadingTCPServer`` writing straight
into the process's native window table, so owner threads never
participate in a transfer — deposits land while the owner computes.

Trust model, stated plainly: the protocol is UNAUTHENTICATED (a magic
word rejects accidental cross-talk, nothing more) — the same posture as
the MPI/NCCL transports it replaces, which also trust the cluster
network.  Bind to a cluster-internal interface (``start(host=...)``);
never expose the port beyond the training fabric.  Malformed requests
cannot corrupt the owner (geometry is validated against the window's
actual shape before any allocation or native call), but a network-level
writer CAN deposit garbage values, as it can with MPI.
"""

from __future__ import annotations

import ctypes
import socket
import socketserver
import struct
import threading
from typing import Optional, Tuple

import numpy as np

from bluefog_tpu.blackbox import recorder as _bb
from bluefog_tpu.metrics import comm as _mt
from bluefog_tpu.runtime import native
from bluefog_tpu.runtime.async_windows import _DTYPES as _DTYPE_IDS

__all__ = ["WindowServer", "RemoteWindow"]

_MAGIC = 0xBF_51_0E_01
_HDR = struct.Struct("<IBH")          # magic, op, name_len
_BODY = struct.Struct("<iBBq")        # slot, flags, dtype, n_elems
_STATUS = struct.Struct("<q")
_SELF_HDR = struct.Struct("<Bq")      # dtype, n_elems

_OP_DEPOSIT = 0
_OP_GET_SELF = 1
_OP_READ_SLOT = 2

# the ONE dtype-id table (async_windows owns np.dtype -> id; invert here)
_DTYPES = {v: k for k, v in _DTYPE_IDS.items()}

# error statuses (negative, disjoint from the native table's -1)
_ERR_GEOMETRY = -2   # dtype/n_elems disagree with the window's geometry
_ERR_NO_WINDOW = -3
_ERR_BAD_OP = -100


def _routable_host() -> str:
    """Best-effort routable address of this host for wildcard binds.
    ``gethostbyname(gethostname())`` alone is a trap: stock Debian/Ubuntu
    /etc/hosts maps the hostname to 127.0.1.1, which would advertise a
    loopback to remote peers.  The outbound-UDP trick (connect() sends no
    packet; the kernel just picks the egress interface) gets the real
    address; loopback-resolving fallbacks are rejected in favor of the
    next method."""
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.connect(("10.255.255.255", 1))  # no packet is sent
            addr = s.getsockname()[0]
        if not addr.startswith("127."):
            return addr
    except OSError:
        pass
    try:
        addr = socket.gethostbyname(socket.gethostname())
        if not addr.startswith("127."):
            return addr
    except OSError:
        pass
    return "127.0.0.1"  # single-host fallback (tests, laptops)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if r == 0:
            raise ConnectionError("peer closed mid-message")
        got += r
    return bytes(buf)


class _Handler(socketserver.BaseRequestHandler):
    def setup(self):
        self.server.track(self.request)  # type: ignore[attr-defined]
        # per-connection flight-recorder records (always-on host path): a
        # hang dump on the OWNER shows which peers were connected and
        # what their last deposits were — the receiving end of the
        # one-sided story that the peers' own dumps cannot show
        _bb.record("tcp_connect", peer=self.client_address[0])

    def finish(self):
        self.server.untrack(self.request)  # type: ignore[attr-defined]
        _bb.record("tcp_disconnect", peer=self.client_address[0])

    def _geometry_ok(self, lib, name, dtype, n_elems):
        """The client's claimed (dtype, n_elems) must MATCH the window's
        actual geometry before anything is allocated or the native table is
        touched: the C entry points validate n_elems only and then copy
        nbytes = n_elems * window_elem_size — a lying dtype would otherwise
        over-read the payload or overflow the reply buffer, and a huge
        n_elems would allocate unbounded memory in the owner process."""
        ns = ctypes.c_int()
        ne = ctypes.c_longlong()
        dt = ctypes.c_int()
        if lib.bf_win_info(name, ctypes.byref(ns), ctypes.byref(ne),
                           ctypes.byref(dt)) != 0:
            return _ERR_NO_WINDOW
        if dt.value != dtype or ne.value != n_elems:
            return _ERR_GEOMETRY
        return 0

    def handle(self):
        lib = self.server.lib  # type: ignore[attr-defined]
        sock = self.request
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        try:
            while True:
                try:
                    hdr = _recv_exact(sock, _HDR.size)
                except ConnectionError:
                    return  # peer done
                magic, op, name_len = _HDR.unpack(hdr)
                if magic != _MAGIC:
                    return  # not ours; drop the connection
                name = _recv_exact(sock, name_len)
                slot, flags, dtype, n_elems = _BODY.unpack(
                    _recv_exact(sock, _BODY.size))
                if dtype not in _DTYPES or op not in (
                        _OP_DEPOSIT, _OP_GET_SELF, _OP_READ_SLOT):
                    sock.sendall(_STATUS.pack(_ERR_BAD_OP))
                    return  # cannot even parse the payload; drop
                err = self._geometry_ok(lib, name, dtype, n_elems)
                if op == _OP_DEPOSIT:
                    if err:
                        # the payload is still on the wire and its length
                        # is client-claimed, so the stream cannot be
                        # resynced — report and drop the connection
                        sock.sendall(_STATUS.pack(err))
                        return
                    nbytes = n_elems * _DTYPES[dtype].itemsize
                    payload = _recv_exact(sock, nbytes)
                    arr = np.frombuffer(payload, _DTYPES[dtype])
                    rc = lib.bf_win_deposit(name, slot, arr.ctypes.data,
                                            n_elems, flags & 1)
                    sock.sendall(_STATUS.pack(rc))
                    if rc >= 0:
                        # per-peer DCN deposit volume, recorded on the
                        # daemon thread (the registry is thread-safe);
                        # no-op when metrics are disabled
                        _mt.inc("bf_tcp_deposit_bytes_total", nbytes,
                                window=name.decode("utf-8", "replace"),
                                peer=self.client_address[0])
                        _mt.inc("bf_tcp_deposits_total", 1.0,
                                peer=self.client_address[0])
                        _bb.record(
                            "tcp_deposit", slot=slot, bytes=nbytes,
                            window=name.decode("utf-8", "replace"),
                            peer=self.client_address[0])
                    continue
                if err:
                    sock.sendall(_STATUS.pack(err))
                    continue
                out = np.empty(n_elems, _DTYPES[dtype])
                if op == _OP_GET_SELF:
                    rc = lib.bf_win_read_self(name, out.ctypes.data, n_elems)
                else:
                    rc = lib.bf_win_read(name, slot, out.ctypes.data,
                                         n_elems, flags & 1)
                sock.sendall(_STATUS.pack(rc))
                if rc >= 0:
                    sock.sendall(_SELF_HDR.pack(dtype, n_elems))
                    sock.sendall(out.tobytes())
                    _bb.record(
                        "tcp_read",
                        op="get_self" if op == _OP_GET_SELF else "read_slot",
                        slot=slot, window=name.decode("utf-8", "replace"),
                        peer=self.client_address[0])
        except (ConnectionError, OSError):
            return


class _Server(socketserver.ThreadingTCPServer):
    daemon_threads = True
    allow_reuse_address = True

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._conns: set = set()
        self._conns_mu = threading.Lock()

    def track(self, sock):
        with self._conns_mu:
            self._conns.add(sock)

    def untrack(self, sock):
        with self._conns_mu:
            self._conns.discard(sock)

    def close_connections(self):
        """stop() must QUIESCE: shutting down the accept loop alone leaves
        persistent handler connections serving deposits into windows the
        owner now believes are frozen."""
        with self._conns_mu:
            conns = list(self._conns)
        for s in conns:
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                s.close()
            except OSError:
                pass


class WindowServer:
    """Expose this process's native windows for remote one-sided access.

    ``WindowServer().start()`` binds (default: an ephemeral port on all
    interfaces) and serves deposits/reads on daemon threads.  The address
    to hand to peers is ``.address``.  Requires the native runtime (the
    same table the shm and in-process paths use)."""

    def __init__(self):
        self._lib = native.load()
        if self._lib is None:
            raise RuntimeError(
                "WindowServer requires the native runtime window table")
        self._server: Optional[_Server] = None
        self._thread: Optional[threading.Thread] = None

    def start(self, host: str = "0.0.0.0", port: int = 0) -> Tuple[str, int]:
        if self._server is not None:
            raise RuntimeError("server already running")
        self._server = _Server((host, port), _Handler)
        self._server.lib = self._lib  # type: ignore[attr-defined]
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True)
        self._thread.start()
        return self.address

    @property
    def address(self) -> Tuple[str, int]:
        """``(host, port)`` for peers.  A wildcard bind is substituted with
        a routable address of this host (peers cannot connect to
        ``0.0.0.0``); pass an explicit ``host`` to ``start`` to control
        exactly what is advertised."""
        assert self._server is not None, "server not started"
        host, port = self._server.server_address[:2]
        if host in ("0.0.0.0", "::"):
            host = _routable_host()
        return host, port

    def stop(self) -> None:
        """Quiesce: stop accepting AND close live peer connections, so no
        deposit can land after stop() returns."""
        if self._server is not None:
            self._server.shutdown()
            self._server.close_connections()
            self._server.server_close()
            if self._thread is not None:
                self._thread.join(timeout=10)
            self._server = None
            self._thread = None


class RemoteWindow:
    """Client handle to a window served by another host's
    :class:`WindowServer` — ``deposit`` is ``MPI_Put``/``MPI_Accumulate``
    across the DCN, ``read_self`` the passive ``win_get``.  One persistent
    connection per handle; NOT thread-safe (one handle per rank thread,
    like an MPI endpoint)."""

    def __init__(self, address: Tuple[str, int], name: str,
                 timeout_s: float = 30.0):
        self.name = name
        self._name_b = name.encode()
        self._sock = socket.create_connection(address, timeout=timeout_s)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)

    def _request(self, op: int, slot: int, flags: int, dtype_id: int,
                 n_elems: int, payload: bytes = b"") -> int:
        msg = (_HDR.pack(_MAGIC, op, len(self._name_b)) + self._name_b +
               _BODY.pack(slot, flags, dtype_id, n_elems) + payload)
        self._sock.sendall(msg)
        (rc,) = _STATUS.unpack(_recv_exact(self._sock, _STATUS.size))
        return rc

    def _recv_array(self) -> np.ndarray:
        dtype, n_elems = _SELF_HDR.unpack(
            _recv_exact(self._sock, _SELF_HDR.size))
        raw = _recv_exact(self._sock, n_elems * _DTYPES[dtype].itemsize)
        return np.frombuffer(raw, _DTYPES[dtype]).copy()

    def deposit(self, slot: int, arr: np.ndarray, *,
                accumulate: bool = True) -> int:
        a = np.ascontiguousarray(arr)
        if a.dtype not in _DTYPE_IDS:
            raise TypeError(f"RemoteWindow supports f32/f64, got {a.dtype}")
        rc = self._request(_OP_DEPOSIT, slot, 1 if accumulate else 0,
                           _DTYPE_IDS[a.dtype], a.size, a.tobytes())
        if rc < 0:
            raise RuntimeError(
                f"remote deposit into {self.name!r}[{slot}] failed ({rc}): "
                "window missing, slot out of range, or size/dtype mismatch")
        return rc

    def read_self(self, n_elems: int, dtype=np.float64) -> np.ndarray:
        rc = self._request(_OP_GET_SELF, 0, 0,
                           _DTYPE_IDS[np.dtype(dtype)], n_elems)
        if rc < 0:
            raise RuntimeError(f"remote read_self of {self.name!r} failed")
        return self._recv_array()

    def read(self, slot: int, n_elems: int, dtype=np.float64, *,
             consume: bool = True) -> Tuple[np.ndarray, int]:
        rc = self._request(_OP_READ_SLOT, slot, 1 if consume else 0,
                           _DTYPE_IDS[np.dtype(dtype)], n_elems)
        if rc < 0:
            raise RuntimeError(f"remote read of {self.name!r}[{slot}] failed")
        return self._recv_array(), rc

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass
