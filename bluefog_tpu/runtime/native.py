"""ctypes bindings for the native host runtime (``bluefog_tpu/csrc``).

The reference ships a C++ core compiled by setup.py's custom build_ext
(SURVEY.md §2.2 "Build").  Here the shared library is built lazily with g++
on first use (no pybind11 in the image; plain ``extern "C"`` + ctypes), cached
next to the sources, and rebuilt when any source is newer than the binary.
Everything degrades gracefully: if no C++ toolchain is available,
``load()`` returns ``None`` and pure-Python fallbacks take over
(`bluefog_tpu.utils.timeline`, :class:`PyEngine` below).
"""

from __future__ import annotations

import ctypes
import os
import queue as _queue
import subprocess
import threading
from typing import Callable, Optional

from bluefog_tpu.utils import lockcheck as _lc
from bluefog_tpu.utils import log

_CSRC = os.path.join(os.path.dirname(os.path.dirname(__file__)), "csrc")
_SOURCES = ("logging.cc", "timeline.cc", "engine.cc", "windows.cc",
            "tfrecord.cc")
_LIB_PATH = os.path.join(_CSRC, "libbf_runtime.so")

_lib = None
_lib_attempted = False
_build_lock = _lc.lock("runtime.native._build_lock")

_CALLBACK_T = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_void_p)


def _needs_build() -> bool:
    if not os.path.exists(_LIB_PATH):
        return True
    lib_mtime = os.path.getmtime(_LIB_PATH)
    srcs = [os.path.join(_CSRC, s) for s in _SOURCES]
    srcs.append(os.path.join(_CSRC, "bf_runtime.h"))
    return any(os.path.getmtime(s) > lib_mtime for s in srcs)


def build(force: bool = False) -> Optional[str]:
    """Compile the runtime library; returns its path or None on failure.

    Cross-process safe: serialized on an fcntl file lock, compiled to a
    temp path, then atomically renamed — a concurrent process can never
    dlopen a partially written library.
    """
    with _build_lock:
        lock_path = os.path.join(_CSRC, ".build.lock")
        try:
            import fcntl

            lock_file = open(lock_path, "w")
            fcntl.lockf(lock_file, fcntl.LOCK_EX)
        except Exception:
            lock_file = None
        try:
            if not force and not _needs_build():
                return _LIB_PATH
            tmp = f"{_LIB_PATH}.tmp.{os.getpid()}"
            cmd = [
                "g++", "-std=c++17", "-O2", "-fPIC", "-shared", "-pthread",
                "-Wall", "-o", tmp,
            ] + [os.path.join(_CSRC, s) for s in _SOURCES] + [
                # librt: shm_open/shm_unlink live there on pre-2.34 glibc;
                # omitting it builds a .so whose shm windows fail to dlopen
                # ("undefined symbol: shm_open") on those hosts
                "-lrt",
            ]
            try:
                try:
                    proc = subprocess.run(
                        cmd, capture_output=True, text=True, timeout=120
                    )
                except (OSError, subprocess.TimeoutExpired) as e:
                    log.warn("native runtime build failed to launch: %s", e)
                    return None
                if proc.returncode != 0:
                    log.warn("native runtime build failed:\n%s", proc.stderr)
                    return None
                os.replace(tmp, _LIB_PATH)
                return _LIB_PATH
            finally:
                if os.path.exists(tmp):
                    try:
                        os.remove(tmp)
                    except OSError:
                        pass
        finally:
            if lock_file is not None:
                lock_file.close()


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    lib.bf_log_level.restype = ctypes.c_int
    lib.bf_set_log_level.argtypes = [ctypes.c_int]
    lib.bf_log.argtypes = [ctypes.c_int, ctypes.c_char_p]

    lib.bf_timeline_start.argtypes = [ctypes.c_char_p]
    lib.bf_timeline_start.restype = ctypes.c_int
    lib.bf_timeline_stop.restype = ctypes.c_int
    lib.bf_timeline_active.restype = ctypes.c_int
    for fn in (lib.bf_timeline_begin, lib.bf_timeline_end,
               lib.bf_timeline_async_begin, lib.bf_timeline_async_end):
        fn.argtypes = [ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int64]
    lib.bf_timeline_instant.argtypes = [ctypes.c_char_p, ctypes.c_char_p]

    lib.bf_engine_start.restype = ctypes.c_int
    lib.bf_engine_shutdown.restype = ctypes.c_int
    lib.bf_engine_running.restype = ctypes.c_int
    lib.bf_enqueue.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p, _CALLBACK_T, ctypes.c_void_p
    ]
    lib.bf_enqueue.restype = ctypes.c_int
    lib.bf_poll.argtypes = [ctypes.c_int]
    lib.bf_poll.restype = ctypes.c_int
    lib.bf_wait.argtypes = [
        ctypes.c_int, ctypes.c_int, ctypes.POINTER(ctypes.c_int)
    ]
    lib.bf_wait.restype = ctypes.c_int
    lib.bf_clear.argtypes = [ctypes.c_int]
    lib.bf_wait_all.argtypes = [ctypes.c_int]
    lib.bf_wait_all.restype = ctypes.c_int
    lib.bf_pending_count.restype = ctypes.c_int

    lib.bf_win_create.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.c_longlong, ctypes.c_int
    ]
    lib.bf_win_create.restype = ctypes.c_int
    lib.bf_win_create_shm.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.c_longlong, ctypes.c_int
    ]
    lib.bf_win_create_shm.restype = ctypes.c_int
    lib.bf_win_attach_shm.argtypes = [ctypes.c_char_p, ctypes.c_int]
    lib.bf_win_attach_shm.restype = ctypes.c_int
    lib.bf_win_shm_unlink.argtypes = [ctypes.c_char_p]
    lib.bf_win_shm_unlink.restype = ctypes.c_int
    lib.bf_win_info.argtypes = [
        ctypes.c_char_p, ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(ctypes.c_longlong), ctypes.POINTER(ctypes.c_int)
    ]
    lib.bf_win_info.restype = ctypes.c_int
    lib.bf_win_exists.argtypes = [ctypes.c_char_p]
    lib.bf_win_exists.restype = ctypes.c_int
    lib.bf_win_free.argtypes = [ctypes.c_char_p]
    lib.bf_win_free.restype = ctypes.c_int
    lib.bf_win_deposit.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.c_void_p, ctypes.c_longlong,
        ctypes.c_int
    ]
    lib.bf_win_deposit.restype = ctypes.c_longlong
    lib.bf_win_read.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.c_void_p, ctypes.c_longlong,
        ctypes.c_int
    ]
    lib.bf_win_read.restype = ctypes.c_longlong
    lib.bf_win_set_self.argtypes = [
        ctypes.c_char_p, ctypes.c_void_p, ctypes.c_longlong
    ]
    lib.bf_win_set_self.restype = ctypes.c_int
    lib.bf_win_read_self.argtypes = [
        ctypes.c_char_p, ctypes.c_void_p, ctypes.c_longlong
    ]
    lib.bf_win_read_self.restype = ctypes.c_int
    lib.bf_win_num_slots.argtypes = [ctypes.c_char_p]
    lib.bf_win_num_slots.restype = ctypes.c_int

    lib.bf_crc32c.argtypes = [ctypes.c_void_p, ctypes.c_longlong]
    lib.bf_crc32c.restype = ctypes.c_uint32
    lib.bf_tfrecord_index.argtypes = [
        ctypes.c_char_p, ctypes.POINTER(ctypes.c_longlong),
        ctypes.POINTER(ctypes.c_longlong), ctypes.c_longlong, ctypes.c_int,
        ctypes.POINTER(ctypes.c_longlong),
    ]
    lib.bf_tfrecord_index.restype = ctypes.c_longlong
    return lib


_load_lock = _lc.lock("runtime.native._load_lock")


def load() -> Optional[ctypes.CDLL]:
    """Build-if-needed and dlopen the native runtime (None on failure).

    Serialized on a lock: concurrent first callers block until the (possibly
    slow) g++ build finishes rather than observing a half-attempted state and
    silently pinning themselves to the pure-Python fallbacks.
    """
    global _lib, _lib_attempted
    with _load_lock:
        if _lib is not None or _lib_attempted:
            return _lib
        if os.environ.get("BLUEFOG_TPU_NO_NATIVE"):
            _lib_attempted = True
            return None
        path = build()
        if path is not None:
            try:
                _lib = _bind(ctypes.CDLL(path))
            except OSError as e:
                log.warn("native runtime load failed: %s", e)
                _lib = None
            except AttributeError as e:
                # A prebuilt .so with mtime newer than the sources (rsync -a,
                # docker layer) can predate newly added symbols; rebuild once
                # from source before giving up.
                log.warn("stale native runtime (%s); rebuilding", e)
                path = build(force=True)
                try:
                    _lib = _bind(ctypes.CDLL(path)) if path else None
                except (OSError, AttributeError) as e2:
                    log.warn("native runtime reload failed: %s", e2)
                    _lib = None
        _lib_attempted = True
        return _lib


class TimelineWriter:
    """Native chrome-trace writer (used by ``bluefog_tpu.utils.timeline``)."""

    def __init__(self, path: str):
        lib = load()
        if lib is None:
            raise RuntimeError("native runtime unavailable")
        if lib.bf_timeline_start(path.encode()) != 0:
            raise RuntimeError(f"bf_timeline_start({path!r}) failed")
        self._lib = lib

    def begin(self, name: bytes, cat: bytes, tid: int = 0):
        self._lib.bf_timeline_begin(name, cat, tid)

    def end(self, name: bytes, cat: bytes, tid: int = 0):
        self._lib.bf_timeline_end(name, cat, tid)

    def begin_async(self, name: bytes, cat: bytes, tid: int = 0):
        self._lib.bf_timeline_async_begin(name, cat, tid)

    def end_async(self, name: bytes, cat: bytes, tid: int = 0):
        self._lib.bf_timeline_async_end(name, cat, tid)

    def instant(self, name: bytes, cat: bytes):
        self._lib.bf_timeline_instant(name, cat)

    def close(self):
        if self._lib is not None:
            self._lib.bf_timeline_stop()
            self._lib = None


# Handle registry shared by every Engine instance: the C++ engine is
# process-global (one background thread, one handle space), so the Python
# bookkeeping that keeps ctypes trampolines alive and carries captured
# exceptions must be process-global too.
_handles_lock = _lc.lock("runtime.native._handles_lock")
_handles: dict = {}  # handle -> (trampoline, holder)


class Engine:
    """Async host-op engine over the native background thread.

    ``enqueue(fn)`` runs ``fn`` on the engine thread (ctypes re-acquires the
    GIL there) and returns a handle with reference ``poll`` /
    ``synchronize`` (= WaitAndClear) semantics.  Exceptions in ``fn`` are
    captured and re-raised at synchronize time.

    Instances are thin views over one process-global engine (the reference's
    single background thread started by ``bluefog_init``): handles are valid
    across instances and ``shutdown`` stops the shared thread.  Prefer the
    :func:`engine` singleton accessor.
    """

    def __init__(self):
        self._lib = load()
        if self._lib is not None:
            self._lib.bf_engine_start()
        else:
            self._py = _py_engine()

    @property
    def native(self) -> bool:
        return self._lib is not None

    def enqueue(self, fn: Callable[[], object], *, op: str = "host_op",
                name: str = "") -> int:
        if self._lib is None:
            return self._py.enqueue(fn, op=op, name=name)

        holder = {}

        def trampoline(_arg) -> int:
            try:
                fn()
                return 0
            except BaseException as e:  # surfaced at synchronize()
                holder["err"] = e
                return 1

        cb = _CALLBACK_T(trampoline)
        # enqueue + registration are atomic under _handles_lock: the handle
        # cannot escape to a racing synchronize() (which pops _handles) until
        # both have happened, and a failed enqueue registers nothing.
        with _handles_lock:
            self._lib.bf_engine_start()  # restartable after shutdown()
            handle = self._lib.bf_enqueue(op.encode(), name.encode(), cb, None)
            if handle >= 0:
                _handles[handle] = (cb, holder)
        if handle < 0:
            raise RuntimeError("engine not running")
        return handle

    def poll(self, handle: int) -> bool:
        if self._lib is None:
            return self._py.poll(handle)
        return self._lib.bf_poll(handle) == 1

    def synchronize(self, handle: int, timeout_s: Optional[float] = None):
        """Block until done, clear the handle, re-raise any exception."""
        if self._lib is None:
            return self._py.synchronize(handle, timeout_s)
        timeout_ms = -1 if timeout_s is None else int(timeout_s * 1000)
        status = ctypes.c_int(0)
        rc = self._lib.bf_wait(handle, timeout_ms, ctypes.byref(status))
        if rc == -2:
            raise TimeoutError(f"handle {handle} still pending")
        if rc == -1:
            raise KeyError(f"unknown handle {handle}")
        self._lib.bf_clear(handle)
        with _handles_lock:
            entry = _handles.pop(handle, None)
        if entry is not None and "err" in entry[1]:
            raise entry[1]["err"]
        return status.value

    def wait_all(self, timeout_s: Optional[float] = None):
        """Drain every known pending op, clearing handles and re-raising the
        first captured exception (checkpoint IO errors must not be lost)."""
        if self._lib is None:
            return self._py.wait_all(timeout_s)
        with _handles_lock:
            outstanding = list(_handles.keys())
        first_err = None
        for h in outstanding:
            try:
                self.synchronize(h, timeout_s=timeout_s)
            except KeyError:
                pass  # cleared by a concurrent synchronize
            except BaseException as e:
                if first_err is None:
                    first_err = e
        if first_err is not None:
            raise first_err

    def pending_count(self) -> int:
        if self._lib is None:
            return self._py.pending_count()
        return self._lib.bf_pending_count()

    def shutdown(self):
        if self._lib is None:
            return self._py.shutdown()
        self._lib.bf_engine_shutdown()


class PyEngine:
    """Pure-Python fallback with identical semantics (no C++ toolchain)."""

    def __init__(self):
        self._q: _queue.Queue = _queue.Queue()
        self._results: dict[int, object] = {}
        self._cv = _lc.condition("runtime.native.PyEngine._cv")
        self._next = 0
        self._stop = False
        self._thread = threading.Thread(
            target=self._loop, args=(self._q,), daemon=True)
        self._thread.start()

    def _loop(self, q):
        # Consumes its own queue (passed in, not read off self): a restart
        # swaps in a fresh queue, so a stale shutdown sentinel can only ever
        # stop the old thread it was meant for.
        while True:
            item = q.get()
            if item is None:
                return
            handle, fn = item
            try:
                fn()
                result = 0
            except BaseException as e:
                result = e
            with self._cv:
                self._results[handle] = result
                self._cv.notify_all()

    def enqueue(self, fn, *, op="host_op", name="") -> int:
        with self._cv:
            # Restartable after shutdown(), matching the native engine's
            # bf_engine_start-on-enqueue behavior.
            if self._stop:
                self._stop = False
                self._q = _queue.Queue()
                self._thread = threading.Thread(
                    target=self._loop, args=(self._q,), daemon=True)
                self._thread.start()
            handle = self._next
            self._next += 1
            self._results[handle] = None  # pending
            q = self._q
        q.put((handle, fn))
        return handle

    def poll(self, handle: int) -> bool:
        with self._cv:
            return self._results.get(handle) is not None

    def synchronize(self, handle: int, timeout_s=None):
        with self._cv:
            if handle not in self._results:
                raise KeyError(f"unknown handle {handle}")
            ok = self._cv.wait_for(
                lambda: self._results[handle] is not None, timeout=timeout_s)
            if not ok:
                raise TimeoutError(f"handle {handle} still pending")
            result = self._results.pop(handle)
        if isinstance(result, BaseException):
            raise result
        return 0

    def wait_all(self, timeout_s=None):
        """Drain all outstanding handles, re-raising the first exception."""
        with self._cv:
            outstanding = list(self._results.keys())
        first_err = None
        for h in outstanding:
            try:
                self.synchronize(h, timeout_s=timeout_s)
            except KeyError:
                pass  # cleared by a concurrent synchronize
            except BaseException as e:
                if first_err is None:
                    first_err = e
        if first_err is not None:
            raise first_err

    def pending_count(self) -> int:
        with self._cv:
            return sum(1 for v in self._results.values() if v is None)

    def shutdown(self):
        with self._cv:
            if self._stop:
                return  # idempotent: never post a second sentinel
            self._stop = True
            q, t = self._q, self._thread
        q.put(None)
        t.join(timeout=5)


# RLock: engine() holds this while Engine.__init__ runs, and the fallback
# path re-enters it through _py_engine() — a plain Lock self-deadlocks
# whenever the native .so is unavailable
_engine_lock = _lc.rlock("runtime.native._engine_lock")
_PY_ENGINE: Optional[PyEngine] = None


def _py_engine() -> PyEngine:
    """Shared fallback engine (keeps Engine instances views over one
    process-global queue, matching the native path)."""
    global _PY_ENGINE
    with _engine_lock:
        if _PY_ENGINE is None:
            _PY_ENGINE = PyEngine()
        return _PY_ENGINE


_ENGINE: Optional[Engine] = None


def engine() -> Engine:
    """Process-wide engine singleton (reference: the global background
    thread started by ``bluefog_init``; SURVEY.md §3.1)."""
    global _ENGINE
    with _engine_lock:
        if _ENGINE is None:
            _ENGINE = Engine()
        return _ENGINE
