"""Round-over-round DELTA encoding for the subscription push channel.

The serving fan-out's steady state is pathological for dense pushes: a
model that moves a little every round is re-shipped WHOLE every round,
to every subscriber, at every tier of a relay tree.  Wire op 10
(``DELTA``, :mod:`bluefog_tpu.runtime.window_server`) ships the
round-over-round difference instead, compressed with the existing
:mod:`~bluefog_tpu.runtime.wire_codec` twins (``topk``/``f32``), with an
**error-feedback residual held sender-side** so the compression error of
one push is folded into the next instead of accumulating silently — the
CHOCO discipline, applied to the read path.

The consistency contract, stated plainly:

- **The sender tracks the receiver.**  :class:`DeltaEncoder` keeps the
  exact reconstruction the receiver holds (``base``) plus the residual;
  a delta frame is always relative to the round the receiver last
  consumed (its cursor), even across skip-to-latest gaps — TCP is
  in-order, so the sender KNOWS the receiver's state until the
  connection dies.
- **Full frames are the resync anchor.**  Every
  ``DeltaConfig.full_every``-th push is a full snapshot (exact, residual
  cleared), and so is the FIRST push of every connection — a reconnect
  (cursor gap) always resyncs on a full frame because the fresh sender
  has no base.  A torn delta never advances the receiver's cursor, so
  after resume the round is re-promised and lands via the anchor.
- **Round stamps stay exact.**  Leaves smaller than
  ``min_delta_elems`` (the ``round`` stamp, push-sum ``p`` mass) ride
  the delta frame DENSE (codec ``none`` over the diff — bit-exact);
  only bulk leaves pay the lossy codec, and those resync exactly at
  every anchor.
- **Desync is loud.**  :class:`DeltaApplier` refuses a delta whose base
  round is not its cursor (:class:`DeltaDesync`, wire status ``-109``)
  — the receiver drops the connection and the resumed stream resyncs
  with a full frame, instead of compounding a wrong reconstruction.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from bluefog_tpu.runtime import wire_codec, wire_status

__all__ = ["DeltaConfig", "DeltaEncoder", "DeltaApplier", "DeltaDesync"]


@dataclasses.dataclass(frozen=True)
class DeltaConfig:
    """Knobs of one push channel's delta cadence.

    ``full_every <= 1`` disables deltas (every push is a full frame);
    the tree control plane (:mod:`bluefog_tpu.control.tree`) actuates
    this field at round boundaries.  ``min_delta_elems`` is the
    exactness floor: leaves below it diff DENSELY (bit-exact), so round
    stamps and scalar mass leaves never pay a lossy codec."""

    full_every: int = 8
    codec: str = "topk"
    topk_ratio: float = 0.05
    min_delta_elems: int = 1024

    def __post_init__(self):
        if self.full_every < 1:
            raise ValueError("full_every must be >= 1 (1 = deltas off)")
        if self.codec not in wire_codec.CODEC_IDS:
            raise ValueError(f"unknown delta codec {self.codec!r}; want "
                             f"one of {sorted(wire_codec.CODEC_IDS)}")
        if not (0.0 < self.topk_ratio <= 1.0):
            raise ValueError("topk_ratio must be in (0, 1]")
        if self.min_delta_elems < 0:
            raise ValueError("min_delta_elems must be >= 0")


class DeltaDesync(RuntimeError):
    """A delta frame's base round does not match the receiver's
    reconstruction cursor (wire status ``-109``): the receiver must
    drop the connection and resubscribe — the resumed stream resyncs
    with a full-frame anchor.  Retriable by construction; applying the
    delta anyway would compound a wrong model silently."""

    status = wire_status.ERR_DELTA_BASE

    def __init__(self, group: str, base_round: int, cursor: int):
        super().__init__(
            f"delta desync for group {group!r}: frame base round "
            f"{base_round} != reconstruction cursor {cursor} ({self.status}: "
            + wire_status.err_text(self.status) + ")")
        self.group = group
        self.base_round = base_round
        self.cursor = cursor


#: one encoded delta leaf: (name, dtype, codec_id, n_elems, payload
#: views for sendmsg, wire_bytes)
DeltaItem = Tuple[str, np.dtype, int, int, List, int]


class DeltaEncoder:
    """Per-subscription sender state: the receiver's reconstruction
    twin (``base``), the error-feedback residual, and the anchor
    counter.  One encoder per push sender — it is the SENDER-side half
    of the delta contract and must live exactly as long as the
    connection (a fresh connection gets a fresh encoder, which is what
    forces the full-frame resync after every cursor gap)."""

    def __init__(self):
        self._base: Dict[str, np.ndarray] = {}
        self._resid: Dict[str, np.ndarray] = {}
        self._base_round = -1
        self._pushes = 0
        self.full_frames = 0
        self.delta_frames = 0
        self.wire_bytes = 0
        self.dense_bytes = 0

    # ------------------------------------------------------------- helpers
    def _geometry_matches(self, leaves: Sequence[Tuple[str, np.ndarray]]
                          ) -> bool:
        if {n for n, _ in leaves} != set(self._base):
            return False
        for name, arr in leaves:
            b = self._base[name]
            if b.shape != arr.reshape(-1).shape or b.dtype != arr.dtype:
                return False
        return True

    def _anchor(self, round_: int,
                leaves: Sequence[Tuple[str, np.ndarray]]) -> None:
        self._base = {n: np.ascontiguousarray(a).reshape(-1).copy()
                      for n, a in leaves}
        self._resid = {}
        self._base_round = int(round_)
        self.full_frames += 1

    # ---------------------------------------------------------------- step
    def step(self, round_: int, leaves: Sequence[Tuple[str, np.ndarray]],
             cfg: DeltaConfig
             ) -> Tuple[int, int, Optional[List[DeltaItem]]]:
        """Encode one due push.  Returns ``(kind, base_round, items)``:
        ``kind`` 0 = full frame (send the leaves dense, ``items`` is
        None) or 10 = delta frame relative to ``base_round``.  The
        anchor cadence and codec come from ``cfg`` — read fresh per
        push, so a TreePlan actuation changes cadence without touching
        the sender."""
        self._pushes += 1
        dense = sum(a.size * a.dtype.itemsize for _, a in leaves)
        self.dense_bytes += dense
        full_due = (cfg.full_every <= 1
                    or (self._pushes - 1) % cfg.full_every == 0)
        if (full_due or self._base_round < 0
                or not self._geometry_matches(leaves)):
            # the resync anchor: exact, residual cleared — and the ONLY
            # frame kind a fresh sender (post-reconnect cursor gap) can
            # open with, because it has no base to diff against
            self._anchor(round_, leaves)
            self.wire_bytes += dense
            return 0, -1, None
        base_round = self._base_round
        items: List[DeltaItem] = []
        for name, arr in leaves:
            flat = np.ascontiguousarray(arr).reshape(-1)
            base = self._base[name]
            diff = flat - base
            resid = self._resid.get(name)
            if resid is not None:
                diff = diff + resid
            if flat.size < cfg.min_delta_elems:
                codec = wire_codec.CODEC_NONE
            else:
                codec = wire_codec.CODEC_IDS[cfg.codec]
            views, wire_b = wire_codec.encode(
                diff, codec, topk_ratio=cfg.topk_ratio)
            if codec == wire_codec.CODEC_NONE:
                dec = diff  # dense diff is bit-exact
            else:
                payload = b"".join(bytes(v) for v in views)
                dec = wire_codec.decode(codec, memoryview(payload),
                                        flat.size, flat.dtype)
            self._resid[name] = diff - dec
            base += dec.astype(base.dtype, copy=False)
            items.append((name, flat.dtype, codec, flat.size, views,
                          wire_b))
            self.wire_bytes += wire_b
        self._base_round = int(round_)
        self.delta_frames += 1
        return 10, base_round, items


class DeltaApplier:
    """Receiver-side reconstruction: the exact mirror of the encoder's
    ``base``.  ``anchor`` installs a full frame; ``apply`` folds a
    delta in — refusing (loudly, :class:`DeltaDesync`) any frame whose
    base round is not the cursor, because applying it would silently
    corrupt every later round."""

    def __init__(self, group: str = ""):
        self.group = group
        self._recon: Dict[str, np.ndarray] = {}
        self.base_round = -1
        self.deltas_applied = 0

    def anchor(self, round_: int, leaves: Dict[str, np.ndarray]) -> None:
        self._recon = {n: np.ascontiguousarray(a).reshape(-1).copy()
                       for n, a in leaves.items()}
        self.base_round = int(round_)

    def apply(self, round_: int, base_round: int,
              items: Sequence[Tuple[str, np.dtype, int, int, memoryview]]
              ) -> Dict[str, np.ndarray]:
        """Fold one delta frame (``(name, dtype, codec, n_elems,
        payload)`` per leaf) into the reconstruction; returns COPIES of
        the reconstructed leaves (the delivered snapshot — the caller
        may hold them while later deltas land)."""
        if base_round != self.base_round or not self._recon:
            raise DeltaDesync(self.group, base_round, self.base_round)
        names = {name for name, *_ in items}
        if names != set(self._recon):
            raise DeltaDesync(self.group, base_round, self.base_round)
        for name, dtype, codec, n_elems, payload in items:
            recon = self._recon[name]
            if recon.size != n_elems or recon.dtype != np.dtype(dtype):
                raise DeltaDesync(self.group, base_round, self.base_round)
            dec = wire_codec.decode(codec, payload, n_elems, recon.dtype)
            recon += dec
        self.base_round = int(round_)
        self.deltas_applied += 1
        return {n: a.copy() for n, a in self._recon.items()}
