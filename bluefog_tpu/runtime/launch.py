"""Multi-host launcher — the reference's ``bfrun`` re-thought for TPU.

Reference parity (upstream-relative): ``bluefog/run/run.py`` builds and execs
an ``mpirun -np N -H hosts ...`` command line (SURVEY.md §3.5).  On TPU pods
there is no mpirun: every host runs the same program and rendezvous happens in
``jax.distributed.initialize`` against the coordinator.  This module provides

- :func:`initialize_cluster` — library-call bring-up (the ``bf.init()``-time
  process/network boundary of SURVEY.md §3.1);
- a thin CLI (``bfrun-tpu``) that sets the coordinator env and execs the
  training script on this host, for parity with ``bfrun`` muscle memory on
  GCE/GKE-style deployments where each host runs the launcher.
"""

from __future__ import annotations

import argparse
import os
import runpy
import sys
from typing import Optional

from bluefog_tpu.utils import log


def initialize_cluster(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Rendezvous all hosts (no-op on single-host).

    Mirrors ``jax.distributed.initialize`` argument conventions; on Cloud TPU
    the arguments are auto-detected from the metadata server.
    """
    import jax

    if num_processes == 1:
        return
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
        log.info("cluster initialized: process %d/%d", jax.process_index(), jax.process_count())
    except Exception as e:  # single-host dev boxes: fine to run undistributed
        log.warn("jax.distributed.initialize skipped: %s", e)


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="bfrun-tpu",
        description="Launch a bluefog_tpu training script (bfrun analog; "
        "run once per host on multi-host pods)",
    )
    ap.add_argument("--coordinator", default=None, help="host:port of process 0")
    ap.add_argument("--num-processes", type=int, default=None)
    ap.add_argument("--process-id", type=int, default=None)
    ap.add_argument("script")
    ap.add_argument("script_args", nargs=argparse.REMAINDER)
    args = ap.parse_args(argv)

    initialize_cluster(args.coordinator, args.num_processes, args.process_id)
    sys.argv = [args.script] + list(args.script_args)
    os.environ.setdefault("BLUEFOG_TPU_LAUNCHED", "1")
    runpy.run_path(args.script, run_name="__main__")


if __name__ == "__main__":
    main()
