"""Multi-host launcher — the reference's ``bfrun``/``ibfrun`` re-thought for TPU.

Reference parity (upstream-relative): ``bluefog/run/run.py`` builds and execs
an ``mpirun -np N -H hosts -x ENV ...`` command line, and ``ibfrun`` starts an
interactive (Jupyter/ipyparallel) cluster (SURVEY.md §3.5, §2.2).  On TPU pods
there is no mpirun: every host runs the same program and rendezvous happens in
``jax.distributed.initialize`` against the coordinator.  This module provides

- :func:`initialize_cluster` — library-call bring-up (the ``bf.init()``-time
  process/network boundary of SURVEY.md §3.1);
- ``bfrun-tpu`` — a thin CLI that prepares the environment (coordinator
  address, env propagation à la ``mpirun -x``, timeline, **virtual-device
  simulation** for laptop debugging) and execs the training script;
- ``ibfrun-tpu`` (:func:`interactive_main`) — drops into a REPL with the
  framework initialized, the ``ibfrun`` analog for poking at topologies and
  collectives interactively.
"""

from __future__ import annotations

import argparse
import code
import os
import runpy
import sys
from typing import List, Optional

from bluefog_tpu.utils import log


def initialize_cluster(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    initialization_timeout: Optional[int] = None,
) -> None:
    """Rendezvous all hosts (no-op on single-host).

    Mirrors ``jax.distributed.initialize`` argument conventions; on Cloud TPU
    the arguments are auto-detected from the metadata server.

    Failure policy: when the caller **asked** for a cluster (any of the
    arguments given), a rendezvous failure raises — a training job silently
    running undistributed at 1/N scale is the worst possible outcome.  Only
    the fully-auto-detected call (no arguments, e.g. a dev box without TPU
    metadata) degrades to single-process with a warning.
    """
    import jax

    if num_processes == 1:
        return
    explicit = (coordinator_address is not None or num_processes is not None
                or process_id is not None)
    kwargs = {}
    if initialization_timeout is not None:
        kwargs["initialization_timeout"] = initialization_timeout
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
            **kwargs,
        )
        log.info("cluster initialized: process %d/%d", jax.process_index(), jax.process_count())
    except Exception as e:
        if explicit:
            raise RuntimeError(
                f"cluster rendezvous failed (coordinator="
                f"{coordinator_address}, num_processes={num_processes}, "
                f"process_id={process_id}): {e}") from e
        log.warn("jax.distributed.initialize skipped (auto-detect found no "
                 "cluster): %s", e)


def _apply_env(args) -> None:
    """Common env preparation for both CLIs (before jax import)."""
    for spec in args.env or []:
        if "=" in spec:
            key, val = spec.split("=", 1)
            os.environ[key] = val
        elif spec not in os.environ:
            raise SystemExit(f"-x {spec}: not set in the launching environment")
        # bare `-x NAME` propagates the current value — already in os.environ
    if args.timeline:
        os.environ["BLUEFOG_TPU_TIMELINE"] = args.timeline
    if args.simulate:
        # Virtual-device debug mesh (the analog of the reference's
        # mpirun-on-localhost testing mode; SURVEY.md §4): N CPU devices in
        # one process.  Env vars cover child processes; the jax.config
        # updates override any platform a sitecustomize pinned at interpreter
        # startup (before our flags existed).  Must run before the backend
        # is first used.
        os.environ["JAX_PLATFORMS"] = "cpu"
        flags = os.environ.get("XLA_FLAGS", "")
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count={args.simulate}".strip()
        )
        os.environ["PALLAS_AXON_POOL_IPS"] = ""  # disable TPU tunnel plugins
        import jax

        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_num_cpu_devices", args.simulate)


def _add_common_args(ap: argparse.ArgumentParser) -> None:
    ap.add_argument("--coordinator", default=None, help="host:port of process 0")
    ap.add_argument("--num-processes", type=int, default=None)
    ap.add_argument("--process-id", type=int, default=None)
    ap.add_argument(
        "-x", dest="env", action="append", metavar="NAME[=VALUE]",
        help="propagate/set an environment variable (mpirun -x parity)")
    ap.add_argument(
        "--timeline", default=None, metavar="FILE",
        help="write a chrome-trace timeline (BLUEFOG_TPU_TIMELINE)")
    ap.add_argument(
        "--simulate", type=int, default=None, metavar="N",
        help="debug on N virtual CPU devices instead of TPU hardware")


def main(argv: Optional[List[str]] = None):
    ap = argparse.ArgumentParser(
        prog="bfrun-tpu",
        description="Launch a bluefog_tpu training script (bfrun analog; "
        "run once per host on multi-host pods)",
    )
    _add_common_args(ap)
    ap.add_argument(
        "--restart-backoff", type=float, default=2.0, metavar="SECONDS",
        help="with --supervise: initial delay before a restart (doubles "
             "per attempt with jitter, capped; 0 = restart immediately)")
    ap.add_argument(
        "--supervise", type=int, default=None, metavar="MAX_RESTARTS",
        help="run the script as a supervised subprocess, restarting it from "
        "its latest checkpoint when it dies (peer failure kills survivors "
        "via the coordination service; the hang watchdog kills wedged "
        "collectives) — up to MAX_RESTARTS times")
    ap.add_argument(
        "--incident-dir", default=None, metavar="DIR",
        help="with --supervise: directory collecting blackbox flight-"
        "recorder dumps across restarts (one incident tree for "
        "bfblackbox-tpu; the child inherits it as BLUEFOG_TPU_BLACKBOX_DIR "
        "and earlier attempts' dumps are layered into restart-N/).  "
        "Default: $BLUEFOG_TPU_BLACKBOX_DIR, else ./bf-incident")
    ap.add_argument("script")
    ap.add_argument("script_args", nargs=argparse.REMAINDER)
    args = ap.parse_args(argv)

    _apply_env(args)
    os.environ.setdefault("BLUEFOG_TPU_LAUNCHED", "1")
    if args.supervise is not None:
        if (args.coordinator is not None or args.num_processes is not None
                or args.process_id is not None):
            # The supervised child must rendezvous afresh on every restart —
            # initialize_cluster here (once, in the parent) cannot provide
            # that, and silently dropping the flags would run the job
            # undistributed at 1/N scale.  The script owns its own
            # initialize_cluster call in supervised mode.
            raise SystemExit(
                "--supervise cannot be combined with --coordinator/"
                "--num-processes/--process-id: the supervised script must "
                "call initialize_cluster itself so every restart "
                "re-rendezvouses")
        from bluefog_tpu.utils.failure import run_supervised

        incident = (args.incident_dir
                    or os.environ.get("BLUEFOG_TPU_BLACKBOX_DIR")
                    or "bf-incident")
        raise SystemExit(run_supervised(
            [sys.executable, args.script] + list(args.script_args),
            max_restarts=args.supervise, incident_dir=incident,
            restart_backoff_s=args.restart_backoff))
    if args.process_id is not None:
        # name this process's blackbox/faulthandler files by its real
        # rank BEFORE install() opens them — co-located processes with a
        # shared incident dir must not truncate each other's rank-0 files
        os.environ.setdefault("BLUEFOG_TPU_RANK", str(args.process_id))
    if args.num_processes is not None:
        os.environ.setdefault("BLUEFOG_TPU_WORLD", str(args.num_processes))
    try:
        # dump triggers armed in the launched process itself: scripts that
        # never call bf.init() (pure host runs) still leave a blackbox
        # file behind on an uncaught exception or fatal signal.  The
        # --supervise branch above deliberately skips this — the CHILD
        # arms its own triggers (via bf.init or this path on re-exec);
        # the supervisor only collects.
        from bluefog_tpu import blackbox

        blackbox.install()
    except Exception:
        pass
    initialize_cluster(args.coordinator, args.num_processes, args.process_id)
    sys.argv = [args.script] + list(args.script_args)
    runpy.run_path(args.script, run_name="__main__")


def interactive_main(argv: Optional[List[str]] = None):
    """``ibfrun-tpu``: REPL with the framework brought up (ibfrun analog)."""
    ap = argparse.ArgumentParser(
        prog="ibfrun-tpu",
        description="Interactive bluefog_tpu session (ibfrun analog)",
    )
    _add_common_args(ap)
    ap.add_argument("--topology", default="exp2",
                    choices=["exp2", "ring", "grid", "star", "full"],
                    help="initial virtual topology")
    args = ap.parse_args(argv)

    _apply_env(args)
    initialize_cluster(args.coordinator, args.num_processes, args.process_id)

    import jax

    import bluefog_tpu as bf
    from bluefog_tpu import topology as topo_lib

    n = len(jax.devices())
    builders = {
        "exp2": topo_lib.ExponentialTwoGraph,
        "ring": topo_lib.RingGraph,
        "grid": topo_lib.MeshGrid2DGraph,
        "star": topo_lib.StarGraph,
        "full": topo_lib.FullyConnectedGraph,
    }
    ctx = bf.init(topology=builders[args.topology](n)) if n > 1 else bf.init()
    banner = (
        f"bluefog_tpu interactive — {n} device(s), rank axis "
        f"'{ctx.axis_name}', topology={args.topology}\n"
        "Bound names: bf (the framework), jax, ctx (active context)."
    )
    code.interact(banner=banner, local={"bf": bf, "jax": jax, "ctx": ctx})


if __name__ == "__main__":
    main()
