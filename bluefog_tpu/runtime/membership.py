"""Elastic-membership records: the barrier directory as the roster.

PR 5's resilience layer already disseminates *unplanned* membership
change through the shared barrier directory (``dead.<r>`` tombstones —
the one medium every rank polls anyway).  This module is the vocabulary
for *intentional* change on the same channel:

- ``member.<r>``  — a JOIN announcement: rank ``r`` has attached its
  window server to the running job, warm-started from a neighbor's
  window, and asks to be admitted at the next round boundary.  The file
  content is a **generation token**: a rank can join, leave, and rejoin
  (a flapping autoscaler target), and every admission rendezvous is
  named by its token so stage files from a previous life can never
  satisfy a new rendezvous.
- ``leaving.<r>`` — a graceful-drain INTENT: rank ``r`` wants out and
  asks the live members to fence their deposit streams to it and meet
  at the leave rendezvous, after which nothing is in flight toward it
  and it can hand its push-sum mass to its out-neighbors exactly.
- ``left.<r>``    — drain COMPLETE: the final flagged deposits were
  acknowledged as applied; the mass is conserved among the remaining
  members (the audit treats a leaver's mass opposite to a corpse's,
  which is written off via ``dead.<r>``).

Records are written atomically (tmp + rename, like the ``winaddr``
files) so a reader never sees a torn token, and a joiner clears its own
stale ``dead``/``left`` records from a previous life before announcing.

The protocol that consumes these records lives in
:func:`bluefog_tpu.runtime.async_windows.run_async_dsgd_rank`; the
thread-mode twin keeps membership in shared memory and only uses the
state machine (:mod:`bluefog_tpu.runtime.resilience` JOINING/LEFT).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, Optional, Set

__all__ = [
    "MembershipView",
    "clear_record",
    "new_token",
    "read_record",
    "scan",
    "write_record",
]

_KINDS = ("member", "leaving", "left", "dead")


def new_token() -> str:
    """A per-announcement generation token: unique across a rank's
    lives (pid + random), filesystem-safe, torn-read-proof via the
    atomic record write."""
    return f"{os.getpid()}-{os.urandom(4).hex()}"


def _path(dirpath: str, kind: str, rank: int) -> str:
    if kind not in _KINDS:
        raise ValueError(f"unknown membership record kind {kind!r}")
    return os.path.join(dirpath, f"{kind}.{int(rank)}")


def write_record(dirpath: str, kind: str, rank: int,
                 token: str = "") -> None:
    """Atomically publish ``<kind>.<rank>`` with ``token`` as content."""
    path = _path(dirpath, kind, rank)
    with open(path + ".tmp", "w") as f:
        f.write(token)
    os.replace(path + ".tmp", path)


def read_record(dirpath: str, kind: str, rank: int) -> Optional[str]:
    """The record's token, or None when absent."""
    try:
        with open(_path(dirpath, kind, rank)) as f:
            return f.read().strip()
    except OSError:
        return None


def clear_record(dirpath: str, kind: str, rank: int) -> bool:
    """Remove a record (a rejoiner clearing its previous life); True if
    one existed."""
    try:
        os.unlink(_path(dirpath, kind, rank))
        return True
    except OSError:
        return False


@dataclasses.dataclass
class MembershipView:
    """One scan of the roster directory.

    ``announced``/``leaving``/``left`` map rank -> generation token;
    ``dead`` is the PR-5 tombstone set (no token — a corpse announces
    nothing).  ``addressed`` is the set of ranks that ever published a
    window address (``winaddr.<r>``) — the joiner's member-discovery
    universe."""

    announced: Dict[int, str]
    leaving: Dict[int, str]
    left: Dict[int, str]
    dead: Set[int]
    addressed: Set[int]

    def current_members(self) -> Set[int]:
        """Best-effort live set from records alone: every rank that
        published an address, minus tombstones and completed leavers.
        A rejoiner's fresh ``member`` record overrides its old
        ``left``/``dead`` state (it cleared those before announcing)."""
        return self.addressed - self.dead - set(self.left)


def scan(dirpath: str, n_ranks: int) -> MembershipView:
    announced: Dict[int, str] = {}
    leaving: Dict[int, str] = {}
    left: Dict[int, str] = {}
    dead: Set[int] = set()
    addressed: Set[int] = set()
    for r in range(n_ranks):
        tok = read_record(dirpath, "member", r)
        if tok is not None:
            announced[r] = tok
        tok = read_record(dirpath, "leaving", r)
        if tok is not None:
            leaving[r] = tok
        tok = read_record(dirpath, "left", r)
        if tok is not None:
            left[r] = tok
        if os.path.exists(os.path.join(dirpath, f"dead.{r}")):
            dead.add(r)
        if os.path.exists(os.path.join(dirpath, f"winaddr.{r}")):
            addressed.add(r)
    return MembershipView(announced=announced, leaving=leaving, left=left,
                          dead=dead, addressed=addressed)
