"""Peer-fault tolerance primitives: health states, bounded backoff.

Bluefog's premise (arXiv:2111.04287) is that decentralized training keeps
making progress through heterogeneity; the blackbox/watchdog layer
(:mod:`bluefog_tpu.blackbox`, :mod:`bluefog_tpu.utils.failure`) already
*detects* that a peer stopped responding.  This module is the vocabulary
the runtime uses to go one step further and survive the failure in
place:

- :class:`Backoff` — exponential backoff with deterministic (seedable)
  jitter and a MANDATORY retry budget or deadline.  Every reconnect /
  restart loop in the tree iterates one of these; an unbounded retry
  loop is a lint error (BF-RES001, :mod:`bluefog_tpu.analysis.
  resilience_lint`) because a crash loop with no bound hammers shared
  resources (checkpoint store, window-server ports) forever.
- :class:`PeerHealth` — the per-peer state machine
  ``HEALTHY -> SUSPECT -> DEAD -> REJOINED -> HEALTHY`` fed by transport
  evidence (acks, heartbeat replies, connect failures).  One instance
  per :class:`~bluefog_tpu.runtime.window_server.DepositStream`; every
  transition lands in the flight recorder (``peer_suspect`` /
  ``peer_dead`` / ``peer_rejoin``) and the ``bf_peer_state`` gauge, so
  an incident dump shows the health timeline next to the flush spans.
- :class:`HealthBoard` — the same state machine for N co-located rank
  *threads* (:func:`~bluefog_tpu.runtime.async_windows.run_async_dsgd`):
  ranks beat it once per round; a rank whose thread died (or is stalled
  by chaos injection) stops beating and the survivors observe
  SUSPECT/DEAD by silence, exactly as a remote peer's ack silence reads.
- :class:`ResilienceConfig` — the one knob bag the async runners accept
  (``resilience=``): detection deadlines, reconnect budget, heartbeat
  interval.

The state machine, plainly::

            ok/beat                 silence > suspect_after_s
   HEALTHY <-------- SUSPECT  <--------------------- HEALTHY
      ^                 |  silence > dead_after_s
      | admit()         v  (or reconnect budget exhausted)
   REJOINED <-------- DEAD
            ok/beat

   LEFT  --------> JOINING --------> HEALTHY      (elastic membership)
       mark_joining()        admit()
   HEALTHY/SUSPECT -----------------> LEFT        (graceful drain)
                      mark_left()

A DEAD peer is healed out of the gossip (mixing weights re-normalized
over the survivors — :func:`bluefog_tpu.topology.heal`); a beat from a
DEAD peer moves it to REJOINED, and the gossip loop re-admits it at the
next round boundary (``admit()`` completes the cycle back to HEALTHY).

Elastic membership (intentional change) adds the second lane: a slot
that has not joined yet — or whose peer drained gracefully — is LEFT
(inert, never promoted by silence); a join announcement moves it to
JOINING (warm-starting, sticky like REJOINED), and the same round-
boundary ``admit()`` completes admission.  ``mark_left`` is the graceful
counterpart of ``mark_dead``: a leaver's push-sum mass was HANDED OFF to
its out-neighbors, not written off, so the audit treats the two
terminally differently (see :mod:`bluefog_tpu.runtime.async_windows`).
"""

from __future__ import annotations

import dataclasses
import random
import threading
import time
from typing import Callable, Dict, Iterator, List, Optional, Set, Tuple

from bluefog_tpu.blackbox import recorder as _bb
from bluefog_tpu.metrics import comm as _mt
from bluefog_tpu.utils import lockcheck as _lc

__all__ = [
    "HEALTHY",
    "SUSPECT",
    "DEAD",
    "REJOINED",
    "JOINING",
    "LEFT",
    "STATE_NAMES",
    "Backoff",
    "BudgetExhausted",
    "read_backoff",
    "PeerHealth",
    "HealthBoard",
    "ResilienceConfig",
]

# peer-health states (gauge values of ``bf_peer_state{peer=...}``)
HEALTHY = 0
SUSPECT = 1
DEAD = 2
REJOINED = 3
# elastic membership (intentional change, the complement of failure):
# JOINING — a NEW peer announced itself and is warm-starting; like
# REJOINED it is sticky until the gossip loop's admit() at a round
# boundary (weights change between rounds, never inside one).  LEFT — a
# peer drained gracefully (mass handed off, not written off) or has not
# joined yet; sticky until a new join announcement.
JOINING = 4
LEFT = 5

STATE_NAMES = {HEALTHY: "healthy", SUSPECT: "suspect", DEAD: "dead",
               REJOINED: "rejoined", JOINING: "joining", LEFT: "left"}

_STATE_EVENT = {SUSPECT: "peer_suspect", DEAD: "peer_dead",
                REJOINED: "peer_rejoin", JOINING: "peer_join",
                LEFT: "peer_leave"}


class BudgetExhausted(RuntimeError):
    """A :class:`Backoff`'s retry budget (or deadline) ran out."""


def read_backoff(overrides=None) -> "Backoff":
    """The READ path's standard bounded retry schedule — one source of
    truth for the sync window client, the snapshot client, and the
    subscriber (all pure-read retries, so the same posture fits):
    0.05 s base doubling to a 1 s cap, ±50 % jitter, budget 6.
    ``overrides`` is a dict of :class:`Backoff` kwargs (what callers
    accept as their ``retry=``/``reconnect=`` knobs)."""
    return Backoff(**{**dict(base_s=0.05, cap_s=1.0, factor=2.0,
                             jitter=0.5, budget=6),
                      **(overrides or {})})


class Backoff:
    """Exponential backoff with jitter and a mandatory bound.

    Iterating yields the delay (seconds) to sleep before the NEXT
    attempt: ``base_s * factor**k``, capped at ``cap_s``, with uniform
    jitter of ``±jitter`` relative (a ``jitter`` of 0.5 scatters each
    delay over ``[0.5d, 1.5d]``).  Jitter is drawn from a private
    ``random.Random(seed)`` so a seeded schedule is exactly reproducible
    — the chaos tests rely on this.

    The bound is NOT optional: pass ``budget`` (max attempts) and/or
    ``deadline_s`` (wall-clock cap measured from the first ``next_delay``)
    — both default to sane values rather than to "forever".  Exhaustion
    raises :class:`BudgetExhausted` (iteration just stops), which is the
    caller's cue to declare the peer DEAD instead of retrying into the
    void.  This shape is what the BF-RES001 lint looks for.
    """

    def __init__(self, *, base_s: float = 0.05, cap_s: float = 2.0,
                 factor: float = 2.0, jitter: float = 0.5,
                 budget: Optional[int] = 8,
                 deadline_s: Optional[float] = None,
                 seed: Optional[int] = None):
        if budget is None and deadline_s is None:
            raise ValueError(
                "Backoff requires a bound: pass budget= and/or deadline_s= "
                "(an unbounded retry loop is exactly what BF-RES001 exists "
                "to reject)")
        if budget is not None and budget < 1:
            raise ValueError(f"budget must be >= 1, got {budget}")
        self.base_s = float(base_s)
        self.cap_s = float(cap_s)
        self.factor = float(factor)
        self.jitter = min(max(float(jitter), 0.0), 1.0)
        self.budget = budget
        self.deadline_s = deadline_s
        self._rng = random.Random(seed)
        self.attempts = 0
        self._t0: Optional[float] = None

    def max_total_s(self) -> float:
        """Worst-case total sleep across the whole budget — the
        *configured detection deadline* a caller can quote (budget-bound
        form only; with a deadline the deadline itself is the answer)."""
        if self.budget is None:
            return float(self.deadline_s)  # type: ignore[arg-type]
        total = 0.0
        for k in range(self.budget):
            d = min(self.base_s * (self.factor ** k), self.cap_s)
            total += d * (1.0 + self.jitter)
        if self.deadline_s is not None:
            total = min(total, self.deadline_s)
        return total

    def next_delay(self) -> float:
        """The next delay to sleep, or raise :class:`BudgetExhausted`."""
        now = time.monotonic()
        if self._t0 is None:
            self._t0 = now
        if self.budget is not None and self.attempts >= self.budget:
            raise BudgetExhausted(
                f"retry budget of {self.budget} attempt(s) exhausted")
        if self.deadline_s is not None and now - self._t0 > self.deadline_s:
            raise BudgetExhausted(
                f"retry deadline of {self.deadline_s}s exhausted after "
                f"{self.attempts} attempt(s)")
        d = min(self.base_s * (self.factor ** self.attempts), self.cap_s)
        if self.jitter:
            d *= 1.0 + self._rng.uniform(-self.jitter, self.jitter)
        self.attempts += 1
        return d

    def __iter__(self) -> Iterator[float]:
        while True:
            try:
                yield self.next_delay()
            except BudgetExhausted:
                return


class _HealthCore:
    """Shared transition bookkeeping for :class:`PeerHealth` /
    :class:`HealthBoard` entries: emits one blackbox event + gauge update
    per transition and keeps a short transition log for tests/forensics."""

    def __init__(self, label: str, suspect_after_s: float,
                 dead_after_s: float,
                 clock: Callable[[], float] = time.monotonic):
        self.label = label
        self.suspect_after_s = float(suspect_after_s)
        self.dead_after_s = float(dead_after_s)
        self._clock = clock
        self.state = HEALTHY
        self.last_ok = clock()
        self.transitions: List[Tuple[float, int, int]] = []  # (t, old, new)

    def _set(self, new: int, **fields) -> None:
        if new == self.state:
            return
        old = self.state
        self.state = new
        self.transitions.append((self._clock(), old, new))
        del self.transitions[:-64]  # bounded forensics, newest kept
        ev = _STATE_EVENT.get(new)
        if ev is None and new == HEALTHY:
            if old in (DEAD, REJOINED):
                ev = "peer_rejoin"
            elif old == SUSPECT:
                ev = "peer_recovered"
        if ev is not None:
            _bb.record(ev, peer=self.label, from_state=STATE_NAMES[old],
                       to_state=STATE_NAMES[new], **fields)
        _mt.set("bf_peer_state", float(new), peer=self.label)

    # ------------------------------------------------------------ evidence
    def note_ok(self) -> int:
        """Positive evidence (ack, heartbeat reply, beat) arrived."""
        self.last_ok = self._clock()
        if self.state == DEAD:
            self._set(REJOINED)
        elif self.state == SUSPECT:
            # SUSPECT -> HEALTHY recovery is a rejoin in the loose sense
            # (the peer answered again) but keeps its gossip weights, so
            # it maps back to HEALTHY directly
            self._set(HEALTHY, recovered_from="suspect")
        return self.state

    def poll(self, now: Optional[float] = None) -> int:
        """Time-based evaluation: silence promotes HEALTHY -> SUSPECT ->
        DEAD.  REJOINED and JOINING are sticky until :meth:`admit` (the
        gossip loop re-admits at a round boundary, not mid-round); LEFT
        is sticky until a new join announcement — an absent peer is not
        a silent one."""
        if self.state in (DEAD, REJOINED, JOINING, LEFT):
            return self.state
        now = self._clock() if now is None else now
        silent = now - self.last_ok
        if silent >= self.dead_after_s:
            self._set(DEAD, silent_s=round(silent, 3))
        elif silent >= self.suspect_after_s:
            self._set(SUSPECT, silent_s=round(silent, 3))
        return self.state

    def mark_dead(self, reason: str = "") -> None:
        """Hard evidence (reconnect budget exhausted, process reaped)."""
        self._set(DEAD, reason=reason)

    def mark_joining(self, **fields) -> None:
        """A join announcement arrived (membership record / first HELLO
        of a new peer): the slot enters the admission pipeline.  Sticky
        until the gossip loop's :meth:`admit` at a round boundary."""
        self.last_ok = self._clock()
        self._set(JOINING, **fields)

    def mark_left(self, **fields) -> None:
        """The peer drained gracefully (or the slot has not joined yet).
        Terminal-but-revivable: unlike DEAD, a LEFT peer's push-sum mass
        was handed off, not written off, and a later join announcement
        (:meth:`mark_joining`) revives the slot."""
        self._set(LEFT, **fields)

    def admit(self) -> None:
        """Complete a REJOINED/JOINING peer's cycle to HEALTHY (called
        by the gossip loop at the round boundary where it restores the
        peer's mixing weights)."""
        self.last_ok = self._clock()
        if self.state in (REJOINED, JOINING, DEAD, SUSPECT):
            self._set(HEALTHY, admitted=True)


class PeerHealth(_HealthCore):
    """Health of ONE remote peer, fed by its transport: every batch ack
    and heartbeat reply is :meth:`note_ok`; connect failures are
    :meth:`note_failure`; the stream's idle waits call :meth:`poll`."""

    def __init__(self, peer: str, *, suspect_after_s: float = 2.0,
                 dead_after_s: float = 20.0,
                 clock: Callable[[], float] = time.monotonic):
        super().__init__(peer, suspect_after_s, dead_after_s, clock)
        self.failures = 0

    def note_failure(self) -> int:
        """A connect/send attempt failed.  Failures do not mark DEAD by
        themselves (that is the reconnect budget's call) but promote
        HEALTHY straight to SUSPECT — an RST is stronger evidence than
        silence."""
        self.failures += 1
        if self.state == HEALTHY:
            self._set(SUSPECT, failures=self.failures)
        return self.state


class HealthBoard:
    """Shared health table for N co-located rank threads.

    Each rank calls :meth:`beat` once per gossip round; any rank may ask
    :meth:`poll` / :meth:`dead_ranks` about the others.  Detection is by
    *silence*, exactly like the wire path: a chaos-killed thread simply
    stops beating.  Thread-safe (one lock, O(1) per beat)."""

    def __init__(self, n_ranks: int, *, suspect_after_s: float = 0.5,
                 dead_after_s: float = 1.5,
                 clock: Callable[[], float] = time.monotonic,
                 members: Optional[Set[int]] = None):
        """``members`` (elastic runs) names the slots that participate
        from the start; the rest begin LEFT — capacity reserved for
        later joiners, never promoted to SUSPECT/DEAD by their silence.
        Default: every slot is a member (the fixed-fleet behavior)."""
        self._mu = _lc.lock("runtime.resilience.HealthBoard._mu")
        self._cores = [
            _HealthCore(f"rank{r}", suspect_after_s, dead_after_s, clock)
            for r in range(n_ranks)
        ]
        if members is not None:
            absent = set(range(n_ranks)) - {int(r) for r in members}
            for r in absent:
                self._cores[r].state = LEFT  # initial, not a transition

    def beat(self, rank: int) -> None:
        with self._mu:
            self._cores[rank].note_ok()

    def poll(self, rank: int) -> int:
        with self._mu:
            return self._cores[rank].poll()

    def state(self, rank: int) -> int:
        with self._mu:
            return self._cores[rank].state

    def states(self) -> Dict[int, int]:
        """One consistent snapshot of every slot's state under a single
        lock acquisition — what the communication controller's per-round
        evidence collection reads (N ``state()`` calls would each see a
        different instant)."""
        with self._mu:
            return {r: c.state for r, c in enumerate(self._cores)}

    def dead_ranks(self) -> Set[int]:
        """Ranks currently DEAD (REJOINED ranks are NOT in this set —
        the healer re-admits them)."""
        with self._mu:
            return {r for r, c in enumerate(self._cores)
                    if c.poll() == DEAD}

    def rejoined_ranks(self) -> Set[int]:
        with self._mu:
            return {r for r, c in enumerate(self._cores)
                    if c.state == REJOINED}

    def joining_ranks(self) -> Set[int]:
        with self._mu:
            return {r for r, c in enumerate(self._cores)
                    if c.state == JOINING}

    def left_ranks(self) -> Set[int]:
        with self._mu:
            return {r for r, c in enumerate(self._cores)
                    if c.state == LEFT}

    def admit(self, rank: int) -> None:
        with self._mu:
            self._cores[rank].admit()

    def mark_dead(self, rank: int, reason: str = "") -> None:
        with self._mu:
            self._cores[rank].mark_dead(reason)

    def mark_joining(self, rank: int) -> None:
        with self._mu:
            self._cores[rank].mark_joining()

    def mark_left(self, rank: int) -> None:
        with self._mu:
            self._cores[rank].mark_left()

    def transitions(self, rank: int) -> List[Tuple[float, int, int]]:
        with self._mu:
            return list(self._cores[rank].transitions)


@dataclasses.dataclass
class ResilienceConfig:
    """Knobs for the fault-tolerant async runners (``resilience=``).

    ``None`` (the default everywhere) keeps the pre-resilience behavior:
    any peer failure is fatal to the run, exactly as before.

    Detection deadline: a SIGKILLed peer is declared DEAD after at most
    ``suspect``/``dead`` thresholds (thread mode, silence-based) or the
    reconnect budget's worst-case total sleep (wire mode) —
    :meth:`detection_deadline_s` quotes the configured bound."""

    # silence thresholds (thread-mode board AND wire-mode peer health)
    suspect_after_s: float = 0.5
    dead_after_s: float = 2.0
    # wire-mode reconnect policy (DepositStream)
    reconnect_base_s: float = 0.05
    reconnect_cap_s: float = 0.5
    reconnect_factor: float = 2.0
    reconnect_budget: int = 5
    reconnect_jitter: float = 0.5
    # lightweight peer-heartbeat wire op, ON by default (0 disables).
    # Health evidence otherwise comes only from deposit acks — and the
    # resilient dsgd loop WITHHOLDS deposits to a SUSPECT peer, so
    # without heartbeats suspicion could never clear and would escalate
    # a healthy-but-briefly-silent peer to DEAD.  An idle stream must be
    # able to prove the peer alive on its own.
    heartbeat_interval_s: float = 0.25
    # how long survivors wait at a rendezvous before treating the missing
    # ranks as dead (FileBarrier exclusion learning)
    barrier_timeout_s: float = 20.0
    # deterministic jitter for tests
    seed: Optional[int] = None

    def backoff_kwargs(self) -> dict:
        return dict(base_s=self.reconnect_base_s,
                    cap_s=self.reconnect_cap_s,
                    factor=self.reconnect_factor,
                    budget=self.reconnect_budget,
                    jitter=self.reconnect_jitter,
                    seed=self.seed)

    def detection_deadline_s(self) -> float:
        """The configured worst-case time to declare a dead peer DEAD."""
        wire = Backoff(**self.backoff_kwargs()).max_total_s()
        return max(self.dead_after_s, wire)
