"""Host-side wire codecs for the DCN window transport (protocol v2).

The device-side CHOCO path (:mod:`bluefog_tpu.ops.compression`) compresses
gossip innovations *inside the jitted program* — its payloads ride
``lax.ppermute`` and never touch the host.  The cross-host TCP deposit
stream (:mod:`bluefog_tpu.runtime.window_server`) is the one wire where
bandwidth is genuinely scarce (DCN, not ICI), and it runs entirely on the
host — so it needs numpy twins of the same operators, usable from a socket
sender thread with no jax import or trace.

Two lossy codecs plus the identity, negotiated per connection via the v2
HELLO feature mask and selected per deposit item by a codec byte:

- ``none``  — dense little-endian array bytes (the window's dtype).
- ``f32``   — values downcast to float32 on the wire, widened back to the
  window dtype on receipt.  Halves the bytes of an f64 window; exact for
  f32 windows.  (The quantize disposition of the reference-adjacent
  compression literature; cheap enough for a per-step hot path.)
- ``topk``  — keep the ``ceil(ratio * n)`` largest-|x| coordinates;
  the wire carries ``k | int32 idx[k] | f32 vals[k]`` — the same
  data-dependent value+index format as :func:`bluefog_tpu.ops.
  compression.top_k`, with :func:`kept` matching its ``_kept`` arithmetic
  exactly (asserted by the twin test in ``tests/test_window_transport``).
  The receiver reconstructs a DENSE vector (zeros off-support) and applies
  it through the normal deposit path, so accumulate semantics compose: a
  top-k deposit scatter-adds its kept coordinates.

Lossy codecs change deposited *values*, so they are strictly opt-in: the
exactly-once / mass-conservation paths (push-sum ``p`` mass) must run with
``none``.  The achieved ratio is exported on the host metrics path as
``bf_compression_ratio{compressor="wire_<name>",transport="tcp"}`` —
the same gauge the device CHOCO path accounts to.
"""

from __future__ import annotations

import struct
from typing import List, Optional, Tuple

import numpy as np

__all__ = [
    "CODEC_NONE", "CODEC_F32", "CODEC_TOPK",
    "CODEC_IDS", "CODEC_NAMES",
    "kept", "encode", "decode", "wire_bytes_bound",
]

CODEC_NONE = 0
CODEC_F32 = 1
CODEC_TOPK = 2

CODEC_IDS = {"none": CODEC_NONE, "f32": CODEC_F32, "topk": CODEC_TOPK}
CODEC_NAMES = {v: k for k, v in CODEC_IDS.items()}

_TOPK_HDR = struct.Struct("<q")  # k, then int32 idx[k], then f32 vals[k]


def kept(n: int, ratio: float) -> int:
    """Kept-coordinate count for ``topk`` — numpy twin of
    ``ops.compression._kept`` (kept in lockstep by a test, not an import:
    this module must stay importable without jax on server daemon
    threads and bench workers)."""
    return max(1, min(n, int(round(ratio * n))))


def encode(arr: np.ndarray, codec: int, *, topk_ratio: float = 0.1,
           ) -> Tuple[List, int]:
    """Encode a contiguous 1-D window payload for the wire.

    Returns ``(views, wire_bytes)`` where ``views`` is a scatter-gather
    list of buffer objects for ``sendmsg`` (never a joined copy) and
    ``wire_bytes`` their total length.  The input is not modified; for
    the lossy codecs the returned views own fresh arrays, so the caller
    may reuse ``arr`` immediately.
    """
    if codec == CODEC_NONE:
        mv = memoryview(np.ascontiguousarray(arr)).cast("B")
        return [mv], len(mv)
    if codec == CODEC_F32:
        mv = memoryview(np.ascontiguousarray(arr, np.float32)).cast("B")
        return [mv], len(mv)
    if codec == CODEC_TOPK:
        flat = np.ascontiguousarray(arr).reshape(-1)
        n = flat.size
        k = kept(n, topk_ratio)
        if k >= n:
            idx = np.arange(n, dtype=np.int32)
        else:
            # argpartition: O(n) selection of the k largest |x|; index
            # order on the wire is unspecified (scatter is order-free)
            idx = np.argpartition(np.abs(flat), n - k)[n - k:]
            idx = idx.astype(np.int32)
        vals = flat[idx].astype(np.float32)
        views = [_TOPK_HDR.pack(int(k)),
                 memoryview(idx).cast("B"), memoryview(vals).cast("B")]
        return views, _TOPK_HDR.size + k * 8
    raise ValueError(f"unknown wire codec id {codec}")


def wire_bytes_bound(n_elems: int, itemsize: int) -> int:
    """Largest wire size any codec may legally claim for a window of
    ``n_elems`` — the server-side allocation guard (a lying length field
    must never make the owner allocate unbounded memory)."""
    return max(n_elems * itemsize,            # dense in the window dtype
               _TOPK_HDR.size + n_elems * 8)  # full-support topk


def decode(codec: int, payload: memoryview, n_elems: int,
           dtype: np.dtype, out: Optional[np.ndarray] = None
           ) -> np.ndarray:
    """Decode a wire payload into a DENSE ``(n_elems,)`` array of the
    window's dtype.  ``out`` (when given, correctly sized) is reused as
    the destination scratch — the server passes a per-connection buffer
    so the hot path allocates nothing.  Raises ``ValueError`` on any
    inconsistent geometry (the caller maps that to a protocol error and
    keeps the stream alive: lengths were known before the payload was
    read, so the framing survives a bad item)."""
    dtype = np.dtype(dtype)
    if out is None or out.size != n_elems or out.dtype != dtype:
        out = np.empty(n_elems, dtype)
    if codec == CODEC_NONE:
        if len(payload) != n_elems * dtype.itemsize:
            raise ValueError("dense payload length mismatch")
        out[:] = np.frombuffer(payload, dtype, count=n_elems)
        return out
    if codec == CODEC_F32:
        if len(payload) != n_elems * 4:
            raise ValueError("f32 payload length mismatch")
        np.copyto(out, np.frombuffer(payload, np.float32, count=n_elems),
                  casting="unsafe")
        return out
    if codec == CODEC_TOPK:
        if len(payload) < _TOPK_HDR.size:
            raise ValueError("topk payload too short")
        # bfwire: layout-ok codec payload headers are op-agnostic
        # (encode/decode live in this module; the codec twin tests pin
        # their symmetry, so op contexts inherited from callers can
        # never represent a one-sided frame)
        (k,) = _TOPK_HDR.unpack_from(payload, 0)
        if k < 0 or k > n_elems or len(payload) != _TOPK_HDR.size + k * 8:
            raise ValueError("topk payload geometry mismatch")
        idx = np.frombuffer(payload, np.int32, count=k,
                            offset=_TOPK_HDR.size)
        vals = np.frombuffer(payload, np.float32, count=k,
                             offset=_TOPK_HDR.size + k * 4)
        if k and (idx.min() < 0 or idx.max() >= n_elems):
            raise ValueError("topk index out of range")
        out[:] = 0
        out[idx] = vals  # duplicate indices are a client bug; last wins
        return out
    raise ValueError(f"unknown wire codec id {codec}")


def wire_ratio(codec: int, n_elems: int, itemsize: int, *,
               topk_ratio: float = 0.1) -> float:
    """wire bytes / dense bytes — the ``bf_compression_ratio`` accounting
    (mirrors ``Compressor.wire_ratio`` on the device path)."""
    dense = n_elems * itemsize
    if codec == CODEC_NONE:
        return 1.0
    if codec == CODEC_F32:
        return n_elems * 4 / dense
    if codec == CODEC_TOPK:
        return (_TOPK_HDR.size + kept(n_elems, topk_ratio) * 8) / dense
    raise ValueError(f"unknown wire codec id {codec}")
