"""The ONE registry of wire v2 status codes.

Three modules speak these codes — the server
(:mod:`bluefog_tpu.runtime.window_server`), the snapshot reader
(:mod:`bluefog_tpu.serving.client`), and the push subscriber
(:mod:`bluefog_tpu.serving.subscriber`) — and until this table existed
each hand-carried its own literals, which had already drifted once per
review notes.  Import from here; never re-type a code.

Dependency-free by design (stdlib only): the serving clients import it
without pulling the server machinery, and the analysis passes import it
without touching sockets.  The BF-DOC001 lint
(:mod:`bluefog_tpu.analysis.doc_lint`) checks that ``docs/transport.md``
documents every code in :data:`WIRE_V2_CODES`, so the doc cannot drift
from this table again.

Conventions: codes are negative ``i64`` statuses on the wire.  ``-1``
(native-table op failure) and the geometry/window codes predate wire v2
and are shared with the in-process table; ``-100`` and below are
wire-protocol codes proper.
"""

from __future__ import annotations

from typing import Dict

__all__ = [
    "ERR_BAD_OP",
    "ERR_BUSY",
    "ERR_CODEC",
    "ERR_DELTA_BASE",
    "ERR_GEOMETRY",
    "ERR_NO_SNAPSHOT",
    "ERR_NO_WINDOW",
    "ERR_RELAY_LOOP",
    "ERR_ROUND_ROLLED",
    "ERR_STALE_EPOCH",
    "ERR_TOO_LARGE",
    "ERR_VERSION",
    "PROTOCOL_VERSION",
    "STATUS_TEXT",
    "WIRE_V2_CODES",
    "err_text",
    "is_retriable",
]

PROTOCOL_VERSION = 2

# table-level statuses (shared with the native/fallback window table)
ERR_GEOMETRY = -2     # dtype/n_elems disagree with the window's geometry
ERR_NO_WINDOW = -3    # no such window on the serving host

# wire-protocol statuses (v2)
ERR_BAD_OP = -100        # unparseable request
ERR_VERSION = -101       # protocol version mismatch (v1 frame/bad HELLO)
ERR_CODEC = -102         # codec not granted / payload undecodable
# -103 is deliberately unassigned (a v2 draft code that never shipped);
# keep the gap so an old peer emitting it is recognizably foreign
ERR_TOO_LARGE = -104     # claimed length exceeds any legal encoding
ERR_STALE_EPOCH = -105   # attach/batch/subscribe from a superseded epoch
ERR_BUSY = -106          # previous stream generation could not quiesce
ERR_ROUND_ROLLED = -107  # RETRIABLE: pinned snapshot round superseded
ERR_NO_SNAPSHOT = -108   # group/leaf has no published snapshot (yet)
ERR_DELTA_BASE = -109    # RETRIABLE: DELTA frame base round != the
                         # receiver's reconstruction cursor — drop the
                         # connection; the resumed stream resyncs with a
                         # full-frame anchor (wire op 10, docs/serving.md)
ERR_RELAY_LOOP = -110    # a relay refused a subscription that would
                         # close a cycle (its upstream IS its own serving
                         # address) — terminal: a relay tree must be a
                         # tree

STATUS_TEXT: Dict[int, str] = {
    ERR_GEOMETRY: "size/dtype mismatch with the window's geometry",
    ERR_NO_WINDOW: "no such window on the serving host",
    ERR_BAD_OP: "unparseable request",
    ERR_VERSION: (f"protocol version mismatch (this client speaks "
                  f"v{PROTOCOL_VERSION}; peer rejected the handshake)"),
    ERR_CODEC: "wire codec not negotiated or payload undecodable",
    ERR_TOO_LARGE: "claimed payload length exceeds any legal encoding",
    ERR_STALE_EPOCH: ("stream epoch superseded (a newer connection of "
                      "this DepositStream attached; this one is a "
                      "zombie)"),
    ERR_BUSY: ("previous stream generation still draining; attach "
               "again after backoff"),
    ERR_ROUND_ROLLED: ("snapshot round rolled: the pinned round is no "
                       "longer current (retriable — re-pin at the "
                       "table's new round and re-read)"),
    ERR_NO_SNAPSHOT: ("no round-stamped snapshot published for this "
                      "group/leaf (retriable while the publisher warms "
                      "up; terminal for a misspelled name)"),
    ERR_DELTA_BASE: ("delta base round does not match the receiver's "
                     "reconstruction cursor (retriable: drop the push "
                     "connection and resubscribe — the resumed stream "
                     "resyncs with a full-frame anchor)"),
    ERR_RELAY_LOOP: ("relay subscription refused: the upstream address "
                     "is the relay's own serving address, which would "
                     "close a cycle — point the relay at its parent "
                     "tier, not itself"),
}

# the v2 wire-protocol codes docs/transport.md must document (BF-DOC001)
WIRE_V2_CODES = (ERR_BAD_OP, ERR_VERSION, ERR_CODEC, ERR_TOO_LARGE,
                 ERR_STALE_EPOCH, ERR_BUSY, ERR_ROUND_ROLLED,
                 ERR_NO_SNAPSHOT, ERR_DELTA_BASE, ERR_RELAY_LOOP)

# codes the doc may mention as explicitly-unassigned gaps (the doc lint
# accepts these without requiring a registry constant).  DERIVED from
# the registry — every gap in the contiguous v2 range is by definition
# unassigned — so adding a code can never leave this tuple stale; the
# BF-WIRE002 check (analysis/protocol_check.py) asserts the derivation
# holds on the live module.
UNASSIGNED_CODES = tuple(
    c for c in range(max(WIRE_V2_CODES), min(WIRE_V2_CODES) - 1, -1)
    if c not in WIRE_V2_CODES)

# codes a client may retry without changing anything (vs. terminal
# protocol rejections, where retrying only relabels the real error)
_RETRIABLE = frozenset({ERR_BUSY, ERR_ROUND_ROLLED, ERR_NO_SNAPSHOT,
                        ERR_DELTA_BASE})


def is_retriable(rc: int) -> bool:
    """True for statuses a well-behaved client retries (after backoff /
    re-pin); False for terminal rejections."""
    return rc in _RETRIABLE


def err_text(rc: int) -> str:
    """Human-readable explanation of a negative wire status."""
    return STATUS_TEXT.get(rc, "window missing, slot out of range, or "
                           "size/dtype mismatch")
