"""Genuinely asynchronous one-sided windows over the native host runtime.

The portable ``ops/windows.py`` path expresses one-sided *dataflow* inside an
SPMD program: both sides' programs contain the ppermute, so ranks advance in
lockstep (the reference's NCCL-emulation disposition).  The reference's MPI
backend is stronger — ``MPI_Put`` lands in the target's window with **no
receiver involvement**, so ranks progress at different rates with no global
barrier anywhere (upstream ``bluefog/common/mpi_controller.cc`` Win* +
lock/flush epochs; SURVEY.md §3.4).

This module reproduces that execution model on the TPU build's host runtime:

- :class:`AsyncWindow` — a rank's landing zone, backed by the native window
  table (``csrc/windows.cc``): per-slot locked buffers with deposit
  (put/accumulate), consume-exactly-once reads, and deposit-count staleness
  bookkeeping.  Within a process, "remote" writes are direct memory
  deposits into the target rank's table entry; across OS *processes* on a
  host the same table rides named POSIX shared memory (``shm=True`` /
  ``attach=True`` — the shared-memory MPI disposition, robust process-shared
  mutexes included); within a TPU slice the device-side analog is the
  Pallas remote-DMA kernel (:mod:`bluefog_tpu.ops.pallas_gossip`).

- :class:`TreePacker` — the device↔window bridge: packs a pytree of jax
  device arrays into one contiguous host vector (one batched
  ``jax.device_get``) and unpacks it back, so model parameters ride the
  window table.

- :func:`run_async_pushsum` — the demonstration the SPMD path cannot
  express: N rank-threads run push-sum with **rank-dependent step rates**
  (deliberate compute skew), depositing weighted (x, p) mass into neighbors'
  windows and consuming whatever has landed whenever they step.  Because
  deposits accumulate and consumes are exactly-once, mass is conserved under
  arbitrary interleaving, and every rank's ``x / p`` converges to the true
  global mean despite the skew — the defining property of asynchronous
  push-sum (Kempe et al.; the reference's ``DistributedWinPutOptimizer``
  foundation).

- :func:`run_async_dsgd` / :class:`AsyncWinPutOptimizer` — asynchronous
  decentralized *training* on that foundation (subgradient-push, Nedić &
  Olshevsky): each rank-thread consumes landed (x, p) mass, de-biases
  ``z = x / p``, takes a gradient step on real model parameters through
  :class:`TreePacker`, and split-deposits to its out-neighbors — no barrier
  anywhere, ranks step at independent rates.  This is the execution model of
  the reference's ``DistributedWinPutOptimizer`` production path
  (``bluefog/torch/optimizers.py`` + ``mpi_win_ops.cc``, SURVEY.md §3.4).
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import contextlib

import numpy as np

from bluefog_tpu import chaos as _chaos
from bluefog_tpu.utils import lockcheck as _lc
from bluefog_tpu.blackbox import recorder as _bb
from bluefog_tpu.control import (CommController as _CommController,
                                 ControlConfig as _ControlConfig,
                                 EvidenceBoard as _EvidenceBoard,
                                 TransportConfig as _TransportConfig,
                                 TransportPlan as _TransportPlan,
                                 decide_transport_plan
                                 as _decide_transport_plan,
                                 evidence as _ctlev)
from bluefog_tpu.fleet.wiring import (FleetConfig as _FleetConfig,
                                      FleetRuntime as _FleetRuntime)
from bluefog_tpu.metrics import comm as _mt
from bluefog_tpu.metrics.health import MixingTracker as _MixingTracker
from bluefog_tpu.runtime import (membership as _mship, native,
                                 resilience as _res)
from bluefog_tpu.serving import snapshots as _snapshots
from bluefog_tpu.topology.graphs import (Topology, heal as _heal,
                                         replan as _replan)
from bluefog_tpu.tracing import recorder as _tr
from bluefog_tpu.utils import log as _log, timeline as _timeline


@contextlib.contextmanager
def _host_span(name: str):
    """B/E timeline span in a PER-THREAD lane (tid = thread ident): the
    async windows are deposited into by concurrent rank threads, and
    same-name spans from different threads must neither overwrite each
    other's bookkeeping nor mis-nest in one trace lane.  No-op (no jax
    annotation either — that bookkeeping is per-call cost) when no
    timeline is recording."""
    tl = _timeline.current()
    if tl is None:
        yield
        return
    tid = threading.get_ident() % 1_000_000
    tl.begin(name, "async_window", tid)
    try:
        yield
    finally:
        tl.end(name, "async_window", tid)

__all__ = [
    "AsyncWindow",
    "TreePacker",
    "run_async_pushsum",
    "run_async_dsgd",
    "run_async_dsgd_rank",
    "AsyncWinPutOptimizer",
    "PushSumReport",
    "DSGDReport",
    "DoubleBuffer",
    "FileBarrier",
    "shm_unlink_window",
]

_DTYPES = {np.dtype(np.float32): 0, np.dtype(np.float64): 1}


class _PyWinTable:
    """Pure-Python fallback mirroring ``csrc/windows.cc`` semantics
    (BLUEFOG_TPU_NO_NATIVE / no C++ toolchain)."""

    def __init__(self):
        self._mu = _lc.lock("runtime.async_windows._PyWinTable._mu")
        self._wins: Dict[str, dict] = {}

    def create(self, name, n_slots, n_elems, dtype):
        with self._mu:
            if name in self._wins:
                return -2
            self._wins[name] = {
                "self": np.zeros(n_elems, dtype),
                "self_mu": _lc.lock(
                    "runtime.async_windows._PyWinTable.self_mu"),
                "slots": [
                    {"mu": _lc.lock("runtime.async_windows._PyWinTable.slot_mu"),
                     "buf": np.zeros(n_elems, dtype),
                     "deposits": 0, "fresh": 0}
                    for _ in range(n_slots)
                ],
            }
            return 0

    def _get(self, name):
        with self._mu:
            return self._wins.get(name)

    def info(self, name):
        """(n_slots, n_elems, dtype) or None — the fallback twin of the
        native ``bf_win_info`` (the TCP window server validates remote
        geometry through this before touching any buffer)."""
        w = self._get(name)
        if w is None:
            return None
        return len(w["slots"]), int(w["self"].size), w["self"].dtype

    def free(self, name):
        with self._mu:
            return 0 if self._wins.pop(name, None) is not None else -1

    def deposit(self, name, slot, arr, accumulate):
        w = self._get(name)
        if w is None or not (0 <= slot < len(w["slots"])):
            return -1
        s = w["slots"][slot]
        with s["mu"]:
            if accumulate:
                s["buf"] += arr
            else:
                s["buf"][:] = arr
            s["deposits"] += 1
            s["fresh"] += 1
            return s["deposits"]

    def read(self, name, slot, consume):
        w = self._get(name)
        if w is None or not (0 <= slot < len(w["slots"])):
            return None, -1
        s = w["slots"][slot]
        with s["mu"]:
            out = s["buf"].copy()
            fresh = s["fresh"]
            if consume:
                s["buf"][:] = 0
                s["fresh"] = 0
            return out, fresh

    def set_self(self, name, arr):
        w = self._get(name)
        if w is None:
            return -1
        with w["self_mu"]:
            w["self"][:] = arr
        return 0

    def read_self(self, name):
        w = self._get(name)
        if w is None:
            return None
        with w["self_mu"]:
            return w["self"].copy()


# One process-wide pool for TreePacker's parallel leaf casts (np.copyto /
# astype release the GIL): shared across packer instances so N concurrent
# rank loops cannot multiply idle worker threads, created under a lock.
# ThreadPoolExecutor workers are joined at interpreter exit (they are NOT
# daemon threads); the casts are plain memory ops, so a wedged worker means
# wedged memory — at which point exit semantics are moot.
_CAST_WORKERS = min(8, os.cpu_count() or 1)
_cast_pool_obj = None
_cast_pool_mu = _lc.lock("runtime.async_windows._cast_pool_mu")


def _cast_pool():
    global _cast_pool_obj
    with _cast_pool_mu:
        if _cast_pool_obj is None:
            from concurrent.futures import ThreadPoolExecutor

            _cast_pool_obj = ThreadPoolExecutor(max_workers=_CAST_WORKERS)
        return _cast_pool_obj


_py_table: Optional[_PyWinTable] = None
_py_table_mu = _lc.lock("runtime.async_windows._py_table_mu")


def _fallback() -> _PyWinTable:
    global _py_table
    with _py_table_mu:
        if _py_table is None:
            _py_table = _PyWinTable()
        return _py_table


class AsyncWindow:
    """A rank's passive-target window: self buffer + one landing slot per
    in-neighbor, living in process-global native memory so ANY thread (an
    engine worker delivering a remote payload, a peer rank on the same host)
    can deposit without this rank's participation.

    With ``shm=True`` the window is backed by named POSIX shared memory
    instead (``csrc/windows.cc`` create/attach_shm): the owner process
    creates its landing zone, peer *processes* attach the same name and
    deposit directly — ``MPI_Put`` crossing a real process boundary with no
    receiver involvement (upstream ``mpi_controller.cc`` Win*, SURVEY §3.4).
    ``attach=True`` opens a window another process owns (geometry is read
    from the segment; ``n_slots``/``n_elems``/``dtype`` args are ignored);
    the attach spins up to ``attach_timeout_s`` so create/attach order
    between processes is free.  Cross-process mode requires the native
    runtime (no pure-Python fallback — process-shared robust mutexes are a
    pthread feature).

    Flat f32/f64 vectors; callers pack pytrees/low-precision leaves
    themselves (the associated push-sum scalar is one extra trailing
    element — see :func:`run_async_pushsum`).
    """

    def __init__(self, name: str, n_slots: int = 0, n_elems: int = 0,
                 dtype=np.float32, *, shm: bool = False, attach: bool = False,
                 attach_timeout_s: float = 10.0):
        self.name = name
        self.shm = bool(shm or attach)
        self._lib = native.load()
        if self.shm:
            if self._lib is None:
                raise RuntimeError(
                    "cross-process (shm) windows require the native runtime "
                    "(unset BLUEFOG_TPU_NO_NATIVE / install a C++ toolchain)")
            if attach:
                rc = self._lib.bf_win_attach_shm(
                    name.encode(), int(attach_timeout_s * 1000))
                if rc == -2:
                    raise ValueError(
                        f"window {name!r} already open in this process")
                if rc != 0:
                    raise RuntimeError(
                        f"attach to shm window {name!r} failed ({rc}): owner "
                        f"did not publish within {attach_timeout_s}s?")
                import ctypes

                ns = ctypes.c_int()
                ne = ctypes.c_longlong()
                dt = ctypes.c_int()
                self._lib.bf_win_info(name.encode(), ctypes.byref(ns),
                                      ctypes.byref(ne), ctypes.byref(dt))
                self.n_slots = ns.value
                self.n_elems = int(ne.value)
                self.dtype = np.dtype(np.float64 if dt.value == 1
                                      else np.float32)
                return
        self.n_slots = n_slots
        self.n_elems = int(n_elems)
        self.dtype = np.dtype(dtype)
        if self.dtype not in _DTYPES:
            raise TypeError(f"AsyncWindow supports f32/f64, got {self.dtype}")
        if self.shm:
            rc = self._lib.bf_win_create_shm(
                name.encode(), n_slots, self.n_elems, _DTYPES[self.dtype])
            if rc == -2:
                raise ValueError(
                    f"shm window {name!r} already exists (live duplicate or "
                    "stale segment from a crashed run — "
                    "shm_unlink_window() cleans the latter)")
            if rc != 0:
                raise RuntimeError(f"bf_win_create_shm({name!r}) failed: {rc}")
        elif self._lib is not None:
            rc = self._lib.bf_win_create(
                name.encode(), n_slots, self.n_elems, _DTYPES[self.dtype])
            if rc == -2:
                raise ValueError(f"window {name!r} already exists")
            if rc != 0:
                raise RuntimeError(f"bf_win_create({name!r}) failed: {rc}")
        else:
            rc = _fallback().create(name, n_slots, self.n_elems, self.dtype)
            if rc == -2:
                raise ValueError(f"window {name!r} already exists")

    def _check(self, arr: np.ndarray) -> np.ndarray:
        a = np.ascontiguousarray(arr, dtype=self.dtype).ravel()
        if a.size != self.n_elems:
            raise ValueError(f"size {a.size} != window n_elems {self.n_elems}")
        return a

    def deposit(self, slot: int, arr: np.ndarray, *,
                accumulate: bool = True) -> int:
        """Land a payload in ``slot`` (MPI_Accumulate when ``accumulate``,
        MPI_Put otherwise).  Callable from any thread; never blocks on the
        window's owner.  Returns the slot's deposit count."""
        a = self._check(arr)
        op = "win_accumulate" if accumulate else "win_put"
        with _host_span(f"{op}.{self.name}"):
            if self._lib is None:
                v = _fallback().deposit(self.name, slot, a, accumulate)
            else:
                v = self._lib.bf_win_deposit(
                    self.name.encode(), slot, a.ctypes.data, self.n_elems,
                    1 if accumulate else 0)
        if v < 0:
            raise RuntimeError(f"deposit into {self.name!r}[{slot}] failed")
        # host-path metrics (guarded no-ops when disabled): per-window
        # deposit volume and count — this is the "bytes gossiped" of the
        # asynchronous execution model
        _mt.inc("bf_window_deposit_bytes_total",
                a.size * a.dtype.itemsize, window=self.name,
                transport="shm" if self.shm else "local")
        _mt.inc("bf_window_deposits_total", 1.0, window=self.name,
                op=op)
        # flight recorder (always-on host path): the last deposits before
        # a wedge are exactly what a hang dump needs to show
        _bb.record("window_deposit", window=self.name, slot=slot,
                   bytes=a.size * a.dtype.itemsize, op=op)
        return int(v)

    def deposit_async(self, slot: int, arr: np.ndarray, *,
                      accumulate: bool = True, copy: bool = True,
                      drain: bool = False) -> int:
        """Pipelined-transport-compatible spelling of :meth:`deposit`.
        In-process and shm deposits are already one-sided memory writes
        with nothing in flight afterwards, so this IS the synchronous
        deposit — the alias exists so loops written against the pipelined
        DCN handles (``deposit_async`` + :meth:`flush` fence) run
        unchanged on every transport.  ``copy`` is accepted for exact
        signature parity with ``PipelinedRemoteWindow.deposit_async``
        (asserted by a test so the one-loop-body invariant cannot
        drift); both values behave identically here because the payload
        is consumed before this call returns.  ``drain=True`` marks a
        graceful leaver's final mass handoff (same record the wire
        transport's flag bit2 produces on the owner)."""
        del copy
        if drain:
            _mt.inc("bf_drain_deposits_total", 1.0,
                    peer="local")
            _bb.record("drain_deposit", window=self.name, slot=slot,
                       peer="local")
        return self.deposit(slot, arr, accumulate=accumulate)

    def flush(self, timeout_s: Optional[float] = None) -> None:
        """Fence for :meth:`deposit_async` — a no-op here (deposits land
        before the call returns on the in-process/shm transports)."""

    def ack_ewma(self) -> Optional[float]:
        """Wire-transport parity: in-process/shm deposits have no ack
        channel, so there is no latency evidence here (always None) —
        the controller's thread-mode evidence is deposit staleness
        instead."""
        return None

    @property
    def reconnects(self) -> int:
        """Wire-transport parity: memory never reconnects."""
        return 0

    def set_codec(self, codec: Optional[str]) -> None:
        """Wire-transport parity: the in-process/shm path has no wire,
        so only ``None``/``"none"`` (no compression) is accepted."""
        if codec not in (None, "none"):
            raise ValueError(
                f"in-process/shm windows have no wire codec; cannot set "
                f"{codec!r}")

    def read(self, slot: int, *, consume: bool = True
             ) -> Tuple[np.ndarray, int]:
        """Read a landing slot; ``consume`` zero-fills it afterwards (mass is
        consumed exactly once).  Returns ``(value, deposits_since_last_
        consume)`` — 0 fresh deposits means the content is stale."""
        with _host_span(f"win_update.{self.name}"):
            if self._lib is None:
                out, fresh = _fallback().read(self.name, slot, consume)
                if out is None:
                    fresh = -1
            else:
                out = np.empty(self.n_elems, self.dtype)
                fresh = self._lib.bf_win_read(
                    self.name.encode(), slot, out.ctypes.data, self.n_elems,
                    1 if consume else 0)
        if fresh < 0:
            raise RuntimeError(f"read of {self.name!r}[{slot}] failed")
        # deposit staleness: fresh-count distribution per consume, plus an
        # explicit stale-read counter (0 fresh deposits = the content was
        # already consumed — the rank is outrunning its in-neighbors)
        _mt.observe("bf_window_fresh_per_read", float(fresh),
                    window=self.name)
        if consume and fresh == 0:
            _mt.inc("bf_window_stale_reads_total", 1.0, window=self.name)
        _bb.record("window_read", window=self.name, slot=slot,
                   fresh=int(fresh), consume=consume)
        return out, int(fresh)

    def set_self(self, arr: np.ndarray) -> None:
        """Publish this rank's value (what passive ``win_get`` readers see)."""
        a = self._check(arr)
        if self._lib is None:
            rc = _fallback().set_self(self.name, a)
        else:
            rc = self._lib.bf_win_set_self(self.name.encode(), a.ctypes.data,
                                           self.n_elems)
        if rc != 0:
            raise RuntimeError(f"set_self of {self.name!r} failed")

    def read_self(self) -> np.ndarray:
        if self._lib is None:
            out = _fallback().read_self(self.name)
            if out is None:
                raise RuntimeError(f"read_self of {self.name!r} failed")
            return out
        out = np.empty(self.n_elems, self.dtype)
        if self._lib.bf_win_read_self(self.name.encode(), out.ctypes.data,
                                      self.n_elems) != 0:
            raise RuntimeError(f"read_self of {self.name!r} failed")
        return out

    def free(self) -> None:
        if self._lib is None:
            _fallback().free(self.name)
        else:
            self._lib.bf_win_free(self.name.encode())


class TreePacker:
    """Pack a pytree of (jax or numpy) arrays into ONE contiguous host
    vector and back — the bridge that lets model parameters ride the native
    window table (whose buffers are flat f32/f64).

    Packing does a single batched ``jax.device_get`` for the whole tree (one
    host transfer, not one per leaf); unpacking restores original shapes and
    dtypes, optionally as jax arrays.

    ``sharding`` (a :class:`bluefog_tpu.sharding.mesh.ShardView`: resolved
    spec tree + inner-mesh axes + this packer's coordinate) makes the
    packer SPEC-AWARE: :meth:`pack` extracts only the coordinate's shard
    of each sharded leaf (replicated leaves ride whole), so the packed
    vector is shard-local — the wire unit of gossip-of-meshes — and
    :meth:`unpack` restores SHARD-shaped leaves.  ``pack`` accepts either
    the full tree (slices out the shard) or an already-shard-shaped tree
    (copies as-is), so both the publish path (full params) and a
    shard-local compute loop repack without gathering.  Reassembling the
    full tree from every coordinate's vector is
    :func:`bluefog_tpu.sharding.apply.reassemble_vectors` — the read
    boundary, never the hot path.
    """

    # float dtypes (width <= 32 bit) eligible for the fused device fast
    # path: staging through an f32 wire is lossless for them.  Integer
    # leaves (PRNG keys, step counters) stay on the host loop — int32
    # through f32 would corrupt values above 2^24, while the f64 host wire
    # keeps them exact.
    _F32_SAFE = (np.dtype(np.float32), np.dtype(np.float16))

    def __init__(self, template, dtype=np.float64, *, sharding=None):
        import jax
        import jax.numpy as jnp

        leaves, self._treedef = jax.tree_util.tree_flatten(template)
        self._full_shapes = [tuple(np.shape(l)) for l in leaves]
        self.sharding = sharding
        if sharding is not None:
            spec_flat = sharding.spec_leaves(template)
            self._shapes = [tuple(sharding.leaf_shape(s, sp))
                            for s, sp in zip(self._full_shapes, spec_flat)]
            self._slices = [sharding.leaf_slices(s, sp)
                            for s, sp in zip(self._full_shapes, spec_flat)]
        else:
            self._shapes = self._full_shapes
            self._slices = None
        self._sizes = [int(np.prod(s, dtype=np.int64)) for s in self._shapes]
        self._dtypes = [np.dtype(getattr(l, "dtype", None) or
                                 np.asarray(l).dtype) for l in leaves]
        self.size = int(sum(self._sizes))
        self.dtype = np.dtype(dtype)
        # device fusion pays on real accelerators (ONE host transfer instead
        # of per-leaf); on the CPU backend it only adds copies — there the
        # win is parallel host casts (numpy releases the GIL in copyto).
        # Spec-aware packers stay on the host path: the shard slice is
        # host-side numpy arithmetic by design.
        self._fusable = all(
            dt in self._F32_SAFE or dt == jnp.bfloat16.dtype
            for dt in self._dtypes) and jax.default_backend() != "cpu" \
            and sharding is None
        self._device_pack = None    # built lazily, cached per instance
        self._device_unpack = None
        self._offs = np.cumsum([0] + self._sizes)

    def pack(self, tree, out: Optional[np.ndarray] = None) -> np.ndarray:
        import jax

        leaves = jax.tree_util.tree_leaves(tree)
        if len(leaves) != len(self._sizes):
            raise ValueError(
                f"tree has {len(leaves)} leaves, template {len(self._sizes)}")
        vec = np.empty(self.size, self.dtype) if out is None else out
        if vec.shape != (self.size,) or vec.dtype != self.dtype:
            raise ValueError(f"out must be ({self.size},) {self.dtype}")
        if self._fusable and all(isinstance(l, jax.Array) for l in leaves):
            # fused fast path: ravel+concat ON DEVICE (one compiled
            # program), ONE contiguous f32 transfer, one vectorized host
            # widen — instead of a per-leaf transfer + f64 copy each.
            # Per-leaf shapes are validated as the host path's slice
            # assignment would: a wrong-shaped leaf must raise, not land
            # at the wrong offsets.
            for l, s in zip(leaves, self._shapes):
                if tuple(l.shape) != s:
                    raise ValueError(
                        f"leaf shape {tuple(l.shape)} != template {s}")
            if self._device_pack is None:
                import jax.numpy as jnp

                self._device_pack = jax.jit(lambda ls: jnp.concatenate(
                    [jnp.ravel(l).astype(jnp.float32) for l in ls]))
            vec[:] = np.asarray(self._device_pack(leaves))
            return vec
        host = jax.device_get(leaves)  # one batched transfer
        self._scatter(vec, host)
        return vec

    def _scatter(self, vec: np.ndarray, host) -> None:
        """Cast-copy each host leaf into its slice of ``vec``.  Leaves are
        copied concurrently for large trees: np.copyto releases the GIL, so
        the dominant cost (widening casts to the f64 wire) parallelizes
        across cores.  Spec-aware packers slice out this coordinate's
        shard of a full-shaped leaf here (shard-shaped leaves pass
        through); any other shape is an error, not a mis-landed write."""
        def one(i, a):
            a = np.asarray(a)
            if self._slices is not None:
                if tuple(a.shape) == self._full_shapes[i]:
                    a = np.ascontiguousarray(a[self._slices[i]])
                elif tuple(a.shape) != self._shapes[i]:
                    raise ValueError(
                        f"leaf {i} shape {tuple(a.shape)} is neither the "
                        f"full template shape {self._full_shapes[i]} nor "
                        f"the shard shape {self._shapes[i]}")
            np.copyto(vec[self._offs[i]:self._offs[i + 1]],
                      a.reshape(-1), casting="unsafe")

        if len(host) > 1 and self.size >= (1 << 20) and _CAST_WORKERS > 1:
            list(_cast_pool().map(lambda ia: one(*ia), enumerate(host)))
        else:
            for i, a in enumerate(host):
                one(i, a)

    def unpack(self, vec: np.ndarray, *, as_jax: bool = True):
        import jax

        vec = np.asarray(vec)
        if vec.shape != (self.size,):
            raise ValueError(f"vector shape {vec.shape} != ({self.size},)")
        if as_jax and self._fusable:
            # one narrow host cast, ONE transfer, fused device split
            if self._device_unpack is None:
                def du(flat):
                    return [
                        flat[o:o + sz].reshape(shape).astype(dt)
                        for o, sz, shape, dt in zip(
                            self._offs, self._sizes, self._shapes,
                            self._dtypes)
                    ]

                self._device_unpack = jax.jit(du)
            leaves = self._device_unpack(
                jax.numpy.asarray(np.asarray(vec, np.float32)))
            return jax.tree_util.tree_unflatten(self._treedef, leaves)
        def cut(i):
            return (vec[self._offs[i]:self._offs[i + 1]]
                    .reshape(self._shapes[i]).astype(self._dtypes[i]))

        if (len(self._sizes) > 1 and self.size >= (1 << 20)
                and _CAST_WORKERS > 1):
            host = list(_cast_pool().map(cut, range(len(self._sizes))))
        else:
            host = [cut(i) for i in range(len(self._sizes))]
        leaves = [jax.numpy.asarray(a) if as_jax else a for a in host]
        return jax.tree_util.tree_unflatten(self._treedef, leaves)


def _create_windows(name: str, slots_per_rank: Sequence[int],
                    n_elems: int) -> List[AsyncWindow]:
    """Create one window per rank, freeing the ones already created if any
    creation fails (e.g. a name collision with a previous run whose threads
    never stopped) — a partial failure must not poison the process-global
    window table for every later run."""
    wins: List[AsyncWindow] = []
    try:
        for r, slots in enumerate(slots_per_rank):
            wins.append(AsyncWindow(f"{name}:{r}", slots, n_elems,
                                    np.float64))
    except BaseException:
        for w in wins:
            w.free()
        raise
    return wins


@dataclass
class PushSumReport:
    """Outcome of an async push-sum run."""

    converged: bool
    wall_time_s: float
    steps_per_rank: List[int]
    estimates: np.ndarray      # (n_ranks, n_elems)
    true_mean: np.ndarray      # (n_elems,)
    max_abs_err: float
    total_mass: float          # sum of p over ranks; must stay == n_ranks
    # fault-tolerant runs: ranks declared DEAD and the push-sum mass they
    # carried to the grave (audit invariant: total_mass + died_mass == n)
    dead_ranks: List[int] = field(default_factory=list)
    died_mass: float = 0.0
    # per-rank health transition log [(t, from_state, to_state)] from the
    # shared board — the DEAD -> REJOINED timeline, durable past the
    # blackbox ring's eviction horizon
    health_transitions: Optional[Dict[int, list]] = None


def run_async_pushsum(
    topology: Topology,
    x0: np.ndarray,
    *,
    skew: Optional[Sequence[float]] = None,
    tol: float = 1e-3,
    timeout_s: float = 30.0,
    name: str = "async_pushsum",
    poll_interval_s: float = 0.002,
    resilience: Optional[_res.ResilienceConfig] = None,
) -> PushSumReport:
    """Asynchronous push-sum over ``topology`` with deliberately skewed rank
    step rates; returns once every rank's ``x / p`` is within ``tol`` of the
    true mean (or the timeout expires).

    Args:
      topology: directed graph; rank r deposits to its out-neighbors.
      x0: ``(n_ranks, n_elems)`` initial values; the target is their mean.
      skew: per-rank extra sleep (seconds) per step — rank-dependent compute
        time.  Default makes the slowest rank ~5x the fastest.
      tol / timeout_s: convergence gate.
      resilience: opt into peer-fault tolerance.  Ranks then beat a shared
        :class:`~bluefog_tpu.runtime.resilience.HealthBoard` each round; a
        rank that stops beating (chaos ``die``/``stall``, a crashed thread)
        is declared DEAD after ``dead_after_s`` of silence and the
        survivors re-normalize their mixing weights over the surviving set
        (:func:`bluefog_tpu.topology.heal`) — push-sum's weight channel
        keeps the surviving average unbiased through the change.  A rank
        that beats again is REJOINED and re-admitted at the next round
        boundary.  Convergence is then judged by survivor CONSENSUS (the
        surviving average is the mass-weighted mean of what survived, not
        the original ``x0`` mean).  ``dead_after_s`` must exceed the
        slowest rank's per-step sleep, or healthy-but-slow ranks read as
        dead.

    Protocol per rank step (no barriers anywhere):
      1. consume own landing slots, folding received (x, p) mass in;
      2. split mass: keep ``1/(out_deg+1)``, deposit the same fraction to
         each out-neighbor's window (accumulate);
      3. publish the current estimate; sleep ``skew[r]``.
    A monitor thread watches the published estimates and raises the global
    stop flag on convergence; ranks then drain any remaining in-flight mass
    so the mass-conservation invariant (sum p == n; with deaths,
    ``total_mass + died_mass == n``) holds exactly.
    """
    n = topology.size
    x0 = np.asarray(x0, np.float64)
    if x0.shape[0] != n:
        raise ValueError(f"x0 leading dim {x0.shape[0]} != topology size {n}")
    n_elems = int(np.prod(x0.shape[1:], dtype=np.int64)) if x0.ndim > 1 else 1
    x0 = x0.reshape(n, n_elems)
    true_mean = x0.mean(axis=0)

    if skew is None:
        skew = [poll_interval_s * (1.0 + 4.0 * r / max(n - 1, 1))
                for r in range(n)]

    in_nbrs = [list(topology.in_neighbors(r)) for r in range(n)]
    out_nbrs = [list(topology.out_neighbors(r)) for r in range(n)]
    # slot index of src in dst's window
    slot_of = [{src: k for k, src in enumerate(in_nbrs[r])} for r in range(n)]

    wins = _create_windows(
        name, [len(in_nbrs[r]) for r in range(n)], n_elems + 1)

    stop = threading.Event()
    steps = [0] * n
    estimates = x0.copy()
    est_mu = _lc.lock("runtime.async_windows.run_async_pushsum.est_mu")
    errors: List[BaseException] = []
    board = (_res.HealthBoard(n, suspect_after_s=resilience.suspect_after_s,
                              dead_after_s=resilience.dead_after_s)
             if resilience is not None else None)
    died = [False] * n
    died_mass = [0.0] * n

    def rank_loop(r: int):
        x = x0[r].copy()
        p = 1.0
        try:
            my_out = list(out_nbrs[r])
            frac = 1.0 / (len(my_out) + 1)
            known_dead: set = set()
            while not stop.is_set():
                _chaos.check_step(r, steps[r])
                if board is not None:
                    board.beat(r)
                    dead_now = board.dead_ranks() - {r}
                    if dead_now != known_dead:
                        # round boundary: re-admit any REJOINED rank (it
                        # left the dead set by beating again) and heal
                        # the mixing weights over the current survivors
                        for j in known_dead - dead_now:
                            board.admit(j)
                        known_dead = set(dead_now)
                        healed = _heal(topology, known_dead)
                        my_out = list(healed.out_neighbors(r))
                        frac = 1.0 / (len(my_out) + 1)
                # 1. consume whatever landed (possibly nothing — stale is
                # ok; slots of DEAD in-neighbors still drain their final
                # in-flight mass, which is what keeps the audit exact)
                for k in range(len(in_nbrs[r])):
                    buf, fresh = wins[r].read(k, consume=True)
                    if fresh > 0:
                        x += buf[:-1]
                        p += buf[-1]
                # 2. split mass outward — receivers need not be listening
                payload = np.concatenate([x * frac, [p * frac]])
                for j in my_out:
                    wins[j].deposit(slot_of[j][r], payload, accumulate=True)
                x *= frac
                p *= frac
                # 3. publish estimate, then rank-dependent "compute"
                with est_mu:
                    estimates[r] = x / p
                steps[r] += 1
                time.sleep(skew[r])
            # drain: fold in any mass still in flight so sum(p) == n exactly
            for k in range(len(in_nbrs[r])):
                buf, fresh = wins[r].read(k, consume=True)
                if fresh > 0:
                    x += buf[:-1]
                    p += buf[-1]
            with est_mu:
                estimates[r] = x / p
            wins[r].set_self(np.concatenate([x, [p]]))
        except _chaos.ChaosKill:
            # simulated rank death: no drain, no publish — but being
            # in-process, the corpse can leave a last will recording the
            # mass it took down, which makes the survivors' audit exact:
            # total_mass + died_mass == n
            died[r] = True
            died_mass[r] = p
        except BaseException as e:  # surfaced by the caller
            errors.append(e)
            stop.set()

    threads = [threading.Thread(target=rank_loop, args=(r,), daemon=True)
               for r in range(n)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()

    converged = False
    while time.perf_counter() - t0 < timeout_s:
        time.sleep(poll_interval_s * 5)
        if errors:
            break
        alive = [r for r in range(n) if not died[r]]
        if not alive:
            break  # chaos killed everyone; report below says so
        with est_mu:
            if board is None:
                err = float(np.abs(estimates - true_mean).max())
            else:
                # with deaths the surviving average is the mass-weighted
                # mean of what survived, unknowable in advance — judge
                # survivor CONSENSUS instead
                zs = estimates[alive]
                err = float(np.abs(zs - zs.mean(axis=0)).max())
        # every live rank must also have taken a few steps (no vacuous
        # pass); post-death, survivors must have stepped past the kill
        if err < tol and min(steps[r] for r in alive) >= 3:
            converged = True
            break
    stop.set()
    # A rank can be mid-sleep in its skew delay; give every thread time to
    # wake, drain, and publish before auditing (freeing windows under a live
    # thread would corrupt the mass audit and poison its next deposit).
    join_budget = max(skew) * 2 + 5.0
    for t in threads:
        t.join(timeout=join_budget)
    if any(t.is_alive() for t in threads):
        raise RuntimeError(
            "async push-sum rank threads failed to stop within "
            f"{join_budget:.1f}s; aborting without freeing windows")
    wall = time.perf_counter() - t0

    if errors:
        for w in wins:
            w.free()
        raise errors[0]

    # Mass invariant: self mass + anything deposited after a rank's final
    # drain (threads are joined, so slot reads race with nothing).  A dead
    # rank's window still participates: its landing slots hold the mass
    # that was in flight toward the corpse, and counting it is what makes
    # the audit exact — total + died_mass == n.
    total_mass = 0.0
    for r in range(n):
        total_mass += float(wins[r].read_self()[-1])
        for k in range(len(in_nbrs[r])):
            buf, fresh = wins[r].read(k, consume=False)
            if fresh > 0:
                total_mass += float(buf[-1])
    alive = [r for r in range(n) if not died[r]]
    with est_mu:
        if not alive:
            final_err = float("inf")  # no survivors, no consensus claim
        elif board is None or not any(died):
            final_err = float(np.abs(estimates - true_mean).max())
        else:
            zs = estimates[alive]
            final_err = float(np.abs(zs - zs.mean(axis=0)).max())
    report = PushSumReport(
        converged=converged and final_err < 10 * tol,
        wall_time_s=wall,
        steps_per_rank=list(steps),
        estimates=estimates.copy(),
        true_mean=true_mean,
        max_abs_err=final_err,
        total_mass=total_mass,
        dead_ranks=[r for r in range(n) if died[r]],
        died_mass=float(sum(died_mass)),
        health_transitions=(
            {r: board.transitions(r) for r in range(n)}
            if board is not None else None),
    )
    for w in wins:
        w.free()
    return report


# ---------------------------------------------------------------------------
# Asynchronous decentralized training (subgradient-push over the windows)
# ---------------------------------------------------------------------------


@dataclass
class DSGDReport:
    """Outcome of an asynchronous decentralized SGD run."""

    wall_time_s: float
    steps_per_rank: List[int]
    losses: List[List[float]]        # per rank, per local step
    final_params: list               # per rank, de-biased z = x/p pytrees
    total_mass: float                # sum of p over ranks (+ in flight) == n
    consensus_gap: float             # max over ranks of max|z_r - z_mean|
    # fault-tolerant runs only:
    dead_ranks: List[int] = field(default_factory=list)
    # thread-mode: mass the chaos-killed threads carried to the grave
    # (exact audit: total_mass + died_mass == n)
    died_mass: float = 0.0
    # process-mode: the surviving set's mass measured at the post-heal
    # rendezvous (exact audit: total_mass == baseline_mass); elastic
    # runs re-measure it at every join admission, so the audit stays
    # exact as the fleet grows
    baseline_mass: Optional[float] = None
    # thread-mode: per-rank health transitions [(t, from, to)] from the
    # shared board (see PushSumReport.health_transitions)
    health_transitions: Optional[Dict[int, list]] = None
    # elastic membership: ranks that completed a graceful drain (their
    # push-sum mass was HANDED OFF to out-neighbors — conserved, unlike
    # a corpse's, which shows up in died_mass) and ranks admitted
    # through the JOINING path at least once
    left_ranks: List[int] = field(default_factory=list)
    joined_ranks: List[int] = field(default_factory=list)
    # self-tuning control plane (control= runs): the highest-version
    # CommPlan any rank converged on, and how many plan changes the
    # reporting rank's controller made (bluefog_tpu.control)
    control_plan: Optional[object] = None
    plan_changes: int = 0


class DoubleBuffer:
    """Compute/gossip overlap for the dsgd runners: a background
    harvester consumes landed neighbor deposits from this rank's OWN
    landing window WHILE the round's gradient compute runs, staging them
    per slot; the staged round-(k-1) mass is applied only at the next
    ROUND BOUNDARY (:meth:`apply_staged` — the BF-WIN004 lint holds its
    call sites to round-boundary vocabulary, so a future edit cannot
    fold stale mixing mid-step).

    Correctness invariants:

    - **Mass moves exactly once.**  A harvested read is the window's
      consume-exactly-once take; the taken (x, p) sits in the per-slot
      staging buffer until a boundary applies it (or :meth:`close`
      hands the remainder back).  Between the take and the apply the
      mass is IN this object — :meth:`staged_mass` (after
      :meth:`pause`) is what a quiesce-rendezvous adds to local mass so
      harvested-but-unapplied mass stays visible to the exactness
      audit.
    - **Fold order is the serial order.**  Staging accumulates per slot
      in deposit order and :meth:`apply_staged` returns entries in SLOT
      order — the identical floating-point op sequence the serial
      gossip-IN loop performs, which is what makes the overlap fold
      byte-identical to serial for the same landed deposits (pinned by
      test).
    - **The wire is quiesced at every boundary.**  ``apply_staged`` /
      ``pause`` disarm the harvester and WAIT for its in-flight sweep
      to finish, so a round-boundary audit never races a half-taken
      slot.

    Overlap measurement: the harvester accumulates only the seconds it
    actually spends taking/staging (sweep-gap sleeps excluded);
    ``apply_staged`` returns that hidden time so the runner can report
    ``bf_overlap_fraction`` = hidden / (hidden + boundary-apply)
    seconds per round — 0 is the serial shape, 1 means every bit of
    gossip-IN work rode under compute.
    """

    def __init__(self, win, slots: Sequence[int], n_elems: int, *,
                 poll_s: float = 0.0005):
        self._win = win
        self._slots = [int(s) for s in slots]
        self._n = int(n_elems)
        self._poll_s = float(poll_s)
        self._mu = _lc.lock("runtime.async_windows.DoubleBuffer._mu")
        # _sweep_mu serializes sweeps against pause(): pause clears the
        # arm flag then acquires it, so on return no sweep is running
        # and none can start (the flag is re-checked under the lock)
        self._sweep_mu = _lc.lock(
            "runtime.async_windows.DoubleBuffer._sweep_mu")
        self._staged: Dict[int, np.ndarray] = {}
        self._fresh: Dict[int, int] = {}
        self._busy_s = 0.0
        self._armed = threading.Event()
        self._stopped = False
        self._thread = threading.Thread(
            target=self._harvest_loop, daemon=True,
            name=f"bf-harvest-{getattr(win, 'name', 'win')}")
        self._thread.start()

    # ------------------------------------------------------- harvester
    def _sweep(self, *, count_busy: bool) -> None:
        t0 = time.perf_counter() if count_busy else 0.0
        for k in self._slots:
            buf, fresh = self._win.read(k, consume=True)
            if fresh > 0:
                with self._mu:
                    st = self._staged.get(k)
                    if st is None:
                        self._staged[k] = buf
                    else:
                        st += buf
                    self._fresh[k] = self._fresh.get(k, 0) + int(fresh)
        if count_busy:
            with self._mu:
                self._busy_s += time.perf_counter() - t0

    def _harvest_loop(self) -> None:
        while True:
            self._armed.wait()
            if self._stopped:
                return
            with self._sweep_mu:
                # re-check under the lock: a pause() between the wait
                # and here must win (its return promises quiescence)
                if self._armed.is_set() and not self._stopped:
                    try:
                        self._sweep(count_busy=True)
                    except RuntimeError:
                        # the window vanished under us (an abnormal
                        # teardown): disarm and go idle — the boundary's
                        # own inline sweep surfaces the real error
                        self._armed.clear()
            if self._poll_s > 0:
                time.sleep(self._poll_s)

    # ------------------------------------------------------ boundaries
    def begin(self) -> None:
        """Arm one harvest window: from here until the next boundary
        (:meth:`apply_staged` / :meth:`pause`) the harvester sweeps this
        rank's landing slots concurrently with whatever the caller runs
        — the round's gradient compute, in the dsgd loops."""
        self._armed.set()

    def pause(self) -> None:
        """Disarm and WAIT for the in-flight sweep to finish.  On
        return the harvester is quiescent and the staging buffers are
        stable — the precondition for :meth:`staged_mass` inside a
        quiesce-rendezvous.  Staged mass is kept; the next boundary's
        :meth:`apply_staged` folds it."""
        self._armed.clear()
        with self._sweep_mu:
            pass

    def apply_staged(self) -> Tuple[List[Tuple[int, np.ndarray, int]],
                                    float]:
        """ROUND-BOUNDARY apply: quiesce the harvester, take one final
        inline sweep (a round folds at least what the serial path
        would), and return ``([(slot, payload, fresh)...] in slot
        order, hidden_harvest_seconds)``.  The caller folds the entries
        in the returned order — that IS the serial gossip-IN fold — and
        re-arms with :meth:`begin` when another round follows.  The
        BF-WIN004 lint restricts call sites of this method to functions
        speaking round-boundary vocabulary."""
        self.pause()
        self._sweep(count_busy=False)
        with self._mu:
            entries = [(k, self._staged.pop(k), self._fresh.pop(k, 0))
                       for k in self._slots if k in self._staged]
            busy, self._busy_s = self._busy_s, 0.0
        return entries, busy

    def staged_mass(self) -> float:
        """Sum of staged push-sum weight (last element of each staged
        payload).  Call after :meth:`pause` — the quiesce-rendezvous
        adds this to local mass so taken-but-unapplied mass cannot hide
        from the exactness audit."""
        with self._mu:
            return float(sum(float(buf[-1])
                             for buf in self._staged.values()))

    def close(self) -> List[Tuple[int, np.ndarray, int]]:
        """Stop the harvester and hand back whatever is staged (slot
        order).  The caller folds it — the end-of-run drain, a leaver's
        handoff, or a chaos corpse's last will — so taken mass is never
        dropped.  Idempotent (a second close returns [])."""
        self._stopped = True
        self._armed.set()  # release a parked wait
        if self._thread.is_alive():
            self._thread.join(timeout=10.0)
        with self._mu:
            entries = [(k, self._staged.pop(k), self._fresh.pop(k, 0))
                       for k in self._slots if k in self._staged]
        return entries


def run_async_dsgd(
    topology: Topology,
    params0,
    loss_and_grad,
    *,
    lr: float = 0.05,
    duration_s: float = 5.0,
    skew: Optional[Sequence[float]] = None,
    name: str = "async_dsgd",
    poll_interval_s: float = 0.0,
    resilience: Optional[_res.ResilienceConfig] = None,
    join_at_s: Optional[Dict[int, Sequence[float]]] = None,
    leave_at_s: Optional[Dict[int, float]] = None,
    snapshot_every: int = 0,
    control: Optional[_ControlConfig] = None,
    stop_after_steps: Optional[int] = None,
    fleet: Optional[_FleetConfig] = None,
    profile: Optional[str] = None,
    overlap: bool = False,
) -> DSGDReport:
    """Asynchronous decentralized SGD (subgradient-push, Nedić & Olshevsky)
    over the passive-target windows: the execution model of the reference's
    ``DistributedWinPutOptimizer`` (params pushed one-sidedly each step,
    merged by the receiver whenever it steps — SURVEY.md §3.4), with **no
    barrier anywhere** and rank-dependent step rates.

    Each rank-thread's step:
      1. consume landed ``(x, p)`` mass from its in-neighbor slots;
      2. de-bias ``z = x / p`` (the rank's current model estimate);
      3. ``grads, loss = loss_and_grad(rank, step, z_tree)`` on real model
         parameters (device pytrees via :class:`TreePacker`);
      4. ``x <- x - lr * p * grad`` (scaling by ``p`` makes the *de-biased*
         iterate take the plain gradient step: ``z' = z - lr * grad``);
      5. keep ``1/(out_deg+1)`` of ``(x, p)``, deposit the same fraction to
         each out-neighbor (accumulate) — receivers need not be listening.

    Mass is conserved exactly (sum of ``p`` stays ``n`` under any
    interleaving); consensus pressure comes from the repeated split/merge.

    Bias note (inherent to constant-step asynchronous SGD, not this
    implementation): ranks stepping at different rates weight the global
    objective by their rates — the stationary point is the *rate-weighted*
    optimum.  Homogeneous shards (the usual DP setting) are unaffected;
    heterogeneous objectives need rate-proportional lr correction or a
    diminishing step size, exactly as in the reference's async mode.

    Args:
      topology: directed graph over the rank threads.
      params0: initial model parameters (pytree; same start on every rank,
        the reference's ``broadcast_parameters`` convention).
      loss_and_grad: ``(rank, step, params_tree) -> (loss, grad_tree)``.
        Called concurrently from rank threads (jitted jax fns are safe).
      lr: SGD learning rate applied to the de-biased iterate.
      duration_s: wall-clock training budget (ranks then drain in-flight
        mass so the audit is exact).
      skew: per-rank extra sleep per step; default makes the slowest rank
        ~5x the fastest (the asynchrony the SPMD path cannot express).
      resilience: opt into peer-fault tolerance (see
        :func:`run_async_pushsum`): ranks beat a shared health board each
        round, a silent rank is declared DEAD after ``dead_after_s`` and
        healed out of the mixing weights (:func:`bluefog_tpu.topology.
        heal`); a rank that beats again is re-admitted at the next round
        boundary.  A chaos-killed thread leaves a last will of the mass
        it carried, so the audit stays exact: ``report.total_mass +
        report.died_mass == n``.
      join_at_s / leave_at_s: elastic membership (intentional change, the
        complement of ``resilience``'s unplanned death).  ``join_at_s``
        maps a rank to the wall-clock offsets at which it ATTACHES to the
        running job (an EMPTY offset list marks a reserved capacity slot
        that never joins): the rank starts ABSENT (a reserved capacity slot),
        then at each offset warm-starts by pulling a live member's
        published ``(x, p)`` snapshot from its window (``read_self`` —
        no checkpoint anywhere), enters with fresh push-sum weight
        ``p = 1`` and is admitted through the JOINING state at a round
        boundary.  ``leave_at_s`` maps a rank to the offset of its
        GRACEFUL DRAIN: it fences, hands its entire ``(x, p)`` mass to
        its live out-neighbors in final ``drain``-flagged deposits (a
        leaver's mass is conserved, never written off like a corpse's),
        and exits; a later join offset re-admits it (a flapping member).
        Chaos rules compose: ``rankN:join:after_s=T`` adds a join offset
        and ``rankN:leave:at_step=K`` drains at step K
        (:class:`~bluefog_tpu.chaos.ChaosLeave`).  Live ranks then
        re-plan the mixing graph over the current member set at round
        boundaries (:func:`bluefog_tpu.topology.replan` — deterministic
        in the member list, so every rank converges on the same plan
        with no coordination), and the audit is exact over the churn:
        ``report.total_mass + report.died_mass == len(initial members) +
        len(admissions)`` (= ``report.baseline_mass``).
      snapshot_every: when > 0, every rank publishes a ROUND-STAMPED
        ``(round, x, p)`` snapshot (plus an in-band ``round`` stamp
        leaf) into the process-global serving table every Nth step,
        under group ``f"{name}:{rank}"`` — the serve-while-training
        read path (:mod:`bluefog_tpu.serving`; any
        :class:`~bluefog_tpu.runtime.window_server.WindowServer` in
        this process serves it).  The publish is atomic (double-
        buffered swap under the table lock), so a reader can never
        observe ``x`` and ``p`` from different rounds.  0 (default)
        publishes nothing.
      control: opt into the SELF-TUNING communication control plane
        (:mod:`bluefog_tpu.control`).  Each rank-thread runs a
        :class:`~bluefog_tpu.control.CommController`; evidence (per-
        peer deposit staleness, health states, local disagreement,
        measured-vs-predicted mixing) is shared through an in-process
        :class:`~bluefog_tpu.control.EvidenceBoard` and every rank
        converges on the same round-stamped
        :class:`~bluefog_tpu.control.CommPlan` (decisions are
        deterministic in the disseminated evidence, with hysteresis +
        cooldowns).  Plans are actuated ONLY at round boundaries:
        slow peers' edges drop to the ring spine, the graph densifies
        when measured mixing lags the spectral-gap prediction, and
        gossip cadence stretches/shrinks.  Enabling the controller
        hands topology management to the deterministic replan family
        (``topology`` then defines capacity and rank numbering;
        windows take one landing slot per capacity rank, as elastic
        runs do).  The exact mass audit holds through every plan
        change — a plan moves edges, never mass.
      stop_after_steps: when set, the run ends (all ranks drain) as
        soon as ANY rank completes this many steps — the
        time-to-target mode the control A/B bench measures; otherwise
        ``duration_s`` alone gates the run.
      fleet: opt into the fleet health plane
        (:class:`~bluefog_tpu.fleet.FleetConfig`): each rank-thread
        publishes a round-stamped telemetry record (round-time stats,
        push-sum mass, per-in-neighbor deposit-staleness ages,
        blackbox event counts, host gauges) to ``fleet.<rank>`` under
        ``fleet.dir`` — REQUIRED here, the thread runner has no
        barrier directory — every ``fleet.every``-th round, at round
        boundaries.  ``fleet.slos`` additionally arms the in-loop SLO
        engine; with ``control=`` active, alert-named ranks feed the
        controller's evidence as SUSPECT
        (:meth:`~bluefog_tpu.control.CommController.note_alert`).
        The publisher only reads — the exact mass audit is unchanged.
      profile: directory for the continuous sampling profiler
        (:mod:`bluefog_tpu.profiling`): arms a process-wide sampler for
        the run and writes phase-attributed folded stacks to
        ``profile-rank0.jsonl`` there (rank threads share one process,
        so one file carries every thread's samples).  When the env var
        ``BLUEFOG_TPU_PROFILE`` already armed a profiler, that one is
        left alone — the runner only owns what it started.
      overlap: compute/gossip overlap via :class:`DoubleBuffer` — a
        per-rank harvester consumes landed neighbor deposits WHILE the
        gradient compute runs, and the staged round-(k-1) mixing is
        applied only at the next round boundary, in slot order (the
        serial fold order, so results are byte-identical to the serial
        path for the same landed deposits).  The boundary still sees a
        quiesced wire (the apply waits out any in-flight harvest
        sweep), staged mass stays visible to the exactness audit (a
        chaos corpse's last will and every drain fold it in), and the
        hidden-time share is reported as ``bf_overlap_fraction``.
    """
    n = topology.size
    if fleet is not None and fleet.dir is None:
        raise ValueError(
            "the thread runner has no barrier directory to default to: "
            "pass fleet=FleetConfig(dir=...) naming the shared record "
            "directory the bffleet-tpu dash / --check gate will read")
    packer = TreePacker(params0, np.float64)
    d = packer.size

    if skew is None:
        base = 0.001
        skew = [base * (1.0 + 4.0 * r / max(n - 1, 1)) for r in range(n)]

    in_nbrs = [list(topology.in_neighbors(r)) for r in range(n)]

    # elastic membership: merge the explicit schedules with chaos churn
    # rules (rankN:join:after_s adds a join offset; rankN:leave:at_step
    # raises ChaosLeave inside the loop)
    joins: Dict[int, List[float]] = {}
    for r, ts in (join_at_s or {}).items():
        seq = [ts] if isinstance(ts, (int, float)) else list(ts)
        joins[int(r)] = sorted(float(t) for t in seq)
    for r in range(n):
        ct = _chaos.join_times(r)
        if ct:
            joins.setdefault(r, []).extend(ct)
            joins[r].sort()
    leaves = {int(r): float(t) for r, t in (leave_at_s or {}).items()}
    inj = _chaos.get()
    elastic = bool(joins or leaves) or (
        inj is not None and any(ru.fault in ("leave", "join")
                                for ru in inj.rules))
    members0 = frozenset(range(n)) - frozenset(joins)
    if elastic and not members0:
        raise ValueError("every rank has a join schedule; at least one "
                         "initial member must seed the warm-start chain")

    # Slot scheme: elastic AND control-plane runs take one landing slot
    # PER CAPACITY RANK (slot index == source rank) — stable under
    # arbitrary membership change and under controller replans, which
    # dense in-neighbor slot maps are not (a replanned/penalized graph
    # has edges the original topology had no slot for).  Fixed fleets
    # keep the dense in-degree sizing: at ~log2(n) slots per rank it is
    # O(n log n · d) total where capacity slots are O(n² · d) — a real
    # memory difference when d is model-sized.
    cap_slots = elastic or control is not None
    if cap_slots:
        wins = _create_windows(name, [n] * n, d + 1)
        slot_of = None
    else:
        wins = _create_windows(
            name, [max(len(in_nbrs[r]), 1) for r in range(n)], d + 1)
        slot_of = [{src: k for k, src in enumerate(in_nbrs[r])}
                   for r in range(n)]

    stop = threading.Event()
    steps = [0] * n
    losses: List[List[float]] = [[] for _ in range(n)]
    finals: list = [None] * n
    errors: List[BaseException] = []
    x0 = packer.pack(params0)
    board = (_res.HealthBoard(
        n,
        suspect_after_s=(resilience.suspect_after_s
                         if resilience is not None else 0.5),
        dead_after_s=(resilience.dead_after_s
                      if resilience is not None else 2.0),
        members=members0 if elastic else None)
        if (resilience is not None or elastic or control is not None)
        else None)
    died = [False] * n
    died_mass = [0.0] * n
    # self-tuning control plane: the in-process evidence board every
    # rank's controller publishes to / decides from, and the per-rank
    # outcomes the report carries
    ctl_board = _EvidenceBoard() if control is not None else None
    final_plans: list = [None] * n
    ctl_changes = [0] * n

    # shared membership truth; each rank re-derives its plan from it at
    # round boundaries, so every loop converges on the same replan with
    # no coordination beyond this set (replan is deterministic in the
    # member list)
    mem_mu = _lc.lock("runtime.async_windows.run_async_dsgd.mem_mu")
    members = set(members0)
    left_final: set = set()
    ever_joined: set = set()
    joined_mass = [0.0]
    plan_cache: Dict[frozenset, Topology] = {}

    def _plan(active: frozenset) -> Topology:
        # the gauge tracks the CURRENT set even when the plan itself is
        # a cache hit (a flapping member returns to a set already seen)
        _mt.set("bf_members", float(len(active)))
        with mem_mu:
            cached = plan_cache.get(active)
        if cached is not None:
            return cached
        t0p = time.perf_counter()
        if elastic:
            plan = _replan(topology, active)
        else:
            plan = _heal(topology, frozenset(range(n)) - active)
        _mt.observe("bf_replan_seconds", time.perf_counter() - t0p)
        with mem_mu:
            plan_cache[active] = plan
        return plan

    t_run0 = time.perf_counter()

    def rank_loop(r: int):
        p = 1.0
        # model-sized scratch, allocated once: the hot loop must not
        # churn fresh ~d-element buffers per step (d can be 10^8)
        gvec = np.empty(d, np.float64)
        payload = np.empty(d + 1, np.float64)
        self_buf = np.empty(d + 1, np.float64)
        rec = _bb.get()  # flight recorder (None when off)
        my_joins = list(joins.get(r, []))
        is_member = r in members0
        leave_deadline = leaves.get(r)

        my_slots = (range(n) if cap_slots else range(len(in_nbrs[r])))

        # ---------------------------------------- control plane (opt-in)
        ctl = (_CommController(r, n, config=control)
               if control is not None else None)
        tracker: Optional[_MixingTracker] = None
        tracker_members: Optional[frozenset] = None
        my_in: List[int] = list(in_nbrs[r])
        gossip_every = 1
        # per-peer deposit-staleness clocks: the thread-mode lag signal
        # (seconds since the peer's last fresh deposit — the in-process
        # analog of the wire path's ack EWMA); fed for the controller
        # AND the fleet publisher, whichever is armed
        last_fresh: Dict[int, float] = {}
        # fleet health plane (opt-in): publisher + optional SLO engine.
        # Rank threads SHARE one process's blackbox ring / metrics
        # registry / procfs — rank 0 is elected their one carrier, or a
        # fleet-wide sum over records would count them n-fold
        flt = (_FleetRuntime(r, fleet.dir, fleet, process_stats=(r == 0))
               if fleet is not None else None)
        fleet_dis: Optional[float] = None

        def consume(x, p, observe: bool = False, staged=None):
            nonlocal fleet_dis
            dis = None
            z0 = None
            fleet_due = flt is not None and flt.due(steps[r])
            if observe and (ctl is not None or fleet_due):
                z0 = x / p
            now = time.perf_counter()
            if staged is None:
                # serial path: take the slots here, in slot order
                staged = []
                for k in my_slots:
                    if cap_slots and k == r:
                        continue
                    buf, fresh = wins[r].read(k, consume=True)
                    if fresh > 0:
                        staged.append((k, buf, fresh))
            # the fold — identical whether the entries were read just
            # above or harvested under compute by the DoubleBuffer
            # (apply_staged returns slot order, so the floating-point
            # op sequence matches the serial path byte for byte)
            for k, buf, fresh in staged:
                if fresh > 0:
                    if z0 is not None and buf[-1] > 0:
                        dj = float(np.linalg.norm(
                            buf[:-1] / buf[-1] - z0))
                        dis = dj if dis is None else max(dis, dj)
                    if observe and (ctl is not None or flt is not None):
                        # staleness clocks are keyed by SOURCE RANK:
                        # capacity slots are rank-indexed already, but a
                        # fixed fleet's dense slots must translate
                        # through the in-neighbor list (keying by slot
                        # would attribute rank j's freshness to rank k)
                        last_fresh[k if cap_slots
                                   else in_nbrs[r][k]] = now
                    x += buf[:-1]
                    p += buf[-1]
            if observe and ctl is not None and dis is not None:
                ctl.note_disagreement(dis)
            if fleet_due:
                fleet_dis = dis
            return p

        # compute/gossip overlap (opt-in): the harvester that consumes
        # landed deposits from this rank's landing window while the
        # gradient compute runs.  Disarmed until the first boundary
        # fold re-arms it, so round 0 behaves exactly like serial.
        db = (DoubleBuffer(
            wins[r],
            [k for k in my_slots if not (cap_slots and k == r)],
            d + 1) if overlap else None)

        def fold_staged_at_round_boundary(x, p, *, rearm,
                                          observe: bool = False):
            """ROUND-BOUNDARY apply of the overlapped gossip-IN: quiesce
            the harvester, fold its staged round-(k-1) mass (plus one
            final inline sweep — a round folds at least what serial
            would), report the hidden/total split as
            bf_overlap_fraction, and re-arm for the next compute."""
            t_b = time.perf_counter()
            staged, busy = db.apply_staged()
            p = consume(x, p, observe=observe, staged=staged)
            tot = busy + (time.perf_counter() - t_b)
            if tot > 0:
                _mt.set("bf_overlap_fraction", busy / tot, rank=str(r))
            if rearm:
                db.begin()
            return p

        def harvest_evidence_at_round_boundary():
            """Per-peer observations for this evidence window, sampled
            once per window (the staleness clocks are instantaneous
            ages, so sampling them every round would only overwrite the
            same value at O(n·deg) lock traffic).  Lag evidence covers
            only CURRENT in-neighbors — a peer whose edges the plan
            dropped stops accumulating staleness against ranks it no
            longer feeds (its ring successor keeps reporting, which is
            what lets hysteresis release it on recovery) — and
            observations about ranks outside the surface are FORGOTTEN,
            so a frozen last look at a corpse or a dropped peer cannot
            be republished forever."""
            ctl.retain_peers(k for k in my_in if k != r)
            now = time.perf_counter()
            states = (board.states() if board is not None else {})
            for k in my_in:
                if k == r:
                    continue
                ctl.note_peer(
                    k, lag_s=now - last_fresh.setdefault(k, now),
                    state=states.get(k))

        def actuate_plan_at_round_boundary(active):
            """Install the controller's current plan AT THIS ROUND
            BOUNDARY: in-process deposits are synchronous, so between
            rounds nothing of this rank's is in flight — the quiesce
            the plan-change contract requires.  Returns the plan's
            mixing topology; rebases the mixing tracker so the
            bf_mixing_excess baseline tracks the topology actually in
            effect."""
            nonlocal tracker, gossip_every, tracker_members
            plan_topo = ctl.apply_plan(topology=topology, members=active)
            gossip_every = ctl.plan.gossip_every
            # the feed-window exponent tracks the CADENCE in effect: a
            # stretched gossip_every halves the gossip rounds per
            # evidence window, and a prediction still assuming
            # gossip-every-step would read the stretch as broken mixing
            rpu = max(1, round(control.evidence_every / gossip_every))
            live = frozenset(active)
            if tracker is None:
                tracker = _MixingTracker(
                    plan_topo, rounds_per_update=rpu, rank=str(r))
            else:
                if tracker_members is not None and live != tracker_members:
                    # a MEMBERSHIP boundary: the previous distance was
                    # measured over a different member set, and the
                    # cross-boundary ratio would feed a bogus
                    # bf_mixing_excess into the densify ladder
                    tracker.reset_measurement()
                tracker.rebase(plan_topo, rounds_per_update=rpu)
            tracker_members = live
            ctl_changes[r] = ctl.plan.version
            return plan_topo

        try:
            x = x0.copy()
            while not stop.is_set():
                if not is_member:
                    # ------------------------------------ JOIN the job
                    if not my_joins:
                        return  # reserved capacity slot, never scheduled
                    t_join = my_joins.pop(0)
                    while (time.perf_counter() - t_run0 < t_join
                           and not stop.is_set()):
                        time.sleep(0.002)
                    if stop.is_set():
                        return
                    # warm-start: pull a live member's published (x, p)
                    # snapshot through its window — no checkpoint read
                    # anywhere.  The pair is published atomically (one
                    # set_self under the window's self mutex), so the
                    # joiner's first state is round-consistent.
                    t_ws = time.perf_counter()
                    if board is not None:
                        board.mark_joining(r)
                    z = None
                    deadline = t_ws + max(duration_s, 5.0)
                    while (z is None and not stop.is_set()
                           and time.perf_counter() < deadline):
                        with mem_mu:
                            cand = sorted(members - {r})
                        for nb in cand:
                            s = wins[nb].read_self()
                            if s[-1] > 0.0:
                                z = s[:-1] / s[-1]
                                break
                        if z is None:
                            time.sleep(0.002)
                    if z is None:
                        z = x0  # no member published yet: cold start
                    x = np.array(z, np.float64)
                    p = 1.0  # fresh push-sum weight: mass enters HERE
                    with mem_mu:
                        members.add(r)
                        joined_mass[0] += 1.0
                        ever_joined.add(r)
                        left_final.discard(r)
                    if board is not None:
                        board.admit(r)  # its own first round boundary
                    is_member = True
                    if overlap and db is None:
                        # a flapping member re-joining after a graceful
                        # leave closed its harvester: fresh buffer,
                        # disarmed until its first boundary fold
                        db = DoubleBuffer(
                            wins[r],
                            [k for k in my_slots
                             if not (cap_slots and k == r)],
                            d + 1)
                    _mt.observe("bf_join_warmstart_seconds",
                                time.perf_counter() - t_ws)
                    _bb.record("peer_join", peer=f"rank{r}", rank=r,
                               warmstart_s=round(
                                   time.perf_counter() - t_ws, 6))
                    # publish immediately: a second joiner may warm from
                    # this rank before its first full round
                    self_buf[:-1] = x
                    self_buf[-1] = p
                    wins[r].set_self(self_buf)

                # ------------------------------------------ gossip loop
                my_out: List[int] = []
                frac = 1.0
                known_active: Optional[frozenset] = None
                want_leave = False
                t_rnd0 = time.perf_counter()  # boundary-to-boundary clock
                try:
                    while not stop.is_set():
                        _chaos.check_step(r, steps[r])
                        if (leave_deadline is not None
                                and time.perf_counter() - t_run0
                                >= leave_deadline):
                            leave_deadline = None
                            want_leave = True
                            break
                        if board is not None:
                            board.beat(r)
                        with mem_mu:
                            active = frozenset(members)
                        if resilience is not None:
                            active = active - (board.dead_ranks() - {r})
                        if active != known_active:
                            # round boundary: re-admit ranks that came
                            # back (REJOINED) or announced (JOINING),
                            # then re-plan the graph over the current
                            # member set
                            if known_active is not None \
                                    and board is not None:
                                for j in active - known_active:
                                    if board.state(j) in (_res.REJOINED,
                                                          _res.JOINING):
                                        board.admit(j)
                            known_active = active
                            plan = (actuate_plan_at_round_boundary(active)
                                    if ctl is not None else _plan(active))
                            my_out = list(plan.out_neighbors(r))
                            my_in = list(plan.in_neighbors(r))
                            frac = 1.0 / (len(my_out) + 1)
                        elif (ctl is not None and steps[r] > 0
                              and steps[r] % control.evidence_every == 0):
                            # control round boundary: fold this window's
                            # mixing measurement in, publish evidence,
                            # decide over the disseminated records, and
                            # actuate when the plan version advanced
                            harvest_evidence_at_round_boundary()
                            d_now = ctl.disagreement
                            if tracker is not None and d_now is not None:
                                measured = tracker.update(d_now)
                                excess = None
                                if (measured is not None
                                        and tracker.predicted is not None
                                        and measured < 1.0):
                                    # the excess alarm is interpretable
                                    # only while gossip is actually
                                    # contracting; at the SGD gradient/
                                    # gossip equilibrium the growth band
                                    # governs instead
                                    excess = measured - tracker.predicted
                                ctl.note_mixing_excess(excess)
                            ctl_board.publish(ctl.evidence(steps[r]))
                            # a corpse's frozen record must not keep
                            # voting (the MP path filters by tombstones;
                            # the thread-mode truth is the died[] wills)
                            evs = [ev for ev in ctl_board.snapshot()
                                   if not died[ev.rank]]
                            new_plan = ctl.decide(steps[r], evs)
                            if new_plan.version != ctl_changes[r]:
                                ctl_changes[r] = new_plan.version
                                plan = actuate_plan_at_round_boundary(
                                    active)
                                my_out = list(plan.out_neighbors(r))
                                my_in = list(plan.in_neighbors(r))
                                frac = 1.0 / (len(my_out) + 1)
                        # per-round blackbox markers: a begin without its
                        # end in a dump names the round the loop wedged in
                        if rec is not None:
                            rec.begin("collective",
                                      key=("async_dsgd", r, steps[r]),
                                      op="async_dsgd_round",
                                      cid="async_dsgd_round",
                                      step=steps[r], rank=r, peers=my_out)
                        if db is not None:
                            p = fold_staged_at_round_boundary(
                                x, p, rearm=True, observe=True)
                        else:
                            p = consume(x, p, observe=True)
                        if elastic:
                            # publish a coherent (x, p) snapshot: what a
                            # JOINING peer warm-starts from
                            self_buf[:-1] = x
                            self_buf[-1] = p
                            wins[r].set_self(self_buf)
                        z = x / p
                        loss, grads = loss_and_grad(r, steps[r],
                                                    packer.unpack(z))
                        losses[r].append(float(loss))
                        # x/p-space gradient step:
                        # z' = z - lr*grad  =>  dx = -lr*p*g
                        packer.pack(grads, out=gvec)
                        gvec *= lr * p
                        x -= gvec
                        if ctl is None or steps[r] % gossip_every == 0:
                            # the plan's local-SGD cadence: on a
                            # non-gossip step the whole (x, p) stays
                            # local (no split, no deposits) — mass
                            # trivially conserved
                            payload[:-1] = x
                            payload[-1] = p
                            payload *= frac
                            for j in my_out:
                                wins[j].deposit(
                                    r if cap_slots else slot_of[j][r],
                                    payload, accumulate=True)
                            x *= frac
                            p *= frac
                        if snapshot_every and steps[r] % snapshot_every == 0:
                            # serve-while-training publish: the post-step
                            # (x, p) pair — z = x/p is invariant to the
                            # frac scaling above, so this IS round
                            # steps[r]'s model estimate — swapped in
                            # atomically with its round stamp (an
                            # in-band `round` leaf rides along so wire
                            # readers can audit the stamp end to end)
                            _snapshots.table().publish(
                                f"{name}:{r}", steps[r],
                                {"x": x, "p": np.array([p]),
                                 "round": np.array([float(steps[r])])})
                        if rec is not None:
                            rec.end("collective",
                                    key=("async_dsgd", r, steps[r]),
                                    op="async_dsgd_round",
                                    cid="async_dsgd_round",
                                    step=steps[r], rank=r)
                            rec.record("optimizer_step", step=steps[r],
                                       rank=r, loss=float(loss))
                        # boundary-to-boundary wall clock: the
                        # inter-round skew sleep is part of the cadence
                        now_p = time.perf_counter()
                        rdt = now_p - t_rnd0
                        t_rnd0 = now_p
                        _mt.observe("bf_round_seconds", rdt, rank=str(r))
                        if flt is not None:
                            flt.note_round(rdt)
                            if flt.due(steps[r]):
                                # fleet telemetry at this round
                                # boundary: staleness ages of the
                                # CURRENT in-neighbors (the thread-mode
                                # lag signal) + the round's loop-local
                                # values; the publisher only reads
                                now_t = time.perf_counter()
                                peer_tel = {
                                    k: {"lag": now_t
                                        - last_fresh.setdefault(k, now_t)}
                                    for k in my_in if k != r}
                                flt.boundary(
                                    steps[r], mass=p,
                                    z_mean=float(z.mean()),
                                    dis=fleet_dis,
                                    staleness=(steps[r] % snapshot_every
                                               if snapshot_every
                                               else None),
                                    peers=peer_tel, controller=ctl)
                        steps[r] += 1
                        if (stop_after_steps is not None
                                and steps[r] >= stop_after_steps):
                            stop.set()  # time-to-target reached
                            break
                        if skew[r] > 0 or poll_interval_s > 0:
                            time.sleep(skew[r] + poll_interval_s)
                except _chaos.ChaosLeave:
                    want_leave = True

                if not want_leave:
                    # run ended: drain in-flight mass so the audit below
                    # is exact, publish the final state.  The overlap
                    # harvester goes first — its staged-but-unapplied
                    # take is mass this rank already owns
                    if db is not None:
                        p = consume(x, p, staged=db.close())
                    p = consume(x, p)
                    finals[r] = x / p
                    wins[r].set_self(np.concatenate([x, [p]]))
                    if ctl is not None:
                        final_plans[r] = ctl.plan
                    return

                # -------------------------------------- GRACEFUL DRAIN
                # fence (in-process deposits are applied synchronously,
                # so the flush is the formal round-boundary marker), fold
                # any landed mass, then hand the ENTIRE (x, p) to live
                # out-neighbors in drain-flagged deposits: a leaver's
                # mass is CONSERVED in the audit, never written off like
                # a corpse's
                wins[r].flush()
                if db is not None:
                    # stop the harvester for good: the leaver hands its
                    # mass off below, and a re-join recreates the buffer
                    p = consume(x, p, staged=db.close())
                    db = None
                p = consume(x, p)
                with mem_mu:
                    live = sorted(members - {r})
                live = [j for j in live if not died[j]]
                if board is not None:
                    live = [j for j in live
                            if board.state(j) != _res.DEAD]
                plan = _plan(known_active
                             if known_active else frozenset({r} | set(live)))
                tgt = [j for j in plan.out_neighbors(r) if j in live]
                tgt = tgt or live
                if tgt:
                    payload[:-1] = x
                    payload[-1] = p
                    payload /= float(len(tgt))
                    for j in tgt:
                        wins[j].deposit_async(r, payload,
                                              accumulate=True, drain=True)
                    x[:] = 0.0
                    p = 0.0
                # else: no live member to hand off to — keep the mass
                # and publish it; the audit still counts it below
                self_buf[:-1] = x
                self_buf[-1] = p
                wins[r].set_self(self_buf)
                with mem_mu:
                    members.discard(r)
                    left_final.add(r)
                    n_mem = len(members)
                if board is not None:
                    board.mark_left(r)
                else:
                    _bb.record("peer_leave", peer=f"rank{r}", rank=r,
                               step=steps[r])
                _mt.set("bf_members", float(n_mem))
                finals[r] = None
                is_member = False
                # back to the outer loop: a later join offset re-admits
                # this rank (a flapping member)
        except _chaos.ChaosKill:
            # simulated death: no drain, no final publish; the last will
            # (mass carried to the grave) keeps the audit exact — and
            # the grave includes what the overlap harvester had taken
            # from the window but not yet applied
            died[r] = True
            if db is not None:
                p += sum(float(buf[-1]) for _, buf, _ in db.close())
            died_mass[r] = p
        except BaseException as e:
            errors.append(e)
            stop.set()
        finally:
            if db is not None:
                db.close()  # idempotent; stops the harvester thread
            if flt is not None:
                flt.close()  # records are on disk line by line already

    # continuous profiling: own the sampler only when this call armed it
    # (an env-armed profiler spans runs and is not ours to stop)
    prof_owned = False
    from bluefog_tpu.profiling import sampler as _profiling

    if profile is not None:
        if _profiling.get() is None:
            _profiling.configure(profile, rank=0)
            prof_owned = True
    else:
        # no explicit profile= — still poke the sampler so a
        # BLUEFOG_TPU_PROFILE env arming takes effect for this run
        # (atexit owns its tail flush, not us)
        _profiling.set_rank(0)

    threads = [threading.Thread(target=rank_loop, args=(r,), daemon=True)
               for r in range(n)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    stop.wait(duration_s)
    stop.set()
    join_budget = max(skew) * 4 + 30.0  # a rank may be mid-gradient
    for t in threads:
        t.join(timeout=join_budget)
    if prof_owned:
        from bluefog_tpu.profiling import sampler as _profiling

        _profiling.reset()  # flushes the tail window before the audit
    if any(t.is_alive() for t in threads):
        raise RuntimeError("async DSGD rank threads failed to stop within "
                           f"{join_budget:.1f}s; aborting without freeing")
    wall = time.perf_counter() - t0
    if errors:
        for w in wins:
            w.free()
        raise errors[0]

    total_mass = 0.0
    for r in range(n):
        if not died[r]:
            # a corpse's published snapshot is stale (the authoritative
            # grave mass is its last will, died_mass); everyone else's
            # final set_self is the truth
            total_mass += float(wins[r].read_self()[-1])
        for k in (range(n) if cap_slots else range(len(in_nbrs[r]))):
            if cap_slots and k == r:
                continue
            buf, fresh = wins[r].read(k, consume=False)
            if fresh > 0:
                total_mass += float(buf[-1])

    # consensus over SURVIVORS (a chaos-killed rank has no final z; a
    # leaver handed its state off; their windows' residual mass was
    # already counted by the audit above)
    alive = [r for r in range(n) if finals[r] is not None]
    if alive:
        zs = np.stack([finals[r] for r in alive])
        gap = float(np.abs(zs - zs.mean(axis=0)).max())
    else:
        gap = float("inf")  # chaos killed every rank
    if snapshot_every:
        for r in range(n):
            _snapshots.table().drop(f"{name}:{r}")
    report = DSGDReport(
        wall_time_s=wall,
        steps_per_rank=list(steps),
        losses=losses,
        final_params=[packer.unpack(finals[r]) if finals[r] is not None
                      else None for r in range(n)],
        total_mass=total_mass,
        consensus_gap=gap,
        dead_ranks=[r for r in range(n) if died[r]],
        died_mass=float(sum(died_mass)),
        # elastic: the exact expectation the audit must reproduce —
        # every unit of mass that ever entered (initial members + one
        # per admission) is either held by a window or in a grave
        baseline_mass=(float(len(members0)) + joined_mass[0]
                       if elastic else None),
        health_transitions=(
            {r: board.transitions(r) for r in range(n)}
            if board is not None else None),
        left_ranks=sorted(left_final),
        joined_ranks=sorted(ever_joined),
        # the highest-version plan any rank converged on (deterministic
        # decisions mean ranks differ only in how far their evidence
        # view had propagated when the run ended)
        control_plan=max((pl for pl in final_plans if pl is not None),
                         key=lambda pl: pl.version, default=None),
        plan_changes=max(ctl_changes) if control is not None else 0,
    )
    for w in wins:
        w.free()
    return report


# ---------------------------------------------------------------------------
# Cross-process asynchronous training (one OS process per rank, shm windows)
# ---------------------------------------------------------------------------


def shm_unlink_window(name: str) -> bool:
    """Remove a stale shm window segment (e.g. left by a crashed owner) by
    window name; True if a segment was removed.  Safe to call when nothing
    exists.  Requires the native runtime."""
    lib = native.load()
    if lib is None:
        raise RuntimeError("native runtime unavailable for shm windows")
    return lib.bf_win_shm_unlink(name.encode()) == 0


class FileBarrier:
    """Filesystem barrier between rank *processes* on one host.

    The asynchronous runners need a handful of rendezvous points around the
    training loop (windows created / deposits stopped / results published /
    audit finished) and explicitly NO collective runtime in between — a
    shared directory is the whole requirement, so the barrier does not drag
    jax.distributed into the async path.  Rank ``r`` touches
    ``<dir>/<stage>.<r>`` and waits until all ``n`` exist.

    :attr:`exclude` is the barrier's fault-tolerance: ranks declared DEAD
    by the resilience layer go in this set and are no longer waited for —
    survivors stop burning the full timeout per stage on a corpse.  The
    exclusion set is re-read every poll, so a rank that is declared dead
    *while* others already wait unblocks them immediately."""

    def __init__(self, path: str, n_ranks: int, rank: int):
        self.path = path
        self.n = int(n_ranks)
        self.rank = int(rank)
        self.exclude: set = set()
        os.makedirs(path, exist_ok=True)

    def wait(self, stage: str, timeout_s: float = 120.0) -> None:
        open(os.path.join(self.path, f"{stage}.{self.rank}"), "w").close()

        def missing_ranks():
            return [r for r in range(self.n)
                    if r not in self.exclude and not os.path.exists(
                        os.path.join(self.path, f"{stage}.{r}"))]

        t0 = time.perf_counter()
        while missing_ranks():
            if time.perf_counter() - t0 > timeout_s:
                missing = missing_ranks()
                # rank NUMBERS, not paths: the cross-rank merge needs to
                # name the absent rank, and the blackbox event makes the
                # timeout part of the incident record before the raise
                # unwinds this process
                _bb.record("barrier_timeout", stage=stage,
                           missing_ranks=missing, rank=self.rank,
                           waited_s=round(time.perf_counter() - t0, 3),
                           dir=self.path)
                raise TimeoutError(
                    f"barrier {stage!r} timed out after {timeout_s:.0f}s "
                    f"on rank {self.rank}; missing rank(s) {missing} "
                    f"(dir {self.path})")
            time.sleep(0.005)


class _ShmTransport:
    """Same-host rank processes: windows in named shared memory."""

    def create(self, wname: str, n_slots: int, n_elems: int) -> AsyncWindow:
        # each rank owns its window name exclusively, so a leftover segment
        # can only be stale (crashed previous run) — clean and recreate
        shm_unlink_window(wname)
        return AsyncWindow(wname, n_slots, n_elems, np.float64, shm=True)

    def publish(self, barrier: FileBarrier, rank: int) -> None:
        pass  # the shm namespace IS the rendezvous

    def collect(self, barrier: FileBarrier, ranks) -> None:
        pass

    def open(self, owner: int, wname: str, n_slots: int, n_elems: int):
        return AsyncWindow(wname, attach=True)

    def close(self) -> None:
        pass


class _RemoteHandle:
    """AsyncWindow-shaped adapter over a :class:`RemoteWindow` /
    :class:`PipelinedRemoteWindow` (geometry captured at open time, as the
    remote protocol requires it per call)."""

    def __init__(self, rw, n_slots: int, n_elems: int):
        self._rw = rw
        self.n_slots = n_slots
        self.n_elems = n_elems
        self.dtype = np.dtype(np.float64)

    def deposit(self, slot, arr, *, accumulate=True):
        return self._rw.deposit(
            slot, np.ascontiguousarray(arr, self.dtype),
            accumulate=accumulate)

    def deposit_async(self, slot, arr, *, accumulate=True, copy=True,
                      drain=False):
        """Fire-and-forget on the pipelined DCN transport; synchronous
        (equivalent, just not overlapped) on the plain one, where the
        drain mark is carried by the owner's audit protocol instead of
        a wire flag."""
        fn = getattr(self._rw, "deposit_async", None)
        a = np.ascontiguousarray(arr, self.dtype)
        if fn is None:
            return self._rw.deposit(slot, a, accumulate=accumulate)
        return fn(slot, a, accumulate=accumulate, copy=copy, drain=drain)

    @property
    def health(self):
        """Peer health of the underlying pipelined stream (None on the
        sync client or when resilience is off)."""
        return getattr(self._rw, "health", None)

    def ack_ewma(self) -> Optional[float]:
        """Per-peer ack-latency EWMA (seconds) of the underlying
        pipelined stream — the controller's slow-peer evidence.  None on
        the sync client or before the first ack."""
        fn = getattr(self._rw, "ack_ewma", None)
        return None if fn is None else fn()

    @property
    def reconnects(self) -> int:
        """Completed reconnect+replay cycles (lossy-link evidence); 0 on
        the sync client."""
        return int(getattr(self._rw, "reconnects", 0))

    def set_codec(self, codec: Optional[str]) -> None:
        """Round-boundary wire-codec retune (controller actuation); a
        no-op request for ``None`` on the sync client, an error for a
        real codec there (the sync wire has no codec negotiation)."""
        fn = getattr(self._rw, "set_codec", None)
        if fn is not None:
            fn(codec)
        elif codec not in (None, "none"):
            raise ValueError(
                "the synchronous window client has no wire codec; "
                f"cannot set {codec!r}")

    def flush(self, timeout_s: Optional[float] = None) -> None:
        """Fence for :meth:`deposit_async` (no-op on the sync client)."""
        fn = getattr(self._rw, "flush", None)
        if fn is not None:
            fn(timeout_s)

    def read(self, slot, *, consume=True):
        return self._rw.read(slot, self.n_elems, self.dtype, consume=consume)

    def read_self(self):
        return self._rw.read_self(self.n_elems, self.dtype)

    def free(self):
        self._rw.close()


class _TcpTransport:
    """Any-host rank processes: process-local windows served over TCP
    (``runtime/window_server.py``) — the DCN shape of the one-sided path.
    Addresses rendezvous through the barrier directory (one
    ``winaddr.<rank>`` file per rank).

    ``pipeline=True`` (the default) opens peers as
    :class:`~bluefog_tpu.runtime.window_server.PipelinedRemoteWindow`:
    deposits are fire-and-forget through a per-peer background sender
    (batched frames, windowed acks) and the dsgd loop fences with
    ``flush()`` before its audit barrier.  ``wire_codec`` selects optional
    DCN wire compression (``"f32"``/``"topk"``) — lossy, so it is opt-in
    and must stay off when the exact push-sum mass audit matters.

    Three raw-speed knobs ride ``stream_options`` (popped here, the rest
    forwards to the per-peer :class:`~bluefog_tpu.runtime.window_server.
    DepositStream`):

    - ``shm=True`` — same-host fast path: this rank's OWN windows go
      into named shared memory (so co-located peers can attach them)
      and its deposit streams route same-host deposits through the shm
      table instead of TCP, falling back transparently when detection
      fails (remote peer, no native runtime).
    - ``stripes=N`` — striped DCN: one shared
      :class:`~bluefog_tpu.runtime.window_server.StripedDepositStream`
      per peer (N parallel connections, window names spread by
      :func:`~bluefog_tpu.runtime.window_server.stripe_of`) instead of
      a private stream per window.
    - ``transport_tuning=True | TransportConfig(...)`` — arms the
      closed-loop stripe/coalesce autotuner: the runner calls
      :meth:`retune_transport_at_round_boundary` at its round
      boundaries and the per-peer plan follows the ack-latency/phase
      EWMAs the streams already collect."""

    def __init__(self, bind_host: str = "0.0.0.0", *, pipeline: bool = True,
                 wire_codec: Optional[str] = None,
                 resilience: Optional[_res.ResilienceConfig] = None,
                 stream_options: Optional[Dict] = None):
        from bluefog_tpu.runtime.window_server import WindowServer

        self._server = WindowServer()
        self._server.start(bind_host)
        self._pipeline = pipeline
        self._codec = wire_codec
        self._resilience = resilience
        # per-peer DepositStream tuning (max_in_flight / max_queue_items
        # / timeout_s): a BOUNDED queue is how a deployment opts into
        # honest backpressure — the producer then feels a slow peer
        # instead of buffering unboundedly toward it
        self._stream_options = dict(stream_options or {})
        self._shm = bool(self._stream_options.pop("shm", False))
        self._n_stripes = int(self._stream_options.pop("stripes", 0))
        tuning = self._stream_options.pop("transport_tuning", None)
        self._tuning = (_TransportConfig() if tuning is True
                        else tuning)  # None or a TransportConfig
        if self._tuning is not None and self._n_stripes <= 0:
            # the autotuner's knobs live on the striped stream; arm a
            # minimal pool it can widen from
            self._n_stripes = 1
        self._striped: Dict[int, object] = {}  # owner -> striped stream
        self._plans: Dict[int, _TransportPlan] = {}
        self._addrs: Dict[int, Tuple[str, int]] = {}

    def create(self, wname: str, n_slots: int, n_elems: int) -> AsyncWindow:
        if self._shm and native.load() is not None:
            # same-host fast path: this rank's windows go into the
            # named-shm table so co-located peers' deposit streams can
            # attach them directly.  The name is rank-owned, so a
            # leftover segment can only be a stale crash artifact
            shm_unlink_window(wname)
            return AsyncWindow(wname, n_slots, n_elems, np.float64,
                               shm=True)
        return AsyncWindow(wname, n_slots, n_elems, np.float64)

    def publish(self, barrier: FileBarrier, rank: int) -> None:
        host, port = self._server.address
        path = os.path.join(barrier.path, f"winaddr.{rank}")
        with open(path + ".tmp", "w") as f:
            f.write(f"{host}:{port}")
        os.replace(path + ".tmp", path)

    def collect(self, barrier: FileBarrier, ranks,
                timeout_s: float = 60.0) -> None:
        # the barrier dir may be NFS on the cross-host path: another
        # host's winaddr file can lag the barrier (the same visibility
        # delay FileBarrier.wait polls for), so poll here too.  ``ranks``
        # is the set to resolve — the CURRENT member set for elastic
        # jobs (a reserved capacity slot has no address yet), or one
        # newly-announced joiner during admission.
        deadline = time.perf_counter() + timeout_s
        for r in ranks:
            path = os.path.join(barrier.path, f"winaddr.{r}")
            while True:
                try:
                    with open(path) as f:
                        host, port = f.read().strip().rsplit(":", 1)
                    break
                except (FileNotFoundError, ValueError):
                    # ValueError: file visible but not fully written yet
                    if time.perf_counter() > deadline:
                        raise TimeoutError(
                            f"rank {r}'s window address never appeared at "
                            f"{path}")
                    time.sleep(0.01)
            self._addrs[r] = (host, int(port))

    def open(self, owner: int, wname: str, n_slots: int, n_elems: int):
        from bluefog_tpu.runtime.window_server import (PipelinedRemoteWindow,
                                                       RemoteWindow,
                                                       StripedDepositStream)

        if self._pipeline:
            cfg = self._resilience
            if self._n_stripes > 0:
                # striped DCN: ONE shared per-peer stripe pool; every
                # window bound for this owner rides it (stripe_of
                # spreads the window names over the connections), and
                # the handle's flush fences all stripes at once
                st = self._striped.get(owner)
                if st is None:
                    kw = dict(codec=self._codec, shm=self._shm,
                              **self._stream_options)
                    if cfg is not None:
                        kw.update(
                            reconnect=cfg.backoff_kwargs(),
                            heartbeat_interval_s=(
                                cfg.heartbeat_interval_s or 0.0),
                            suspect_after_s=cfg.suspect_after_s,
                            dead_after_s=cfg.dead_after_s)
                    st = StripedDepositStream(
                        self._addrs[owner], n_stripes=self._n_stripes,
                        **kw)
                    self._striped[owner] = st
                    self._plans[owner] = _TransportPlan(
                        stripes=st.n_stripes,
                        coalesce_bytes=self._stream_options.get(
                            "max_batch_bytes", 16 << 20))
                rw = PipelinedRemoteWindow(
                    self._addrs[owner], wname, stream=st,
                    sync_retry=(cfg.backoff_kwargs()
                                if cfg is not None else None))
            elif cfg is not None:
                rw = PipelinedRemoteWindow(
                    self._addrs[owner], wname, codec=self._codec,
                    reconnect=cfg.backoff_kwargs(),
                    heartbeat_interval_s=cfg.heartbeat_interval_s or None,
                    suspect_after_s=cfg.suspect_after_s,
                    dead_after_s=cfg.dead_after_s,
                    shm=self._shm or None,
                    # the runner's own sync READS (warm-start read_self,
                    # meta/audit reads) retry torn/timed-out replies on
                    # a fresh connection under the same bounded budget —
                    # reader-side faults must not fail a training rank
                    sync_retry=cfg.backoff_kwargs(),
                    **self._stream_options)
            else:
                rw = PipelinedRemoteWindow(self._addrs[owner], wname,
                                           codec=self._codec,
                                           shm=self._shm or None,
                                           **self._stream_options)
        else:
            rw = RemoteWindow(self._addrs[owner], wname)
        return _RemoteHandle(rw, n_slots, n_elems)

    def retune_transport_at_round_boundary(self, round_: int) -> None:
        """Closed-loop transport autotune, called by the dsgd runner AT
        ITS ROUND BOUNDARIES (nothing of this rank's is in flight — the
        quiesce every plan actuation requires): per peer, feed the
        stripe pool's ack-latency + wire-phase EWMAs through the pure
        :func:`~bluefog_tpu.control.decide_transport_plan` step and
        actuate only when a hysteresis band was actually crossed (the
        no-change case returns the previous plan object itself)."""
        if self._tuning is None:
            return
        for owner, st in self._striped.items():
            prev = self._plans[owner]
            plan = _decide_transport_plan(
                prev, round_, ack_ewma_s=st.ack_ewma(),
                phase_s=st.phase_ewma(), cfg=self._tuning)
            if plan is not prev:
                st.apply_plan(plan)
                self._plans[owner] = plan

    def close(self) -> None:
        for st in self._striped.values():
            try:
                st.close()
            except Exception:
                pass
        self._striped.clear()
        self._server.stop()


def run_async_dsgd_rank(
    topology: Topology,
    rank: int,
    params0,
    loss_and_grad,
    *,
    barrier: FileBarrier,
    lr: float = 0.05,
    duration_s: float = 3.0,
    skew_s: float = 0.0,
    name: str = "async_dsgd_mp",
    poll_interval_s: float = 0.0,
    transport: str = "shm",
    tcp_bind: str = "0.0.0.0",
    wire_codec: Optional[str] = None,
    resilience: Optional[_res.ResilienceConfig] = None,
    join: bool = False,
    leave_after_s: Optional[float] = None,
    initial_members: Optional[Sequence[int]] = None,
    snapshot_every: int = 0,
    control: Optional[_ControlConfig] = None,
    stop_after_steps: Optional[int] = None,
    stream_options: Optional[Dict] = None,
    fleet: Optional[_FleetConfig] = None,
    profile: Optional[str] = None,
    overlap: bool = False,
) -> Optional[DSGDReport]:
    """One rank of an asynchronous decentralized SGD run where every rank is
    its own OS PROCESS — the reference's actual deployment shape
    (``mpirun -np N``, one MPI rank per process; SURVEY §3.4) rather than
    :func:`run_async_dsgd`'s rank-thread model.

    Each process creates its own landing window and deposits into its
    out-neighbors' windows directly — cross-process ``MPI_Put`` with no
    receiver involvement and NO barrier anywhere in the training loop
    (``barrier`` fires exactly four times, all outside the loop: windows
    created, deposits stopped, per-rank results published, audit finished;
    the loop itself is rendezvous-free, which is the entire point).

    ``transport`` selects the deposit fabric: ``"shm"`` (named shared
    memory — same-host ranks), ``"tcp"`` (each process serves its
    process-local windows via :class:`~bluefog_tpu.runtime.window_server.
    WindowServer`; ranks may live on DIFFERENT HOSTS as long as the
    barrier directory is shared, e.g. NFS — the DCN deployment shape;
    deposits ride the PIPELINED batched client and the loop fences with
    ``flush()`` before the audit barrier), or ``"tcp-sync"`` (the
    unpipelined per-deposit round-trip wire, kept for A/B measurement).
    ``wire_codec`` (``"f32"``/``"topk"``, tcp only) turns on lossy DCN
    wire compression — leave ``None`` whenever the exact mass audit
    matters, as in these runners' reports.

    The algorithm, mass-conservation invariant, and bias caveats are those
    of :func:`run_async_dsgd` (subgradient-push); ``skew_s`` is this rank's
    extra per-step sleep (pass different values per process to realize the
    skewed execution the SPMD path cannot express).

    ``resilience`` (tcp transport only) opts into peer-fault tolerance:
    deposit streams reconnect with bounded backoff and replay their
    unacked batches; a SUSPECT peer's share is WITHHELD (kept, not
    deposited — unbiased under the push-sum weight channel, and it stops
    the sender bleeding mass into a possible corpse during the detection
    window; idle heartbeats are what clear the suspicion); a peer whose
    reconnect budget is exhausted is declared DEAD, announced to the
    other survivors through a tombstone file in the barrier directory,
    and healed out of the mixing weights.
    The survivors then hold a quiesce-rendezvous (fence + heal barrier,
    dead ranks excluded) and record the surviving set's exact push-sum
    mass as ``report.baseline_mass`` — the final audit over the
    survivors must reproduce it exactly (``report.total_mass ==
    report.baseline_mass``).  Requirements: the barrier directory is the
    dissemination channel, rank 0 (the reporting rank) must survive, and
    one failure event settles before the next is detected (staggered
    single failures are fine; a simultaneous multi-rank wipe may time
    out the heal rendezvous and abort).

    **Elastic membership** (tcp transport; requires ``resilience=``):
    ``topology`` is the job's CAPACITY — its size bounds how many ranks
    can ever participate, and slot indices are rank numbers so the
    windows survive arbitrary membership change.  ``initial_members``
    names the ranks that start the job (default: all); the rest are
    reserved slots.  A later process calls this function with
    ``join=True`` on a reserved (or previously-departed) rank: it
    attaches its window server, **warm-starts by reading a live
    member's published (x, p) snapshot from its window — no checkpoint
    file anywhere**, announces itself through a ``member.<r>`` record in
    the barrier directory (the same dissemination channel as the
    ``dead.<r>`` tombstones), and is admitted at a round boundary
    through a quiesce-rendezvous that re-measures the exact push-sum
    baseline over the grown member set.  ``leave_after_s`` (or a chaos
    ``rankN:leave:at_step`` rule) triggers the graceful-drain
    counterpart: the leaver fences its deposit streams, waits for the
    members to fence theirs (nothing in flight toward it afterwards),
    hands its ENTIRE push-sum mass to its out-neighbors in final
    ``drain``-flagged deposits — a leaver's mass is conserved in the
    audit, unlike a corpse's — writes ``left.<r>``, and exits.  The
    live ranks re-plan the mixing graph over the current member set
    (:func:`bluefog_tpu.topology.replan`, deterministic in the member
    list) at every membership round boundary.  Rank 0 reports; it must
    be a stable initial member.  Membership events are assumed to
    settle one at a time (staggered churn is fine; two simultaneous
    rendezvous can time out each other and degrade the exactness claim,
    loudly, exactly as overlapping failures do).

    ``snapshot_every > 0`` additionally publishes this rank's
    round-stamped ``(round, x, p)`` snapshot into the process-global
    serving table every Nth step (group ``f"{name}:{rank}"``) — with
    ``transport="tcp"`` the rank's own :class:`~bluefog_tpu.runtime.
    window_server.WindowServer` then serves it to
    :class:`~bluefog_tpu.serving.client.SnapshotClient` readers and
    :class:`~bluefog_tpu.serving.subscriber.Subscriber` push channels:
    the serve-while-training read path, fully decoupled from the
    training loop (see ``docs/serving.md``).

    ``control`` (tcp transport; every rank of the job must pass the
    SAME config, like the elastic arguments) opts into the self-tuning
    communication control plane (:mod:`bluefog_tpu.control`): each
    process runs a :class:`~bluefog_tpu.control.CommController` fed by
    its deposit streams' ack-EWMA/heartbeat telemetry, health states,
    reconnect deltas, and local mixing measurements; evidence records
    disseminate through ``ctlev.<rank>`` files in the barrier
    directory (the membership-record pattern), decisions are
    deterministic in the disseminated records (hysteresis +
    cooldowns), and plans actuate only at round boundaries — slow or
    lossy peers' edges drop to the ring spine, cadence
    stretches/shrinks, the wire codec backs off.  Enabling the
    controller hands topology management to the deterministic replan
    family (``topology`` defines capacity/rank numbering; windows take
    one landing slot per capacity rank), and the exact mass audit
    holds through every plan change.  A ``control.max_codec_level > 0``
    requires opening the streams at that ceiling via ``wire_codec=``
    (lossy — keep 0 whenever the exact audit matters).

    ``stop_after_steps`` ends this rank's loop after that many steps
    (time-to-target mode; ``duration_s`` stays the hard cap);
    ``stream_options`` forwards DepositStream tuning
    (``max_in_flight``/``max_queue_items``) through the tcp transport —
    a BOUNDED queue is how a deployment opts into honest backpressure
    instead of buffering unboundedly toward a slow peer.  Three
    raw-speed keys are consumed by the transport itself rather than
    forwarded: ``shm=True`` (same-host shared-memory fast path with
    transparent TCP fallback), ``stripes=N`` (striped per-peer DCN
    streams), and ``transport_tuning=True | TransportConfig(...)``
    (the closed-loop stripe/coalesce autotuner, actuated at round
    boundaries) — see :class:`_TcpTransport`.

    ``overlap=True`` turns on compute/gossip overlap
    (:class:`DoubleBuffer`): landed neighbor deposits are harvested
    from this rank's landing window WHILE the gradient compute runs
    and the staged round-(k-1) mixing is applied at the next round
    boundary (in slot order — byte-identical results vs the serial
    fold for the same landed deposits).  Fence discipline is
    preserved: every quiesce-rendezvous pauses the harvester and
    counts its staged mass, so the exact audit holds; the per-round
    hidden-time share is the ``bf_overlap_fraction`` gauge and the
    ``overlap=`` field on the traced round spans.

    ``fleet`` (:class:`~bluefog_tpu.fleet.FleetConfig`) arms the fleet
    health plane's telemetry publisher: every ``fleet.every``-th round
    boundary this rank appends a round-stamped record (round-time
    stats, push-sum mass, per-peer lag/phase EWMAs, blackbox event
    counts, metrics deltas, ``/proc`` host gauges) to
    ``fleet.<rank>`` in ``fleet.dir`` (default: the barrier
    directory) — what ``bffleet-tpu`` dashboards live and replays as
    the ``--check`` SLO regression gate.  Declaring ``fleet.slos``
    additionally runs the per-rank SLO engine in-loop; with
    ``control=`` active, alert-named ranks feed back into the
    controller's evidence as SUSPECT (see ``docs/fleet.md``).  The
    publisher reads, never moves, mass — the exact audit is unchanged
    with it active (asserted by the bench and the MP acceptance test).

    ``profile`` names a (shared) directory for the continuous sampling
    profiler (:mod:`bluefog_tpu.profiling`): this process arms a
    sampler writing phase-attributed folded stacks to
    ``profile-rank<rank>.jsonl`` there, and stops it when the rank
    returns.  A profiler already armed via ``BLUEFOG_TPU_PROFILE`` is
    left running (the runner only owns what it started); merge the
    per-rank files with ``bfprof-tpu <dir>``.

    Returns a :class:`DSGDReport` on rank 0 (``losses`` filled only at index
    ``rank`` — other ranks' loss curves stay in their processes), ``None``
    elsewhere (including joiners and leavers).
    """
    if control is not None and transport != "tcp":
        raise ValueError(
            "the communication control plane rides the tcp transport's "
            "telemetry (ack EWMA, heartbeats, reconnect counters); "
            f"transport={transport!r} has none")
    if control is not None and resilience is None:
        raise ValueError(
            "the communication control plane needs "
            "resilience=ResilienceConfig(...): heartbeats are what keep "
            "a penalized (idle) stream's ack EWMA fresh — without them "
            "the lag evidence freezes at its worst value and hysteresis "
            "could never release a recovered peer")
    if control is not None and control.max_codec_level > 0:
        from bluefog_tpu.control import CODEC_LADDER

        if wire_codec != CODEC_LADDER[control.max_codec_level]:
            raise ValueError(
                "control.max_codec_level="
                f"{control.max_codec_level} needs the streams opened at "
                f"that ceiling: pass wire_codec="
                f"{CODEC_LADDER[control.max_codec_level]!r} (the "
                "controller backs OFF from the negotiated ceiling; it "
                "cannot step above it)")
    if transport == "shm":
        tx = _ShmTransport()
    elif transport == "tcp":
        tx = _TcpTransport(tcp_bind, pipeline=True, wire_codec=wire_codec,
                           resilience=resilience,
                           stream_options=stream_options)
    elif transport == "tcp-sync":
        # the pre-pipelining wire shape (one blocking round-trip per
        # deposit) — kept selectable for A/B measurement and bisection
        tx = _TcpTransport(tcp_bind, pipeline=False)
    else:
        raise ValueError(
            f"transport must be 'shm', 'tcp' or 'tcp-sync', got "
            f"{transport!r}")
    # continuous profiling: per-process, so each rank writes its own
    # profile-rank<k>.jsonl into the shared directory.  Owned only when
    # this call armed it (env-armed profilers span runs)
    prof_owned = False
    from bluefog_tpu.profiling import sampler as _profiling

    if profile is not None:
        if _profiling.get() is None:
            _profiling.configure(profile, rank=rank)
            prof_owned = True
    else:
        # no explicit profile= — still poke the sampler so a
        # BLUEFOG_TPU_PROFILE env arming takes effect, stamped with
        # this process's true rank (atexit owns its tail flush)
        _profiling.set_rank(rank)
    # the transport may already hold live resources (the TCP server thread +
    # socket start in its constructor): EVERYTHING from here on — including
    # setup failures like a TreePacker TypeError or a window-name collision
    # — must release them, so the try begins immediately
    opened: List = []
    db: Optional[DoubleBuffer] = None
    try:
        if (join or leave_after_s is not None
                or initial_members is not None) and transport != "tcp":
            raise ValueError(
                "elastic membership (join/leave/initial_members) requires "
                "transport='tcp' (member discovery rides the winaddr "
                "records; the shm namespace has none)")
        d = TreePacker(params0, np.float64).size

        # every window/handle this process opens is freed in the finally —
        # a mid-run exception (loss_and_grad raising, a peer dying at a
        # barrier) must not leak shm segments or sockets.  Elastic jobs
        # take one landing slot PER CAPACITY RANK (slot index == source
        # rank — stable under arbitrary membership change, which dense
        # in-neighbor slot maps are not); fixed fleets keep the dense
        # in-degree sizing, whose memory is O(in_degree · d) per rank
        # instead of O(capacity · d).
        if (join or leave_after_s is not None or initial_members is not None
                or control is not None):
            n_slots = topology.size
        else:
            n_slots = max(len(list(topology.in_neighbors(rank))), 1)
        win = tx.create(f"{name}:{rank}", n_slots, d + 1)
        opened.append(win)
        if overlap:
            # compute/gossip overlap: the harvester lives HERE (not in
            # the body) so the finally below stops its thread before
            # any window is freed, on every exit path
            cap = (join or leave_after_s is not None
                   or initial_members is not None or control is not None)
            db = DoubleBuffer(
                win,
                [k for k in range(n_slots) if not (cap and k == rank)],
                d + 1)

        def _create(wname, n_slots, n_elems):
            w = tx.create(wname, n_slots, n_elems)
            opened.append(w)
            return w

        def _open(owner, wname, n_slots, n_elems):
            w = tx.open(owner, wname, n_slots, n_elems)
            opened.append(w)
            return w

        return _run_dsgd_rank_body(
            topology, rank, params0, loss_and_grad, barrier=barrier, lr=lr,
            duration_s=duration_s, skew_s=skew_s, name=name,
            poll_interval_s=poll_interval_s, win=win, transport=tx,
            create_window=_create, open_window=_open,
            resilience=resilience if transport == "tcp" else None,
            join=join, leave_after_s=leave_after_s,
            initial_members=initial_members,
            snapshot_every=snapshot_every, control=control,
            stop_after_steps=stop_after_steps, fleet=fleet,
            overlap_buffer=db)
    finally:
        if db is not None:
            db.close()  # idempotent; must precede the window frees
        if prof_owned:
            from bluefog_tpu.profiling import sampler as _profiling

            _profiling.reset()  # joins the sampler + flushes the tail
        if snapshot_every:
            _snapshots.table().drop(f"{name}:{rank}")
        # land this rank's spans before the process exits the run (the
        # atexit hook also flushes, but a long-lived process may run
        # several jobs into one trace dir) — no-op when tracing is off
        _tr.flush()
        for w in opened:
            try:
                w.free()
            except Exception:
                pass
        tx.close()


def _run_dsgd_rank_body(topology, rank, params0, loss_and_grad, *, barrier,
                        lr, duration_s, skew_s, name, poll_interval_s, win,
                        transport, create_window, open_window,
                        resilience=None, join=False, leave_after_s=None,
                        initial_members=None, snapshot_every=0,
                        control=None, stop_after_steps=None, fleet=None,
                        overlap_buffer=None):
    n = topology.size
    packer = TreePacker(params0, np.float64)
    d = packer.size
    cfg = resilience
    inj = _chaos.get()
    chaos_leave = (inj is not None and any(
        ru.site == "rank" and ru.rank == rank and ru.fault == "leave"
        for ru in inj.rules))
    # elasticity is decided by the ARGUMENTS, which every rank of a job
    # shares by construction — a chaos leave rule alone cannot flip one
    # process into the elastic slot scheme while its peers stay dense
    elastic = bool(join or leave_after_s is not None
                   or initial_members is not None)
    if chaos_leave and not elastic:
        raise ValueError(
            "a rankN:leave chaos rule needs an ELASTIC job (every rank "
            "must run the membership protocol): start the fleet with "
            "initial_members=/join=/leave_after_s= on all ranks")
    if elastic and cfg is None:
        raise ValueError(
            "elastic membership (join/leave/initial_members) rides the "
            "resilient rendezvous machinery; pass "
            "resilience=ResilienceConfig(...)")
    if (join or leave_after_s is not None or chaos_leave) and rank == 0:
        raise ValueError("rank 0 is the reporting rank and must be a "
                         "stable initial member (cannot join or leave)")
    members: set = (set(range(n)) if initial_members is None
                    else {int(r) for r in initial_members})
    if not join and rank not in members:
        raise ValueError(f"rank {rank} is not in initial_members "
                         f"{sorted(members)} (a later process joins "
                         "with join=True)")
    meta = None
    dead: set = set()
    left: set = set()
    ever_joined: set = set()
    handled: set = set()  # (kind, rank, token) records already consumed
    losses: List[float] = []
    steps = 0
    baseline_mass: Optional[float] = None
    exact = True  # False once a failure escapes the rendezvous protocol
    rec = _bb.get()  # per-PROCESS flight recorder (None when off)
    if rec is not None and rec.rank is None:
        # one OS process per rank here: pin the dump identity so a
        # shared (e.g. NFS) incident dir gets blackbox-rank<r>.jsonl per
        # rank instead of every process fighting over rank 0's file
        rec.rank = rank
    # causal tracing rides the same one-process-per-rank shape: pin the
    # trace file identity before the first flush names it (no-op when
    # BLUEFOG_TPU_TRACE is unset)
    _tr.set_rank(rank)
    _chaos.arm(rank)

    x = packer.pack(params0)
    p = 1.0
    gvec = np.empty(d, np.float64)
    payload = np.empty(d + 1, np.float64)
    self_buf = np.empty(d + 1, np.float64)
    peers: Dict[int, object] = {}

    # ------------------------------------------- control plane (opt-in)
    ctl = (_CommController(rank, n, config=control)
           if control is not None else None)
    tracker: Optional[_MixingTracker] = None
    tracker_members: Optional[frozenset] = None
    gossip_every = 1
    if ctl is not None:
        _ctlev.clear_evidence(barrier.path, rank)  # previous life's record

    # fleet health plane (opt-in): per-rank telemetry publisher +
    # optional in-loop SLO engine, appending to fleet.<rank> in the
    # shared directory (default: the barrier dir — the one medium every
    # rank and the bffleet-tpu dash already watch)
    flt = (_FleetRuntime(rank, fleet.dir or barrier.path, fleet)
           if fleet is not None else None)

    # slot scheme (must agree across every rank of the job, which the
    # shared arguments guarantee): elastic AND control-plane runs use
    # slot index == source rank over capacity slots (stable under
    # membership change and controller replans); fixed fleets keep the
    # dense in-neighbor mapping of the original topology
    cap_slots = elastic or control is not None
    in_nbrs = list(topology.in_neighbors(rank))
    my_slots = (range(n) if cap_slots else range(len(in_nbrs)))
    # compute/gossip overlap (opt-in; owned/closed by the caller):
    # harvests landed deposits from the landing window while the
    # gradient compute runs; the staged mixing applies at the next
    # round boundary via _fold_staged_at_round_boundary below
    db: Optional[DoubleBuffer] = overlap_buffer
    # striped-transport autotuner hook (tcp transport with
    # transport_tuning armed): the runner drives the closed loop at its
    # round boundaries
    retune = getattr(transport, "retune_transport_at_round_boundary",
                     None)

    def _peer_slots(j: int) -> int:
        return (n if cap_slots
                else max(len(list(topology.in_neighbors(j))), 1))

    def _slot_in(j: int) -> int:
        """Our landing slot in peer j's window."""
        return (rank if cap_slots
                else list(topology.in_neighbors(j)).index(rank))

    def _ensure_peer(j: int):
        if j not in peers:
            peers[j] = open_window(j, f"{name}:{j}", _peer_slots(j),
                                   d + 1)
        return peers[j]

    def _make_plan():
        """The mixing plan over the CURRENT member set AT THIS ROUND
        BOUNDARY: the controller's penalized rebuild when the control
        plane is on (heals and membership change then keep the plan's
        penalties), a fresh replan for elastic fleets (re-optimized
        degree caps and spectral gap as n changes), the PR-5
        renormalizing heal for fixed ones.  Deterministic in (members,
        dead, CommPlan) — and the CommPlan itself is deterministic in
        the disseminated evidence — so every rank that has seen the
        same records converges on the same matrix with no extra
        coordination."""
        nonlocal tracker, gossip_every, tracker_members
        t0p = time.perf_counter()
        if ctl is not None:
            plan = ctl.apply_plan(topology=topology, members=members - dead)
            gossip_every = ctl.plan.gossip_every
            # feed-window exponent follows the cadence in effect (a
            # stretched gossip_every halves the gossip rounds per
            # evidence window — see MixingTracker.rebase)
            rpu = max(1, round(control.evidence_every / gossip_every))
            live = frozenset(members - dead)
            if tracker is None:
                tracker = _MixingTracker(
                    plan, rounds_per_update=rpu, rank=str(rank))
            else:
                if tracker_members is not None and live != tracker_members:
                    # membership boundary: drop the cross-member-set
                    # sample (see MixingTracker.reset_measurement) —
                    # a departing outlier's miracle ratio must not
                    # walk the densify ladder down
                    tracker.reset_measurement()
                tracker.rebase(plan, rounds_per_update=rpu)
            tracker_members = live
        elif elastic:
            plan = _replan(topology, members - dead)
        else:
            plan = _heal(topology, dead)
        _mt.observe("bf_replan_seconds", time.perf_counter() - t0p)
        _mt.set("bf_members", float(len(members - dead)))
        return plan

    def _local_mass() -> float:
        """Own p + unconsumed landing-slot mass, valid only while
        nothing is in flight (inside a quiesce-rendezvous).  With the
        overlap harvester armed, its staged-but-unapplied take is mass
        this rank already holds: pause the harvester (quiescing the
        window) and count it, or it would hide from the audit."""
        local = p
        if db is not None:
            db.pause()
            local += db.staged_mass()
        for k in my_slots:
            if cap_slots and k == rank:
                continue
            buf, fresh = win.read(k, consume=False)
            if fresh > 0:
                local += float(buf[-1])
        return local

    def _fold_staged_at_round_boundary(z_pre):
        """ROUND-BOUNDARY apply of the overlapped gossip-IN: quiesce
        the harvester, fold the round-(k-1) mass it staged under the
        last compute (plus one final inline sweep, in slot order — the
        serial fold's exact floating-point op sequence), report the
        hidden/total time split as ``bf_overlap_fraction``, and re-arm
        the harvester for the coming compute.  Returns ``(dis, ov)`` —
        the disagreement observation and the overlap fraction.  The
        BF-WIN004 lint restricts ``apply_staged`` call sites to
        round-boundary vocabulary like this function's."""
        nonlocal x, p
        t_b = time.perf_counter()
        staged, busy = db.apply_staged()
        dis = None
        for k, buf, fresh in staged:
            if fresh > 0:
                if z_pre is not None and buf[-1] > 0:
                    dj = float(np.linalg.norm(
                        buf[:-1] / buf[-1] - z_pre))
                    dis = dj if dis is None else max(dis, dj)
                x += buf[:-1]
                p += buf[-1]
        tot = busy + (time.perf_counter() - t_b)
        ov = (busy / tot) if tot > 0 else 0.0
        _mt.set("bf_overlap_fraction", ov, rank=str(rank))
        db.begin()
        return dis, ov

    def _ctl_round_boundary() -> None:
        """Control-plane work at a round boundary: harvest the streams'
        wire telemetry, publish this rank's evidence record, decide
        over the disseminated records, and — when the plan version
        advanced — actuate (new penalized mixing plan, cadence, codec)
        before the next round's deposits leave.  The quiesce contract:
        nothing this changes is consulted mid-round, and a plan moves
        edges/cadence/codec, never mass, so the exact audit holds
        through it."""
        nonlocal my_out, frac, gossip_every
        # a corpse or a leaver is off this rank's observation surface:
        # forget its sticky observations, or the frozen last look would
        # be republished in every future record (a dead peer's SUSPECT
        # state must not keep voting)
        for j in sorted(dead | left):
            ctl.forget_peer(j)
        for j, h in sorted(peers.items()):
            if j in dead or j in left:
                continue
            hp = getattr(h, "health", None)
            pe = getattr(h, "phase_ewma", None)
            ctl.note_peer(
                j, lag_s=h.ack_ewma(),
                state=hp.state if hp is not None else None,
                reconnects_total=h.reconnects,
                # wire-phase decomposition (net/queue/apply EWMA) from
                # the traced extended acks: the slow-link-vs-slow-host
                # evidence; None when tracing is off or the peer's
                # connection never negotiated FEATURE_TRACE
                phase_s=pe() if pe is not None else None)
        d_now = ctl.disagreement
        if tracker is not None and d_now is not None:
            measured = tracker.update(d_now)
            excess = None
            if (measured is not None and tracker.predicted is not None
                    and measured < 1.0):
                # interpretable only while gossip is contracting; at
                # the SGD gradient/gossip equilibrium the growth band
                # governs instead
                excess = measured - tracker.predicted
            ctl.note_mixing_excess(excess)
        _ctlev.write_evidence(barrier.path, ctl.evidence(steps))
        prev_version = ctl.plan.version
        # a corpse's stale record must not keep voting: filter by the
        # disseminated death view (tombstones), which every rank
        # converges on — so the filtered record set converges too
        evs = [ev for ev in _ctlev.read_evidence(barrier.path, n)
               if ev.rank not in dead]
        new_plan = ctl.decide(steps, evs)
        if new_plan.version == prev_version:
            return
        plan_topo = _make_plan()  # routes through ctl.apply_plan
        my_out = list(plan_topo.out_neighbors(rank))
        frac = 1.0 / (len(my_out) + 1)
        gossip_every = new_plan.gossip_every
        if control.max_codec_level > 0:
            # retune wire aggressiveness within the negotiated ceiling
            for j, h in sorted(peers.items()):
                if j in dead:
                    continue
                try:
                    h.set_codec(new_plan.codec)
                except (RuntimeError, OSError, ValueError):
                    pass  # a dying handle's codec no longer matters

    def _round_end_telemetry(z, dis) -> None:
        """Per-round observability at THIS round boundary: the
        ``bf_round_seconds`` histogram plus — when the fleet plane is
        armed and the cadence is due — the telemetry record publish
        (and SLO/alert evaluation over the shared records).  Reads
        loop-local values and the streams' telemetry accessors; moves
        no mass (the exact audit is indifferent to it).  Round time is
        boundary-to-boundary wall clock, so the inter-round skew sleep
        and the boundary work itself are IN it — the cadence an
        operator's p99 question is about."""
        nonlocal t_rnd0
        now_p = time.perf_counter()
        rdt = now_p - t_rnd0
        t_rnd0 = now_p
        _mt.observe("bf_round_seconds", rdt, rank=str(rank))
        if flt is None:
            return
        flt.note_round(rdt)
        if not flt.due(steps):
            return
        peer_tel: Dict[int, Dict[str, float]] = {}
        for j, h in sorted(peers.items()):
            if j in dead or j in left:
                continue
            ae = getattr(h, "ack_ewma", None)
            lag = ae() if ae is not None else None
            if lag is None:
                continue
            entry = {"lag": float(lag)}
            pe = getattr(h, "phase_ewma", None)
            ph = pe() if pe is not None else None
            if ph:
                entry.update({str(k): float(v) for k, v in ph.items()})
            peer_tel[j] = entry
        stale = (steps % snapshot_every) if snapshot_every else None
        with _tr.span("fleet", "dsgd", round_=steps):
            flt.boundary(steps, mass=p,
                         z_mean=(float(z.mean()) if z is not None
                                 else float("nan")),
                         dis=dis, staleness=stale, peers=peer_tel,
                         controller=ctl)

    def _mass_rendezvous(stage: str) -> float:
        """Second half of a quiesce-rendezvous: publish local mass, meet
        at ``<stage>-resume``, and sum the member set's mass files —
        the exact baseline every later audit must reproduce."""
        mpath = os.path.join(barrier.path, f"{stage}.mass.{rank}")
        with open(mpath + ".tmp", "w") as f:
            # repr of a PYTHON float: round-trips to the exact same
            # binary64 (numpy scalar reprs do not parse back)
            f.write(repr(float(_local_mass())))
        os.replace(mpath + ".tmp", mpath)
        barrier.wait(stage + "-resume", timeout_s=cfg.barrier_timeout_s)
        total = 0.0
        for r2 in sorted(members - dead):
            with open(os.path.join(barrier.path,
                                   f"{stage}.mass.{r2}")) as f:
                total += float(f.read())
        return total

    # ---------------------------------------------------- fault handling
    def _tombstone(j: int) -> None:
        # announce a death to survivors that may never touch the dead
        # rank's transport themselves (the barrier dir is the one shared
        # medium every rank already polls)
        path = os.path.join(barrier.path, f"dead.{j}")
        try:
            open(path, "w").close()
        except OSError:
            pass

    def _tombstoned() -> set:
        return {r2 for r2 in sorted(members)
                if r2 != rank and r2 not in dead and os.path.exists(
                    os.path.join(barrier.path, f"dead.{r2}"))}

    def _heal_and_rebase(newly: set) -> None:
        """Declare ``newly`` DEAD, heal the mixing weights over the
        survivors, and hold the quiesce-rendezvous that makes the
        surviving set's mass auditable EXACTLY: every survivor fences
        its live peers, meets at a heal barrier (dead excluded), and
        measures its local mass while nothing is in flight."""
        nonlocal my_out, frac, baseline_mass
        pending = set(newly)
        while pending:
            for j in sorted(pending):
                _tombstone(j)
                _bb.record("peer_dead", peer=f"rank{j}", rank=rank,
                           step=steps)
                _mt.set("bf_peer_state", float(_res.DEAD), peer=f"rank{j}")
            dead.update(pending)
            barrier.exclude |= pending
            for j in pending:
                peers.pop(j, None)  # the caller's finally frees it
            pending = set()
            plan = _make_plan()
            my_out = list(plan.out_neighbors(rank))
            frac = 1.0 / (len(my_out) + 1)
            # FENCE the survivors: nothing of ours may be in flight when
            # the baseline is measured.  A fence that fails names the
            # next corpse — extend and repeat.
            for j in sorted(my_out):
                try:
                    _ensure_peer(j).flush(cfg.barrier_timeout_s)
                except (RuntimeError, TimeoutError, OSError):
                    pending.add(j)
        stage = "heal" + "".join(f"-{j}" for j in sorted(dead))
        nonlocal exact
        try:
            barrier.wait(stage, timeout_s=cfg.barrier_timeout_s)
            # between the two heal barriers no survivor deposits, so
            # local mass is the whole truth
            baseline_mass = _mass_rendezvous(stage)
        except (TimeoutError, OSError, ValueError) as e:
            # a survivor never made the rendezvous (it exited the loop
            # first, or a second failure overlapped the first): the run
            # goes on healed, but the exactness claim is withdrawn
            baseline_mass = None
            exact = False
            _log.warn("rank %d: heal rendezvous %r degraded (%s: %s); "
                      "continuing without an exact baseline", rank, stage,
                      type(e).__name__, e)
        _bb.record("peer_dead_healed", rank=rank, dead=sorted(dead),
                   baseline_mass=baseline_mass, exact=exact)

    # ------------------------------------------------ elastic membership
    def _admit_joiner(j: int, token: str) -> None:
        """A ``member.<j>`` record appeared: admit the joiner at THIS
        round boundary.  Quiesce-rendezvous (fence, join barrier, mass
        files) re-establishes the exact baseline over the grown member
        set — the joiner's fresh ``p = 1`` enters the books here."""
        nonlocal my_out, frac, baseline_mass, exact
        transport.collect(barrier, [j])
        members.add(j)
        dead.discard(j)
        left.discard(j)
        ever_joined.add(j)
        barrier.exclude.discard(j)
        _bb.record("peer_join", peer=f"rank{j}", rank=rank, step=steps)
        _mt.set("bf_peer_state", float(_res.JOINING), peer=f"rank{j}")
        plan = _make_plan()
        my_out = list(plan.out_neighbors(rank))
        frac = 1.0 / (len(my_out) + 1)
        stage = f"join-{j}-{token}"
        try:
            for jj in my_out:
                _ensure_peer(jj)
            # FENCE: nothing of ours may be in flight while the grown
            # member set measures its baseline
            for jj in sorted(k for k in peers if k in members - dead):
                peers[jj].flush(cfg.barrier_timeout_s)
            barrier.wait(stage, timeout_s=cfg.barrier_timeout_s)
            baseline_mass = _mass_rendezvous(stage)
        except (RuntimeError, TimeoutError, OSError, ValueError) as e:
            baseline_mass = None
            exact = False
            _log.warn("rank %d: join rendezvous %r degraded (%s: %s); "
                      "continuing without an exact baseline", rank, stage,
                      type(e).__name__, e)
        _mt.set("bf_peer_state", float(_res.HEALTHY), peer=f"rank{j}")
        _bb.record("peer_admitted", peer=f"rank{j}", rank=rank,
                   members=sorted(members), baseline_mass=baseline_mass,
                   exact=exact)

    def _release_leaver(j: int, token: str) -> None:
        """A ``leaving.<j>`` record appeared: fence our stream to the
        leaver (all our deposits applied), meet at its leave barrier —
        after which nothing is in flight toward it — and wait at the
        ``-fin`` barrier for its mass handoff to land.  The baseline is
        UNCHANGED: the leaver's mass moved into member windows."""
        nonlocal my_out, frac, exact
        stage = f"leave-{j}-{token}"
        _bb.record("peer_leaving", peer=f"rank{j}", rank=rank, step=steps)
        try:
            h = peers.get(j)
            if h is not None:
                h.flush(cfg.barrier_timeout_s)
            barrier.wait(stage, timeout_s=cfg.barrier_timeout_s)
            # the leaver drains its window and hands its mass off
            # between these two barriers
            barrier.wait(stage + "-fin", timeout_s=cfg.barrier_timeout_s)
        except (RuntimeError, TimeoutError, OSError) as e:
            exact = False
            _log.warn("rank %d: leave rendezvous %r degraded (%s: %s)",
                      rank, stage, type(e).__name__, e)
        members.discard(j)
        left.add(j)
        barrier.exclude.add(j)
        peers.pop(j, None)  # the caller's finally closes it
        plan = _make_plan()
        my_out = list(plan.out_neighbors(rank))
        frac = 1.0 / (len(my_out) + 1)
        _mt.set("bf_peer_state", float(_res.LEFT), peer=f"rank{j}")
        _bb.record("peer_leave", peer=f"rank{j}", rank=rank,
                   members=sorted(members))

    def _poll_membership() -> bool:
        """Handle membership records at a round boundary (leaves first —
        their rendezvous must not race an admission), then report
        whether a member finished the run (global end for joiners whose
        own duration clock started late)."""
        mview = _mship.scan(barrier.path, n)
        for j, token in sorted(mview.leaving.items()):
            if j == rank or j in left or j in dead or j not in members:
                continue
            if ("leaving", j, token) in handled:
                continue
            handled.add(("leaving", j, token))
            _release_leaver(j, token)
        for j, token in sorted(mview.announced.items()):
            if j == rank or j in members:
                continue
            if ("member", j, token) in handled:
                continue
            handled.add(("member", j, token))
            _admit_joiner(j, token)
        for m in sorted(members - dead):
            if m != rank and os.path.exists(
                    os.path.join(barrier.path, f"stopped.{m}")):
                return True
        return False

    def _graceful_leave() -> None:
        """This rank's graceful drain: the intentional counterpart of
        dying.  Fence own streams, announce intent, wait for every
        member to fence theirs (the leave barrier — nothing in flight
        toward this window afterwards), drain the window, hand the
        ENTIRE (x, p) to live out-neighbors in drain-flagged deposits,
        record ``left``, and confirm at the ``-fin`` barrier so the
        members know the handoff landed.  The audit stays exact: the
        mass is conserved among the remaining members."""
        nonlocal x, p
        if flt is not None:
            flt.close()  # the leaver's history ends at its last round
        token = _mship.new_token()
        stage = f"leave-{rank}-{token}"
        _bb.record("leave_begin", rank=rank, step=steps)
        # our regular deposits must be applied before the members fence
        for jj in sorted(k for k in peers if k in members - dead):
            peers[jj].flush(cfg.barrier_timeout_s)
        _mship.write_record(barrier.path, "leaving", rank, token)
        barrier.wait(stage, timeout_s=cfg.barrier_timeout_s)
        # every member fenced its stream to us before entering the
        # barrier: nothing is in flight toward this window anymore.
        # The leaver's overlap harvester retires first — its staged
        # take joins the mass handed off below
        if db is not None:
            for j, buf, fresh in db.close():
                if fresh > 0:
                    x += buf[:-1]
                    p += buf[-1]
        for j in range(n):
            if j == rank:
                continue
            buf, fresh = win.read(j, consume=True)
            if fresh > 0:
                x += buf[:-1]
                p += buf[-1]
        live = sorted((members - dead) - {rank})
        plan = _make_plan()
        tgt = [j for j in plan.out_neighbors(rank) if j in live] or live
        if not tgt:
            raise RuntimeError("graceful leave with no live member to "
                               "hand push-sum mass to")
        share = np.empty(d + 1, np.float64)
        share[:-1] = x
        share[-1] = p
        share /= float(len(tgt))
        for j in tgt:
            _ensure_peer(j).deposit_async(rank, share, accumulate=True,
                                          drain=True)
        for j in tgt:
            peers[j].flush(cfg.barrier_timeout_s)  # handoff APPLIED
        x[:] = 0.0
        p = 0.0
        win.set_self(np.zeros(d + 1))
        _mship.write_record(barrier.path, "left", rank, token)
        _mship.clear_record(barrier.path, "leaving", rank)
        barrier.wait(stage + "-fin", timeout_s=cfg.barrier_timeout_s)
        _mt.inc("bf_leaves_total", 1.0)
        _bb.record("leave_done", rank=rank, handed_to=tgt,
                   step=steps)

    def _wait_resilient(stage: str) -> None:
        """Barrier that learns its exclusion set: when ranks die between
        the loop's detection window and a rendezvous, the timeout names
        them and the survivors stop waiting for corpses.  Past the
        rendezvous protocol there is no rebase, so exactness is off."""
        nonlocal exact
        if cfg is None:
            barrier.wait(stage)
            return
        try:
            barrier.wait(stage, timeout_s=cfg.barrier_timeout_s)
        except TimeoutError:
            missing = {r2 for r2 in range(n)
                       if r2 not in barrier.exclude and not os.path.exists(
                           os.path.join(barrier.path, f"{stage}.{r2}"))}
            if not missing:
                raise
            for j in sorted(missing):
                _tombstone(j)
            dead.update(missing)
            barrier.exclude |= missing
            for j in missing:
                peers.pop(j, None)
            exact = False
            barrier.wait(stage, timeout_s=cfg.barrier_timeout_s)

    # ---------------------------------------------------------- startup
    if join:
        # the job is already running: the startup barriers are history.
        # Clear records from this rank's previous life, publish our
        # window address, and discover the roster from the records —
        # every rank that published an address minus tombstones and
        # completed leavers.
        for kind in ("dead", "left", "leaving", "member"):
            _mship.clear_record(barrier.path, kind, rank)
        transport.publish(barrier, rank)
        # poll, not a single scan: on a loaded host (or with the joiner
        # racing the members' own startup) the winaddr records may lag
        # this process by seconds
        roster_deadline = time.perf_counter() + cfg.barrier_timeout_s
        while True:
            mview = _mship.scan(barrier.path, n)
            members = mview.current_members() - {rank}
            if members:
                break
            if time.perf_counter() > roster_deadline:
                raise RuntimeError(
                    f"joiner rank {rank} found no live member records "
                    f"in {barrier.path} within {cfg.barrier_timeout_s}s")
            time.sleep(0.05)
        transport.collect(barrier, sorted(members))
        meta = open_window(0, f"{name}:meta", n, 2)
        barrier.exclude = set(range(n)) - members - {rank}
        # WARM-START from a neighbor's window: one atomic ``read_self``
        # of a live member's published (x, p) snapshot — the pair is
        # published under the window's self mutex, so the joiner's
        # first state is round-consistent by construction.  No
        # checkpoint file is read anywhere.
        t_ws = time.perf_counter()
        z = None
        src = None
        ws_deadline = t_ws + cfg.barrier_timeout_s
        while z is None and time.perf_counter() < ws_deadline:
            for nb in sorted(members):
                try:
                    s = _ensure_peer(nb).read_self()
                except (RuntimeError, OSError, ConnectionError):
                    continue
                if s[-1] > 0.0:
                    z = s[:-1] / s[-1]
                    src = nb
                    break
            if z is None:
                time.sleep(0.01)
        if z is None:
            raise RuntimeError(
                f"joiner rank {rank} could not warm-start: no member "
                "published an (x, p) window snapshot within "
                f"{cfg.barrier_timeout_s}s (was the job started "
                "elastic — initial_members= — so members publish?)")
        x = np.asarray(z, np.float64).copy()
        p = 1.0  # fresh push-sum weight: mass enters the system HERE
        warm_s = time.perf_counter() - t_ws
        _mt.observe("bf_join_warmstart_seconds", warm_s)
        _bb.record("join_warmstart", rank=rank, source=src,
                   warmstart_s=round(warm_s, 6))
        # announce, then meet the members at the admission rendezvous:
        # they fence, everyone measures local mass while nothing is in
        # flight, and the baseline is re-established over the grown set
        token = _mship.new_token()
        members.add(rank)
        ever_joined.add(rank)
        _mship.write_record(barrier.path, "member", rank, token)
        stage = f"join-{rank}-{token}"
        try:
            # The admission wait must survive the roster going stale
            # under it: a member the joiner discovered can drain (or
            # die) before it ever polls this join record, and the
            # joiner would otherwise wait the full timeout for a rank
            # that is gone.  Wait in short slices, re-scanning the
            # records between them and excluding completed leavers /
            # tombstones — slow members (step time stretching the
            # 16-step record poll) still only degrade the rendezvous,
            # never kill the joiner.
            deadline = time.perf_counter() + cfg.barrier_timeout_s
            while True:
                try:
                    barrier.wait(stage, timeout_s=min(
                        2.0, max(0.1, deadline - time.perf_counter())))
                    break
                except TimeoutError:
                    if time.perf_counter() >= deadline:
                        raise
                    mv = _mship.scan(barrier.path, n)
                    gone = (mv.dead | set(mv.left)) & members - {rank}
                    if gone:
                        members -= gone
                        barrier.exclude |= gone
            baseline_mass = _mass_rendezvous(stage)
        except (TimeoutError, OSError, ValueError) as e:
            baseline_mass = None
            exact = False
            _log.warn("rank %d: own join rendezvous degraded (%s: %s); "
                      "continuing without an exact baseline",
                      rank, type(e).__name__, e)
        plan = _make_plan()
        my_out = list(plan.out_neighbors(rank))
        frac = 1.0 / (len(my_out) + 1)
        for j in my_out:
            _ensure_peer(j)
    else:
        if rank == 0:
            # per-rank (steps, last_loss) land here so the report can
            # carry every rank's step count across the process boundary
            meta = create_window(f"{name}:meta", n, 2)
        transport.publish(barrier, rank)
        barrier.exclude |= set(range(n)) - members
        barrier.wait("created")
        transport.collect(barrier, sorted(members))
        if rank != 0:
            meta = open_window(0, f"{name}:meta", n, 2)
        if elastic:
            # every initial member starts with p = 1, so the baseline
            # is exact by construction; admissions re-measure it
            baseline_mass = float(len(members))
        plan = (_make_plan() if (elastic or ctl is not None)
                else topology)
        my_out = list(plan.out_neighbors(rank))
        frac = 1.0 / (len(my_out) + 1)
        for j in my_out:
            _ensure_peer(j)
    if elastic:
        # publish the initial snapshot so a joiner can warm-start even
        # before this rank's first full round lands
        self_buf[:-1] = x
        self_buf[-1] = p
        win.set_self(self_buf)
    leave_deadline = leave_after_s

    t0 = time.perf_counter()
    t_rnd0 = t0  # first round's boundary-to-boundary clock starts here
    while (time.perf_counter() - t0 < duration_s
           and (stop_after_steps is None or steps < stop_after_steps)):
        try:
            _chaos.check_step(rank, steps)
        except _chaos.ChaosLeave:
            _graceful_leave()
            return None
        if (elastic and leave_deadline is not None
                and time.perf_counter() - t0 >= leave_deadline):
            _graceful_leave()
            return None
        if cfg is not None and steps % 16 == 0:
            # throttled: n-1 stat() calls against a possibly-NFS barrier
            # dir have no place on every hot-loop round; 16 rounds adds
            # at most ~tens of ms to a detection deadline that is
            # dominated by the reconnect budget anyway (the deposit
            # failure path below detects independently of this check)
            newly = _tombstoned()
            if newly:
                _heal_and_rebase(newly)
            if elastic and _poll_membership():
                break  # a member finished: converge at the stop barrier
        if ctl is not None and steps > 0 \
                and steps % control.evidence_every == 0:
            with _tr.span("control", "dsgd", round_=steps):
                _ctl_round_boundary()
        if retune is not None and steps > 0 and steps % 16 == 0:
            # transport autotune at this round boundary: the striped
            # streams' ack/phase EWMAs in, a (possibly unchanged)
            # TransportPlan actuated — same cadence as the tombstone
            # poll, cheap either way (the no-change case is a pure
            # function call per peer)
            with _tr.span("control", "dsgd", round_=steps):
                retune(steps)
        trec = _tr.get()
        if trec is not None:
            t_rnd_w = time.time()
            t_rnd_p = time.perf_counter()
        if rec is not None:
            rec.begin("collective", key=("async_dsgd_mp", rank, steps),
                      op="async_dsgd_round", cid="async_dsgd_round",
                      step=steps, rank=rank, peers=my_out)
        # the disagreement observation feeds control evidence every
        # round and the fleet record at its (cheaper) publish cadence
        z_pre = (x / p if (ctl is not None
                           or (flt is not None and flt.due(steps)))
                 else None)
        dis = None
        ov = None
        with _tr.span("gossip", "dsgd", round_=steps):
            if db is not None:
                # overlapped gossip-IN: apply the mass harvested under
                # the previous round's compute (round-(k-1) mixing),
                # then re-arm the harvester for this round's compute
                dis, ov = _fold_staged_at_round_boundary(z_pre)
            else:
                # gossip-IN: consume landed neighbor mass
                for k in my_slots:
                    if cap_slots and k == rank:
                        continue
                    buf, fresh = win.read(k, consume=True)
                    if fresh > 0:
                        if z_pre is not None and buf[-1] > 0:
                            dj = float(np.linalg.norm(
                                buf[:-1] / buf[-1] - z_pre))
                            dis = dj if dis is None else max(dis, dj)
                        x += buf[:-1]
                        p += buf[-1]
        if ctl is not None and dis is not None:
            ctl.note_disagreement(dis)
        if elastic:
            # publish a coherent (x, p) snapshot: what a JOINING peer
            # warm-starts from
            self_buf[:-1] = x
            self_buf[-1] = p
            win.set_self(self_buf)
        z = x / p
        with _tr.span("compute", "dsgd", round_=steps):
            loss, grads = loss_and_grad(rank, steps, packer.unpack(z))
        losses.append(float(loss))
        packer.pack(grads, out=gvec)
        gvec *= lr * p
        x -= gvec
        if ctl is not None and steps % gossip_every != 0:
            # the plan's local-SGD cadence: a non-gossip step keeps the
            # whole (x, p) local — no split, no deposits, mass
            # trivially conserved
            if rec is not None:
                rec.end("collective", key=("async_dsgd_mp", rank, steps),
                        op="async_dsgd_round", cid="async_dsgd_round",
                        step=steps, rank=rank)
                rec.record("optimizer_step", step=steps, rank=rank,
                           loss=float(loss))
            if trec is not None:
                trec.emit("round", "dsgd", t0=t_rnd_w,
                          dur=time.perf_counter() - t_rnd_p,
                          round_=steps, step=steps,
                          **({} if ov is None
                             else {"overlap": round(ov, 4)}))
            _round_end_telemetry(z, dis)
            steps += 1
            if skew_s > 0 or poll_interval_s > 0:
                time.sleep(skew_s + poll_interval_s)
            continue
        payload[:-1] = x
        payload[-1] = p
        payload *= frac
        failed: List[int] = []
        withheld = 0
        # gossip-OUT under the round's active span: deposit_async
        # captures the thread-local (trace_id, span_id, round) here, so
        # every wire frame this round emits is causally stamped
        with _tr.span("gossip", "dsgd", round_=steps):
            for j in my_out:
                if cfg is not None:
                    try:
                        # a replan can add an edge never opened before,
                        # and the peer may have died since: an open
                        # failure here is peer evidence, not a crash
                        h = _ensure_peer(j).health
                    except (RuntimeError, TimeoutError, OSError):
                        failed.append(j)
                        continue
                    if h is not None:
                        state = h.poll()
                        if state == _res.REJOINED:
                            # the stream reconnected to a peer we had
                            # given up on mid-round: re-admit at THIS
                            # round boundary and resume sending
                            h.admit()
                            state = _res.HEALTHY
                        if state == _res.DEAD:
                            failed.append(j)
                            continue
                        if state != _res.HEALTHY:
                            # SUSPECT: withhold this peer's share instead
                            # of bleeding mass into a possible corpse —
                            # any row-stochastic split is unbiased under
                            # the push-sum weight channel, so keeping the
                            # share is free; sending resumes on recovery.
                            # Without this, every round of the detection
                            # window leaks 1/(deg+1) of our mass into
                            # the void.
                            withheld += 1
                            continue
                # fire-and-forget on the pipelined DCN transport: the
                # background sender overlaps the wire with the next
                # gradient step; the payload buffer is snapshotted at
                # enqueue, so its reuse on the next iteration is safe
                try:
                    _ensure_peer(j).deposit_async(_slot_in(j), payload,
                                                  accumulate=True)
                except (RuntimeError, TimeoutError, OSError):
                    if cfg is None:
                        raise
                    failed.append(j)
        x *= frac
        p *= frac
        if failed or withheld:
            # undelivered shares stay OURS — mass must never evaporate
            # at a dead peer's doorstep
            for _ in range(len(failed) + withheld):
                x += payload[:-1]
                p += payload[-1]
        if failed:
            _heal_and_rebase(set(failed))
        if snapshot_every and steps % snapshot_every == 0:
            # serve-while-training publish: the retained post-step
            # (x, p) — z = x/p is invariant to the frac split — swapped
            # in atomically under its round stamp; this rank's
            # WindowServer serves it to SNAPSHOT/SUBSCRIBE readers
            with _tr.span("publish", "dsgd", round_=steps):
                _snapshots.table().publish(
                    f"{name}:{rank}", steps,
                    {"x": x, "p": np.array([p]),
                     "round": np.array([float(steps)])})
        if rec is not None:
            rec.end("collective", key=("async_dsgd_mp", rank, steps),
                    op="async_dsgd_round", cid="async_dsgd_round",
                    step=steps, rank=rank)
            rec.record("optimizer_step", step=steps, rank=rank,
                       loss=float(loss))
        if trec is not None:
            trec.emit("round", "dsgd", t0=t_rnd_w,
                      dur=time.perf_counter() - t_rnd_p, round_=steps,
                      step=steps,
                      **({} if ov is None
                         else {"overlap": round(ov, 4)}))
        _round_end_telemetry(z, dis)
        steps += 1
        if skew_s > 0 or poll_interval_s > 0:
            time.sleep(skew_s + poll_interval_s)
    if flt is not None:
        # the run is over: land the file handle (records already on
        # disk line by line — a crash loses at most the torn tail the
        # readers tolerate)
        flt.close()
    # FENCE before the audit barrier: every pipelined deposit must be
    # acknowledged as APPLIED by its owner before this rank declares "I
    # deposit no more" — otherwise in-flight mass would land after the
    # owners' final drain and break the exactly-once mass audit.  The
    # BF-WIN lint (analysis/window_lint.py) errors on loops that skip
    # this.
    final_failed: set = set()
    for _j, _h in sorted(peers.items()):
        try:
            _h.flush()
        except (RuntimeError, TimeoutError, OSError):
            if cfg is None:
                raise
            final_failed.add(_j)
    if final_failed:
        # a peer died after the last detection window: too late for a
        # rebase rendezvous, so the exactness claim is withdrawn — the
        # run still completes over the survivors
        for j in sorted(final_failed):
            _tombstone(j)
        dead.update(final_failed)
        barrier.exclude |= final_failed
        for j in final_failed:
            peers.pop(j, None)
        exact = False
    # no rank deposits after this barrier, so the drain below is exact
    _wait_resilient("stopped")
    wall = time.perf_counter() - t0
    if db is not None:
        # stop the overlap harvester and fold its staged take before
        # the final window sweep — mass it consumed from the window is
        # mass this rank owns
        for k, buf, fresh in db.close():
            if fresh > 0:
                x += buf[:-1]
                p += buf[-1]
    for k in my_slots:
        if cap_slots and k == rank:
            continue
        buf, fresh = win.read(k, consume=True)
        if fresh > 0:
            x += buf[:-1]
            p += buf[-1]
    win.set_self(np.concatenate([x, [p]]))
    meta.deposit(rank, np.array([steps, losses[-1] if losses else 0.0]),
                 accumulate=False)
    _wait_resilient("done")

    report = None
    if rank == 0:
        wins = {rank: win}
        wins.update(peers)
        alive = sorted(members - dead)
        for r in alive:
            if r not in wins:
                wins[r] = open_window(r, f"{name}:{r}", _peer_slots(r),
                                      d + 1)
        total_mass = 0.0
        zs = np.empty((len(alive), d))
        for i, r in enumerate(alive):
            s = wins[r].read_self()
            zs[i] = s[:-1] / s[-1]
            total_mass += float(s[-1])
            for k in range(wins[r].n_slots):
                buf, fresh = wins[r].read(k, consume=False)
                if fresh > 0:
                    total_mass += float(buf[-1])
        steps_all = [int(meta.read(r, consume=False)[0][0])
                     for r in range(n)]
        all_losses: List[List[float]] = [[] for _ in range(n)]
        all_losses[rank] = losses
        finals: list = [None] * n
        for i, r in enumerate(alive):
            finals[r] = packer.unpack(zs[i])
        report = DSGDReport(
            wall_time_s=wall,
            steps_per_rank=steps_all,
            losses=all_losses,
            final_params=finals,
            total_mass=total_mass,
            consensus_gap=float(np.abs(zs - zs.mean(axis=0)).max()),
            dead_ranks=sorted(dead),
            baseline_mass=baseline_mass if exact else None,
            left_ranks=sorted(left),
            joined_ranks=sorted(ever_joined),
            control_plan=ctl.plan if ctl is not None else None,
            plan_changes=ctl.plan_changes if ctl is not None else 0,
        )
    # owners unlink only after the audit has read every segment (the
    # caller's finally frees everything this process opened)
    _wait_resilient("audited")
    return report


class AsyncWinPutOptimizer:
    """Host-side driver object behind
    ``DistributedWinPutOptimizer(..., async_=True)``.

    Unlike the synchronous factory (an ``optax.GradientTransformation`` whose
    window dataflow compiles into the SPMD step), the asynchronous mode
    cannot live inside one jitted program — its whole point is that ranks do
    NOT share a program counter.  This object therefore runs the rank loops
    on the host runtime (:func:`run_async_dsgd`) while the per-rank gradient
    work stays jitted jax.

    Usage::

        opt = DistributedWinPutOptimizer(optax.sgd(0.05), topology=topo,
                                         axis_name="bf", async_=True)
        report = opt.run(params0, loss_and_grad, duration_s=5.0)
    """

    def __init__(self, topology: Topology, *, lr: float, name: str = "winput_async"):
        self.topology = topology
        self.lr = lr
        self.name = name

    def run(self, params0, loss_and_grad, *, duration_s: float = 5.0,
            skew: Optional[Sequence[float]] = None) -> DSGDReport:
        return run_async_dsgd(
            self.topology, params0, loss_and_grad, lr=self.lr,
            duration_s=duration_s, skew=skew, name=self.name,
        )
