"""Runtime layer: multi-host bring-up, launcher, native (C++) host engine.

Reference parity (upstream-relative): ``bluefog/run/`` (the ``bfrun``/
``ibfrun`` mpirun-wrapper CLI) and the C++ core of ``bluefog/common/``.
On TPU the *device* dataflow (collectives, negotiation ordering) is subsumed
by XLA async dispatch under SPMD (SURVEY.md §7 design stance); the pieces
that remain genuinely host-side are implemented natively in
``bluefog_tpu/csrc`` (C++17, ctypes-bound — see ``native.py``):

- async op engine (tensor queue + background thread + handle manager,
  parity: ``operations.cc``/``tensor_queue.cc``/``handle_manager.cc``) for
  checkpoint IO, DCN staging, and other host work overlapped with the step;
- chrome-trace timeline writer thread (parity: ``timeline.cc``);
- leveled logging (parity: ``logging.cc``).
"""

from bluefog_tpu.runtime.launch import initialize_cluster
from bluefog_tpu.runtime.native import Engine, PyEngine, engine

__all__ = ["initialize_cluster", "Engine", "PyEngine", "engine"]
