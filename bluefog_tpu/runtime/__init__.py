"""Runtime layer: multi-host bring-up, launcher, native (C++) host engine.

Reference parity (upstream-relative): ``bluefog/run/`` (the ``bfrun``/
``ibfrun`` mpirun-wrapper CLI) and the C++ core of ``bluefog/common/``.
On TPU the *device* dataflow (collectives, negotiation ordering) is subsumed
by XLA async dispatch under SPMD (SURVEY.md §7 design stance); the pieces
that remain genuinely host-side are implemented natively in
``bluefog_tpu/csrc`` (C++17, ctypes-bound — see ``native.py``):

- async op engine (tensor queue + background thread + handle manager,
  parity: ``operations.cc``/``tensor_queue.cc``/``handle_manager.cc``) for
  checkpoint IO, DCN staging, and other host work overlapped with the step;
- chrome-trace timeline writer thread (parity: ``timeline.cc``);
- leveled logging (parity: ``logging.cc``);
- passive-target window table (parity: ``mpi_win_ops.cc`` storage manager +
  ``mpi_controller.cc`` Win*) with three transports: in-process
  (``async_windows.AsyncWindow``), named shared memory (same-host
  processes, ``shm=True``), and the TCP window server (cross-host/DCN,
  ``window_server``).
"""

from bluefog_tpu.runtime.async_windows import (AsyncWindow, FileBarrier,
                                               TreePacker, run_async_dsgd,
                                               run_async_dsgd_rank,
                                               run_async_pushsum)
from bluefog_tpu.runtime.launch import initialize_cluster
from bluefog_tpu.runtime.native import Engine, PyEngine, engine
from bluefog_tpu.runtime.window_server import (DepositStream,
                                               PipelinedRemoteWindow,
                                               RemoteWindow, WindowServer)

__all__ = [
    "initialize_cluster", "Engine", "PyEngine", "engine",
    "AsyncWindow", "TreePacker", "FileBarrier",
    "run_async_pushsum", "run_async_dsgd", "run_async_dsgd_rank",
    "WindowServer", "RemoteWindow", "PipelinedRemoteWindow",
    "DepositStream",
]
