"""Runtime layer: multi-host bring-up, launcher, native (C++) components.

Reference parity (upstream-relative): ``bluefog/run/`` (the ``bfrun``/
``ibfrun`` mpirun-wrapper CLI) and the native engine pieces of
``bluefog/common/`` that remain host-side work on TPU (timeline writer,
cross-slice coordination).  Most of the reference's C++ engine — background
thread, tensor queue, negotiation — is subsumed by XLA async dispatch and
does not reappear here (SURVEY.md §7 design stance).
"""
