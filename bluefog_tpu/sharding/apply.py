"""Apply resolved specs: shard and gather leaves on device and on host.

Two symmetric halves of the ``make_shard_and_gather_fns`` pattern
(SNIPPETS.md [3]):

- **Device** (:func:`make_shard_and_gather_fns`): per-leaf callables
  that place a leaf onto the mesh under its resolved
  ``NamedSharding`` (shard) or pull it back replicated (gather) — the
  checkpoint-load / eval-consolidation boundary.  Gathering is the
  COLD path by design: the gossip hot path never calls these.
- **Host** (:func:`shard_tree` / :func:`gather_tree`): pure-numpy
  slicing twins for the window fabric — a :class:`ShardView`'s slice of
  every leaf, and its inverse (reassembling a full tree from all
  coordinates' shard trees), used by serving-snapshot reassembly and
  warm-start reads.

Plus the wire accounting shard-local gossip reports
(:func:`record_shard_savings`): ``bf_sharded_bytes_total{leaf,axis}``
(bytes actually moved) and ``bf_gather_bytes_saved_total`` (bytes a
gather-then-gossip wire would have moved minus what the shard-local
wire moved).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Mapping, Optional, Tuple

import numpy as np
from jax.sharding import PartitionSpec

from bluefog_tpu.metrics import comm as _mt
from bluefog_tpu.sharding.mesh import (ShardView, inner_coords, num_shards,
                                       shard_shape, shard_slices,
                                       shard_size_ratio)
from bluefog_tpu.sharding.rules import (RuleTable, named_leaves,
                                        named_tree_map, spec_entry_axes)

__all__ = [
    "make_shard_and_gather_fns",
    "shard_tree",
    "gather_tree",
    "reassemble_vectors",
    "tree_wire_bytes",
    "record_shard_savings",
]


def _is_spec(x) -> bool:
    return isinstance(x, PartitionSpec)


# ---------------------------------------------------------------------------
# Device side
# ---------------------------------------------------------------------------


def make_shard_and_gather_fns(specs, mesh):
    """``(shard_fns, gather_fns)`` pytrees of per-leaf callables.

    ``shard_fns[leaf](x)`` places ``x`` on ``mesh`` under the leaf's
    resolved spec (``jax.device_put`` with ``NamedSharding`` — XLA
    scatters each device its shard); ``gather_fns[leaf](x)`` returns the
    fully-replicated (host-usable) array.  ``mesh`` is a real
    ``jax.sharding.Mesh``; use the host-side twins for AbstractMesh /
    windows-path work."""
    import jax
    from jax.sharding import NamedSharding

    def mk_shard(spec):
        def shard(x):
            return jax.device_put(jax.numpy.asarray(x),
                                  NamedSharding(mesh, spec))

        return shard

    def mk_gather(spec):
        del spec

        def gather(x):
            return np.asarray(jax.device_get(x))

        return gather

    shard_fns = jax.tree_util.tree_map(mk_shard, specs, is_leaf=_is_spec)
    gather_fns = jax.tree_util.tree_map(mk_gather, specs, is_leaf=_is_spec)
    return shard_fns, gather_fns


# ---------------------------------------------------------------------------
# Host side (window fabric)
# ---------------------------------------------------------------------------


def shard_tree(tree, view: ShardView):
    """``view``'s shard of every leaf, as numpy arrays (host copy)."""
    import jax

    spec_flat = view.spec_leaves(tree)
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    out = []
    for leaf, spec in zip(leaves, spec_flat):
        a = np.asarray(jax.device_get(leaf))
        # np.array (not ascontiguousarray, which promotes 0-d to (1,))
        # keeps scalar leaves scalar-shaped for gather_tree's validation
        out.append(np.array(a[view.leaf_slices(a.shape, spec)]))
    return jax.tree_util.tree_unflatten(treedef, out)


def gather_tree(template, specs, axes: Mapping[str, int],
                shard_trees: Mapping[Any, Any]):
    """Inverse of :func:`shard_tree` over ALL coordinates: reassemble the
    full tree from per-coordinate shard trees.

    ``shard_trees`` maps a coordinate key — either the coord dict's
    items as a sorted tuple, or the positional tuple in ``axes`` key
    order — to that coordinate's shard tree (what each sub-mesh's
    :class:`~bluefog_tpu.runtime.async_windows.TreePacker` unpacked).
    Every coordinate must be present; shard shapes are validated against
    the template so a mis-keyed shard cannot land at the wrong offset."""
    import jax

    names = list(axes.keys())
    coords = inner_coords(axes)

    def key_of(coord: Dict[str, int]):
        pos = tuple(coord[n] for n in names)
        if pos in shard_trees:
            return pos
        srt = tuple(sorted(coord.items()))
        if srt in shard_trees:
            return srt
        raise KeyError(
            f"missing shard for coordinate {coord} (keys tried: {pos} "
            f"and {srt}; have {sorted(map(str, shard_trees.keys()))})")

    spec_flat = ShardView(specs=specs, axes=axes,
                          coord=coords[0]).spec_leaves(template)
    t_leaves, treedef = jax.tree_util.tree_flatten(template)
    shard_flat = {tuple(c[n] for n in names):
                  jax.tree_util.tree_leaves(shard_trees[key_of(c)])
                  for c in coords}
    for pos, leaves in shard_flat.items():
        if len(leaves) != len(t_leaves):
            raise ValueError(
                f"shard at {pos} has {len(leaves)} leaves, "
                f"template {len(t_leaves)}")
    out = []
    for i, (tleaf, spec) in enumerate(zip(t_leaves, spec_flat)):
        shape = tuple(int(s) for s in np.shape(tleaf))
        dtype = getattr(tleaf, "dtype", None) or np.asarray(tleaf).dtype
        full = np.empty(shape, dtype)
        loc = shard_shape(shape, spec, axes)
        for c in coords:
            pos = tuple(c[n] for n in names)
            piece = np.asarray(shard_flat[pos][i])
            if tuple(piece.shape) != loc:
                raise ValueError(
                    f"shard {pos} leaf {i} has shape {tuple(piece.shape)}, "
                    f"expected {loc} (spec {spec}, full {shape})")
            full[shard_slices(shape, spec, axes, c)] = piece
        out.append(full)
    return jax.tree_util.tree_unflatten(treedef, out)


def reassemble_vectors(template, specs, axes: Mapping[str, int],
                       vectors: Mapping[Any, np.ndarray], *,
                       dtype=np.float64):
    """Reassemble a full tree from per-coordinate PACKED flat vectors —
    the serving-snapshot / warm-start read path: each sub-mesh published
    its shard-local packed vector; this unpacks every one through a
    spec-aware :class:`TreePacker` and gathers."""
    from bluefog_tpu.runtime.async_windows import TreePacker

    names = list(axes.keys())
    shard_trees = {}
    for coord in inner_coords(axes):
        view = ShardView(specs=specs, axes=axes, coord=coord)
        packer = TreePacker(template, dtype, sharding=view)
        pos = tuple(coord[n] for n in names)
        key = pos if pos in vectors else tuple(sorted(coord.items()))
        shard_trees[pos] = packer.unpack(np.asarray(vectors[key]),
                                         as_jax=False)
    return gather_tree(template, specs, axes, shard_trees)


# ---------------------------------------------------------------------------
# Wire accounting
# ---------------------------------------------------------------------------


def tree_wire_bytes(tree, specs, axes: Mapping[str, int]
                    ) -> Tuple[int, int]:
    """``(shard_bytes, full_bytes)`` one deposit of ``tree`` moves under
    shard-local vs gather-then-gossip wiring."""
    import jax

    spec_flat = jax.tree_util.tree_leaves(specs, is_leaf=_is_spec)
    leaves = jax.tree_util.tree_leaves(tree)
    shard_b = full_b = 0
    for leaf, spec in zip(leaves, spec_flat):
        a_shape = tuple(int(s) for s in np.shape(leaf))
        item = np.dtype(getattr(leaf, "dtype", None)
                        or np.asarray(leaf).dtype).itemsize
        full = int(np.prod(a_shape, dtype=np.int64)) * item
        full_b += full
        shard_b += full // shard_size_ratio(spec, axes)
    return shard_b, full_b


def record_shard_savings(tree, specs, axes: Mapping[str, int], *,
                         deposits: int = 1) -> Tuple[int, int]:
    """Account one (or ``deposits``) shard-local deposits of ``tree`` on
    the wire-savings counters; returns ``(shard_bytes, saved_bytes)``
    per deposit.  Labels: ``leaf`` = the leaf's tree path, ``axis`` =
    the joined mentioned axes ('' for replicated leaves)."""
    import jax

    spec_flat = jax.tree_util.tree_leaves(specs, is_leaf=_is_spec)
    shard_total = saved_total = 0
    for (name, leaf), spec in zip(named_leaves(tree), spec_flat):
        a_shape = tuple(int(s) for s in np.shape(leaf))
        item = np.dtype(getattr(leaf, "dtype", None)
                        or np.asarray(leaf).dtype).itemsize
        full = int(np.prod(a_shape, dtype=np.int64)) * item
        shard = full // shard_size_ratio(spec, axes)
        axis = "+".join(ax for entry in tuple(spec)
                        for ax in spec_entry_axes(entry))
        _mt.inc("bf_sharded_bytes_total", float(shard * deposits),
                leaf=name, axis=axis)
        if full > shard:
            _mt.inc("bf_gather_bytes_saved_total",
                    float((full - shard) * deposits), leaf=name, axis=axis)
        shard_total += shard
        saved_total += full - shard
    return shard_total, saved_total
