"""The rule table: ONE ordered ``regex -> PartitionSpec`` mapping as the
single source of truth for how every leaf family is partitioned.

The pattern is the ``match_partition_rules`` idiom production JAX LLM
stacks converged on (SNIPPETS.md [3]): parameter leaves are named by their
``'/'``-joined tree path, an ordered list of ``(regex, PartitionSpec)``
rules is searched first-match-wins, and scalars are never partitioned.
What this module adds over the idiom is the *unification* the gossip
stack needs — the same table resolves

- **parameters** (:meth:`RuleTable.resolve_tree`),
- **optimizer state** (:func:`opt_state_specs` — ``m``/``v``-style moment
  leaves inherit the spec of the parameter they shadow, by tree-path
  suffix matching, so Adam state is never silently replicated while its
  parameter is sharded), and
- **gossip window buffers** (``ops.windows.win_create(rule_table=)`` and
  the spec-aware :class:`~bluefog_tpu.runtime.async_windows.TreePacker`),

so changing a single rule re-shards all three families consistently —
the acceptance invariant ``tests/test_sharding.py`` pins.

Resolution is LOUD by design: a non-scalar leaf matched by no rule
raises :class:`UnmatchedLeafError` (the silent-replication leak is the
failure mode — a 10 GB embedding quietly replicated over every chip),
and :meth:`RuleTable.coverage` reports both directions (unmatched leaves
AND dead rules) for the BF-SHD001 lint.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Any, Callable, List, Mapping, Optional, Sequence, Tuple

import numpy as np
from jax.sharding import PartitionSpec

__all__ = [
    "Rule",
    "RuleTable",
    "ShardingRuleError",
    "UnmatchedLeafError",
    "UnusedRuleError",
    "named_leaves",
    "named_tree_map",
    "norm_spec",
    "opt_state_specs",
    "spec_entry_axes",
    "spec_mentions",
]


class ShardingRuleError(ValueError):
    """Base class for rule-table resolution failures."""


class UnmatchedLeafError(ShardingRuleError):
    """A non-scalar leaf matched no rule — the silent-replication leak."""


class UnusedRuleError(ShardingRuleError):
    """A rule matched no leaf — a typo'd pattern shards nothing."""


def _is_spec(x) -> bool:
    return isinstance(x, PartitionSpec)


def _keystr(k) -> str:
    """One path component as a clean name (no brackets/quotes)."""
    for attr in ("key", "name", "idx"):
        v = getattr(k, attr, None)
        if v is not None:
            return str(v)
    return str(k)


def named_leaves(tree, *, sep: str = "/", is_leaf: Optional[Callable] = None
                 ) -> List[Tuple[str, Any]]:
    """``[(path_name, leaf)]`` with ``'/'``-joined component names —
    the naming contract every rule pattern is written against (flax
    param trees come out as e.g. ``block_0/up/kernel``)."""
    import jax

    flat, _ = jax.tree_util.tree_flatten_with_path(tree, is_leaf=is_leaf)
    return [(sep.join(_keystr(k) for k in path), leaf)
            for path, leaf in flat]


def named_tree_map(fn: Callable[[str, Any], Any], tree, *, sep: str = "/",
                   is_leaf: Optional[Callable] = None):
    """``tree_map`` where ``fn`` also receives the leaf's joined path."""
    import jax

    def wrap(path, leaf):
        return fn(sep.join(_keystr(k) for k in path), leaf)

    return jax.tree_util.tree_map_with_path(wrap, tree, is_leaf=is_leaf)


def _leaf_shape(leaf) -> Tuple[int, ...]:
    shape = getattr(leaf, "shape", None)
    if shape is None:
        shape = np.shape(leaf)
    return tuple(int(s) for s in shape)


def _is_scalar(shape: Tuple[int, ...]) -> bool:
    return len(shape) == 0 or int(np.prod(shape, dtype=np.int64)) == 1


@dataclass(frozen=True)
class Rule:
    """One table entry: ``re.search(pattern, leaf_path)`` -> ``spec``."""

    pattern: str
    spec: PartitionSpec

    def __post_init__(self):
        re.compile(self.pattern)  # fail at construction, not resolution
        if isinstance(self.spec, str):
            # a bare axis name means "shard dim 0 over it" — splatting
            # the string would silently make per-CHARACTER axes
            # (P('t','p') from "tp"), which then replicate on the wire
            object.__setattr__(self, "spec", PartitionSpec(self.spec))
        elif not isinstance(self.spec, PartitionSpec):
            object.__setattr__(self, "spec", PartitionSpec(*self.spec))

    def matches(self, name: str) -> bool:
        return re.search(self.pattern, name) is not None


class RuleTable:
    """Ordered first-match-wins ``regex -> PartitionSpec`` resolution.

    Args:
      rules: ``Rule`` instances or ``(pattern, spec)`` pairs, most
        specific first — resolution takes the FIRST match.
      axes: optional ``{axis_name: size}`` of the inner (within-rank)
        mesh; when given, every rule's spec is validated to mention only
        these axes at construction, and :meth:`shard_shape` /
        :meth:`shard_slices` become available.
    """

    def __init__(self, rules: Sequence, *,
                 axes: Optional[Mapping[str, int]] = None):
        self.rules: Tuple[Rule, ...] = tuple(
            r if isinstance(r, Rule) else Rule(r[0], r[1]) for r in rules)
        self.axes = dict(axes) if axes is not None else None
        if self.axes is not None:
            for r in self.rules:
                for entry in r.spec:
                    for ax in spec_entry_axes(entry):
                        if ax not in self.axes:
                            raise ShardingRuleError(
                                f"rule {r.pattern!r} mentions axis "
                                f"{ax!r}, not one of {sorted(self.axes)}")

    # ------------------------------------------------------------ resolve
    def resolve(self, name: str, shape: Sequence[int] = ()) -> PartitionSpec:
        """Spec for one named leaf.  Scalars (and size-1 leaves) are never
        partitioned; a non-scalar leaf matching no rule raises
        :class:`UnmatchedLeafError` (first-match-wins otherwise)."""
        shape = tuple(int(s) for s in shape)
        if _is_scalar(shape):
            return PartitionSpec()
        for r in self.rules:
            if r.matches(name):
                if len(r.spec) > len(shape):
                    raise ShardingRuleError(
                        f"rule {r.pattern!r} spec {r.spec} has more entries "
                        f"than leaf {name!r} has dims {shape}")
                return r.spec
        raise UnmatchedLeafError(
            f"no partition rule matches leaf {name!r} (shape {shape}) — "
            "add a rule (or an explicit replicate-rule, e.g. "
            r"Rule('.*', PartitionSpec())) so replication is a decision, "
            "not a leak")

    def resolve_tree(self, tree, *, is_leaf: Optional[Callable] = None):
        """Pytree of :class:`PartitionSpec` matching ``tree``'s structure."""
        return named_tree_map(
            lambda name, leaf: self.resolve(name, _leaf_shape(leaf)),
            tree, is_leaf=is_leaf)

    # ----------------------------------------------------------- coverage
    def coverage(self, tree, *, is_leaf: Optional[Callable] = None
                 ) -> Tuple[List[str], List[str]]:
        """``(unmatched_leaf_names, unused_rule_patterns)`` over ``tree``
        — both directions of the BF-SHD001 contract.  Scalar leaves are
        exempt from matching (they resolve replicated without consuming
        a rule), but they CAN satisfy a rule's liveness."""
        unmatched: List[str] = []
        used = [False] * len(self.rules)
        for name, leaf in named_leaves(tree, is_leaf=is_leaf):
            hit = None
            for i, r in enumerate(self.rules):
                if r.matches(name):
                    hit = i
                    break
            if hit is not None:
                used[hit] = True
            elif not _is_scalar(_leaf_shape(leaf)):
                unmatched.append(name)
        unused = [r.pattern for r, u in zip(self.rules, used) if not u]
        return unmatched, unused

    def check(self, tree, *, is_leaf: Optional[Callable] = None) -> None:
        """Raise unless the table and ``tree`` cover each other exactly."""
        unmatched, unused = self.coverage(tree, is_leaf=is_leaf)
        if unmatched:
            raise UnmatchedLeafError(
                f"leaves matched by no rule: {unmatched}")
        if unused:
            raise UnusedRuleError(
                f"rules matching no leaf: {unused}")

    def __len__(self) -> int:
        return len(self.rules)

    def __repr__(self) -> str:
        return (f"RuleTable({len(self.rules)} rules"
                + (f", axes={self.axes}" if self.axes is not None else "")
                + ")")

    def replaced(self, pattern: str, spec) -> "RuleTable":
        """A new table with the rule whose pattern equals ``pattern``
        swapped for ``spec`` (order preserved) — the one-rule-change
        surface the re-sharding acceptance test drives."""
        if pattern not in [r.pattern for r in self.rules]:
            raise KeyError(f"no rule with pattern {pattern!r}")
        return RuleTable(
            [Rule(r.pattern, spec if r.pattern == pattern else r.spec)
             for r in self.rules],
            axes=self.axes)


def spec_entry_axes(entry) -> Tuple[str, ...]:
    """Axis names of one PartitionSpec entry (None | str | tuple) — THE
    entry-semantics helper; every consumer (mesh arithmetic, lints,
    gradient correction) goes through here so a change to entry shapes
    lands once."""
    if entry is None:
        return ()
    if isinstance(entry, str):
        return (entry,)
    return tuple(entry)


def norm_spec(spec) -> Tuple[Tuple[str, ...], ...]:
    """Canonical comparable form of a spec: per-dim axis tuples with
    trailing replicated entries trimmed (``P()``, ``P(None)``, and an
    absent entry all mean the same thing) — the equality the
    BF-SHD002 lint and :func:`parallel.tensor.check_rule_agreement`
    compare under."""
    out = [spec_entry_axes(e) for e in tuple(spec)]
    while out and out[-1] == ():
        out.pop()
    return tuple(out)


def spec_mentions(spec, axis: str) -> bool:
    """Whether ``spec`` shards any dim over ``axis``."""
    return any(axis in spec_entry_axes(e) for e in tuple(spec))


# ---------------------------------------------------------------------------
# Optimizer-state derivation: moment leaves inherit the param's spec
# ---------------------------------------------------------------------------


def opt_state_specs(table: RuleTable, params, opt_state, *,
                    is_leaf: Optional[Callable] = None):
    """Spec tree for ``opt_state`` derived from the SAME rule table that
    shards ``params`` — the state-tree rule derivation.

    Optimizer states (optax's ``ScaleByAdamState.mu/nu``, the repo's
    ``_DecentralizedState.base_state``, gradient-tracking trackers) embed
    one or more copies of the parameter tree under wrapper path prefixes
    like ``0/mu``.  For each opt-state leaf:

    - scalar leaves (step counters, cadence gates) -> replicated;
    - otherwise the LONGEST parameter path that is a ``/``-component
      suffix of the leaf's path, with a matching shape, donates its
      resolved spec (so ``m``/``v`` inherit exactly the param's
      partitioning — changing the param's rule re-shards its moments);
    - a leaf shadowing no parameter falls back to direct table
      resolution (it is a first-class leaf with its own rule), which
      raises :class:`UnmatchedLeafError` when nothing matches.
    """
    param_index: List[Tuple[Tuple[str, ...], Tuple[int, ...],
                            PartitionSpec]] = []
    for name, leaf in named_leaves(params, is_leaf=is_leaf):
        shape = _leaf_shape(leaf)
        param_index.append(
            (tuple(name.split("/")), shape, table.resolve(name, shape)))
    # longest-suffix-first: sort by path length descending once
    param_index.sort(key=lambda t: len(t[0]), reverse=True)

    def derive(name: str, leaf) -> PartitionSpec:
        shape = _leaf_shape(leaf)
        if _is_scalar(shape):
            return PartitionSpec()
        comps = tuple(name.split("/"))
        for ppath, pshape, pspec in param_index:
            if (len(comps) >= len(ppath) and comps[-len(ppath):] == ppath
                    and shape == pshape):
                return pspec
        return table.resolve(name, shape)

    return named_tree_map(derive, opt_state, is_leaf=is_leaf)
