"""Gossip-of-meshes over the window fabric: shard-local neighbor gossip.

Each gossip rank is a whole inner mesh (``fsdp``/``tp`` shards); the
gossip graph connects *meshes*, not chips.  The wire model this module
implements — and the equivalence tests pin — is:

- every inner-mesh coordinate owns its OWN window per rank
  (``{name}:{rank}:{shard}``), sized to the SHARD-local packed vector
  (plus the push-sum mass scalar);
- coordinate ``c`` of rank ``r`` deposits only to coordinate ``c`` of
  its out-neighbors — same-shard-to-same-shard, **no gather anywhere on
  the hot path** (the full tree is reassembled only at the read/serving
  boundary, via :func:`bluefog_tpu.sharding.apply.gather_tree`);
- push-sum mass is carried per shard, so the exactly-once mass audit
  holds per coordinate: ``sum_r p[r, c] == n`` under any interleaving,
  and stays exact through a :func:`~bluefog_tpu.topology.heal`.

Because gossip is element-wise, the shard-local run is numerically
IDENTICAL (same floating-point operations in the same order per
element) to the gathered single-chip reference — ``run_sharded_gossip``
with ``axes={}`` *is* that reference, which is how
``tests/test_sharding.py`` asserts 1e-12 equivalence for ring and
exponential topologies.

:func:`run_sharded_gossip` executes deterministic synchronous rounds
(every rank deposits, then every rank consumes) so the equivalence
claim is testable bit-for-bit; the genuinely asynchronous execution
model with rank-dependent rates lives in
:func:`bluefog_tpu.runtime.async_windows.run_async_dsgd`, whose
spec-aware :class:`~bluefog_tpu.runtime.async_windows.TreePacker` uses
the same :class:`~bluefog_tpu.sharding.mesh.ShardView` plans.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from bluefog_tpu.blackbox import recorder as _bb
from bluefog_tpu.metrics import comm as _mt
from bluefog_tpu.runtime.async_windows import AsyncWindow, TreePacker
from bluefog_tpu.sharding.apply import (gather_tree, record_shard_savings,
                                        tree_wire_bytes)
from bluefog_tpu.sharding.mesh import ShardView, inner_coords
from bluefog_tpu.sharding.rules import RuleTable
from bluefog_tpu.topology.graphs import Topology, heal as _heal

__all__ = ["ShardedGossipReport", "run_sharded_gossip"]


@dataclass
class ShardedGossipReport:
    """Outcome of a shard-local gossip run."""

    rounds: int
    # per-rank de-biased estimates, REASSEMBLED to full trees (None for
    # ranks healed out) — the only gather in the run, at the read
    # boundary
    params: List[Any]
    # per-coordinate mass sums over every window + unconsumed slot
    # (exact audit: each entry == topology.size, deaths included —
    # a healed-out rank keeps the mass it held, it is never duplicated
    # or lost)
    total_mass: Dict[Tuple[int, ...], float]
    # wire accounting per deposit: shard-local bytes actually moved and
    # bytes a gather-then-gossip wire would have added
    shard_bytes_per_deposit: int
    saved_bytes_per_deposit: int
    deposits: int = 0
    dead_ranks: List[int] = field(default_factory=list)


def run_sharded_gossip(
    topology: Topology,
    params0: Sequence[Any],
    rule_table,
    axes: Mapping[str, int],
    *,
    rounds: int = 10,
    name: str = "shard_gossip",
    heal_after: Optional[int] = None,
    dead_ranks: Sequence[int] = (),
    dtype=np.float64,
) -> ShardedGossipReport:
    """Run ``rounds`` of shard-local push-sum gossip over ``topology``.

    Args:
      topology: the gossip graph over RANKS (each a whole inner mesh).
      params0: one pytree per rank (``len == topology.size``), all with
        the template structure/shapes of ``params0[0]``.
      rule_table: the :class:`~bluefog_tpu.sharding.rules.RuleTable`
        resolving every leaf's spec (the single source of truth), or an
        already-resolved spec pytree.
      axes: inner-mesh ``{axis: size}``.  ``{}`` = one shard per rank =
        the gathered single-chip reference.
      heal_after / dead_ranks: after round ``heal_after`` the ranks in
        ``dead_ranks`` stop participating and survivors re-plan through
        :func:`bluefog_tpu.topology.heal` — the per-coordinate mass
        audit must stay exact through the change.
    """
    n = topology.size
    if len(params0) != n:
        raise ValueError(f"{len(params0)} param trees != topology size {n}")
    template = params0[0]
    if isinstance(rule_table, RuleTable):
        specs = rule_table.resolve_tree(template)
    else:
        specs = rule_table
    coords = inner_coords(axes)
    views = [ShardView(specs=specs, axes=axes, coord=c) for c in coords]
    packers = [TreePacker(template, dtype, sharding=v) for v in views]
    d = packers[0].size
    dead = set(int(r) for r in dead_ranks)
    if heal_after is None and dead:
        raise ValueError("dead_ranks without heal_after")

    in_nbrs = [list(topology.in_neighbors(r)) for r in range(n)]
    out_nbrs = [list(topology.out_neighbors(r)) for r in range(n)]
    slot_of = [{src: k for k, src in enumerate(in_nbrs[r])} for r in range(n)]

    # one window per (rank, coordinate): the shard-local landing zone.
    # The ``name:r:ci`` naming is also the DCN STRIPE UNIT — over the
    # striped transport, :func:`~bluefog_tpu.runtime.window_server.
    # stripe_of` spreads a rank's per-coordinate windows deterministically
    # across a StripedDepositStream's parallel connections, so one owner's
    # coordinates ride N senders/appliers instead of serializing on one
    wins: List[List[AsyncWindow]] = []
    try:
        for r in range(n):
            row = []
            wins.append(row)
            for ci in range(len(coords)):
                row.append(AsyncWindow(f"{name}:{r}:{ci}",
                                       max(len(in_nbrs[r]), 1), d + 1,
                                       np.float64))
    except BaseException:
        for row in wins:
            for w in row:
                w.free()
        raise

    try:
        x = [[packers[ci].pack(params0[r]).astype(np.float64)
              for ci in range(len(coords))] for r in range(n)]
        p = [[1.0] * len(coords) for _ in range(n)]
        live = list(range(n))
        my_out = [list(out_nbrs[r]) for r in range(n)]
        deposits = 0

        for k in range(rounds):
            if heal_after is not None and k == heal_after and dead:
                healed = _heal(topology, frozenset(dead))
                live = [r for r in range(n) if r not in dead]
                my_out = [list(healed.out_neighbors(r)) for r in range(n)]
                _bb.record("sharded_gossip_heal", round=k,
                           dead=sorted(dead))
            # deposit phase: same-shard to same-shard, shard-sized wire
            for r in live:
                frac = 1.0 / (len(my_out[r]) + 1)
                for ci in range(len(coords)):
                    payload = np.concatenate(
                        [x[r][ci] * frac, [p[r][ci] * frac]])
                    for j in my_out[r]:
                        wins[j][ci].deposit(slot_of[j][r], payload,
                                            accumulate=True)
                        deposits += 1
                    x[r][ci] *= frac
                    p[r][ci] *= frac
            # consume phase: fold whatever landed
            for r in live:
                for ci in range(len(coords)):
                    for s in range(len(in_nbrs[r])):
                        buf, fresh = wins[r][ci].read(s, consume=True)
                        if fresh > 0:
                            x[r][ci] += buf[:-1]
                            p[r][ci] += buf[-1]
                    # publish (x, p) so same-coordinate warm-start /
                    # serving readers see a round-consistent pair
                    wins[r][ci].set_self(
                        np.concatenate([x[r][ci], [p[r][ci]]]))

        # ------------------------------------------------- mass audit
        # every coordinate's mass ledger: held by live + dead ranks,
        # plus anything never consumed (a dead rank's landing slots)
        total_mass: Dict[Tuple[int, ...], float] = {}
        names = list(axes.keys())
        for ci, c in enumerate(coords):
            tot = 0.0
            for r in range(n):
                tot += p[r][ci]
                for s in range(len(in_nbrs[r])):
                    if r in dead:
                        buf, fresh = wins[r][ci].read(s, consume=False)
                        if fresh > 0:
                            tot += float(buf[-1])
            total_mass[tuple(c[nm] for nm in names)] = tot

        # ------------------------------------- read boundary (gather)
        params: List[Any] = [None] * n
        for r in range(n):
            if r in dead:
                continue
            shard_trees = {}
            for ci, c in enumerate(coords):
                z = x[r][ci] / p[r][ci]
                shard_trees[tuple(c[nm] for nm in names)] = (
                    packers[ci].unpack(z, as_jax=False))
            params[r] = gather_tree(template, specs, axes, shard_trees)

        shard_b, full_b = tree_wire_bytes(template, specs, axes)
        if deposits:
            record_shard_savings(template, specs, axes, deposits=deposits)
        return ShardedGossipReport(
            rounds=rounds,
            params=params,
            total_mass=total_mass,
            shard_bytes_per_deposit=shard_b,
            saved_bytes_per_deposit=full_b - shard_b,
            deposits=deposits,
            dead_ranks=sorted(dead),
        )
    finally:
        for row in wins:
            for w in row:
                w.free()
