"""Gossip-of-meshes geometry: each gossip rank is a whole pjit mesh.

The hybrid mesh is ``('bf', <inner axes...>)`` — the outer ``bf`` axis
carries the decentralized data-parallel gossip (``neighbor_allreduce`` /
window deposits between ranks), the inner axes (``fsdp``/``tp``/``pp``)
shard each rank's model *within* its mesh.  This module owns the
geometry both execution paths share:

- **device side** (:class:`GossipMesh`): build the ``jax.sharding.Mesh``
  (ICI snake order via ``parallel.make_hybrid_mesh``) or its
  ``AbstractMesh`` twin for tracing/tests off-TPU;
- **host side** (:func:`shard_shape` / :func:`shard_slices` /
  :class:`ShardView`): pure-numpy slice arithmetic for a leaf's shard
  under a :class:`~bluefog_tpu.sharding.rules.RuleTable` spec — what the
  spec-aware :class:`~bluefog_tpu.runtime.async_windows.TreePacker` and
  the shard-local window gossip use.  The wire model follows: a window
  deposit moves ``shard_bytes``, never ``full_bytes``, and the two
  differ by exactly ``prod(sizes of mentioned axes)``.

Host-side coordinates are dicts ``{axis_name: index}``; a leaf dim whose
spec entry names several axes (``('fsdp', 'tp')``) is split row-major in
the listed order, matching XLA's NamedSharding convention.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np
from jax.sharding import PartitionSpec

from bluefog_tpu.sharding.rules import spec_entry_axes

__all__ = [
    "GossipMesh",
    "ShardView",
    "num_shards",
    "inner_coords",
    "shard_shape",
    "shard_slices",
    "shard_size_ratio",
]


def _spec_entries(spec: PartitionSpec, ndim: int) -> List[Tuple[str, ...]]:
    """Per-dim axis tuples, padded with replicated entries to ``ndim``."""
    entries = [spec_entry_axes(e) for e in tuple(spec)]
    if len(entries) > ndim:
        raise ValueError(
            f"spec {spec} has {len(entries)} entries for a {ndim}-d leaf")
    entries += [()] * (ndim - len(entries))
    return entries


def num_shards(axes: Mapping[str, int]) -> int:
    """Total inner-mesh size: how many shards one rank's mesh holds."""
    n = 1
    for s in axes.values():
        n *= int(s)
    return n


def inner_coords(axes: Mapping[str, int]) -> List[Dict[str, int]]:
    """Every inner-mesh coordinate, row-major in ``axes``'s key order —
    the iteration order shard ids use everywhere (window names, serving
    reassembly)."""
    names = list(axes.keys())
    return [dict(zip(names, idx))
            for idx in itertools.product(*(range(int(axes[n]))
                                           for n in names))]


def shard_shape(shape: Sequence[int], spec: PartitionSpec,
                axes: Mapping[str, int]) -> Tuple[int, ...]:
    """Shape of one shard of a ``shape``-d leaf under ``spec``.

    Every mentioned axis must divide its dim evenly — ragged shards are
    refused loudly (XLA pads them; the host wire must not)."""
    shape = tuple(int(s) for s in shape)
    out = []
    for dim, entry in zip(shape, _spec_entries(spec, len(shape))):
        div = 1
        for ax in entry:
            # an axis the mesh does not have = one shard along it: this
            # is what makes ``axes={}`` the gathered single-chip
            # reference of any spec tree.  Typo'd axis names are caught
            # loudly where specs are authored (RuleTable(axes=)) and by
            # the BF-SHD lint, not here.
            div *= int(axes.get(ax, 1))
        if dim % div:
            raise ValueError(
                f"dim {dim} not divisible by axes {entry} (= {div}) "
                f"in spec {spec} for shape {shape}")
        out.append(dim // div)
    return tuple(out)


def shard_slices(shape: Sequence[int], spec: PartitionSpec,
                 axes: Mapping[str, int], coord: Mapping[str, int]
                 ) -> Tuple[slice, ...]:
    """Index slices selecting coordinate ``coord``'s shard of a leaf."""
    shape = tuple(int(s) for s in shape)
    local = shard_shape(shape, spec, axes)
    out = []
    for dim, loc, entry in zip(shape, local, _spec_entries(spec, len(shape))):
        idx = 0
        for ax in entry:  # row-major over the listed axes
            if ax not in axes:
                continue  # absent axis = one shard (see shard_shape)
            idx = idx * int(axes[ax]) + int(coord[ax])
        start = idx * loc
        out.append(slice(start, start + loc))
    return tuple(out)


def shard_size_ratio(spec: PartitionSpec, axes: Mapping[str, int]) -> int:
    """``full_size / shard_size`` for a leaf under ``spec`` — the wire
    savings factor of shard-local gossip over gather-then-gossip."""
    r = 1
    for entry in (tuple(spec) or ()):
        for ax in spec_entry_axes(entry):
            r *= int(axes.get(ax, 1))
    return r


@dataclass(frozen=True)
class ShardView:
    """One inner-mesh coordinate's view of a spec'd tree — the plan the
    spec-aware :class:`~bluefog_tpu.runtime.async_windows.TreePacker`
    packs through.

    Attributes:
      specs: pytree of :class:`PartitionSpec` matching the template
        (from :meth:`RuleTable.resolve_tree` — the single source of
        truth).
      axes: ``{inner_axis: size}``.
      coord: ``{inner_axis: index}`` — which shard this view is.
    """

    specs: Any
    axes: Mapping[str, int] = field(default_factory=dict)
    coord: Mapping[str, int] = field(default_factory=dict)

    def __post_init__(self):
        missing = set(self.axes) - set(self.coord)
        if missing:
            raise ValueError(f"coord missing axes {sorted(missing)}")
        for ax, i in self.coord.items():
            if not 0 <= int(i) < int(self.axes[ax]):
                raise ValueError(
                    f"coord {ax}={i} out of range [0, {self.axes[ax]})")

    def spec_leaves(self, template) -> List[PartitionSpec]:
        """Flattened specs aligned with ``template``'s leaf order."""
        import jax

        spec_flat = jax.tree_util.tree_leaves(
            self.specs, is_leaf=lambda x: isinstance(x, PartitionSpec))
        n = len(jax.tree_util.tree_leaves(template))
        if len(spec_flat) != n:
            raise ValueError(
                f"spec tree has {len(spec_flat)} leaves, template {n}")
        return spec_flat

    def leaf_shape(self, shape: Sequence[int], spec: PartitionSpec
                   ) -> Tuple[int, ...]:
        return shard_shape(shape, spec, self.axes)

    def leaf_slices(self, shape: Sequence[int], spec: PartitionSpec
                    ) -> Tuple[slice, ...]:
        return shard_slices(shape, spec, self.axes, self.coord)


class GossipMesh:
    """The hybrid ``(bf, inner...)`` mesh, as one object both sides use.

    ``bf`` ranks gossip over the outer axis; each rank's model is
    sharded over the inner axes.  :meth:`jax_mesh` builds the real
    device mesh (gossip axis outermost so inner collectives land on
    nearest-neighbor ICI); :meth:`abstract` the tracing twin;
    :meth:`views` the per-coordinate host plans for a resolved spec
    tree."""

    def __init__(self, bf: int, inner: Mapping[str, int], *,
                 bf_axis: str = "bf"):
        if bf < 1:
            raise ValueError(f"bf size must be >= 1, got {bf}")
        if bf_axis in inner:
            raise ValueError(f"inner axes shadow the gossip axis {bf_axis!r}")
        self.bf = int(bf)
        self.bf_axis = bf_axis
        self.inner: Dict[str, int] = {k: int(v) for k, v in inner.items()}

    @property
    def inner_size(self) -> int:
        return num_shards(self.inner)

    @property
    def axis_sizes(self) -> Dict[str, int]:
        return {self.bf_axis: self.bf, **self.inner}

    def coords(self) -> List[Dict[str, int]]:
        return inner_coords(self.inner)

    def jax_mesh(self, devices=None, *, use_ici_order: bool = True):
        from bluefog_tpu.parallel.tensor import make_hybrid_mesh

        return make_hybrid_mesh(self.axis_sizes, devices=devices,
                                use_ici_order=use_ici_order)

    def abstract(self):
        from bluefog_tpu.parallel.api import abstract_mesh

        sizes = self.axis_sizes
        return abstract_mesh(tuple(sizes.values()), tuple(sizes.keys()))

    def views(self, specs) -> List[ShardView]:
        return [ShardView(specs=specs, axes=self.inner, coord=c)
                for c in self.coords()]

    def __repr__(self) -> str:
        return (f"GossipMesh({self.bf_axis}={self.bf}, "
                + ", ".join(f"{k}={v}" for k, v in self.inner.items()) + ")")
