"""Unified rule-driven sharding: one table governs every leaf family.

The gossip-of-meshes subsystem (ROADMAP item 2): a regex-rule ->
``PartitionSpec`` resolution engine where ONE ordered rule table
(:class:`RuleTable`) is the single source of truth for how

- model **parameters**,
- **optimizer state** (moment leaves inherit their parameter's spec —
  :func:`opt_state_specs`), and
- **gossip window buffers** (``ops.windows.win_create(rule_table=)``,
  the spec-aware ``runtime.async_windows.TreePacker``)

are partitioned over a hybrid ``(bf, fsdp/tp)`` mesh
(:class:`GossipMesh`).  On top of it, the gossip graph connects
*meshes*, not chips: ``neighbor_allreduce`` and the async window
deposit/read path operate on sharded leaves shard-local — each inner
coordinate exchanges only its own shard with the same coordinate on
neighbor meshes, with no gather on the hot path
(:func:`run_sharded_gossip`; asserted by the BF-SHD lint pass).

See ``docs/sharding.md`` for the rule grammar, resolution order, and
the wire model.
"""

from bluefog_tpu.sharding.apply import (gather_tree,
                                        make_shard_and_gather_fns,
                                        reassemble_vectors,
                                        record_shard_savings, shard_tree,
                                        tree_wire_bytes)
from bluefog_tpu.sharding.gossip import (ShardedGossipReport,
                                         run_sharded_gossip)
from bluefog_tpu.sharding.mesh import (GossipMesh, ShardView, inner_coords,
                                       num_shards, shard_shape, shard_slices,
                                       shard_size_ratio)
from bluefog_tpu.sharding.rules import (Rule, RuleTable, ShardingRuleError,
                                        UnmatchedLeafError, UnusedRuleError,
                                        named_leaves, named_tree_map,
                                        norm_spec, opt_state_specs,
                                        spec_entry_axes, spec_mentions)

__all__ = [
    "Rule",
    "RuleTable",
    "ShardingRuleError",
    "UnmatchedLeafError",
    "UnusedRuleError",
    "named_leaves",
    "named_tree_map",
    "norm_spec",
    "opt_state_specs",
    "spec_entry_axes",
    "spec_mentions",
    "GossipMesh",
    "ShardView",
    "inner_coords",
    "num_shards",
    "shard_shape",
    "shard_slices",
    "shard_size_ratio",
    "make_shard_and_gather_fns",
    "shard_tree",
    "gather_tree",
    "reassemble_vectors",
    "record_shard_savings",
    "tree_wire_bytes",
    "ShardedGossipReport",
    "run_sharded_gossip",
]
