"""Continuous profiling — the seventh observability leg.

An always-on-capable, off-by-default sampling profiler: a dedicated
daemon thread walks ``sys._current_frames()`` at a configurable rate,
tags every sample with the sampled thread's current tracing phase
(compute / gossip / publish / net-wait, read lock-free from the
tracing plane's cross-thread span map), and appends folded-stack
windows to per-rank JSONL.  ``bfprof-tpu`` merges ranks, renders
flamegraphs, joins against ``bftrace-tpu`` critical paths, and gates
A/B differential profiles with an exit code.

Arming follows the tracing plane's env-lazy pattern: set
``BLUEFOG_TPU_PROFILE=<dir>`` (and optionally
``BLUEFOG_TPU_PROFILE_HZ``) or call :func:`configure` explicitly.
When disarmed there is no sampler thread, no import-time side effect,
and zero change to compiled programs.
"""

from bluefog_tpu.profiling.sampler import (
    PHASES,
    Profiler,
    configure,
    enabled,
    flush,
    get,
    phase_for_span,
    reset,
    set_rank,
)
from bluefog_tpu.profiling.report import (
    diff,
    load_profiles,
    merge,
    phase_frames,
    render_folded,
    render_svg,
    top_table,
)

__all__ = [
    "PHASES",
    "Profiler",
    "configure",
    "diff",
    "enabled",
    "flush",
    "get",
    "load_profiles",
    "merge",
    "phase_for_span",
    "phase_frames",
    "render_folded",
    "render_svg",
    "reset",
    "set_rank",
    "top_table",
]
