"""Merge / report / diff over per-rank profile JSONL.

The file format (one JSON object per line, torn tails tolerated — the
blackbox-merge discipline):

- ``{"kind": "meta", "rank": k, "pid": p, "hz": h, "t0": t}`` — once
  per file;
- ``{"kind": "window", "t0": a, "t1": b, "rank": k, "hz": h,
  "samples": n, "phases": {phase: n}, "stacks": [[phase, folded, n],
  ...]}`` — one per flush window, ``folded`` being a
  ``frame;frame;frame`` stack string (flamegraph.pl's folded format).

:func:`merge` folds every window across every rank into ONE report
dict; :func:`diff` compares two such reports and names hot-frame
regressions — the machine-checkable A/B gate ``bfprof-tpu --diff``
exits on.
"""

from __future__ import annotations

import glob
import hashlib
import json
import os
from typing import Dict, List, Optional, Tuple

__all__ = ["load_profiles", "merge", "diff", "top_table",
           "render_folded", "render_svg", "phase_frames"]

#: a frame must hold at least this share of base samples to be eligible
#: as a regression subject (noise floor)
DIFF_MIN_FRAC = 0.01
#: a frame absent from base counts as a regression when it holds at
#: least this share of head samples (a NEW hot frame)
DIFF_NEW_HOT_FRAC = 0.05


def load_profiles(directory: str) -> List[dict]:
    """Every parseable record under ``directory`` (recursive).  Torn
    tails (a crashed writer's final partial line) are skipped, not
    fatal."""
    recs: List[dict] = []
    paths = sorted(
        glob.glob(os.path.join(directory, "**", "profile-rank*.jsonl"),
                  recursive=True)
        + glob.glob(os.path.join(directory, "**", "profile-pid*.jsonl"),
                    recursive=True))
    for path in paths:
        try:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue  # torn tail
                    if isinstance(rec, dict) and rec.get("kind") in (
                            "meta", "window"):
                        recs.append(rec)
        except OSError:
            continue
    return recs


def merge(directory: str, *, records: Optional[List[dict]] = None
          ) -> dict:
    """One report over every rank's windows: total samples, per-phase
    split + attribution fraction, per-frame self/total sample counts,
    and the merged folded stacks (with phase kept as the fold's root
    frame, so flamegraphs group by phase)."""
    if records is None:
        records = load_profiles(directory)
    ranks = sorted({r.get("rank") for r in records
                    if r.get("rank") is not None})
    hz = next((float(r["hz"]) for r in records if r.get("hz")), None)
    samples = 0
    phases: Dict[str, int] = {}
    stacks: Dict[Tuple[str, str], int] = {}
    self_counts: Dict[str, int] = {}
    total_counts: Dict[str, int] = {}
    t0 = t1 = None
    for rec in records:
        if rec.get("kind") != "window":
            continue
        if rec.get("t0") is not None:
            t0 = rec["t0"] if t0 is None else min(t0, rec["t0"])
        if rec.get("t1") is not None:
            t1 = rec["t1"] if t1 is None else max(t1, rec["t1"])
        for ph, n in (rec.get("phases") or {}).items():
            phases[ph] = phases.get(ph, 0) + int(n)
        for entry in rec.get("stacks") or ():
            try:
                ph, folded, n = entry
                n = int(n)
            except (TypeError, ValueError):
                continue
            samples += n
            stacks[(ph, folded)] = stacks.get((ph, folded), 0) + n
            frames = folded.split(";")
            if frames:
                leaf = frames[-1]
                self_counts[leaf] = self_counts.get(leaf, 0) + n
            for fr in set(frames):
                total_counts[fr] = total_counts.get(fr, 0) + n
    attributed = sum(n for ph, n in phases.items() if ph != "other")
    phase_total = sum(phases.values()) or 1
    report = {
        "kind": "bfprof_report",
        "ranks": ranks,
        "hz": hz,
        "samples": samples,
        "wall_s": (round(t1 - t0, 3)
                   if t0 is not None and t1 is not None else None),
        "phases": dict(sorted(phases.items())),
        "phase_frac": {ph: round(n / phase_total, 4)
                       for ph, n in sorted(phases.items())},
        "attributed_frac": round(attributed / phase_total, 4),
        "frames": {fr: {"self": self_counts.get(fr, 0),
                        "total": total_counts.get(fr, 0)}
                   for fr in sorted(set(self_counts) | set(total_counts))},
        "stacks": [[ph, folded, n]
                   for (ph, folded), n in sorted(stacks.items())],
    }
    return report


def top_table(report: dict, n: int = 15, *, by: str = "self"
              ) -> List[Tuple[str, int, float]]:
    """Top-N ``(frame, samples, fraction)`` by self or total samples."""
    total = report.get("samples") or 1
    rows = sorted(report.get("frames", {}).items(),
                  key=lambda kv: (-kv[1].get(by, 0), kv[0]))
    return [(fr, int(c.get(by, 0)), round(c.get(by, 0) / total, 4))
            for fr, c in rows[:n] if c.get(by, 0) > 0]


def phase_frames(report: dict, phase: str, n: int = 10
                 ) -> List[Tuple[str, int]]:
    """Top leaf frames whose samples attributed to ``phase`` — the
    trace-join answer ("the gating edge's wall-clock maps to these
    frames")."""
    counts: Dict[str, int] = {}
    for entry in report.get("stacks") or ():
        ph, folded, cnt = entry
        if ph != phase:
            continue
        leaf = folded[folded.rfind(";") + 1:]
        counts[leaf] = counts.get(leaf, 0) + int(cnt)
    return sorted(counts.items(), key=lambda kv: (-kv[1], kv[0]))[:n]


def render_folded(report: dict, *, with_phase_root: bool = True
                  ) -> str:
    """flamegraph.pl-compatible folded output: ``stack count`` lines.
    With ``with_phase_root`` the phase becomes the root frame, so a
    standard flamegraph groups by phase at its base."""
    agg: Dict[str, int] = {}
    for ph, folded, n in report.get("stacks") or ():
        key = f"{ph};{folded}" if with_phase_root else folded
        agg[key] = agg.get(key, 0) + int(n)
    return "\n".join(f"{stack} {n}"
                     for stack, n in sorted(agg.items())) + "\n"


# ---------------------------------------------------------------------------
# Differential profiles — the regression gate
# ---------------------------------------------------------------------------


def diff(base: dict, head: dict, *, threshold: float = 0.2,
         min_frac: float = DIFF_MIN_FRAC,
         new_hot_frac: float = DIFF_NEW_HOT_FRAC) -> dict:
    """Compare two merged reports by per-frame SELF-sample share.

    A frame regresses when its share of all samples grew by at least
    ``threshold`` RELATIVE to base (0.2 = +20%) while holding at least
    ``min_frac`` of base samples, or when a frame absent from base
    holds at least ``new_hot_frac`` of head samples (a new hot frame).
    Returns ``{ok, regressions, improvements, ...}`` — the
    ``bffleet-tpu --check`` posture: machine-checkable, exit-code
    friendly."""
    bn = base.get("samples") or 0
    hn = head.get("samples") or 0
    if bn <= 0 or hn <= 0:
        raise ValueError("diff needs nonempty base and head reports "
                         f"(samples: base={bn}, head={hn})")
    bframes = base.get("frames", {})
    hframes = head.get("frames", {})
    regressions: List[dict] = []
    improvements: List[dict] = []
    for fr in sorted(set(bframes) | set(hframes)):
        bf = bframes.get(fr, {}).get("self", 0) / bn
        hf = hframes.get(fr, {}).get("self", 0) / hn
        if fr not in bframes or bf == 0.0:
            if hf >= new_hot_frac:
                regressions.append({"frame": fr, "base_frac": 0.0,
                                    "head_frac": round(hf, 4),
                                    "rel_change": None, "new": True})
            continue
        if bf < min_frac:
            continue
        rel = hf / bf - 1.0
        entry = {"frame": fr, "base_frac": round(bf, 4),
                 "head_frac": round(hf, 4), "rel_change": round(rel, 4)}
        if rel >= threshold:
            regressions.append(entry)
        elif rel <= -threshold:
            improvements.append(entry)
    regressions.sort(key=lambda e: -(e["head_frac"] - e["base_frac"]))
    improvements.sort(key=lambda e: e["rel_change"])
    return {"ok": not regressions,
            "threshold": threshold,
            "base_samples": bn, "head_samples": hn,
            "regressions": regressions,
            "improvements": improvements}


# ---------------------------------------------------------------------------
# Self-contained flamegraph SVG (no external flamegraph.pl dependency)
# ---------------------------------------------------------------------------

_SVG_ROW_H = 17
_SVG_WIDTH = 1200
_SVG_FONT = 11


def _color(name: str) -> str:
    """Deterministic warm color per frame name (hash-seeded, the
    flamegraph convention) — same frame, same color, across renders."""
    h = hashlib.blake2b(name.encode(), digest_size=3).digest()
    r = 205 + h[0] % 50
    g = 60 + h[1] % 110
    b = h[2] % 60
    return f"rgb({r},{g},{b})"


def _esc(s: str) -> str:
    return (s.replace("&", "&amp;").replace("<", "&lt;")
            .replace(">", "&gt;").replace('"', "&quot;"))


def render_svg(report: dict, *, title: str = "bfprof-tpu") -> str:
    """A minimal self-contained flamegraph: phase-rooted merged stacks,
    width proportional to samples, one ``<rect>`` + hover ``<title>``
    per node.  Not interactive beyond hover — the point is a committed
    artifact viewable in any browser with zero tooling."""
    # fold into a tree: node = [children: dict, self+child samples]
    root: list = [{}, 0]
    for ph, folded, n in report.get("stacks") or ():
        node = root
        node[1] += int(n)
        for frame in [ph] + folded.split(";"):
            child = node[0].get(frame)
            if child is None:
                child = node[0][frame] = [{}, 0]
            child[1] += int(n)
            node = child

    total = root[1] or 1
    depth_max = [1]
    cells: List[Tuple[int, float, float, str]] = []  # depth, x, w, name

    def walk(node, depth, x0):
        depth_max[0] = max(depth_max[0], depth)
        x = x0
        for name, child in sorted(node[0].items()):
            w = child[1] / total
            if w * _SVG_WIDTH >= 1.0:  # sub-pixel nodes are noise
                cells.append((depth, x, w, name))
                walk(child, depth + 1, x)
            x += w

    walk(root, 0, 0.0)
    height = (depth_max[0] + 3) * _SVG_ROW_H
    out = [
        f'<svg xmlns="http://www.w3.org/2000/svg" width="{_SVG_WIDTH}" '
        f'height="{height}" font-family="monospace" '
        f'font-size="{_SVG_FONT}">',
        f'<text x="4" y="{_SVG_ROW_H - 4}">{_esc(title)} — '
        f'{report.get("samples", 0)} samples, attributed '
        f'{report.get("attributed_frac", 0.0):.0%}</text>',
    ]
    for depth, x, w, name in cells:
        px = x * _SVG_WIDTH
        pw = max(1.0, w * _SVG_WIDTH)
        py = height - (depth + 2) * _SVG_ROW_H
        n_samples = int(round(w * total))
        out.append(
            f'<g><rect x="{px:.1f}" y="{py}" width="{pw:.1f}" '
            f'height="{_SVG_ROW_H - 1}" fill="{_color(name)}" '
            f'rx="1"><title>{_esc(name)} — {n_samples} samples '
            f'({w:.1%})</title></rect>'
            + (f'<text x="{px + 2:.1f}" y="{py + _SVG_ROW_H - 5}" '
               f'clip-path="inset(0)">'
               f'{_esc(name[:max(1, int(pw / 7))])}</text>'
               if pw >= 30 else "")
            + "</g>")
    out.append("</svg>")
    return "\n".join(out) + "\n"
