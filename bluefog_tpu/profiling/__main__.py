"""``python -m bluefog_tpu.profiling`` == ``bfprof-tpu``."""

import sys

from bluefog_tpu.profiling.cli import main

if __name__ == "__main__":
    sys.exit(main())
