"""In-process sampling profiler: the seventh observability leg.

Six legs can name the slow *edge* (tracing's critical path), the slow
*rank* (fleet SLOs), the last *events* (blackbox), *how much* (metrics),
*when* (timeline) and *what would happen* (sim) — none can name the slow
**code**.  This module does: a dedicated daemon thread walks
``sys._current_frames()`` at a configurable rate (default 97 Hz — prime,
so the sampler never phase-locks to decimal-cadenced loops) and folds
each thread's stack into a ``frame;frame;frame`` string, tagging every
sample with the SAMPLED thread's current tracing context (innermost span
name + round, read lock-free from
:func:`bluefog_tpu.tracing.recorder.active_phases` — the cross-thread
mirror of the PR-11 thread-local span stack).  Span names map onto the
same ``{compute, gossip, publish, net-wait}`` phases the trace analyzer
names, so ``bfprof-tpu`` can answer "the gating edge's wall-clock maps
to THESE frames".

Recording is OFF by default — zero threads, zero imports on the jax
path, byte-identical HLO (asserted in tests).  ``BLUEFOG_TPU_PROFILE=
<dir>`` (read lazily, the tracing/metrics discipline) or
:func:`configure` arms it; ``BLUEFOG_TPU_PROFILE_HZ`` overrides the
rate.  Samples aggregate in sampler-thread-owned dicts and land in
``profile-rank<k>.jsonl`` (``profile-pid<p>.jsonl`` for a rank-less
process) as per-flush-window records; a bounded deque additionally keeps
the last ~30 s of samples for blackbox hang forensics
(:func:`recent_folded`).

Hot-path discipline (BF-PROF001, enforced by
:mod:`bluefog_tpu.analysis.profiling_lint`): the per-sample path — from
``sys._current_frames`` to the aggregation-dict update — must never
acquire a package lock, perform IO, serialize JSON, sleep, or touch the
metrics registry.  The sampler samples threads that may themselves hold
any package lock; one lock acquire on the sampling path is a latent
deadlock against every lock in the package.  All IO happens on the
sampler thread BETWEEN ticks (the periodic flush), and cross-thread
reads (``snapshot``/``recent_folded``) rely on GIL-atomic dict/deque
operations, not locks.
"""

from __future__ import annotations

import atexit
import collections
import json
import os
import sys
import threading
import time
from typing import Deque, Dict, List, Optional, Tuple

from bluefog_tpu.utils import lockcheck as _lc

__all__ = [
    "PHASES",
    "Profiler",
    "configure",
    "enabled",
    "flush",
    "get",
    "phase_for_span",
    "reset",
    "set_rank",
]

#: the phase vocabulary — the trace analyzer's round decomposition plus
#: the wire-side wait states, collapsed to what a frame budget needs
PHASES = ("compute", "gossip", "publish", "net-wait")

#: span name -> phase.  Client + server tracing span names (see
#: tracing/analyze.py CLIENT_PHASES/SERVER_PHASES) and the dsgd loop's
#: own phase spans; anything unknown attributes to "other".
_SPAN_PHASE = {
    "compute": "compute",
    "round": "compute",
    "gossip": "gossip",
    "consume": "gossip",
    "apply": "gossip",
    "mix": "gossip",
    "snapshot": "gossip",
    "publish": "publish",
    "snapshot_publish": "publish",
    "fleet": "publish",
    "control": "publish",
    "wire": "net-wait",
    "ack_wait": "net-wait",
    "ack": "net-wait",
    "flush": "net-wait",
    "recv": "net-wait",
    "queue_wait": "net-wait",
    "enqueue": "net-wait",
    "coalesce": "net-wait",
}

#: frames deeper than this are truncated (the root side is kept)
_MAX_DEPTH = 64
#: recent-sample ring: ~30 s at the default rate, bounded regardless
_RECENT_MAXLEN = 4096
#: seconds of samples the blackbox dump embeds
RECENT_WINDOW_S = 30.0


def phase_for_span(name: Optional[str]) -> str:
    """Map a tracing span name to its profile phase ("other" when no
    span is open or the name is unknown)."""
    if name is None:
        return "other"
    return _SPAN_PHASE.get(name, "other")


def _default_hz() -> float:
    try:
        return float(os.environ.get("BLUEFOG_TPU_PROFILE_HZ", "") or 97.0)
    except ValueError:
        return 97.0


class Profiler:
    """One process's sampling profiler: sampler thread + JSONL writer.

    ``start()`` spawns the daemon sampler; ``stop()`` joins it after a
    final flush.  Aggregation dicts are owned by the sampler thread;
    every cross-thread read path uses GIL-atomic snapshots (``dict``
    swap, ``deque`` iteration), never a lock — see the module docstring
    for why.
    """

    #: sampler thread name — tests and the disabled-path bench key on it
    THREAD_NAME = "bf-prof-sampler"

    def __init__(self, directory: str, rank: Optional[int] = None,
                 hz: Optional[float] = None):
        self.directory = directory
        self.rank = rank
        self.hz = float(hz) if hz else _default_hz()
        if self.hz <= 0 or self.hz > 1000:
            raise ValueError(f"sampling rate must be in (0, 1000] Hz, "
                             f"got {self.hz}")
        self.samples = 0
        self.windows_flushed = 0
        self.dropped = 0
        # sampler-thread-owned aggregation: (phase, folded) -> count,
        # swapped out wholesale at flush time (GIL-atomic)
        self._agg: Dict[Tuple[str, str], int] = {}
        self._agg_round: Dict[str, int] = {}  # phase -> samples
        # last ~30 s of (wall_t, folded, phase, round) for blackbox
        # forensics — bounded deque, appends are GIL-atomic
        self._recent: Deque[Tuple[float, str, str, Optional[int]]] = \
            collections.deque(maxlen=_RECENT_MAXLEN)
        # code object -> "pkg/file.py:func" label (bounded by the
        # process's live code objects; grows once per function, not per
        # sample)
        self._labels: Dict[object, str] = {}
        self._stop = threading.Event()
        # serializes flush windows only (sampler-thread periodic flush
        # vs an explicit cross-thread flush()/stop()); NEVER touched on
        # the per-sample path — BF-PROF001
        self._io_lock = _lc.lock("profiling.sampler.Profiler._io_lock")
        self._thread: Optional[threading.Thread] = None
        self._t0 = time.time()
        self._last_flush = self._t0
        self._flush_every_s = 1.0
        self._wrote_meta = False

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "Profiler":
        if self._thread is not None:
            return self
        self._thread = threading.Thread(
            target=self._run, name=self.THREAD_NAME, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        """Stop sampling and flush the tail window; idempotent."""
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None
        self._flush_window(final=True)

    # ------------------------------------------------------------- sampling
    def _run(self) -> None:
        # resolved ONCE, outside the tick loop: even a cached import
        # statement is sys.modules machinery the per-sample path must
        # not pay (or depend on — the import lock is a lock)
        from bluefog_tpu.tracing.recorder import active_phases

        period = 1.0 / self.hz
        own = threading.get_ident()
        phases = active_phases()  # the live dict, read lock-free
        while not self._stop.wait(period):
            self._sample_once(own, phases)
            now = time.time()
            if now - self._last_flush >= self._flush_every_s:
                # IO strictly BETWEEN ticks, never on the sample path
                self._flush_window()

    def _sample_once(self, own_ident: int, phases: Dict) -> None:
        """Walk every thread's stack once.  THE hot path: no locks, no
        IO, no JSON, no sleeps, no metrics — BF-PROF001."""
        now = time.time()
        agg = self._agg
        agg_round = self._agg_round
        recent = self._recent
        labels = self._labels
        n = 0
        for ident, frame in sys._current_frames().items():
            if ident == own_ident:
                continue
            parts: List[str] = []
            depth = 0
            f = frame
            while f is not None and depth < _MAX_DEPTH:
                code = f.f_code
                lbl = labels.get(code)
                if lbl is None:
                    fn = code.co_filename
                    sep = fn.rfind(os.sep, 0, fn.rfind(os.sep))
                    lbl = f"{fn[sep + 1:]}:{code.co_name}"
                    labels[code] = lbl
                parts.append(lbl)
                f = f.f_back
                depth += 1
            parts.reverse()
            folded = ";".join(parts)
            ctx = phases.get(ident)
            if ctx is None:
                phase, rnd = "other", None
            else:
                phase = _SPAN_PHASE.get(ctx[0], "other")
                rnd = ctx[1]
            key = (phase, folded)
            agg[key] = agg.get(key, 0) + 1
            agg_round[phase] = agg_round.get(phase, 0) + 1
            recent.append((now, folded, phase, rnd))
            n += 1
        self.samples += n

    # ---------------------------------------------------------------- flush
    def _path(self) -> str:
        if self.rank is None:
            return os.path.join(self.directory,
                                f"profile-pid{os.getpid()}.jsonl")
        return os.path.join(self.directory,
                            f"profile-rank{self.rank}.jsonl")

    def _flush_window(self, final: bool = False) -> Optional[str]:
        """Swap the aggregation dicts out (GIL-atomic) and append one
        window record.  Runs on the sampler thread between ticks, or on
        a caller's thread via ``flush()``/``stop()``; the io lock
        serializes the two (it is never taken on the sample path)."""
        with self._io_lock:
            agg, self._agg = self._agg, {}
            phases, self._agg_round = self._agg_round, {}
            t1 = time.time()
            t0, self._last_flush = self._last_flush, t1
            if not agg and not final:
                return None
            rec = {"kind": "window", "t0": round(t0, 3),
                   "t1": round(t1, 3), "rank": self.rank, "hz": self.hz,
                   "samples": sum(agg.values()),
                   "phases": phases,
                   "stacks": [[ph, folded, n]
                              for (ph, folded), n in sorted(agg.items())]}
            try:
                os.makedirs(self.directory, exist_ok=True)
                with open(self._path(), "a") as f:
                    if not self._wrote_meta:
                        f.write(json.dumps(
                            {"kind": "meta", "rank": self.rank,
                             "pid": os.getpid(), "hz": self.hz,
                             "t0": round(self._t0, 3)}) + "\n")
                        self._wrote_meta = True
                    f.write(json.dumps(rec) + "\n")
            except OSError:
                self.dropped += sum(agg.values())
                return None
            self.windows_flushed += 1
            return self._path()

    # ------------------------------------------------------------ snapshots
    def recent_folded(self, seconds: float = RECENT_WINDOW_S) -> dict:
        """The last ``seconds`` of samples as ``{stacks, phases,
        samples}`` — what the blackbox dump embeds.  Reads the bounded
        deque with GIL-atomic iteration (a sample landing mid-snapshot
        is either in or out, never torn); newest-first walk with an
        early stop, the ``FlightRecorder.counts_since`` discipline."""
        cutoff = time.time() - float(seconds)
        stacks: Dict[str, int] = {}
        phases: Dict[str, int] = {}
        n = 0
        for t, folded, phase, _rnd in reversed(self._recent):
            if t < cutoff:
                break
            stacks[folded] = stacks.get(folded, 0) + 1
            phases[phase] = phases.get(phase, 0) + 1
            n += 1
        return {"window_s": float(seconds), "samples": n,
                "phases": phases, "stacks": stacks}

    def top_frames(self, n: int = 3) -> List[Tuple[str, float]]:
        """Top leaf frames by self-sample share over the recent ring —
        the FleetRecord digest.  Cheap (ring-bounded) and lock-free."""
        self_counts: Dict[str, int] = {}
        total = 0
        for _t, folded, _phase, _rnd in reversed(self._recent):
            leaf = folded[folded.rfind(";") + 1:]
            self_counts[leaf] = self_counts.get(leaf, 0) + 1
            total += 1
        if not total:
            return []
        top = sorted(self_counts.items(), key=lambda kv: (-kv[1], kv[0]))
        return [(lbl, round(c / total, 4)) for lbl, c in top[:n]]


# ---------------------------------------------------------------------------
# Process-global profiler (lazy env activation, the tracing discipline)
# ---------------------------------------------------------------------------

_PROFILER: Optional[Profiler] = None
_state_lock = _lc.lock("profiling.sampler._state_lock")
_STOPPED = False
_atexit_armed = False


def enabled() -> bool:
    return get() is not None


def get() -> Optional[Profiler]:
    """The process profiler, or None when profiling is off.  Lazily
    honors ``BLUEFOG_TPU_PROFILE=<dir>``; an explicit :func:`reset`
    sticks."""
    global _PROFILER
    if _PROFILER is None:
        if _STOPPED:
            return None
        d = os.environ.get("BLUEFOG_TPU_PROFILE")
        if not d:
            return None
        with _state_lock:
            if _PROFILER is None and not _STOPPED:
                _configure_locked(d, None, None)
    return _PROFILER


def configure(directory: str, rank: Optional[int] = None,
              hz: Optional[float] = None) -> Profiler:
    """Install and start a profiler with explicit settings (replaces
    the lazy one); also un-sticks a previous :func:`reset`."""
    global _STOPPED
    with _state_lock:
        old = _PROFILER
        _STOPPED = False
        prof = _configure_locked(directory, rank, hz)
    if old is not None:
        old.stop()
    return prof


def _configure_locked(directory, rank, hz) -> Profiler:
    global _PROFILER, _atexit_armed
    from bluefog_tpu.tracing import recorder as _tr

    prof = Profiler(directory, rank=rank, hz=hz)
    # phase-only context tracking: span() maintains the thread->phase
    # map even when tracing itself is off
    _tr.set_phase_tracking(True)
    _PROFILER = prof.start()
    if not _atexit_armed:
        _atexit_armed = True
        atexit.register(reset)
    return _PROFILER


def set_rank(rank: int) -> None:
    """Pin the file identity (the per-process dsgd body calls this) —
    must happen before the first flush names the file."""
    prof = get()
    if prof is not None and prof.rank is None:
        prof.rank = int(rank)


def reset() -> None:
    """Stop the sampler and drop the profiler (tests, run teardown);
    sticky against the env var until :func:`configure` runs again."""
    global _PROFILER, _STOPPED
    with _state_lock:
        prof, _PROFILER = _PROFILER, None
        _STOPPED = True
    if prof is not None:
        prof.stop()
        from bluefog_tpu.tracing import recorder as _tr

        _tr.set_phase_tracking(False)


def flush() -> None:
    prof = _PROFILER
    if prof is not None:
        prof._flush_window()
