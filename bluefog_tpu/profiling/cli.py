"""``bfprof-tpu`` — merge, render, and gate continuous profiles.

Modes (mutually composable where sensible):

- ``bfprof-tpu DIR`` — merge every ``profile-rank*.jsonl`` under DIR
  and print the summary + top-N self table.
- ``--json`` — print the merged report JSON instead (the input format
  ``--diff`` consumes).
- ``--folded`` — flamegraph.pl-compatible folded stacks on stdout.
- ``--svg PATH`` — self-contained flamegraph SVG.
- ``--trace TRACEDIR`` — join against a ``bftrace-tpu`` trace: name
  the critical path's dominant phase and the profile frames behind it.
- ``bfprof-tpu --diff BASE.json HEAD.json [--threshold 0.2]`` —
  differential gate; exits 3 when a hot frame regressed, the same
  machine-checkable posture as ``bffleet-tpu --check``.

Exit codes: 0 ok, 2 usage/load error, 3 regression detected.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

from bluefog_tpu.profiling import report as _rep

__all__ = ["main"]

#: trace critical-path phases → profile phases (the trace speaks span
#: names, the profiler speaks the four-phase vocabulary)
_TRACE_PHASE_MAP = {
    "queue_wait": "net-wait",
    "wire": "net-wait",
    "ack_wait": "net-wait",
    "flush": "net-wait",
    "compute": "compute",
    "round": "compute",
    "gossip": "gossip",
    "consume": "gossip",
    "apply": "gossip",
    "mix": "gossip",
    "publish": "publish",
    "fleet": "publish",
    "control": "publish",
}


def _load_report(path: str) -> dict:
    with open(path) as f:
        rep = json.load(f)
    if not isinstance(rep, dict) or rep.get("kind") != "bfprof_report":
        raise ValueError(f"{path}: not a bfprof_report JSON "
                         "(generate one with `bfprof-tpu DIR --json`)")
    return rep


def _print_summary(rep: dict, top: int, out) -> None:
    ranks = rep.get("ranks") or []
    print(f"bfprof-tpu: {rep.get('samples', 0)} samples, "
          f"{len(ranks)} rank(s), hz={rep.get('hz')}, "
          f"wall={rep.get('wall_s')}s", file=out)
    print(f"attributed: {rep.get('attributed_frac', 0.0):.1%}", file=out)
    for ph, frac in sorted((rep.get("phase_frac") or {}).items(),
                           key=lambda kv: -kv[1]):
        print(f"  {ph:<10} {frac:7.1%}", file=out)
    rows = _rep.top_table(rep, top)
    if rows:
        print(f"top {len(rows)} frames by self samples:", file=out)
        for fr, n, frac in rows:
            print(f"  {frac:7.1%} {n:>8}  {fr}", file=out)


def _trace_join(rep: dict, trace_dir: str, out) -> None:
    from bluefog_tpu.tracing import analyze as _an

    spans = _an.load_traces(trace_dir)
    if not spans:
        print(f"trace join: no spans under {trace_dir}", file=out)
        return
    cp = _an.critical_path(_an.build_graph(spans))
    dom = cp.get("dominant_phase")
    prof_phase = _TRACE_PHASE_MAP.get(dom or "", "other")
    print(f"trace join: critical path dominated by span "
          f"'{dom}' ({cp.get('dominant_frac', 0.0):.1%} of gate time "
          f"{cp.get('gate_time_s')}s) -> profile phase "
          f"'{prof_phase}'", file=out)
    frames = _rep.phase_frames(rep, prof_phase)
    if frames:
        total = sum(n for _, n in frames) or 1
        print(f"frames behind '{prof_phase}':", file=out)
        for fr, n in frames:
            print(f"  {n / total:7.1%} {n:>8}  {fr}", file=out)
    else:
        print(f"no profile samples attributed to '{prof_phase}'",
              file=out)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="bfprof-tpu",
        description="merge, render, and gate bluefog-tpu continuous "
                    "profiles")
    ap.add_argument("directory", nargs="?",
                    help="directory holding profile-rank*.jsonl files")
    ap.add_argument("--top", type=int, default=15,
                    help="rows in the top-frames table (default 15)")
    ap.add_argument("--json", action="store_true",
                    help="print the merged report JSON")
    ap.add_argument("--folded", action="store_true",
                    help="print flamegraph.pl-compatible folded stacks")
    ap.add_argument("--svg", metavar="PATH",
                    help="write a self-contained flamegraph SVG")
    ap.add_argument("--trace", metavar="TRACEDIR",
                    help="join against a bftrace-tpu trace directory")
    ap.add_argument("--diff", nargs=2, metavar=("BASE", "HEAD"),
                    help="differential gate over two --json reports")
    ap.add_argument("--threshold", type=float, default=0.2,
                    help="relative self-share growth that counts as a "
                         "regression (default 0.2 = +20%%)")
    args = ap.parse_args(argv)
    out = sys.stdout

    if args.diff:
        if args.directory:
            print("bfprof-tpu: --diff takes two report files, not a "
                  "directory", file=sys.stderr)
            return 2
        try:
            base = _load_report(args.diff[0])
            head = _load_report(args.diff[1])
            verdict = _rep.diff(base, head, threshold=args.threshold)
        except (OSError, ValueError) as e:
            print(f"bfprof-tpu: {e}", file=sys.stderr)
            return 2
        print(json.dumps(verdict, indent=2, sort_keys=True), file=out)
        if not verdict["ok"]:
            n = len(verdict["regressions"])
            print(f"bfprof-tpu: FAIL — {n} frame(s) regressed beyond "
                  f"+{args.threshold:.0%}", file=sys.stderr)
            return 3
        print("bfprof-tpu: ok — no hot-frame regression", file=out)
        return 0

    if not args.directory:
        ap.print_usage(sys.stderr)
        print("bfprof-tpu: a profile directory (or --diff) is required",
              file=sys.stderr)
        return 2
    rep = _rep.merge(args.directory)
    if not rep["samples"]:
        print(f"bfprof-tpu: no profile samples under {args.directory}",
              file=sys.stderr)
        return 2

    if args.json:
        print(json.dumps(rep, indent=2, sort_keys=True), file=out)
    elif args.folded:
        out.write(_rep.render_folded(rep))
    else:
        _print_summary(rep, args.top, out)
    if args.svg:
        with open(args.svg, "w") as f:
            f.write(_rep.render_svg(rep, title=args.directory))
        print(f"bfprof-tpu: wrote {args.svg}", file=out)
    if args.trace:
        _trace_join(rep, args.trace, out)
    return 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
