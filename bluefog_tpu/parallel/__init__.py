"""Mesh/context machinery and the array-level (host-side) API.

``bluefog_tpu.parallel.context`` is the analog of the reference's
``bluefog/common/basics.py`` + ``global_state.h`` (upstream-relative): the
process-wide singleton holding the device mesh, current topology, compiled
gossip schedules, and the window registry.

``bluefog_tpu.parallel.api`` is the analog of ``bluefog/torch/mpi_ops.py``'s
module-level functions, re-expressed for SPMD: tensors carry a leading
``size``-sized rank axis sharded over the gossip mesh axis, and each call is a
``shard_map`` around the in-SPMD primitive from ``bluefog_tpu.ops``.
"""

from bluefog_tpu.parallel.context import BluefogContext, get_context, init, shutdown
from bluefog_tpu.parallel import api
from bluefog_tpu.parallel import tensor
