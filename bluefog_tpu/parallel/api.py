"""Array-level (host-side) op API — the ``bf.*`` surface of the reference.

Reference parity (upstream-relative): module-level functions of
``bluefog/torch/mpi_ops.py`` and the helpers of ``bluefog/torch/utility.py``.

Representation: where the reference's process-per-rank model gives each rank a
private ``tensor``, the SPMD model stacks all ranks' values into one global
array with a leading ``size``-length *rank axis*, sharded over the gossip mesh
axis (``P('bf')``).  ``x[r]`` is rank ``r``'s value.  Every function here
wraps the corresponding in-SPMD primitive from ``bluefog_tpu.ops`` in a
``shard_map`` over the context mesh; inside a user's own ``shard_map``-ed
training step, call the ``bluefog_tpu.ops`` primitives directly instead.

Because everything is jitted XLA, the reference's nonblocking/handle surface
(``*_nonblocking``, ``poll``, ``synchronize`` — SURVEY.md §3.2) maps onto
JAX's async dispatch: every call here *is* nonblocking (returns a future-like
Array); ``jax.block_until_ready`` is the ``synchronize`` analog, and overlap
with compute is handled by the XLA scheduler rather than a background thread.
"""

from __future__ import annotations

import contextlib
import functools
import inspect
import threading
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from bluefog_tpu import ops as _ops
from bluefog_tpu.ops.windows import WindowState
from bluefog_tpu.parallel.context import get_context
from bluefog_tpu.topology.graphs import Topology
from bluefog_tpu.topology.schedule import GossipSchedule, build_schedule
from bluefog_tpu.utils import lockcheck as _lc

try:  # JAX >= 0.4.35
    from jax import shard_map as _shard_map_mod  # type: ignore

    _shard_map_impl = (_shard_map_mod.shard_map
                       if hasattr(_shard_map_mod, "shard_map")
                       else _shard_map_mod)
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map_impl  # type: ignore

_SHARD_MAP_PARAMS = frozenset(
    inspect.signature(_shard_map_impl).parameters)


@functools.wraps(_shard_map_impl)
def shard_map(*args, **kwargs):
    """``jax.shard_map`` with version-portable kwargs.

    The replication-check flag was renamed ``check_rep`` -> ``check_vma``
    across jax releases; every call site here (and the test suite) uses
    the new name, so translate to whatever the installed jax accepts —
    the same boolean under either name — and drop flags it lacks
    entirely.
    """
    for new, old in (("check_vma", "check_rep"), ("check_rep", "check_vma")):
        if new in kwargs and new not in _SHARD_MAP_PARAMS:
            val = kwargs.pop(new)
            if old in _SHARD_MAP_PARAMS:
                kwargs[old] = val
    return _shard_map_impl(*args, **kwargs)


def abstract_mesh(axis_sizes: Sequence[int], axis_names: Sequence[str]):
    """``jax.sharding.AbstractMesh`` with version-portable construction
    (same compat pattern as :func:`shard_map` above).

    Newer jax takes ``AbstractMesh(axis_sizes, axis_names)``; older
    releases take a single ``shape_tuple`` of ``(name, size)`` pairs.
    Device-free lowering (program-size censuses, pod-scale compile
    checks) should come through here so a jax upgrade changes one line.
    """
    from jax.sharding import AbstractMesh

    sizes = tuple(int(s) for s in axis_sizes)
    names = tuple(axis_names)
    if len(sizes) != len(names):
        raise ValueError(f"{len(sizes)} axis sizes vs {len(names)} names")
    try:
        return AbstractMesh(sizes, names)
    except TypeError:
        return AbstractMesh(tuple(zip(names, sizes)))

__all__ = [
    "abstract_mesh",
    "allreduce",
    "allgather",
    "broadcast",
    "barrier",
    "neighbor_allreduce",
    "neighbor_allreduce_aperiodic",
    "neighbor_allgather",
    "hierarchical_neighbor_allreduce",
    "win_create",
    "win_free",
    "win_put",
    "win_get",
    "win_accumulate",
    "win_update",
    "win_update_then_collect",
    "win_mutex",
    "win_mutex_break",
    "win_mutex_sweep",
    "broadcast_parameters",
    "allreduce_parameters",
    "broadcast_optimizer_state",
    "rank_stack",
    "rank_shard",
]


@functools.lru_cache(maxsize=256)
def _schedule_for(topology: Topology) -> GossipSchedule:
    # Topologies hash by identity, so repeated calls with the same Topology
    # object reuse one schedule — keeping _cached_op / _cached_win_op warm
    # instead of recompiling per call.
    return build_schedule(topology)


def _sched(topology) -> GossipSchedule:
    if topology is None:
        return get_context().schedule
    if isinstance(topology, GossipSchedule):
        return topology
    return _schedule_for(topology)


def _smap(fn, n_in: int = 1, replicated_in: int = 0):
    ctx = get_context()
    ax = ctx.axis_name
    in_specs = tuple([P(ax)] * n_in + [P()] * replicated_in)
    return shard_map(
        fn, mesh=ctx.mesh, in_specs=in_specs, out_specs=P(ax), check_vma=False,
    )


# Cache of jitted shard_map callables.  Eager api calls would otherwise
# re-stage the shard_map on every invocation (the analog of the reference
# re-registering MPI datatypes per call); keyed by everything that changes the
# staged program.  Schedules hash by identity — reuse the context's schedule
# (or hold on to your own) to stay cache-warm.
@functools.lru_cache(maxsize=512)
def _cached_op(op_name: str, mesh, axis_name: str, sched, *static):
    ax = axis_name

    if op_name == "neighbor_allreduce":
        has_sw, has_rw, has_dw = static

        def fn(xs, sw, rw, dw):
            return _ops.neighbor_allreduce(
                xs, sched, ax,
                self_weight=sw if has_sw else None,
                recv_weights=rw if has_rw else None,
                send_weights=dw if has_dw else None,
            )

        return jax.jit(shard_map(
            fn, mesh=mesh, in_specs=(P(ax), P(), P(), P()), out_specs=P(ax),
            check_vma=False,
        ))

    if op_name == "neighbor_allreduce_aperiodic":
        (max_rotations,) = static

        def ap_fn(xs, w):
            return _ops.neighbor_allreduce_aperiodic(
                xs, w, ax, max_rotations=max_rotations)

        return jax.jit(shard_map(
            ap_fn, mesh=mesh, in_specs=(P(ax), P()), out_specs=P(ax),
            check_vma=False,
        ))

    if op_name == "allreduce":
        (average,) = static
        f = lambda xs: _ops.allreduce(xs, ax, average=average)
    elif op_name == "broadcast":
        (root,) = static
        f = lambda xs: _ops.broadcast(xs, root, ax)
    elif op_name == "allgather":
        # [None] must apply per leaf, not to the tree_map'd result
        f = lambda xs: jax.tree_util.tree_map(
            lambda leaf: lax.all_gather(leaf, ax, axis=0, tiled=True)[None], xs
        )
    else:
        raise KeyError(op_name)
    return jax.jit(shard_map(
        f, mesh=mesh, in_specs=(P(ax),), out_specs=P(ax), check_vma=False,
    ))


def rank_stack(x, size: Optional[int] = None):
    """Replicate a host value into the stacked per-rank representation:
    ``out[r] = x`` for every rank (pytree-polymorphic)."""
    n = size or get_context().size
    return jax.tree_util.tree_map(
        lambda leaf: jnp.broadcast_to(jnp.asarray(leaf)[None], (n,) + jnp.asarray(leaf).shape), x
    )


def rank_shard(x):
    """Device-put a stacked array so the rank axis lies on the gossip mesh."""
    ctx = get_context()
    sharding = jax.sharding.NamedSharding(ctx.mesh, P(ctx.axis_name))
    return jax.tree_util.tree_map(lambda leaf: jax.device_put(leaf, sharding), x)


# ---------------------------------------------------------------------------
# Collectives
# ---------------------------------------------------------------------------


def neighbor_allreduce(x, *, topology=None, self_weight=None, recv_weights=None,
                       send_weights=None):
    """Stacked-array ``bf.neighbor_allreduce``: ``out[i] = W[i,i] x[i] +
    sum_j W[i,j] x[j]`` with ``W`` from ``topology`` (default: context).

    ``send_weights`` is the reference's per-call ``dst_weights``: slot-indexed
    sender-side scaling applied to the shipped payload (``(num_slots,)``, or
    ``(size, num_slots)`` for a per-rank table)."""
    ctx = get_context()
    sched = _sched(topology)
    f = _cached_op(
        "neighbor_allreduce", ctx.mesh, ctx.axis_name, sched,
        self_weight is not None, recv_weights is not None,
        send_weights is not None,
    )
    sw = jnp.asarray(self_weight if self_weight is not None else 0.0, jnp.float32)
    rw = jnp.asarray(
        recv_weights if recv_weights is not None else jnp.zeros((sched.num_slots,)),
        jnp.float32,
    )
    dw = jnp.asarray(
        send_weights if send_weights is not None else jnp.zeros((sched.num_slots,)),
        jnp.float32,
    )
    return f(x, sw, rw, dw)


def neighbor_allreduce_aperiodic(x, mixing_matrix, *,
                                 max_rotations: Optional[int] = None):
    """Stacked-array gossip with an arbitrary per-call topology: ``out =
    W @ xs`` for any row-stochastic ``(size, size)`` ``W`` — edge set *and*
    weights are data, so changing them never recompiles.  ``max_rotations``
    caps program size for large meshes (degree-bounded dynamic graphs); see
    :func:`bluefog_tpu.ops.collectives.neighbor_allreduce_aperiodic`."""
    ctx = get_context()
    f = _cached_op(
        "neighbor_allreduce_aperiodic", ctx.mesh, ctx.axis_name, None,
        max_rotations)
    return f(x, jnp.asarray(mixing_matrix, jnp.float32))


def neighbor_allgather(x, *, topology=None):
    """Stacked ``bf.neighbor_allgather``: returns ``(slots, mask)``; see
    :func:`bluefog_tpu.ops.collectives.neighbor_allgather` for the padding
    deviation from the reference's ragged concatenation."""
    ctx = get_context()
    sched = _sched(topology)

    def fn(xs):
        slots, mask = _ops.neighbor_allgather(xs[0], sched, ctx.axis_name)
        return slots[None], mask[None]

    f = shard_map(
        fn, mesh=ctx.mesh, in_specs=(P(ctx.axis_name),),
        out_specs=(P(ctx.axis_name), P(ctx.axis_name)), check_vma=False,
    )
    return f(x)


def allreduce(x, *, average: bool = True):
    ctx = get_context()
    return _cached_op("allreduce", ctx.mesh, ctx.axis_name, None, average)(x)


def allgather(x):
    """Stacked allgather: every rank's row becomes the full stack — output
    shape ``(size, size, ...)`` per the stacked-representation convention."""
    ctx = get_context()
    return _cached_op("allgather", ctx.mesh, ctx.axis_name, None)(x)


def broadcast(x, root_rank: int = 0):
    ctx = get_context()
    return _cached_op("broadcast", ctx.mesh, ctx.axis_name, None, root_rank)(x)


def barrier():
    """Block the host until all in-flight device work completes."""
    ctx = get_context()
    out = _smap(lambda xs: xs + _ops.barrier(ctx.axis_name))(
        jnp.zeros((ctx.size,), jnp.float32)
    )
    jax.block_until_ready(out)
    return True


def hierarchical_neighbor_allreduce(x, *, machine_topology=None, self_weight=None,
                                    recv_weights=None, two_level_mesh=False):
    """Stacked ``bf.hierarchical_neighbor_allreduce`` (intra-machine exact
    average + machine-level gossip; requires ``init(local_size=...)``).

    ``two_level_mesh=True`` runs over ``ctx.hier_mesh`` — an explicit
    ``(machine, local)`` mesh where the local average is a ``pmean`` on the
    inner (ICI) axis and the machine gossip a ``ppermute`` on the outer (DCN)
    axis; numerically identical to the flat path, and the form a multi-slice
    deployment uses so the machine hops ride DCN."""
    ctx = get_context()
    msched = machine_topology
    if msched is None:
        if ctx.machine_schedule is None:
            raise RuntimeError("no machine topology: init(local_size=...) first")
        msched = ctx.machine_schedule
    elif isinstance(msched, Topology):
        msched = build_schedule(msched)
    if two_level_mesh:
        mesh2 = ctx.hier_mesh
        spec = P((ctx.machine_axis_name, ctx.local_axis_name))
        return shard_map(
            lambda xs: _ops.hierarchical_neighbor_allreduce_2d(
                xs, msched,
                machine_axis=ctx.machine_axis_name,
                local_axis=ctx.local_axis_name,
                self_weight=self_weight, recv_weights=recv_weights,
            ),
            mesh=mesh2, in_specs=(spec,), out_specs=spec, check_vma=False,
        )(x)
    return _smap(
        lambda xs: _ops.hierarchical_neighbor_allreduce(
            xs, msched, ctx.axis_name, local_size=ctx.local_size,
            self_weight=self_weight, recv_weights=recv_weights,
        )
    )(x)


# ---------------------------------------------------------------------------
# Window registry (one-sided ops)
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=512)
def _cached_win_op(op_name: str, mesh, axis_name: str, sched, *static):
    """Jitted shard_map callables for window ops (same caching story as
    :func:`_cached_op`)."""
    ax = axis_name

    if op_name == "create":
        (name,) = static

        def create_fn(xs):
            return _ops.win_create(xs, sched, ax, name=name)

        return jax.jit(shard_map(
            create_fn, mesh=mesh, in_specs=(P(ax),), out_specs=P(ax),
            check_vma=False,
        ))

    if op_name in ("put", "accumulate"):
        op = _ops.win_put if op_name == "put" else _ops.win_accumulate

        def deliver_fn(st, xs, dw):
            return op(st, xs, ax, dst_weight=dw)

        return jax.jit(shard_map(
            deliver_fn, mesh=mesh, in_specs=(P(ax), P(ax), P()),
            out_specs=P(ax), check_vma=False,
        ))

    if op_name == "get":
        return jax.jit(shard_map(
            lambda st: _ops.win_get(st, ax), mesh=mesh, in_specs=(P(ax),),
            out_specs=P(ax), check_vma=False,
        ))

    if op_name == "update":
        has_sw, has_rw = static

        def update_fn(st, sw, rw):
            return _ops.win_update(
                st, ax,
                self_weight=sw if has_sw else None,
                recv_weights=rw if has_rw else None,
            )

        return jax.jit(shard_map(
            update_fn, mesh=mesh, in_specs=(P(ax), P(), P()),
            out_specs=(P(ax), P(ax)), check_vma=False,
        ))

    if op_name == "update_then_collect":
        return jax.jit(shard_map(
            lambda st: _ops.win_update_then_collect(st, ax), mesh=mesh,
            in_specs=(P(ax),), out_specs=(P(ax), P(ax)), check_vma=False,
        ))

    raise KeyError(op_name)


def win_create(x, name: str, *, topology=None, zero_init: bool = False) -> bool:
    """Register window ``name`` over stacked tensor(-tree) ``x``
    (reference ``bf.win_create``; collective there, pure allocation here)."""
    ctx = get_context()
    sched = _sched(topology)
    if zero_init:
        x = jax.tree_util.tree_map(lambda leaf: jnp.zeros_like(leaf), x)
    f = _cached_win_op("create", ctx.mesh, ctx.axis_name, sched, name)
    ctx.windows[name] = f(x)
    return True


def win_free(name: Optional[str] = None) -> bool:
    """Drop one window (or all, matching the reference's ``win_free()``)."""
    from bluefog_tpu.ops import pallas_gossip as _pg

    ctx = get_context()
    if name is None:
        for n in ctx.windows:
            _pg.release_window_collective_id(n)
        ctx.windows.clear()
    else:
        ctx.windows.pop(name, None)
        # a freed window must not poison its collective-id bucket for the
        # rest of a long-lived process
        _pg.release_window_collective_id(name)
    return True


def _get_win(name: str) -> WindowState:
    ctx = get_context()
    if name not in ctx.windows:
        raise KeyError(f"no window named {name!r}; call win_create first")
    return ctx.windows[name]


def win_put(x, name: str, *, dst_weight=1.0) -> bool:
    ctx = get_context()
    state = _get_win(name)
    f = _cached_win_op("put", ctx.mesh, ctx.axis_name, state.spec.schedule)
    ctx.windows[name] = f(state, x, jnp.asarray(dst_weight, jnp.float32))
    return True


def win_accumulate(x, name: str, *, dst_weight=1.0) -> bool:
    ctx = get_context()
    state = _get_win(name)
    f = _cached_win_op("accumulate", ctx.mesh, ctx.axis_name, state.spec.schedule)
    ctx.windows[name] = f(state, x, jnp.asarray(dst_weight, jnp.float32))
    return True


def win_get(name: str) -> bool:
    ctx = get_context()
    state = _get_win(name)
    f = _cached_win_op("get", ctx.mesh, ctx.axis_name, state.spec.schedule)
    ctx.windows[name] = f(state)
    return True


def win_update(name: str, *, self_weight=None, recv_weights=None):
    """Returns the stacked averaged tensor and refreshes the window
    (reference ``bf.win_update``)."""
    ctx = get_context()
    state = _get_win(name)
    sched = state.spec.schedule
    f = _cached_win_op(
        "update", ctx.mesh, ctx.axis_name, sched,
        self_weight is not None, recv_weights is not None,
    )
    sw = jnp.asarray(self_weight if self_weight is not None else 0.0, jnp.float32)
    rw = jnp.asarray(
        recv_weights if recv_weights is not None else jnp.zeros((sched.num_slots,)),
        jnp.float32,
    )
    out, new_state = f(state, sw, rw)
    ctx.windows[name] = new_state
    return out


def win_update_then_collect(name: str):
    ctx = get_context()
    state = _get_win(name)
    f = _cached_win_op(
        "update_then_collect", ctx.mesh, ctx.axis_name, state.spec.schedule
    )
    out, new_state = f(state)
    ctx.windows[name] = new_state
    return out


_win_mutexes: Dict[str, object] = {}
_win_mutexes_guard = _lc.lock("parallel.api._win_mutexes_guard")
_dist_held = threading.local()  # per-thread reentrancy counts per name


def _coordination_client():
    """The jax.distributed coordination-service client, or None when this is
    a single-controller process (no distributed runtime to coordinate with).

    In a multi-controller job a missing client is an ERROR, not a fallback:
    silently downgrading to the process-local lock would let two controllers
    into the critical section — the exact race win_mutex exists to prevent.
    """
    import jax

    if jax.process_count() <= 1:
        return None
    try:
        from jax._src.distributed import global_state

        client = global_state.client
    except Exception as e:
        raise RuntimeError(
            "win_mutex: multi-controller job but the jax.distributed "
            "coordination-service client is unavailable — refusing to "
            "downgrade to a process-local lock") from e
    if client is None:
        raise RuntimeError(
            "win_mutex: multi-controller job but jax.distributed was not "
            "initialized with a coordination service")
    return client


_WIN_MUTEX_PREFIX = "bluefog_tpu/win_mutex/"
# break subkeys live in a DISJOINT prefix: a lock key derived from a window
# literally named "x.break" can never collide with window "x"'s break key
_WIN_MUTEX_BREAK_PREFIX = "bluefog_tpu/win_mutex_break/"
_LEASE_MARK = " lease_until="


def _is_not_found(e: BaseException) -> bool:
    """The coordination client raises (rather than returning None) for a
    missing key; distinguish that definitive answer from transient RPC
    failures."""
    return "NOT_FOUND" in str(e)


def _parse_lock_value(v: str):
    """``(owner, lease_expiry_unix_or_None, lease_duration_s_or_None)``
    from a lock key's value (stamp format ``<expiry>[/<duration>]``).
    Values without the lease marker (older writers, hand-planted keys) have
    no lease and are NEVER auto-stolen."""
    if _LEASE_MARK in v:
        owner, _, stamp = v.rpartition(_LEASE_MARK)
        expiry, _, dur = stamp.partition("/")
        try:
            return owner, float(expiry), (float(dur) if dur else None)
        except ValueError:
            return v, None, None
    return v, None, None


@contextlib.contextmanager
def win_mutex(name: str = "win", *, for_self: bool = True, ranks=None,
              timeout_s: float = 60.0, poll_interval_s: float = 0.002,
              lease_s: float = 30.0):
    """Mutual exclusion over window ``name`` (reference ``bf.win_mutex``,
    an MPI passive-target ``MPI_Win_lock_all`` epoch guarding concurrent
    one-sided access — ``bluefog/torch/mpi_win_ops.cc``).

    Scope — stated precisely, per deployment shape:

    - **Single controller** (``jax.process_count() == 1``): a process-local
      reentrant lock per window name.  Device-side one-sided transfers inside
      a jitted step are ordered by data dependencies, so the only real race
      is host threads (background :func:`enqueue_host_op` workers vs the main
      thread) mutating the same named window — which this serializes.
    - **Multi-controller** (``jax.distributed`` initialized, >1 processes): a
      **distributed lock on the coordination service** — acquisition is an
      atomic key creation (the service rejects duplicates), release deletes
      the key, and contenders poll.  This is the cross-process exclusion the
      reference gets from ``MPI_Win_lock_all``; it is reentrant within a
      thread, and raises ``TimeoutError`` after ``timeout_s``.

    **Lease / failure semantics** (multi-controller): the lock value carries
    a lease stamp (expiry + duration) that a background heartbeat refreshes
    every ``lease_s/3`` while the holder is alive — a live holder is never
    stolen no matter how long its critical section runs.  If the holder
    DIES, the heartbeat stops and the next contender recovers the lock
    automatically.  Stealing requires ALL of: (a) the stamp is wall-clock
    expired, (b) the contender has watched the value stay *unchanged* for a
    full lease duration on its own monotonic clock — so cross-host clock
    skew alone can never steal from a heartbeating holder — and (c) the
    contender wins the atomic break subkey and re-confirms the value is
    still unchanged immediately before deleting.  Keys without a lease
    stamp (planted by hand or by older writers) are never auto-stolen;
    those still need :func:`win_mutex_break` after the owner is known dead.
    ``lease_s=None`` disables the lease entirely (release failures then
    propagate, since no self-healing would follow them).  A holder frozen
    (not dead) past its lease can be stolen; its refresher detects the loss
    on its next beat, logs it, and stops re-stamping so the double-hold is
    bounded by one refresh period.  Residual window, stated honestly: the
    service has no compare-and-delete, so a breaker dying between its
    re-confirmation and the delete can still race a revival — the same
    post-failure ambiguity MPI has after ``MPI_Win_lock_all`` owner loss.

    ``for_self``/``ranks`` are accepted for reference call-site
    compatibility; the lock is per-window-name, not per-rank.
    """
    del for_self, ranks  # lock granularity is the window name
    client = _coordination_client()
    if client is None:
        with _win_mutexes_guard:
            lock = _win_mutexes.setdefault(
                name, _lc.rlock("parallel.api._win_mutexes[]"))
        with lock:
            yield
        return

    import time as _time

    held = getattr(_dist_held, "counts", None)
    if held is None:
        held = _dist_held.counts = {}
    if held.get(name, 0) > 0:  # reentrant within this thread
        held[name] += 1
        try:
            yield
        finally:
            held[name] -= 1
        return

    import jax
    import os as _os

    key = _WIN_MUTEX_PREFIX + name
    owner = f"{jax.process_index()}:{_os.getpid()}:{threading.get_ident()}"

    def stamped():
        if lease_s is None:
            return owner
        return (f"{owner}{_LEASE_MARK}"
                f"{_time.time() + lease_s:.3f}/{lease_s:.1f}")

    deadline = _time.monotonic() + timeout_s
    backoff = poll_interval_s
    tracker = _StealTracker(client, key, owner)
    while True:
        try:
            client.key_value_set(key, stamped())  # atomic: raises if held
            break
        except Exception as e:
            if "ALREADY_EXISTS" not in str(e):
                raise
            tracker.poll()
            if _time.monotonic() > deadline:
                holder = ""
                try:
                    holder = client.key_value_try_get(key)
                except Exception:
                    pass
                raise TimeoutError(
                    f"win_mutex({name!r}): lock held for {timeout_s:.0f}s "
                    f"by {holder!r} (process:pid:thread); a leased lock "
                    "recovers automatically when its owner dies — if this "
                    "one has no lease and the owner is dead, recover with "
                    "win_mutex_break(name)") from e
            # exponential backoff: N contenders busy-polling the (single)
            # coordination service with failing RPCs would starve its
            # heartbeat work at pod scale
            _time.sleep(backoff)
            backoff = min(backoff * 2, 0.1)
    held[name] = 1
    stop_refresh = threading.Event()
    refresher = None
    if lease_s is not None:
        def refresh():
            # a live holder's lease must never lapse: re-stamp well inside
            # the lease period until release.  If the key is no longer ours
            # (stolen from a frozen incarnation of us), say so and STOP —
            # blindly re-stamping would silently overwrite the new holder.
            # TRANSIENT RPC errors must NOT kill the heartbeat: the next
            # beat is only lease_s/3 away and the lease survives two missed
            # beats — exiting on the first blip would make a live holder
            # silently stealable, the exact thing the lease forbids.
            from bluefog_tpu.utils import log

            while not stop_refresh.wait(lease_s / 3.0):
                try:
                    cur = client.key_value_try_get(key)
                except Exception as e:
                    if _is_not_found(e):
                        cur = None  # definitively gone: lost
                    else:
                        continue  # transient: retry next beat
                if cur is None or _parse_lock_value(cur)[0] != owner:
                    log.error(
                        "win_mutex(%r): lease LOST (key now %r) — this "
                        "holder was frozen past its lease and the lock was "
                        "stolen; exclusion is no longer guaranteed for the "
                        "remainder of this critical section", name, cur)
                    return
                try:
                    client.key_value_set(key, stamped(),
                                         allow_overwrite=True)
                except Exception:
                    continue  # transient: the stamp retries next beat
        refresher = threading.Thread(target=refresh, daemon=True)
        refresher.start()
    try:
        yield
    finally:
        held[name] = 0
        stop_refresh.set()
        joined = True
        if refresher is not None:
            refresher.join(timeout=5)
            joined = not refresher.is_alive()
        if not joined:
            # a refresher stuck in an in-flight key_value_set could land
            # AFTER our delete and resurrect the key as a ghost; leave the
            # key to lease expiry instead (self-healing, bounded by lease_s)
            from bluefog_tpu.utils import log

            log.warn("win_mutex(%r): refresher still in flight at release; "
                     "leaving key to lease expiry", name)
        elif lease_s is None:
            # no lease means no self-healing: a failed delete here must be
            # LOUD, or the key wedges every later acquisition silently
            client.key_value_delete(key)
        else:
            try:
                # shrink (not close — no CAS) the stolen-lock window: only
                # delete what is still ours
                cur = client.key_value_try_get(key)
                if _parse_lock_value(cur)[0] == owner:
                    client.key_value_delete(key)
            except Exception as e:
                # a missing key is a CLEAN outcome (stolen and already
                # released by the thief), not an RPC failure to warn about
                if not _is_not_found(e):
                    from bluefog_tpu.utils import log

                    log.warn("win_mutex(%r): release delete failed (%s); "
                             "the lease will self-heal", name, e)


class _StealTracker:
    """Per-contender steal state: recovers a key whose leased holder died.

    Rate-limited (one try_get per ~lease/10, not per poll — N contenders
    must not double the coordination service's RPC load), and skew-immune:
    stealing additionally requires the value to have stayed UNCHANGED for a
    full lease duration on this contender's monotonic clock, which a live
    holder's heartbeat (every lease/3) makes impossible regardless of how
    far apart the hosts' wall clocks are."""

    def __init__(self, client, key: str, owner: str):
        self.client = client
        self.key = key
        self.owner = owner
        self.observed: Optional[str] = None
        self.first_seen = 0.0   # monotonic time self.observed appeared
        self.next_check = 0.0   # monotonic rate limiter

    def poll(self) -> None:
        import time as _time

        now_m = _time.monotonic()
        if now_m < self.next_check:
            return
        try:
            cur = self.client.key_value_try_get(self.key)
        except Exception:
            self.observed = None
            return  # key gone — the acquire loop will race for it
        if cur != self.observed:
            self.observed, self.first_seen = cur, now_m
        _, expiry, dur = _parse_lock_value(cur)
        if expiry is None:
            self.next_check = now_m + 1.0
            return  # lease-less values are never auto-stolen
        confirm_s = max(1.0, dur if dur is not None else 2.0)
        self.next_check = now_m + max(0.5, confirm_s / 10.0)
        if _time.time() <= expiry:
            return  # writer-clock says live
        if now_m - self.first_seen < confirm_s:
            return  # not yet watched unchanged for a full lease
        if _break_stale(self.client, self.key, self.owner, cur):
            self.observed = None


def _break_stale(client, key: str, breaker: str, observed: str) -> bool:
    """Delete ``key`` iff its value is still exactly ``observed``,
    serialized through an atomic break subkey (one breaker at a time; a
    last-moment refresh or re-acquire changes the value and aborts).
    Returns True if the stale key was deleted."""
    import time as _time

    now = _time.time()
    assert key.startswith(_WIN_MUTEX_PREFIX), key
    bkey = _WIN_MUTEX_BREAK_PREFIX + key[len(_WIN_MUTEX_PREFIX):]
    bval = f"{breaker}{_LEASE_MARK}{now + 10.0:.3f}/10.0"
    try:
        client.key_value_set(bkey, bval)  # atomic: one breaker at a time
    except Exception as e:
        if "ALREADY_EXISTS" not in str(e):
            return False
        # the previous breaker may itself have died mid-break
        try:
            bheld = client.key_value_try_get(bkey)
            _, bexp, _ = _parse_lock_value(bheld)
            if bexp is not None and now > bexp:
                client.key_value_delete(bkey)
        except Exception:
            pass
        return False
    stole = False
    try:
        cur = client.key_value_try_get(key)
        if cur == observed:  # unchanged since observed expired: truly stale
            client.key_value_delete(key)
            stole = True
            from bluefog_tpu.utils import log

            log.warn("win_mutex: broke expired lock %s (was %r)", key,
                     observed)
    except Exception:
        pass
    finally:
        try:
            client.key_value_delete(bkey)
        except Exception:
            pass
    return stole


def win_mutex_sweep(grace_s: float = 0.0) -> int:
    """Clear every win_mutex key whose lease expired more than ``grace_s``
    ago — the restart-path janitor (a supervisor-restarted worker calls this
    before re-entering training so locks its previous incarnation died
    holding cannot deadlock the job until per-acquire stealing notices).

    Deletions go through the same break-subkey + value-unchanged protocol
    as per-acquire stealing (on a FRESH read, not the enumeration snapshot),
    so the sweep serializes with live contenders and cannot delete a lock
    that was just stolen and re-acquired.  Returns the number of keys
    cleared; 0 under a single controller or when the service cannot
    enumerate keys."""
    import os as _os
    import time as _time

    client = _coordination_client()
    if client is None:
        return 0
    try:
        entries = client.key_value_dir_get(_WIN_MUTEX_PREFIX)
    except Exception:
        return 0
    removed = 0
    now = _time.time()
    breaker = f"sweep:{_os.getpid()}:{threading.get_ident()}"
    for entry in entries:
        key = entry[0] if isinstance(entry, (tuple, list)) else entry
        try:
            value = client.key_value_try_get(key)  # fresh, never snapshot
        except Exception:
            continue
        _, expiry, _ = _parse_lock_value(value)
        if expiry is not None and now > expiry + grace_s:
            if _break_stale(client, key, breaker, value):
                removed += 1
    return removed


def win_mutex_break(name: str = "win") -> bool:
    """Forcibly release a distributed :func:`win_mutex` whose holder died
    (the ``MPI_Win_unlock_all``-after-failure analog).  Returns True if a
    held lock was cleared.  **Only** call this when the owner named by the
    TimeoutError is known dead — breaking a live holder's lock removes the
    exclusion it is relying on."""
    client = _coordination_client()
    if client is None:
        # single-controller: a holder's death is process death, so there is
        # no dead-owner state to clear — and dropping a live RLock would let
        # a second thread into the critical section. Pure no-op.
        return False
    try:
        client.key_value_delete(_WIN_MUTEX_PREFIX + name)
        return True
    except Exception:
        return False


# ---------------------------------------------------------------------------
# Parameter-sync helpers (reference bluefog/torch/utility.py)
# ---------------------------------------------------------------------------


def broadcast_parameters(params, root_rank: int = 0):
    """Make every rank's parameter tree equal to ``root_rank``'s (reference
    ``bf.broadcast_parameters`` — used at init so all ranks start agreed)."""
    return broadcast(params, root_rank)


def allreduce_parameters(params):
    """Replace each rank's parameters with the global average (reference
    ``bf.allreduce_parameters`` — post-training consensus averaging)."""
    return allreduce(params, average=True)


def broadcast_optimizer_state(opt_state, root_rank: int = 0):
    """Broadcast an optimizer state tree (reference
    ``bf.broadcast_optimizer_state``; here any pytree of arrays works,
    non-array leaves pass through untouched)."""
    arrays, treedef = jax.tree_util.tree_flatten(opt_state)
    is_arr = [hasattr(a, "dtype") or isinstance(a, (int, float, np.ndarray)) for a in arrays]
    stacked = [a for a, ok in zip(arrays, is_arr) if ok]
    if stacked:
        out = broadcast(stacked, root_rank)
        it = iter(out)
        arrays = [next(it) if ok else a for a, ok in zip(arrays, is_arr)]
    return jax.tree_util.tree_unflatten(treedef, arrays)


# ---------------------------------------------------------------------------
# Nonblocking host-op surface (reference bluefog/torch/mpi_ops.py poll /
# synchronize over handle_manager; SURVEY.md §3.2).  Device collectives are
# XLA-async by construction, so handles here track *host* ops (checkpoint IO,
# DCN staging, metric flushes) running on the native C++ engine thread.
# ---------------------------------------------------------------------------


def enqueue_host_op(fn, *, op: str = "host_op", name: str = "") -> int:
    """Run ``fn()`` on the background engine thread; returns a handle."""
    from bluefog_tpu.runtime import engine

    return engine().enqueue(fn, op=op, name=name)


def poll(handle: int) -> bool:
    """True once the host op behind ``handle`` has completed."""
    from bluefog_tpu.runtime import engine

    return engine().poll(handle)


def synchronize(handle: int, timeout_s=None):
    """Block until the host op completes and clear its handle (reference
    ``bf.synchronize`` = WaitAndClear).  Re-raises the op's exception."""
    from bluefog_tpu.runtime import engine

    return engine().synchronize(handle, timeout_s=timeout_s)


def wait_all_host_ops(timeout_s=None):
    """Drain every pending host op (used before shutdown / checkpoints)."""
    from bluefog_tpu.runtime import engine

    return engine().wait_all(timeout_s=timeout_s)
