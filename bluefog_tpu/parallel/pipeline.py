"""Pipeline parallelism — GPipe-style microbatch pipelining over a mesh axis.

No counterpart exists in the reference (SURVEY.md §2.3: PP absent).  The TPU
build adds it as the third mesh level: ``('bf', 'pp', 'tp')`` — gossip-DP
outermost, pipeline stages in the middle, tensor parallel innermost.

Design (TPU-first, the scaling-book shard_map pipelining recipe):

- Each ``pp`` rank holds the parameters of its contiguous block of layers
  (``stack_stage_params`` shards a per-layer-stacked tree over the axis).
- One jitted ``lax.scan`` runs ``num_micro + pp - 1`` ticks; each tick every
  stage applies its block to the activation it holds and hands the result to
  the next stage with a single ``lax.ppermute`` hop (nearest-neighbor on
  ICI).  No data-dependent control flow — stage 0's input injection and the
  last stage's output collection are ``jnp.where`` selects on the tick index,
  so XLA compiles one static program.
Two schedules:

- **GPipe** (:func:`pipeline_apply` + ``jax.grad``): backward is plain
  autodiff through the scan — ``ppermute`` transposes to the reverse
  permute, giving the mirrored backward pipeline for free.  Activation
  stash grows with ``num_micro`` (every microbatch's activations live
  until its backward).
- **1F1B** (:func:`pipeline_train_step_1f1b`): a hand-rolled
  one-forward-one-backward schedule with activation-checkpointed
  backward.  Stage ``s`` runs the forward of microbatch ``m`` at global
  tick ``s + 2m`` and its backward at tick ``2S - 1 - s + 2m``; the two
  families land on opposite tick parities per stage, both the forward
  activation and the backward cotangent arrive exactly one tick after
  they are sent (one ``ppermute`` per rail per tick), and the whole
  schedule closes in the canonical ``2(M + S - 1)`` ticks — the same
  bubble fraction as GPipe, ``(S-1)/(M+S-1)``.  The win is MEMORY: each
  stage stashes only its in-flight microbatch inputs (``<= S - s``
  slots, a ring buffer of ``min(S, M)``) instead of all ``M``, so
  ``num_micro`` can scale without activation memory scaling with it
  (measured in ``benchmarks/pipeline_bench.py``).

Non-shape-preserving embed/head stages: the pipeline carries ONE static
inter-stage activation shape (SPMD: all stages execute the same program),
so token->embedding and head->loss live at the rim: embed the raw
microbatches BEFORE injection (``input_grads`` from the 1F1B step give
the cotangents to continue into the embed's backward), and fold the head
into ``loss_fn(head_params, y, target)``, whose parameter gradients the
1F1B step accumulates alongside the stage gradients.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

from bluefog_tpu.metrics import comm as _mt

__all__ = [
    "stack_stage_params",
    "stage_param_specs",
    "pipeline_apply",
    "pipeline_spmd_axis_perm",
    "pipeline_train_step_1f1b",
    "pipeline_train_step_gpipe",
]


def stack_stage_params(per_layer_params, num_stages: int):
    """Regroup a tree of per-layer-stacked arrays (leading dim ``L``) into
    per-stage blocks (leading dim ``num_stages``, second dim ``L //
    num_stages``), ready to shard over the ``pp`` axis with
    ``P('pp', ...)``."""

    def regroup(leaf):
        L = leaf.shape[0]
        if L % num_stages:
            raise ValueError(f"{L} layers not divisible by {num_stages} stages")
        return leaf.reshape((num_stages, L // num_stages) + leaf.shape[1:])

    return jax.tree_util.tree_map(regroup, per_layer_params)


def stage_param_specs(rule_table, stacked_params, *, pp_axis: str = "pp"):
    """Resolve a :func:`stack_stage_params` tree's placement through the
    unified :class:`~bluefog_tpu.sharding.RuleTable` — the pipeline's
    specs come from the SAME table as everything else, not a hand-placed
    ``P('pp', ...)`` per call site.

    Each leaf's leading stage dim is sharded over ``pp_axis``; the
    remaining dims resolve through the table by leaf path (so a
    tensor-sharded kernel inside a stage gets ``P('pp', ..., 'tp')``
    from one rule).  The table's rule is matched against the leaf's
    WITHIN-STAGE shape (leading ``(stages, layers-per-stage)`` dims
    stripped), which is what the rule grammar names."""
    from jax.sharding import PartitionSpec as P

    from bluefog_tpu.sharding.rules import named_tree_map

    def spec_of(name, leaf):
        inner_shape = tuple(int(s) for s in leaf.shape[2:])
        inner = rule_table.resolve(name, inner_shape)
        # stage dim over pp, the per-stage layer dim replicated
        return P(pp_axis, None, *tuple(inner))

    return named_tree_map(spec_of, stacked_params)


def pipeline_spmd_axis_perm(num_stages: int):
    """The stage-to-next-stage edge list for ``lax.ppermute`` (linear, not a
    ring: the last stage's output falls off the end by design)."""
    return [(i, i + 1) for i in range(num_stages - 1)]


def pipeline_apply(
    stage_fn: Callable,
    stage_params,
    microbatches,
    *,
    pp_axis: str = "pp",
    num_stages: int,
    num_micro: Optional[int] = None,
):
    """Run microbatches through the pipeline; call inside ``shard_map``.

    Args:
      stage_fn: ``(stage_params_local, activation) -> activation`` — this
        rank's block of layers.  Activation shape must be preserved (as in
        GPipe); wrap embed/head layers outside the pipeline or fold them into
        stage 0 / stage -1 via ``lax.cond``-free selects.
      stage_params: this rank's slice of :func:`stack_stage_params` output
        (leading dim ``L // num_stages``).
      microbatches: ``(num_micro, micro_batch, ...)`` — the full input,
        present (replicated or sharded upstream) on every stage; only stage
        0 reads it.
      num_stages: static size of the ``pp`` axis.
      num_micro: defaults to ``microbatches.shape[0]``.

    Returns:
      ``(num_micro, micro_batch, ...)`` outputs, valid on the **last** stage
      (other stages hold garbage of the right shape — psum/collect it out
      yourself, or use the loss pattern in tests/test_pipeline.py).
    """
    if num_micro is None:
        num_micro = microbatches.shape[0]
    stage = lax.axis_index(pp_axis)
    total_ticks = num_micro + num_stages - 1
    perm = pipeline_spmd_axis_perm(num_stages)

    act0 = jnp.zeros_like(microbatches[0])

    def tick(carry, t):
        incoming = carry
        # stage 0 injects microbatch t (clamped: reads garbage past the end,
        # discarded by the output select below)
        inject = microbatches[jnp.minimum(t, num_micro - 1)]
        x = jnp.where(stage == 0, inject.astype(act0.dtype), incoming)
        y = stage_fn(stage_params, x)
        # hand to the next stage; stage 0 receives zeros (overwritten above)
        nxt = lax.ppermute(y, pp_axis, perm)
        return nxt, y

    _, ys = lax.scan(tick, act0, jnp.arange(total_ticks))

    # last stage emitted microbatch m at tick m + num_stages - 1
    out = lax.dynamic_slice_in_dim(ys, num_stages - 1, num_micro, axis=0)
    return out


def pipeline_train_step_1f1b(
    stage_fn: Callable,
    stage_params,
    microbatches,
    targets,
    loss_fn: Callable,
    *,
    pp_axis: str = "pp",
    num_stages: int,
    head_params=None,
    collect_input_grads: bool = False,
):
    """One 1F1B training step; call inside ``shard_map``.

    Schedule (see the module docstring): stage ``s`` forwards microbatch
    ``m`` at tick ``s + 2m`` and backwards it at ``2S - 1 - s + 2m``; the
    backward RECOMPUTES the stage forward from the stashed input
    (activation checkpointing — the standard 1F1B memory discipline), so
    per-stage stash is a ``min(S, M)``-slot ring of microbatch inputs
    rather than GPipe's all-``M`` activation tape.

    Args:
      stage_fn: ``(stage_params_local, activation) -> activation`` (shape-
        preserving, as in :func:`pipeline_apply`).
      stage_params: this rank's stage block.
      microbatches: ``(M, micro_batch, ...)`` INTER-STAGE-shaped inputs —
        already embedded if the model has a token embedding (see
        ``collect_input_grads``).
      targets: ``(M, ...)`` per-microbatch loss targets (consumed by the
        last stage only).
      loss_fn: ``(head_params, y, target) -> scalar`` — the model head
        folded into the loss.  Runs only on the LAST stage, selected by a
        runtime ``lax.cond`` (non-last stages take the identity branch;
        see the inline comment in the backward tick for why
        masked-everywhere evaluation was rejected), so only the last
        stage's value and gradients are ever computed or accumulated.
      head_params: parameters of ``loss_fn``'s head; ``None`` for a bare
        loss.
      collect_input_grads: also return ``(M, ...)`` cotangents of the
        microbatch inputs (valid on stage 0) — chain these into the
        embedding's backward outside the pipeline.

    Returns:
      ``(loss_sum, stage_grads, head_grads, input_grads)`` — all LOCAL to
      this stage: ``loss_sum``/``head_grads`` are nonzero on the last
      stage, ``input_grads`` (or ``None``) on stage 0, ``stage_grads``
      are this stage's own block gradients.  Not psum'd: per-stage
      ownership is the natural sharding for the optimizer step.
    """
    S = num_stages
    M = microbatches.shape[0]
    # the schedule's idle fraction is a static property of (S, M): export
    # it at trace time so capacity planning sees how much of the pipeline
    # budget microbatching actually recovers (no-op when metrics are off)
    _mt.set("bf_pipeline_bubble_fraction", (S - 1) / (M + S - 1),
            schedule="1f1b", stages=S, micro=M)
    K = min(S, M)  # stash depth: stage s holds <= S - s in-flight micros
    stage = lax.axis_index(pp_axis)
    is_last = stage == S - 1
    total_ticks = 2 * (M + S - 1)
    fwd_perm = pipeline_spmd_axis_perm(S)
    bwd_perm = [(i, i - 1) for i in range(1, S)]
    if head_params is None:
        head_params = {}

    act0 = jnp.zeros_like(microbatches[0])
    f32 = lambda t: jnp.zeros(jnp.shape(t), jnp.float32)
    g_acc0 = jax.tree_util.tree_map(f32, stage_params)
    h_acc0 = jax.tree_util.tree_map(f32, head_params)
    dx_buf0 = (jnp.zeros_like(microbatches) if collect_input_grads else
               jnp.zeros((), act0.dtype))

    def tick(carry, t):
        fwd_msg, bwd_msg, stash, g_acc, h_acc, loss_acc, dx_buf = carry

        # Each tick is on exactly ONE rail for a given stage (the two
        # families have opposite tick parities), so a real runtime
        # conditional — lax.cond on the scalar per-device predicate, not a
        # both-branches select — runs one stage_fn application on forward
        # ticks and one recompute+vjp on backward ticks.  Without it every
        # tick would execute both rails and the schedule would cost 2x
        # GPipe's compute.
        diff_f = t - stage
        on_fwd_rail = diff_f % 2 == 0
        is_f = (diff_f >= 0) & on_fwd_rail & (diff_f // 2 < M)
        m_f = jnp.clip(diff_f // 2, 0, M - 1)
        diff_b = t - (2 * S - 1 - stage)
        is_b = (diff_b >= 0) & (diff_b % 2 == 0) & (diff_b // 2 < M)
        m_b = jnp.clip(diff_b // 2, 0, M - 1)

        zero_g = lambda tree: jax.tree_util.tree_map(
            lambda r: jnp.zeros(jnp.shape(r), jnp.asarray(r).dtype), tree)

        def fwd_branch(stash):
            inject = lax.dynamic_index_in_dim(microbatches, m_f, 0,
                                              keepdims=False)
            x_in = jnp.where(stage == 0, inject.astype(act0.dtype), fwd_msg)
            y = stage_fn(stage_params, x_in)
            stash = jnp.where(
                is_f,
                lax.dynamic_update_index_in_dim(stash, x_in, m_f % K, 0),
                stash)
            return (y, jnp.zeros_like(act0), stash,
                    zero_g(stage_params), zero_g(head_params),
                    jnp.zeros((), jnp.float32))

        def bwd_branch(stash):
            x_saved = lax.dynamic_index_in_dim(stash, m_b % K, 0,
                                               keepdims=False)
            yb, vjp_fn = jax.vjp(stage_fn, stage_params, x_saved)
            tgt = lax.dynamic_index_in_dim(targets, m_b, 0, keepdims=False)
            # last stage seeds the cotangent from the loss; others use the
            # message from stage s+1.  The head runs ONLY on the last
            # stage (nested runtime cond): with an LM-sized head its
            # forward+backward rivals a thin stage's flops, so evaluating
            # it masked on every stage would waste (S-1)x that compute.
            def head_branch(yb):
                loss_m, (dh, dy_loss) = jax.value_and_grad(
                    loss_fn, argnums=(0, 1))(head_params, yb, tgt)
                return (loss_m.astype(jnp.float32), dh,
                        dy_loss.astype(act0.dtype))

            def no_head(yb):
                return (jnp.zeros((), jnp.float32), zero_g(head_params),
                        bwd_msg)

            loss_m, dh, dy = lax.cond(is_last, head_branch, no_head, yb)
            dp, dx = vjp_fn(dy)
            return (jnp.zeros_like(act0), dx, stash, dp, dh, loss_m)

        y, dx, stash, dp, dh, loss_m = lax.cond(
            on_fwd_rail, fwd_branch, bwd_branch, stash)

        take_b = is_b
        take_h = is_b & is_last
        g_acc = jax.tree_util.tree_map(
            lambda a, g: a + jnp.where(take_b, g.astype(jnp.float32), 0.0),
            g_acc, dp)
        h_acc = jax.tree_util.tree_map(
            lambda a, g: a + jnp.where(take_h, g.astype(jnp.float32), 0.0),
            h_acc, dh)
        loss_acc = loss_acc + jnp.where(take_h, loss_m, 0.0)
        if collect_input_grads:
            dx_buf = jnp.where(
                take_b & (stage == 0),
                lax.dynamic_update_index_in_dim(dx_buf, dx, m_b, 0),
                dx_buf)
        fwd_out = lax.ppermute(y, pp_axis, fwd_perm)
        bwd_out = lax.ppermute(dx, pp_axis, bwd_perm)

        return ((fwd_out, bwd_out, stash, g_acc, h_acc, loss_acc, dx_buf),
                None)

    carry0 = (act0, act0, jnp.zeros((K,) + act0.shape, act0.dtype),
              g_acc0, h_acc0, jnp.zeros((), jnp.float32), dx_buf0)
    (_, _, _, g_acc, h_acc, loss_acc, dx_buf), _ = lax.scan(
        tick, carry0, jnp.arange(total_ticks))

    cast = lambda acc, ref: jax.tree_util.tree_map(
        lambda a, r: a.astype(jnp.asarray(r).dtype), acc, ref)
    return (loss_acc, cast(g_acc, stage_params), cast(h_acc, head_params),
            dx_buf if collect_input_grads else None)


def pipeline_train_step_gpipe(
    stage_fn: Callable,
    stage_params,
    microbatches,
    targets,
    loss_fn: Callable,
    *,
    pp_axis: str = "pp",
    num_stages: int,
    head_params=None,
    collect_input_grads: bool = False,
    remat: bool = False,
):
    """GPipe counterpart of :func:`pipeline_train_step_1f1b` — same
    signature and return contract, backward via ``jax.grad`` through the
    forward scan (optionally with ``jax.checkpoint`` on ``stage_fn``:
    recompute-in-backward like 1F1B, but still an all-``M`` stash of
    STAGE INPUTS in the scan's saved residuals)."""
    S = num_stages
    M = microbatches.shape[0]
    _mt.set("bf_pipeline_bubble_fraction", (S - 1) / (M + S - 1),
            schedule="gpipe", stages=S, micro=M)
    if head_params is None:
        head_params = {}
    sfn = jax.checkpoint(stage_fn) if remat else stage_fn
    is_last = lax.axis_index(pp_axis) == S - 1

    def local_loss(stage_params, head_params, microbatches):
        outs = pipeline_apply(sfn, stage_params, microbatches,
                              pp_axis=pp_axis, num_stages=S)
        losses = jax.vmap(loss_fn, in_axes=(None, 0, 0))(head_params, outs,
                                                         targets)
        # masked LOCAL loss: non-last stages contribute 0; the last
        # stage's gradient flows back through the ppermute transposes
        return jnp.sum(jnp.where(is_last, losses.astype(jnp.float32), 0.0))

    if collect_input_grads:
        loss, (g, h, dxs) = jax.value_and_grad(local_loss, argnums=(0, 1, 2))(
            stage_params, head_params, microbatches)
    else:
        # don't differentiate wrt the inputs when unused: the (M, ...)
        # cotangent buffer would inflate temp memory for nothing
        loss, (g, h) = jax.value_and_grad(local_loss, argnums=(0, 1))(
            stage_params, head_params, microbatches)
        dxs = None
    return loss, g, h, dxs
