"""Pipeline parallelism — GPipe-style microbatch pipelining over a mesh axis.

No counterpart exists in the reference (SURVEY.md §2.3: PP absent).  The TPU
build adds it as the third mesh level: ``('bf', 'pp', 'tp')`` — gossip-DP
outermost, pipeline stages in the middle, tensor parallel innermost.

Design (TPU-first, the scaling-book shard_map pipelining recipe):

- Each ``pp`` rank holds the parameters of its contiguous block of layers
  (``stack_stage_params`` shards a per-layer-stacked tree over the axis).
- One jitted ``lax.scan`` runs ``num_micro + pp - 1`` ticks; each tick every
  stage applies its block to the activation it holds and hands the result to
  the next stage with a single ``lax.ppermute`` hop (nearest-neighbor on
  ICI).  No data-dependent control flow — stage 0's input injection and the
  last stage's output collection are ``jnp.where`` selects on the tick index,
  so XLA compiles one static program.
- Backward is plain ``jax.grad`` through the scan: ``ppermute`` transposes to
  the reverse permute, giving the mirrored backward pipeline for free — no
  hand-written 1F1B schedule.  Combine with ``jax.checkpoint`` on ``stage_fn``
  to keep activation memory at GPipe levels.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

__all__ = [
    "stack_stage_params",
    "pipeline_apply",
    "pipeline_spmd_axis_perm",
]


def stack_stage_params(per_layer_params, num_stages: int):
    """Regroup a tree of per-layer-stacked arrays (leading dim ``L``) into
    per-stage blocks (leading dim ``num_stages``, second dim ``L //
    num_stages``), ready to shard over the ``pp`` axis with
    ``P('pp', ...)``."""

    def regroup(leaf):
        L = leaf.shape[0]
        if L % num_stages:
            raise ValueError(f"{L} layers not divisible by {num_stages} stages")
        return leaf.reshape((num_stages, L // num_stages) + leaf.shape[1:])

    return jax.tree_util.tree_map(regroup, per_layer_params)


def pipeline_spmd_axis_perm(num_stages: int):
    """The stage-to-next-stage edge list for ``lax.ppermute`` (linear, not a
    ring: the last stage's output falls off the end by design)."""
    return [(i, i + 1) for i in range(num_stages - 1)]


def pipeline_apply(
    stage_fn: Callable,
    stage_params,
    microbatches,
    *,
    pp_axis: str = "pp",
    num_stages: int,
    num_micro: Optional[int] = None,
):
    """Run microbatches through the pipeline; call inside ``shard_map``.

    Args:
      stage_fn: ``(stage_params_local, activation) -> activation`` — this
        rank's block of layers.  Activation shape must be preserved (as in
        GPipe); wrap embed/head layers outside the pipeline or fold them into
        stage 0 / stage -1 via ``lax.cond``-free selects.
      stage_params: this rank's slice of :func:`stack_stage_params` output
        (leading dim ``L // num_stages``).
      microbatches: ``(num_micro, micro_batch, ...)`` — the full input,
        present (replicated or sharded upstream) on every stage; only stage
        0 reads it.
      num_stages: static size of the ``pp`` axis.
      num_micro: defaults to ``microbatches.shape[0]``.

    Returns:
      ``(num_micro, micro_batch, ...)`` outputs, valid on the **last** stage
      (other stages hold garbage of the right shape — psum/collect it out
      yourself, or use the loss pattern in tests/test_pipeline.py).
    """
    if num_micro is None:
        num_micro = microbatches.shape[0]
    stage = lax.axis_index(pp_axis)
    total_ticks = num_micro + num_stages - 1
    perm = pipeline_spmd_axis_perm(num_stages)

    act0 = jnp.zeros_like(microbatches[0])

    def tick(carry, t):
        incoming = carry
        # stage 0 injects microbatch t (clamped: reads garbage past the end,
        # discarded by the output select below)
        inject = microbatches[jnp.minimum(t, num_micro - 1)]
        x = jnp.where(stage == 0, inject.astype(act0.dtype), incoming)
        y = stage_fn(stage_params, x)
        # hand to the next stage; stage 0 receives zeros (overwritten above)
        nxt = lax.ppermute(y, pp_axis, perm)
        return nxt, y

    _, ys = lax.scan(tick, act0, jnp.arange(total_ticks))

    # last stage emitted microbatch m at tick m + num_stages - 1
    out = lax.dynamic_slice_in_dim(ys, num_stages - 1, num_micro, axis=0)
    return out
