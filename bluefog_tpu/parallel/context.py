"""Process-wide framework context: mesh, topology, schedules, windows.

Reference parity (upstream-relative): ``bluefog/common/basics.py``
(``BlueFogBasics``: init/shutdown/rank/size/local_rank/set_topology/...) and
``bluefog/common/global_state.h``.  What the reference does with
``MPI_Init_thread`` + a background engine thread, the TPU build does by
constructing a ``jax.sharding.Mesh`` over the (ICI-ordered) devices — there is
no engine thread because XLA's async dispatch plays that role (SURVEY.md §7).

SPMD semantics note: the reference is one-process-per-rank, so ``bf.rank()``
identifies the calling process.  Under a single JAX controller every gossip
rank lives in the same process; ``rank()`` therefore refers to *mesh
positions*: host-level code passes an explicit rank to neighbor queries, and
device-level code uses ``lax.axis_index(ctx.axis_name)``.  In multi-controller
deployments (``jax.distributed``), ``process_rank()`` exposes the controller
index like the reference's ``rank()``.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from bluefog_tpu.topology.graphs import ExponentialTwoGraph, Topology
from bluefog_tpu.topology.mapping import ici_ring_order
from bluefog_tpu.topology.schedule import GossipSchedule, build_schedule
from bluefog_tpu.utils import log

__all__ = [
    "BluefogContext",
    "init",
    "shutdown",
    "initialized",
    "get_context",
    "size",
    "rank",
    "local_size",
    "local_rank",
    "machine_size",
    "machine_rank",
    "process_rank",
    "set_topology",
    "load_topology",
    "set_machine_topology",
    "load_machine_topology",
    "in_neighbor_ranks",
    "out_neighbor_ranks",
    "in_neighbor_machine_ranks",
    "out_neighbor_machine_ranks",
]


@dataclasses.dataclass
class BluefogContext:
    """Everything the framework holds between calls."""

    mesh: Any  # jax.sharding.Mesh
    axis_name: str
    devices: List[Any]
    local_size: int
    topology: Topology
    schedule: GossipSchedule
    machine_topology: Optional[Topology] = None
    machine_schedule: Optional[GossipSchedule] = None
    dynamic_schedules: Optional[List[GossipSchedule]] = None
    windows: Dict[str, Any] = dataclasses.field(default_factory=dict)

    @property
    def size(self) -> int:
        return len(self.devices)

    @property
    def n_machines(self) -> int:
        return self.size // self.local_size

    @property
    def machine_axis_name(self) -> str:
        return self.axis_name + "_machine"

    @property
    def local_axis_name(self) -> str:
        return self.axis_name + "_local"

    @property
    def hier_mesh(self):
        """Two-level ``(machine, local)`` mesh over the same devices — the
        multi-slice deployment form (outer axis rides DCN, inner axis each
        slice's ICI; reference analog: cross vs local MPI communicators,
        ``bluefog/common/mpi_context.cc``).  Built lazily; rank ``r`` sits at
        mesh position ``(r // local_size, r % local_size)``, so flat-mesh and
        two-level collectives agree rank-for-rank."""
        if self._hier_mesh is None:
            from jax.sharding import Mesh

            self._hier_mesh = Mesh(
                np.array(self.devices).reshape(self.n_machines, self.local_size),
                (self.machine_axis_name, self.local_axis_name),
            )
        return self._hier_mesh

    _hier_mesh: Any = None


_CTX: Optional[BluefogContext] = None


def init(
    *,
    topology: Optional[Topology] = None,
    machine_topology: Optional[Topology] = None,
    size: Optional[int] = None,
    local_size: Optional[int] = None,
    devices: Optional[Sequence[Any]] = None,
    axis_name: str = "bf",
    use_ici_order: bool = True,
) -> BluefogContext:
    """Initialize the framework (the reference's ``bf.init()``, SURVEY.md §3.1).

    Builds the gossip mesh over ``devices`` (default: all of
    ``jax.devices()``, snake-ordered along ICI so ring edges are physical
    hops), installs the default ``ExponentialTwoGraph`` topology exactly as
    the reference does, and — when ``local_size > 1`` — a machine-level
    topology for hierarchical ops.

    Args:
      size: number of gossip ranks (default: all devices).
      local_size: devices per "machine" for hierarchical mode (default: JAX's
        ``local_device_count`` when running multi-process, else 1).
    """
    global _CTX
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    if use_ici_order:
        devices = ici_ring_order(devices)
    if size is not None:
        if size > len(devices):
            raise ValueError(f"size {size} exceeds available devices {len(devices)}")
        devices = devices[:size]
    n = len(devices)

    if local_size is None:
        local_size = jax.local_device_count() if jax.process_count() > 1 else 1
        if n % local_size != 0:
            local_size = 1
    if n % local_size != 0:
        raise ValueError(f"size {n} not divisible by local_size {local_size}")

    topo = topology if topology is not None else ExponentialTwoGraph(n)
    if topo.size != n:
        raise ValueError(f"topology size {topo.size} != mesh size {n}")

    n_machines = n // local_size
    mtopo = machine_topology
    if mtopo is None and n_machines > 1:
        mtopo = ExponentialTwoGraph(n_machines)

    mesh = Mesh(np.array(devices), (axis_name,))
    _CTX = BluefogContext(
        mesh=mesh,
        axis_name=axis_name,
        devices=devices,
        local_size=local_size,
        topology=topo,
        schedule=build_schedule(topo),
        machine_topology=mtopo,
        machine_schedule=build_schedule(mtopo) if mtopo is not None else None,
    )
    log.info(
        "bluefog_tpu.init: %d ranks (%d machines x %d local), topology=%s",
        n, n_machines, local_size, topo.name,
    )
    try:
        # arm the blackbox crash/hang dump triggers (excepthooks, fatal
        # signals, faulthandler, atexit-after-exception) at framework
        # bring-up — the watchdog path dumps on its own, but a rank dying
        # of an uncaught exception must leave its flight recorder behind
        # too.  No-op when BLUEFOG_TPU_BLACKBOX=0; idempotent.
        from bluefog_tpu import blackbox

        blackbox.install()
    except Exception:
        pass
    return _CTX


def shutdown() -> None:
    """Tear down the context (reference ``bf.shutdown()``)."""
    global _CTX
    _CTX = None


def initialized() -> bool:
    return _CTX is not None


def get_context() -> BluefogContext:
    if _CTX is None:
        raise RuntimeError("bluefog_tpu.init() has not been called")
    return _CTX


def size() -> int:
    return get_context().size


def rank(default: int = 0) -> int:
    """Mesh-rank of this controller's first device (see module docstring for
    SPMD semantics; use ``lax.axis_index`` inside device code)."""
    import jax

    ctx = get_context()
    if jax.process_count() > 1:
        first_local = [d for d in ctx.devices if d.process_index == jax.process_index()]
        if first_local:
            return ctx.devices.index(first_local[0])
    return default


def process_rank() -> int:
    import jax

    return jax.process_index()


def local_size() -> int:
    return get_context().local_size


def local_rank(rank_: Optional[int] = None) -> int:
    r = rank() if rank_ is None else rank_
    return r % get_context().local_size


def machine_size() -> int:
    return get_context().n_machines


def machine_rank(rank_: Optional[int] = None) -> int:
    r = rank() if rank_ is None else rank_
    return r // get_context().local_size


def set_topology(topology: Optional[Topology] = None, is_weighted: bool = True) -> bool:
    """Install a new virtual topology and recompile the gossip schedule
    (reference ``bf.set_topology`` — which rebuilds the MPI dist-graph
    communicator; here we rebuild the ppermute schedule).

    ``is_weighted=False`` mirrors the upstream flag: the topology's weights are
    replaced by uniform ``1/(in_degree+1)`` rows.
    """
    ctx = get_context()
    topo = topology if topology is not None else ExponentialTwoGraph(ctx.size)
    if hasattr(topo, "number_of_nodes"):  # networkx interop
        topo = Topology.from_networkx(topo)
    if topo.size != ctx.size:
        raise ValueError(f"topology size {topo.size} != mesh size {ctx.size}")
    if not is_weighted:
        topo = Topology.from_edges(topo.size, topo.edges, name=topo.name)
    if ctx.windows:
        log.warn("set_topology with %d live windows: window schedules keep the "
                 "topology they were created with", len(ctx.windows))
    ctx.topology = topo
    ctx.schedule = build_schedule(topo)
    ctx.dynamic_schedules = None
    return True


def load_topology() -> Topology:
    """Reference ``bf.load_topology()``."""
    return get_context().topology


def set_machine_topology(topology: Topology, is_weighted: bool = True) -> bool:
    """Machine-level analog for hierarchical ops (upstream
    ``set_machine_topology``)."""
    ctx = get_context()
    if topology.size != ctx.n_machines:
        raise ValueError(
            f"machine topology size {topology.size} != n_machines {ctx.n_machines}"
        )
    if not is_weighted:
        topology = Topology.from_edges(topology.size, topology.edges, name=topology.name)
    ctx.machine_topology = topology
    ctx.machine_schedule = build_schedule(topology)
    return True


def load_machine_topology() -> Optional[Topology]:
    return get_context().machine_topology


def in_neighbor_ranks(rank_: Optional[int] = None) -> List[int]:
    r = rank() if rank_ is None else rank_
    return get_context().topology.in_neighbors(r)


def out_neighbor_ranks(rank_: Optional[int] = None) -> List[int]:
    r = rank() if rank_ is None else rank_
    return get_context().topology.out_neighbors(r)


def in_neighbor_machine_ranks(machine_rank_: Optional[int] = None) -> List[int]:
    ctx = get_context()
    if ctx.machine_topology is None:
        return []
    m = machine_rank() if machine_rank_ is None else machine_rank_
    return ctx.machine_topology.in_neighbors(m)


def out_neighbor_machine_ranks(machine_rank_: Optional[int] = None) -> List[int]:
    ctx = get_context()
    if ctx.machine_topology is None:
        return []
    m = machine_rank() if machine_rank_ is None else machine_rank_
    return ctx.machine_topology.out_neighbors(m)
