"""Tensor (model) parallelism — a second mesh level under the gossip axis.

No counterpart exists in the reference (SURVEY.md §2.3: TP absent — Bluefog is
a pure data-parallel library).  The TPU build adds it because decentralized
DP composes naturally with intra-rank model sharding on a 2-level mesh: the
outer ``'bf'`` axis carries gossip (``neighbor_allreduce`` / window ops over
ICI ring hops), the inner ``'tp'`` axis shards each rank's model Megatron-
style (column-parallel then row-parallel matmuls, heads sharded for
attention).  Sequence parallelism (``bluefog_tpu.ops.ring_attention``) rides a
third axis the same way.

Design notes (TPU-first):

- Everything runs inside one ``shard_map`` over the hybrid mesh, so XLA
  schedules the tp-axis ``psum`` (ICI nearest-neighbor ring, innermost mesh
  axis = closest chips) together with the gossip permutes.
- Parameters are flax ``nn.Partitioned`` boxes (``manual_partitioning``);
  the axis names double as the source of truth for the gradient correction
  (below) and for ``gather_tp_params`` at checkpoint/eval time.
- **Gradient correction**: the repo's train steps call ``jax.grad`` *inside*
  ``shard_map`` (per-rank losses — required for decentralized DP, where ranks
  hold different parameters).  In that regime XLA transposes the row-parallel
  forward ``psum`` into a backward ``psum``, so w.r.t. a tp-sharded leaf the
  raw gradient is ``tp_size ×`` the true one, while a replicated leaf's raw
  gradient sees only the local shard's path.  The exact fix (verified
  numerically in tests/test_tensor_parallel.py) is::

      sharded leaf:    g / tp_size
      replicated leaf: pmean(g, tp_axis)

  which :func:`tp_value_and_grad` applies automatically from the partitioning
  metadata.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Mapping, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from flax.core import meta as flax_meta
from jax import lax

from bluefog_tpu.models.transformer import GPTConfig
from bluefog_tpu.ops.ring_attention import local_attention
from bluefog_tpu.parallel.rng import fold_axis_rng, sharded_init
from bluefog_tpu.topology.mapping import ici_ring_order

__all__ = [
    "make_hybrid_mesh",
    "fold_axis_rng",
    "column_parallel_dense",
    "row_parallel_dense",
    "ColumnParallelDense",
    "RowParallelDense",
    "TPBlock",
    "TPTransformerLM",
    "tp_value_and_grad",
    "tp_correct_grads",
    "tp_param_rules",
    "box_specs",
    "check_rule_agreement",
    "PartitionDisagreement",
    "gather_tp_params",
    "unbox_params",
]


def make_hybrid_mesh(axes: Mapping[str, int], *, devices=None,
                     use_ici_order: bool = True):
    """Build a multi-axis ``jax.sharding.Mesh`` from ``{name: size}`` pairs.

    Axis order is the dict's insertion order, **outermost first** — put the
    gossip axis (``'bf'``) first and the tensor axis (``'tp'``) last so tp
    collectives land on nearest-neighbor ICI links (the device list is
    snake-ordered along ICI, and the innermost mesh axis gets consecutive
    devices).

    Example::

        mesh = make_hybrid_mesh({"bf": 4, "tp": 2})
        # 4 gossip ranks x 2-way tensor parallel over 8 chips
    """
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    if use_ici_order:
        devices = ici_ring_order(devices)
    names = tuple(axes.keys())
    sizes = tuple(int(axes[n]) for n in names)
    need = int(np.prod(sizes))
    if need > len(devices):
        raise ValueError(f"mesh {dict(axes)} needs {need} devices, "
                         f"have {len(devices)}")
    return Mesh(np.array(devices[:need]).reshape(sizes), names)




def _tp_size(tp_axis: str):
    return lax.psum(1, tp_axis)


# ---------------------------------------------------------------------------
# Functional primitives (use inside shard_map; arrays are local shards)
# ---------------------------------------------------------------------------


def column_parallel_dense(x, kernel, bias=None, *, tp_axis: str = "tp",
                          gather_output: bool = False):
    """``y_local = x @ kernel_local`` with the **output** feature dim sharded.

    No forward collective; the backward pass psums the input gradient.  With
    ``gather_output`` the shards are all-gathered onto the last dim (use only
    at boundaries — the point of Megatron pairing is to stay sharded until
    the matching row-parallel layer).
    """
    y = x @ kernel
    if bias is not None:
        y = y + bias
    if gather_output:
        y = lax.all_gather(y, tp_axis, axis=y.ndim - 1, tiled=True)
    return y


def row_parallel_dense(x, kernel, bias=None, *, tp_axis: str = "tp"):
    """``y = psum_tp(x_local @ kernel_local)`` with the **input** feature dim
    sharded (x is the sharded output of a column-parallel layer).  Bias is
    added once, after the reduction."""
    y = lax.psum(x @ kernel, tp_axis)
    if bias is not None:
        y = y + bias
    return y


# ---------------------------------------------------------------------------
# Flax modules
# ---------------------------------------------------------------------------


class ManualPartitioned(flax_meta.Partitioned):
    """``nn.Partitioned`` whose unbox skips the sharding constraint.

    Under a Manual (``shard_map``) mesh the arrays *are* the local shards —
    ``with_sharding_constraint`` is both illegal and meaningless there, but
    stock ``Partitioned.unbox`` inserts one whenever a global/abstract mesh
    is defined.  The ``names`` metadata is kept purely as the source of truth
    for :func:`tp_correct_grads` / :func:`gather_tp_params`.
    """

    def unbox(self, apply_constraint=True):
        del apply_constraint
        return self.value


def manual_partitioning(fn, names):
    """``manual_partitioning`` variant producing :class:`ManualPartitioned`
    boxes (for params created inside ``shard_map``)."""

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        return ManualPartitioned(fn(*args, **kwargs), names)

    return wrapper




class ColumnParallelDense(nn.Module):
    """Dense with output features sharded over ``tp_axis``.

    ``features`` is the **global** feature count; each shard holds
    ``features // tp_size`` columns, annotated ``nn.Partitioned`` on the
    output dim.
    """

    features: int
    tp_size: int
    tp_axis: str = "tp"
    use_bias: bool = True
    gather_output: bool = False
    dtype: Any = jnp.bfloat16
    kernel_init: Callable = nn.initializers.lecun_normal()

    @nn.compact
    def __call__(self, x):
        if self.features % self.tp_size:
            raise ValueError(f"features {self.features} % tp {self.tp_size}")
        local = self.features // self.tp_size
        kernel = self.param(
            "kernel",
            manual_partitioning(sharded_init(self.kernel_init, self.tp_axis),
                                 (None, self.tp_axis)),
            (x.shape[-1], local), jnp.float32)
        bias = None
        if self.use_bias:
            bias = self.param(
                "bias",
                manual_partitioning(nn.initializers.zeros, (self.tp_axis,)),
                (local,), jnp.float32)
            bias = bias.astype(self.dtype)
        return column_parallel_dense(
            x.astype(self.dtype), kernel.astype(self.dtype), bias,
            tp_axis=self.tp_axis, gather_output=self.gather_output)


class RowParallelDense(nn.Module):
    """Dense with input features sharded over ``tp_axis`` (the Megatron pair
    of :class:`ColumnParallelDense`); output is psum-reduced and replicated."""

    features: int
    tp_size: int
    tp_axis: str = "tp"
    use_bias: bool = True
    dtype: Any = jnp.bfloat16
    kernel_init: Callable = nn.initializers.lecun_normal()

    @nn.compact
    def __call__(self, x):
        kernel = self.param(
            "kernel",
            manual_partitioning(sharded_init(self.kernel_init, self.tp_axis),
                                 (self.tp_axis, None)),
            (x.shape[-1], self.features), jnp.float32)
        y = row_parallel_dense(x.astype(self.dtype), kernel.astype(self.dtype),
                               tp_axis=self.tp_axis)
        if self.use_bias:
            bias = self.param("bias", nn.initializers.zeros, (self.features,),
                              jnp.float32)
            y = y + bias.astype(self.dtype)
        return y


class TPBlock(nn.Module):
    """Megatron-style tensor-parallel transformer block: attention heads and
    MLP hidden dim sharded over ``tp_axis``; one psum per sublayer.  The
    attention core is pluggable exactly like :class:`~bluefog_tpu.models.
    transformer.Block`, so sequence parallelism (ring / Ulysses over another
    mesh axis) composes with TP."""

    cfg: GPTConfig
    tp_size: int
    tp_axis: str = "tp"

    @nn.compact
    def __call__(self, x, attn_fn):
        cfg = self.cfg
        if cfg.num_heads % self.tp_size:
            raise ValueError(f"heads {cfg.num_heads} % tp {self.tp_size}")
        local_heads = cfg.num_heads // self.tp_size
        head_dim = cfg.hidden_size // cfg.num_heads

        y = nn.LayerNorm(dtype=jnp.float32, name="ln1")(x).astype(cfg.dtype)
        # Fused qkv as an (in, 3, local) kernel sharded on the LAST dim — a
        # flat (in, 3H/tp) column-parallel shard would interleave q/k/v chunks
        # across ranks and not survive gather_tp_params with the right
        # correspondence.
        local = local_heads * head_dim
        qkv_kernel = self.param(
            "qkv_kernel",
            manual_partitioning(
                sharded_init(nn.initializers.lecun_normal(in_axis=0, out_axis=(1, 2)),
                              self.tp_axis),
                (None, None, self.tp_axis)),
            (cfg.hidden_size, 3, local), jnp.float32)
        qkv_bias = self.param(
            "qkv_bias",
            manual_partitioning(nn.initializers.zeros, (None, self.tp_axis)),
            (3, local), jnp.float32)
        qkv = (jnp.einsum("...i,ijk->...jk", y, qkv_kernel.astype(cfg.dtype))
               + qkv_bias.astype(cfg.dtype))
        q, k, v = qkv[..., 0, :], qkv[..., 1, :], qkv[..., 2, :]

        def heads(t):
            return t.reshape(t.shape[:-1] + (local_heads, head_dim))

        a = attn_fn(heads(q), heads(k), heads(v))
        a = a.reshape(a.shape[:-2] + (local_heads * head_dim,))
        x = x + RowParallelDense(cfg.hidden_size, self.tp_size,
                                 tp_axis=self.tp_axis, dtype=cfg.dtype,
                                 name="proj")(a)

        y = nn.LayerNorm(dtype=jnp.float32, name="ln2")(x).astype(cfg.dtype)
        y = ColumnParallelDense(cfg.mlp_ratio * cfg.hidden_size, self.tp_size,
                                tp_axis=self.tp_axis, dtype=cfg.dtype,
                                name="up")(y)
        y = nn.gelu(y)
        return x + RowParallelDense(cfg.hidden_size, self.tp_size,
                                    tp_axis=self.tp_axis, dtype=cfg.dtype,
                                    name="down")(y)


class TPTransformerLM(nn.Module):
    """Tensor-parallel :class:`~bluefog_tpu.models.transformer.TransformerLM`.

    Embeddings, layernorms, and the LM head are replicated; every block is
    tensor-parallel.  Run inside ``shard_map`` over a mesh with ``tp_axis``;
    with ``tp_size=1`` it is numerically the full model (used by the parity
    tests, which gather a tp>1 model's shards and replay them at tp=1).
    """

    cfg: GPTConfig
    tp_size: int
    tp_axis: str = "tp"

    @nn.compact
    def __call__(self, tokens, *, attn_fn=None, position_offset=0):
        cfg = self.cfg
        if attn_fn is None:
            attn_fn = lambda q, k, v: local_attention(q, k, v, causal=True,
                                                      backend="auto")
        positions = position_offset + jnp.arange(tokens.shape[1])[None, :]
        x = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype,
                     name="tok")(tokens)
        x = x + nn.Embed(cfg.max_position, cfg.hidden_size, dtype=cfg.dtype,
                         name="pos")(positions)
        for i in range(cfg.num_layers):
            x = TPBlock(cfg, self.tp_size, tp_axis=self.tp_axis,
                        name=f"block_{i}")(x, attn_fn)
        x = nn.LayerNorm(dtype=jnp.float32, name="ln_f")(x)
        return nn.Dense(cfg.vocab_size, dtype=jnp.float32, use_bias=False,
                        name="lm_head")(x)


# ---------------------------------------------------------------------------
# Rule-table resolution (the unified sharding story)
# ---------------------------------------------------------------------------


def tp_param_rules(tp_axis: str = "tp"):
    """The default :class:`~bluefog_tpu.sharding.RuleTable` for
    :class:`TPTransformerLM`'s parameter naming — the ONE table the
    gossip stack, the optimizer state, and the window fabric resolve
    through.  Megatron placement: qkv/up column-sharded on the output
    feature dim, proj/down row-sharded on the input dim, everything else
    (embeddings, layernorms, head, row-parallel biases) replicated."""
    from jax.sharding import PartitionSpec as P

    from bluefog_tpu.sharding.rules import RuleTable

    return RuleTable([
        ("qkv_kernel$", P(None, None, tp_axis)),
        ("qkv_bias$", P(None, tp_axis)),
        (r"up/kernel$", P(None, tp_axis)),
        (r"up/bias$", P(tp_axis)),
        (r"(proj|down)/kernel$", P(tp_axis, None)),
        # explicit replicate tail: embeddings, layernorms, lm_head,
        # row-parallel biases — replication is a decision, not a leak
        (".*", P()),
    ])


def box_specs(template, tp_axis: str = "tp"):
    """The flax-metadata view of a boxed template: each leaf's
    ``nn.Partitioned`` axis names as a ``PartitionSpec`` (unboxed leaves
    -> replicated).  This is the LEGACY source of shardedness — use it
    only to compare against the rule table
    (:func:`check_rule_agreement`), never as the resolution path."""
    from jax.sharding import PartitionSpec as P

    def spec_of(leaf):
        if _is_box(leaf):
            return P(*leaf.names)
        return P()

    return jax.tree_util.tree_map(spec_of, template, is_leaf=_is_box)


class PartitionDisagreement(ValueError):
    """The flax box metadata and the rule table disagree on a leaf —
    the dual-source-of-truth hazard: the gradient correction would scale
    by one story while the wire shards by the other."""


def check_rule_agreement(template, rule_table, tp_axis: str = "tp"):
    """Compare every boxed leaf's ``nn.Partitioned`` names against the
    rule table's resolution; returns ``[(leaf_path, box_spec,
    table_spec)]`` for each disagreement.  Empty list = the two sources
    of truth agree (the state :func:`tp_value_and_grad` requires before
    it trusts the table)."""
    from bluefog_tpu.sharding.rules import named_leaves, norm_spec

    mismatches = []
    for name, leaf in named_leaves(template, is_leaf=_is_box):
        val = leaf.value if _is_box(leaf) else leaf
        shape = tuple(int(s) for s in np.shape(val))
        resolved = rule_table.resolve(name, shape)
        from jax.sharding import PartitionSpec as P

        boxed = P(*leaf.names) if _is_box(leaf) else P()
        if norm_spec(boxed) != norm_spec(resolved):
            mismatches.append((name, boxed, resolved))
    return mismatches


# ---------------------------------------------------------------------------
# Gradient correction + parameter gather
# ---------------------------------------------------------------------------


def _is_box(x) -> bool:
    return isinstance(x, nn.Partitioned)


def _box_mentions(box: nn.Partitioned, axis: str) -> bool:
    return axis in tuple(box.names)


def tp_correct_grads(grads, template, tp_axis: str = "tp", *,
                     rule_table=None):
    """Fix raw inside-``shard_map`` gradients of a tp-parallel model (see
    module docstring): sharded leaves ``/ tp_size``, replicated leaves
    ``pmean`` over ``tp_axis``.

    Shardedness is read from ``rule_table`` (the unified
    :class:`~bluefog_tpu.sharding.RuleTable` — the resolved specs are
    the single source of truth) when one is given; otherwise from
    ``template``'s ``nn.Partitioned`` boxes (the legacy metadata path).
    ``grads`` is the plain tree matching ``template``."""
    tp = _tp_size(tp_axis)

    if rule_table is not None:
        from bluefog_tpu.sharding.rules import named_tree_map, spec_mentions

        def fix_spec(name, box):
            leaf = box.value if _is_box(box) else box
            spec = rule_table.resolve(
                name, tuple(int(s) for s in np.shape(leaf)))
            return spec

        specs = named_tree_map(fix_spec, template, is_leaf=_is_box)

        def fix(spec, g):
            if spec_mentions(spec, tp_axis):
                return g / tp
            return lax.pmean(g, tp_axis)

        from jax.sharding import PartitionSpec as _P

        return jax.tree_util.tree_map(
            fix, specs, grads, is_leaf=lambda s: isinstance(s, _P))

    def fix(box, g):
        if _is_box(box) and _box_mentions(box, tp_axis):
            return g / tp
        return lax.pmean(g, tp_axis)

    return jax.tree_util.tree_map(fix, template, grads, is_leaf=_is_box)


def tp_value_and_grad(loss_fn, template, tp_axis: str = "tp", *,
                      rule_table=None):
    """``jax.value_and_grad`` drop-in for tensor-parallel models
    differentiated *inside* ``shard_map``: ``loss_fn`` takes a **plain**
    parameter tree (apply the model with plain arrays — flax's
    ``Partitioned.unbox`` inserts a ``with_sharding_constraint`` that is
    illegal under a Manual mesh), ``template`` is the boxed tree from
    ``model.init``.  Returns exact per-gossip-rank gradients (verified
    against a gathered single-shard reference in
    tests/test_tensor_parallel.py).

    ``rule_table``: resolve shardedness through the unified
    :class:`~bluefog_tpu.sharding.RuleTable` instead of the box
    metadata.  The two sources are compared ONCE, eagerly, at wrap time
    (:func:`check_rule_agreement`) and a disagreement raises
    :class:`PartitionDisagreement` — a box silently contradicting the
    table would make the gradient correction scale by one story while
    the wire shards by the other (the regression
    ``tests/test_sharding.py`` plants)."""

    if rule_table is not None:
        mismatches = check_rule_agreement(template, rule_table, tp_axis)
        if mismatches:
            lines = "; ".join(
                f"{name}: box={b} table={t}" for name, b, t in mismatches)
            raise PartitionDisagreement(
                "nn.Partitioned metadata disagrees with the rule table "
                f"on {len(mismatches)} leaf(s): {lines} — fix the rule "
                "or the module annotation; the table is the single "
                "source of truth")

    vag = jax.value_and_grad(loss_fn)

    def wrapped(params, *args, **kwargs):
        if any(_is_box(l) for l in jax.tree_util.tree_leaves(
                params, is_leaf=_is_box)):
            params = unbox_params(params)
        loss, grads = vag(params, *args, **kwargs)
        return loss, tp_correct_grads(grads, template, tp_axis,
                                      rule_table=rule_table)

    return wrapped


def unbox_params(params):
    """Strip ``nn.Partitioned`` boxes, keeping raw arrays."""
    return jax.tree_util.tree_map(
        lambda x: x.value if _is_box(x) else x, params, is_leaf=_is_box)


def gather_tp_params(params, tp_axis: str = "tp", template=None):
    """All-gather every tp-sharded leaf back to its full (unsharded) array
    and strip the boxes — for checkpointing one consolidated model, eval on
    fewer chips, or the tp-parity tests.  Call inside ``shard_map``.

    ``template``: boxed tree to read shardedness from when ``params`` itself
    is plain (e.g. a gradient tree matching a boxed parameter tree)."""
    if template is None:
        template = params

    def gather(box, leaf):
        val = leaf.value if _is_box(leaf) else leaf
        if _is_box(box) and _box_mentions(box, tp_axis):
            dim = tuple(box.names).index(tp_axis)
            return lax.all_gather(val, tp_axis, axis=dim, tiled=True)
        return val

    return jax.tree_util.tree_map(gather, template, params, is_leaf=_is_box)
