"""Per-shard RNG helpers — leaf module (no model/ops deps) shared by the
tensor-parallel layers (kernel shards) and the MoE model (per-shard experts).

Inside ``shard_map`` every rank sees the same base PRNG key; folding the mesh
position in makes nominally 'different-per-shard' parameters actually draw
independent values.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["fold_axis_rng", "sharded_init"]


def fold_axis_rng(key, *axis_names: str):
    """Per-shard RNG: fold each mesh position in so shards initialize
    differently (inside ``shard_map`` all ranks see the same base key)."""
    for ax in axis_names:
        key = jax.random.fold_in(key, lax.axis_index(ax))
    return key


def sharded_init(base_init, fold_axis: Optional[str]):
    """Wrap an initializer to fold the mesh position along ``fold_axis`` into
    the RNG so shards draw independent values (otherwise every shard of a
    'different' slice would be identical).  Shared by TP (kernel shards) and
    EP (per-shard experts — models/moe.py)."""

    def init(key, shape, dtype=jnp.float32):
        if fold_axis is not None:
            key = fold_axis_rng(key, fold_axis)
        return base_init(key, shape, dtype)

    return init
