"""BERT-style transformer encoder — for the reference's hierarchical
fine-tune config (BASELINE.json config[4]: BERT-large decentralized fine-tune
with hierarchical_neighbor_allreduce).

TPU-first: bf16 activations/matmuls with f32 layernorm + softmax, head and
hidden dims multiples of 128 (MXU tiles), fused QKV projection, no dynamic
shapes.  The attention core later swaps in the ring-attention layer
(``bluefog_tpu.ops.ring_attention``) for sequence parallelism.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import flax.linen as nn
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 1024          # BERT-large
    num_layers: int = 24
    num_heads: int = 16
    intermediate_size: int = 4096
    max_position: int = 512
    type_vocab_size: int = 2
    dropout_rate: float = 0.1
    dtype: jnp.dtype = jnp.bfloat16
    remat: bool = False              # jax.checkpoint each encoder layer

    @staticmethod
    def large() -> "BertConfig":
        return BertConfig()

    @staticmethod
    def base() -> "BertConfig":
        return BertConfig(hidden_size=768, num_layers=12, num_heads=12,
                          intermediate_size=3072)

    @staticmethod
    def tiny() -> "BertConfig":
        """For tests/dryruns."""
        return BertConfig(vocab_size=1024, hidden_size=128, num_layers=2,
                          num_heads=2, intermediate_size=256, max_position=128)


class SelfAttention(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, x, mask, deterministic: bool):
        cfg = self.cfg
        head_dim = cfg.hidden_size // cfg.num_heads
        # fused QKV: one big MXU matmul instead of three
        qkv = nn.Dense(3 * cfg.hidden_size, dtype=cfg.dtype, name="qkv")(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads(t):
            return t.reshape(t.shape[:-1] + (cfg.num_heads, head_dim))

        q, k, v = heads(q), heads(k), heads(v)
        scale = 1.0 / np.sqrt(head_dim)
        logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
        if mask is not None:
            logits = jnp.where(mask[:, None, None, :], logits, -1e9)
        probs = nn.softmax(logits, axis=-1).astype(cfg.dtype)
        probs = nn.Dropout(cfg.dropout_rate)(probs, deterministic=deterministic)
        out = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
        out = out.reshape(out.shape[:-2] + (cfg.hidden_size,))
        return nn.Dense(cfg.hidden_size, dtype=cfg.dtype, name="out")(out)


class EncoderLayer(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, x, mask, deterministic: bool):
        cfg = self.cfg
        y = SelfAttention(cfg)(x, mask, deterministic)
        y = nn.Dropout(cfg.dropout_rate)(y, deterministic=deterministic)
        x = nn.LayerNorm(dtype=jnp.float32)(x + y)
        y = nn.Dense(cfg.intermediate_size, dtype=cfg.dtype)(x)
        y = nn.gelu(y)
        y = nn.Dense(cfg.hidden_size, dtype=cfg.dtype)(y)
        y = nn.Dropout(cfg.dropout_rate)(y, deterministic=deterministic)
        return nn.LayerNorm(dtype=jnp.float32)(x + y)


class BertEncoder(nn.Module):
    """Embeddings + transformer stack + pooled/classification head."""

    cfg: BertConfig
    num_classes: Optional[int] = None  # None: return sequence embeddings

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 deterministic: bool = True):
        cfg = self.cfg
        b, s = input_ids.shape
        pos_ids = jnp.arange(s)[None, :]
        x = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype, name="tok")(input_ids)
        x = x + nn.Embed(cfg.max_position, cfg.hidden_size, dtype=cfg.dtype, name="pos")(pos_ids)
        if token_type_ids is not None:
            x = x + nn.Embed(cfg.type_vocab_size, cfg.hidden_size, dtype=cfg.dtype,
                             name="typ")(token_type_ids)
        x = nn.LayerNorm(dtype=jnp.float32)(x)
        x = nn.Dropout(cfg.dropout_rate)(x, deterministic=deterministic)
        layer_cls = (nn.remat(EncoderLayer, static_argnums=(3,))
                     if cfg.remat else EncoderLayer)
        for i in range(cfg.num_layers):
            x = layer_cls(cfg, name=f"layer_{i}")(x, attention_mask, deterministic)
        if self.num_classes is None:
            return x
        pooled = jnp.tanh(nn.Dense(cfg.hidden_size, dtype=jnp.float32, name="pooler")(x[:, 0]))
        return nn.Dense(self.num_classes, dtype=jnp.float32, name="cls")(pooled)
