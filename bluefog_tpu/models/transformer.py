"""Decoder-only transformer LM — the long-context flagship.

No counterpart exists in the reference (it predates LLMs; SURVEY.md §5
"long-context": absent) — this model exists to exercise the framework's
first-class sequence parallelism: the attention core is *pluggable*, so the
same module runs

- single-device / data-parallel with plain causal attention, or
- sequence-parallel inside ``shard_map`` with
  :func:`bluefog_tpu.ops.ring_attention.ring_attention` (KV ring over ICI) or
  :func:`~bluefog_tpu.ops.ring_attention.all_to_all_attention` (Ulysses),
  passing ``position_offset = rank * T_local`` for the sharded positions.

TPU-first: bf16 activations/matmuls with f32 layernorm + softmax-accumulate,
fused QKV, static shapes, dims sized for 128-lane MXU tiles.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional

import flax.linen as nn
import jax.numpy as jnp

from bluefog_tpu.ops.ring_attention import local_attention

AttnFn = Callable[..., jnp.ndarray]  # (q, k, v) -> (B, T, H, D)


@dataclasses.dataclass(frozen=True)
class GPTConfig:
    vocab_size: int = 50304          # 50257 padded up to a 128 multiple
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    mlp_ratio: int = 4
    max_position: int = 8192
    dtype: jnp.dtype = jnp.bfloat16
    remat: bool = False              # rematerialize each block's activations
    # (jax.checkpoint): backward recomputes the block instead of storing its
    # intermediates — O(sqrt-ish) HBM for long sequences at ~1/3 extra FLOPs

    @staticmethod
    def small() -> "GPTConfig":
        return GPTConfig()

    @staticmethod
    def large() -> "GPTConfig":
        return GPTConfig(hidden_size=1536, num_layers=24, num_heads=16)

    @staticmethod
    def tiny() -> "GPTConfig":
        """For tests/dryruns."""
        return GPTConfig(vocab_size=512, hidden_size=64, num_layers=2,
                         num_heads=4, max_position=512, dtype=jnp.float32)


class Block(nn.Module):
    """Pre-LN attention + MLP residual block.

    ``mlp`` is a pluggable sublayer factory ``() -> nn.Module`` (the module
    maps ``(B, T, D) -> (B, T, D)``); ``None`` gives the dense GELU MLP.
    The MoE variant (models/moe.py) injects a Switch-MoE FFN here instead of
    duplicating the attention trunk.
    """

    cfg: GPTConfig
    mlp: Optional[Callable[[], nn.Module]] = None

    @nn.compact
    def __call__(self, x, attn_fn: AttnFn):
        cfg = self.cfg
        head_dim = cfg.hidden_size // cfg.num_heads
        y = nn.LayerNorm(dtype=jnp.float32, name="ln1")(x).astype(cfg.dtype)
        qkv = nn.Dense(3 * cfg.hidden_size, dtype=cfg.dtype, name="qkv")(y)
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads(t):
            return t.reshape(t.shape[:-1] + (cfg.num_heads, head_dim))

        a = attn_fn(heads(q), heads(k), heads(v))
        a = a.reshape(a.shape[:-2] + (cfg.hidden_size,))
        x = x + nn.Dense(cfg.hidden_size, dtype=cfg.dtype, name="proj")(a)

        y = nn.LayerNorm(dtype=jnp.float32, name="ln2")(x).astype(cfg.dtype)
        if self.mlp is not None:
            return x + self.mlp()(y)
        y = nn.Dense(cfg.mlp_ratio * cfg.hidden_size, dtype=cfg.dtype, name="up")(y)
        y = nn.gelu(y)
        return x + nn.Dense(cfg.hidden_size, dtype=cfg.dtype, name="down")(y)


class TransformerLM(nn.Module):
    """Tokens → logits.  ``attn_fn(q, k, v) -> out`` defaults to full causal
    attention; inject a sequence-parallel attention inside ``shard_map`` and
    pass this rank's global ``position_offset``.  ``mlp`` (a sublayer factory,
    see :class:`Block`) swaps every block's MLP — e.g. for Switch-MoE."""

    cfg: GPTConfig
    mlp: Optional[Callable[[], nn.Module]] = None

    @nn.compact
    def __call__(self, tokens, *, attn_fn: Optional[AttnFn] = None,
                 position_offset=0, positions=None):
        cfg = self.cfg
        if attn_fn is None:
            # the model layer is the perf path: opt into the fused TPU flash
            # kernel whenever eligible (parity: tests/test_flash_attention.py)
            attn_fn = lambda q, k, v: local_attention(q, k, v, causal=True,
                                                      backend="auto")
        if positions is None:
            positions = position_offset + jnp.arange(tokens.shape[1])[None, :]
        # else: explicit per-token global positions — required by layouts
        # whose local block is not contiguous (e.g. the zigzag causal ring,
        # where a rank holds a front chunk and its mirrored back chunk)
        x = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype,
                     name="tok")(tokens)
        x = x + nn.Embed(cfg.max_position, cfg.hidden_size, dtype=cfg.dtype,
                         name="pos")(positions)
        block_cls = nn.remat(Block, static_argnums=(2,)) if cfg.remat else Block
        for i in range(cfg.num_layers):
            x = block_cls(cfg, mlp=self.mlp, name=f"block_{i}")(x, attn_fn)
        x = nn.LayerNorm(dtype=jnp.float32, name="ln_f")(x)
        return nn.Dense(cfg.vocab_size, dtype=jnp.float32, use_bias=False,
                        name="lm_head")(x)
