"""ResNet v1.5 — the reference's headline benchmark model
(BASELINE.json config[1]: ResNet-50/ImageNet, ExponentialTwoGraph,
DistributedNeighborAllreduceOptimizer; north-star metric images/sec/chip).

TPU-first choices: NHWC layout (XLA's native conv layout on TPU), bf16
compute with f32 BatchNorm statistics and f32 final logits, 3x3/1x1 convs
that tile cleanly onto the 128x128 MXU.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


def space_to_depth(x, block: int = 2):
    """Fold ``block x block`` spatial tiles into channels: [N,H,W,C] ->
    [N,H/b,W/b,C*b*b].

    The TPU stem trick (used by the MLPerf ResNet submissions): the raw
    ImageNet input has C=3, so the 7x7/s2 stem conv feeds the 128-lane MXU
    at 3/128 occupancy.  Space-to-depth quadruples the contraction depth
    (12 channels) and halves the spatial extent, and the 7x7/s2 conv is
    replaced by an exactly-equivalent 4x4/s1 conv on the folded input
    (see :func:`s2d_stem_kernel_from_7x7` for the constructive proof).
    Channel order within a tile is (row a, col b, channel c) ->
    (a*block + b)*C + c.
    """
    n, h, w, c = x.shape
    x = x.reshape(n, h // block, block, w // block, block, c)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(n, h // block, w // block, block * block * c)


def s2d_stem_kernel_from_7x7(w7):
    """Embed a [7,7,C,K] stride-2 stem kernel as the [4,4,4C,K] stride-1
    kernel that computes the IDENTICAL function on space-to-depth(2) input
    with spatial padding (2, 1).

    Derivation: with z[p, (a*2+b)*C + c] = x[2p+a, 2q+b, c] and the s2d conv
    y[i] = sum_{dp=0..3} W'[dp, ...] z[i+dp-2], each tap reads
    x[2i + 2dp + a - 4], while the original stride-2 conv with padding 3
    reads x[2i + di - 3]; matching gives di = 2dp + a - 1, a bijection from
    (dp, a) in {0..3}x{0,1} onto di in {-1..6} — the single di = -1 slot is
    zero-filled.  Used by the equivalence test; training simply learns the
    4x4 kernel directly (a superset: the zero slot is trainable, giving an
    8x8/s2 effective receptive field).
    """
    import numpy as np

    w7 = np.asarray(w7)
    kh, kw, c, k = w7.shape
    assert (kh, kw) == (7, 7), w7.shape
    w4 = np.zeros((4, 4, 4 * c, k), w7.dtype)
    for dp in range(4):
        for a in range(2):
            di = 2 * dp + a - 1
            if not 0 <= di < 7:
                continue
            for dq in range(4):
                for b in range(2):
                    dj = 2 * dq + b - 1
                    if not 0 <= dj < 7:
                        continue
                    w4[dp, dq, (a * 2 + b) * c:(a * 2 + b) * c + c, :] = w7[di, dj]
    return w4


class ResNetBlock(nn.Module):
    """Basic block (ResNet-18/34)."""

    filters: int
    conv: ModuleDef
    norm: ModuleDef
    strides: Tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), self.strides)(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters, (1, 1), self.strides, name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return nn.relu(residual + y)


class BottleneckBlock(nn.Module):
    """Bottleneck block (ResNet-50/101/152)."""

    filters: int
    conv: ModuleDef
    norm: ModuleDef
    strides: Tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        # zero-init the last norm's scale: residual branch starts as identity
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters * 4, (1, 1), self.strides, name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 1000
    num_filters: int = 64
    dtype: jnp.dtype = jnp.bfloat16
    # "conv" = reference 7x7/s2 + maxpool (ImageNet); "s2d" = space-to-depth
    # 4x4/s1 MXU-friendly equivalent; "cifar" = 3x3/s1, no maxpool (the
    # standard small-image stem — 32x32 inputs keep a 4x4 final map after
    # the three stage strides instead of collapsing to 1x1 under the
    # ImageNet stem's extra /4)
    stem: str = "conv"

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        norm = partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=self.dtype,
            param_dtype=jnp.float32,
        )
        x = x.astype(self.dtype)
        if self.stem == "s2d":
            # MXU-friendly stem: fold 2x2 tiles into channels (3 -> 12 input
            # lanes) and convolve 4x4/s1 — same function class as the 7x7/s2
            # stem (s2d_stem_kernel_from_7x7 embeds any 7x7 kernel exactly).
            # Accepts raw [N,H,W,3] (folds here; XLA fuses the reshape) or
            # pre-folded [N,H/2,W/2,12] from the data pipeline.
            if x.shape[-1] == 3:
                if x.shape[1] % 2 or x.shape[2] % 2:
                    raise ValueError(
                        "s2d stem needs even H and W to fold 2x2 tiles; got "
                        f"{x.shape[1]}x{x.shape[2]}")
                x = space_to_depth(x, 2)
            elif x.shape[-1] != 12:
                # any other channel count would silently skip folding and run
                # the 4x4/s1 conv at full resolution — different stride and
                # receptive field than the 7x7/s2 stem it stands in for
                raise ValueError(
                    "s2d stem accepts raw [N,H,W,3] or pre-folded "
                    f"[N,H/2,W/2,12] input; got C={x.shape[-1]}")
            x = conv(self.num_filters, (4, 4), (1, 1),
                     padding=[(2, 1), (2, 1)], name="conv_init")(x)
        elif self.stem == "cifar":
            x = conv(self.num_filters, (3, 3), (1, 1),
                     padding=[(1, 1), (1, 1)], name="conv_init")(x)
        else:
            x = conv(self.num_filters, (7, 7), (2, 2), padding=[(3, 3), (3, 3)], name="conv_init")(x)
        x = norm(name="bn_init")(x)
        x = nn.relu(x)
        if self.stem != "cifar":
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        for i, block_size in enumerate(self.stage_sizes):
            for j in range(block_size):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = self.block_cls(
                    self.num_filters * 2**i, conv=conv, norm=norm, strides=strides
                )(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)
        return x


ResNet18 = partial(ResNet, stage_sizes=[2, 2, 2, 2], block_cls=ResNetBlock)
ResNet50 = partial(ResNet, stage_sizes=[3, 4, 6, 3], block_cls=BottleneckBlock)
ResNet101 = partial(ResNet, stage_sizes=[3, 4, 23, 3], block_cls=BottleneckBlock)
