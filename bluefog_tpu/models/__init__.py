"""Model zoo for the reference's example/benchmark configs.

BASELINE.json configs: LeNet/MNIST (config 0), ResNet-50/ImageNet (config 1),
BERT-large fine-tune (config 4).  Models are flax.linen modules written
TPU-first: NHWC layouts, bf16-friendly, channel dims sized for the MXU.
"""

from bluefog_tpu.models.lenet import LeNet5
from bluefog_tpu.models.resnet import (
    ResNet,
    ResNet18,
    ResNet50,
    s2d_stem_kernel_from_7x7,
    space_to_depth,
)
from bluefog_tpu.models.bert import BertConfig, BertEncoder
from bluefog_tpu.models.transformer import GPTConfig, TransformerLM
from bluefog_tpu.models.moe import MoEConfig, MoETransformerLM
from bluefog_tpu.models.vit import ViTConfig, ViT
