"""LeNet-5 — the model of the reference's ``examples/pytorch_mnist.py``
(BASELINE.json config[0]: LeNet on ring topology, neighbor_allreduce)."""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class LeNet5(nn.Module):
    """Classic LeNet-5 for 28x28x1 inputs (NHWC)."""

    num_classes: int = 10
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.dtype)
        x = nn.Conv(6, (5, 5), padding="SAME", dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(16, (5, 5), padding="VALID", dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(120, dtype=self.dtype)(x))
        x = nn.relu(nn.Dense(84, dtype=self.dtype)(x))
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x)
