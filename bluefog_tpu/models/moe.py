"""Mixture-of-experts transformer LM — the expert-parallel flagship variant.

No counterpart in the reference (SURVEY.md §2.3: EP absent).  Pairs
:mod:`bluefog_tpu.ops.moe` (Switch routing + all_to_all expert parallelism)
with the :class:`~bluefog_tpu.models.transformer.TransformerLM` skeleton:
every block's MLP is replaced by a Switch-MoE FFN whose experts are sharded
over the ``'ep'`` mesh axis, with tokens batch-sharded over the same axis.

Loss convention for training inside ``shard_map``: normalize by the GLOBAL
token count (see ops/moe.py docstring) so raw ``jax.grad`` is exact.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import flax.linen as nn
import jax.numpy as jnp

from bluefog_tpu.models.transformer import GPTConfig, TransformerLM
from bluefog_tpu.ops.moe import expert_parallel_ffn, moe_ffn_reference
from bluefog_tpu.parallel.rng import sharded_init

__all__ = ["MoEConfig", "MoEMLP", "MoETransformerLM", "moe_param_rules"]


def moe_param_rules(ep_axis: str = "ep", tp_axis: Optional[str] = None):
    """The unified :class:`~bluefog_tpu.sharding.RuleTable` for a
    :class:`MoETransformerLM`'s parameters: expert weights (``wi``/``wo``)
    sharded over ``ep_axis`` on their leading expert dim, the router
    replicated, and — with ``tp_axis`` — the attention trunk in Megatron
    placement against THIS model's naming (fused ``qkv/kernel`` sharded
    on its output dim, ``proj/kernel`` row-sharded on its input dim;
    there is no ``up``/``down`` pair, the MLP is the MoE layer) — so EP,
    TP, the optimizer state, and the gossip windows all resolve through
    ONE table."""
    from jax.sharding import PartitionSpec as P

    from bluefog_tpu.sharding.rules import Rule, RuleTable

    rules = [
        Rule(r"moe/w[io]$", P(ep_axis)),
        Rule(r"moe/router$", P()),
    ]
    if tp_axis is not None:
        rules.extend([
            Rule(r"qkv/kernel$", P(None, tp_axis)),
            Rule(r"qkv/bias$", P(tp_axis)),
            Rule(r"proj/kernel$", P(tp_axis, None)),
        ])
    # explicit replicate tail: embeddings, layernorms, lm_head,
    # row-parallel biases — replication is a decision, not a leak
    rules.append(Rule(".*", P()))
    return RuleTable(rules)


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    """Switch-MoE hyperparameters on top of a :class:`GPTConfig`."""

    gpt: GPTConfig
    num_experts: int = 8
    ep_size: int = 1
    ep_axis: str = "ep"
    capacity_factor: float = 1.25
    aux_loss_weight: float = 0.01
    router: str = "top1"  # 'top1' (Switch) or 'top2' (GShard)

    def __post_init__(self):
        if self.router not in ("top1", "top2"):
            raise ValueError(
                f"unknown router {self.router!r}; expected 'top1' or 'top2'")
        if self.router == "top2" and self.num_experts < 2:
            raise ValueError(
                f"router='top2' requires num_experts >= 2, got "
                f"{self.num_experts} (the second choice would duplicate "
                "the first and silently halve capacity)")

    @staticmethod
    def tiny(ep_size: int = 1, router: str = "top1") -> "MoEConfig":
        return MoEConfig(gpt=GPTConfig.tiny(), num_experts=4,
                         ep_size=ep_size, capacity_factor=2.0, router=router)

    def capacity(self, tokens_per_shard: int) -> int:
        # top-2 makes two assignments per token: scale capacity with k so
        # capacity_factor keeps meaning "headroom over a perfect balance"
        k = 2 if self.router == "top2" else 1
        c = int(self.capacity_factor * k * tokens_per_shard
                / self.num_experts)
        return max(c, 1)


class MoEMLP(nn.Module):
    """Switch-MoE FFN; expert weights sharded over ``cfg.ep_axis`` when
    ``cfg.ep_size > 1`` (params hold only the local experts), dense reference
    path when ``ep_size == 1``."""

    cfg: MoEConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        gpt = cfg.gpt
        if cfg.num_experts % cfg.ep_size:
            raise ValueError(
                f"experts {cfg.num_experts} % ep {cfg.ep_size}")
        local_e = cfg.num_experts // cfg.ep_size
        hidden = gpt.mlp_ratio * gpt.hidden_size
        fold = cfg.ep_axis if cfg.ep_size > 1 else None

        router = self.param("router", nn.initializers.lecun_normal(),
                            (gpt.hidden_size, cfg.num_experts), jnp.float32)
        wi = self.param(
            "wi", sharded_init(
                nn.initializers.lecun_normal(in_axis=1, out_axis=2), fold),
            (local_e, gpt.hidden_size, hidden), jnp.float32)
        wo = self.param(
            "wo", sharded_init(
                nn.initializers.lecun_normal(in_axis=1, out_axis=2), fold),
            (local_e, hidden, gpt.hidden_size), jnp.float32)

        B, T, D = x.shape
        flat = x.reshape(B * T, D)
        cap = cfg.capacity(B * T)
        if cfg.ep_size == 1:
            y, aux, metrics = moe_ffn_reference(
                flat, router, wi.astype(gpt.dtype), wo.astype(gpt.dtype),
                num_experts=cfg.num_experts, capacity=cap,
                router=cfg.router)
        else:
            y, aux, metrics = expert_parallel_ffn(
                flat, router, wi.astype(gpt.dtype), wo.astype(gpt.dtype),
                ep_axis=cfg.ep_axis, num_experts=cfg.num_experts,
                capacity=cap, router=cfg.router)
        self.sow("aux_loss", "moe", aux)
        # drop/load accounting (stop-gradiented in the router): collect
        # with mutable=["moe_metrics"] — the bench surfaces dropped_frac
        self.sow("moe_metrics", "dropped_frac", metrics["dropped_frac"])
        self.sow("moe_metrics", "fully_dropped_frac",
                 metrics["fully_dropped_frac"])
        return y.reshape(B, T, D)


def MoETransformerLM(cfg: MoEConfig) -> TransformerLM:
    """Switch-MoE decoder LM: the :class:`TransformerLM` trunk with every
    block's MLP swapped for a :class:`MoEMLP` (one shared attention/embedding
    implementation — no duplicated trunk).

    Inside ``shard_map`` over an ``'ep'`` axis, pass the per-shard token
    batch; collect the aux loss via ``mutable=["aux_loss"]`` and add
    ``cfg.aux_loss_weight * sum``.  Gradient convention for replicated vs
    ep-sharded params: see the ops/moe.py module docstring.
    """
    return TransformerLM(cfg.gpt, mlp=lambda: MoEMLP(cfg, name="moe"))
