"""Mixture-of-experts transformer LM — the expert-parallel flagship variant.

No counterpart in the reference (SURVEY.md §2.3: EP absent).  Pairs
:mod:`bluefog_tpu.ops.moe` (Switch routing + all_to_all expert parallelism)
with the :class:`~bluefog_tpu.models.transformer.TransformerLM` skeleton:
every block's MLP is replaced by a Switch-MoE FFN whose experts are sharded
over the ``'ep'`` mesh axis, with tokens batch-sharded over the same axis.

Loss convention for training inside ``shard_map``: normalize by the GLOBAL
token count (see ops/moe.py docstring) so raw ``jax.grad`` is exact.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from bluefog_tpu.models.transformer import GPTConfig
from bluefog_tpu.ops.moe import expert_parallel_ffn, moe_ffn_reference
from bluefog_tpu.ops.ring_attention import local_attention

__all__ = ["MoEConfig", "MoEMLP", "MoEBlock", "MoETransformerLM"]


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    """Switch-MoE hyperparameters on top of a :class:`GPTConfig`."""

    gpt: GPTConfig
    num_experts: int = 8
    ep_size: int = 1
    ep_axis: str = "ep"
    capacity_factor: float = 1.25
    aux_loss_weight: float = 0.01

    @staticmethod
    def tiny(ep_size: int = 1) -> "MoEConfig":
        return MoEConfig(gpt=GPTConfig.tiny(), num_experts=4,
                         ep_size=ep_size, capacity_factor=2.0)

    def capacity(self, tokens_per_shard: int) -> int:
        c = int(self.capacity_factor * tokens_per_shard / self.num_experts)
        return max(c, 1)


def _expert_init(base_init, ep_axis: Optional[str]):
    """Fold the ep position into the RNG so each shard's experts draw
    independent values (mirrors parallel.tensor._sharded_init)."""

    def init(key, shape, dtype=jnp.float32):
        if ep_axis is not None:
            key = jax.random.fold_in(key, lax.axis_index(ep_axis))
        return base_init(key, shape, dtype)

    return init


class MoEMLP(nn.Module):
    """Switch-MoE FFN; expert weights sharded over ``cfg.ep_axis`` when
    ``cfg.ep_size > 1`` (params hold only the local experts), dense reference
    path when ``ep_size == 1``."""

    cfg: MoEConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        gpt = cfg.gpt
        if cfg.num_experts % cfg.ep_size:
            raise ValueError(
                f"experts {cfg.num_experts} % ep {cfg.ep_size}")
        local_e = cfg.num_experts // cfg.ep_size
        hidden = gpt.mlp_ratio * gpt.hidden_size
        fold = cfg.ep_axis if cfg.ep_size > 1 else None

        router = self.param("router", nn.initializers.lecun_normal(),
                            (gpt.hidden_size, cfg.num_experts), jnp.float32)
        wi = self.param(
            "wi", _expert_init(
                nn.initializers.lecun_normal(in_axis=1, out_axis=2), fold),
            (local_e, gpt.hidden_size, hidden), jnp.float32)
        wo = self.param(
            "wo", _expert_init(
                nn.initializers.lecun_normal(in_axis=1, out_axis=2), fold),
            (local_e, hidden, gpt.hidden_size), jnp.float32)

        B, T, D = x.shape
        flat = x.reshape(B * T, D)
        cap = cfg.capacity(B * T)
        if cfg.ep_size == 1:
            y, aux = moe_ffn_reference(
                flat, router, wi.astype(gpt.dtype), wo.astype(gpt.dtype),
                num_experts=cfg.num_experts, capacity=cap)
        else:
            y, aux = expert_parallel_ffn(
                flat, router, wi.astype(gpt.dtype), wo.astype(gpt.dtype),
                ep_axis=cfg.ep_axis, num_experts=cfg.num_experts,
                capacity=cap)
        self.sow("aux_loss", "moe", aux)
        return y.reshape(B, T, D)


class MoEBlock(nn.Module):
    cfg: MoEConfig

    @nn.compact
    def __call__(self, x, attn_fn):
        gpt = self.cfg.gpt
        head_dim = gpt.hidden_size // gpt.num_heads
        y = nn.LayerNorm(dtype=jnp.float32, name="ln1")(x).astype(gpt.dtype)
        qkv = nn.Dense(3 * gpt.hidden_size, dtype=gpt.dtype, name="qkv")(y)
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads(t):
            return t.reshape(t.shape[:-1] + (gpt.num_heads, head_dim))

        a = attn_fn(heads(q), heads(k), heads(v))
        a = a.reshape(a.shape[:-2] + (gpt.hidden_size,))
        x = x + nn.Dense(gpt.hidden_size, dtype=gpt.dtype, name="proj")(a)

        y = nn.LayerNorm(dtype=jnp.float32, name="ln2")(x).astype(gpt.dtype)
        return x + MoEMLP(self.cfg, name="moe")(y)


class MoETransformerLM(nn.Module):
    """Switch-MoE decoder LM.  Inside ``shard_map`` over an ``'ep'`` axis,
    pass the per-shard token batch; collect the aux loss via
    ``mutable=["aux_loss"]`` and add ``cfg.aux_loss_weight * sum``."""

    cfg: MoEConfig

    @nn.compact
    def __call__(self, tokens, *, attn_fn=None, position_offset=0):
        cfg = self.cfg
        gpt = cfg.gpt
        if attn_fn is None:
            attn_fn = lambda q, k, v: local_attention(q, k, v, causal=True)
        positions = position_offset + jnp.arange(tokens.shape[1])[None, :]
        x = nn.Embed(gpt.vocab_size, gpt.hidden_size, dtype=gpt.dtype,
                     name="tok")(tokens)
        x = x + nn.Embed(gpt.max_position, gpt.hidden_size, dtype=gpt.dtype,
                         name="pos")(positions)
        for i in range(gpt.num_layers):
            x = MoEBlock(cfg, name=f"block_{i}")(x, attn_fn)
        x = nn.LayerNorm(dtype=jnp.float32, name="ln_f")(x)
        return nn.Dense(gpt.vocab_size, dtype=jnp.float32, use_bias=False,
                        name="lm_head")(x)
