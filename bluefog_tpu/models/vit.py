"""Vision Transformer — attention on the image side of the zoo.

No counterpart exists in the reference (CNN-era data-parallel library;
SURVEY.md §2.2 examples are LeNet/ResNet) — this model exists so the
framework's attention stack (pluggable ``attn_fn``, flash backend, remat)
is exercised by an *image* workload as well as the LM, under any of the
gossip/data-parallel optimizers.

Reuses the transformer trunk (:class:`bluefog_tpu.models.transformer.Block`)
with non-causal attention: ViT is the same pre-LN residual architecture with
patch embedding instead of token embedding and a classification head over
the [CLS] position.  TPU-first: bf16 matmuls, f32 layernorm/softmax, static
shapes; the patchify is one strided conv (an MXU matmul after im2col).
"""

from __future__ import annotations

import dataclasses

import flax.linen as nn
import jax.numpy as jnp

from bluefog_tpu.models.transformer import Block, GPTConfig
from bluefog_tpu.ops.ring_attention import local_attention

__all__ = ["ViTConfig", "ViT"]


@dataclasses.dataclass(frozen=True)
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    num_classes: int = 1000
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    mlp_ratio: int = 4
    dtype: jnp.dtype = jnp.bfloat16
    remat: bool = False

    @staticmethod
    def base() -> "ViTConfig":
        return ViTConfig()  # ViT-B/16

    @staticmethod
    def tiny() -> "ViTConfig":
        """For tests/dryruns."""
        return ViTConfig(image_size=32, patch_size=8, num_classes=10,
                         hidden_size=64, num_layers=2, num_heads=4,
                         dtype=jnp.float32)

    def trunk(self) -> GPTConfig:
        """The transformer-block config this ViT shares with the LM trunk."""
        n_tokens = (self.image_size // self.patch_size) ** 2 + 1
        return GPTConfig(
            vocab_size=1,  # unused by Block
            hidden_size=self.hidden_size, num_layers=self.num_layers,
            num_heads=self.num_heads, mlp_ratio=self.mlp_ratio,
            max_position=n_tokens, dtype=self.dtype, remat=self.remat)


class ViT(nn.Module):
    """Images ``(B, H, W, C)`` → logits ``(B, num_classes)``."""

    cfg: ViTConfig

    @nn.compact
    def __call__(self, x, *, train: bool = False, attn_fn=None):
        cfg = self.cfg
        trunk = cfg.trunk()
        if attn_fn is None:
            attn_fn = lambda q, k, v: local_attention(q, k, v, causal=False,
                                                      backend="auto")
        b = x.shape[0]
        x = nn.Conv(cfg.hidden_size, (cfg.patch_size, cfg.patch_size),
                    strides=(cfg.patch_size, cfg.patch_size),
                    dtype=cfg.dtype, name="patchify")(x.astype(cfg.dtype))
        x = x.reshape(b, -1, cfg.hidden_size)  # (B, n_patches, D)
        cls = self.param("cls", nn.initializers.zeros,
                         (1, 1, cfg.hidden_size), jnp.float32)
        x = jnp.concatenate(
            [jnp.broadcast_to(cls.astype(cfg.dtype), (b, 1, cfg.hidden_size)),
             x], axis=1)
        pos = self.param("pos_embed", nn.initializers.normal(0.02),
                         (1, x.shape[1], cfg.hidden_size), jnp.float32)
        x = x + pos.astype(cfg.dtype)

        block_cls = (nn.remat(Block, static_argnums=(2,))
                     if trunk.remat else Block)
        for i in range(cfg.num_layers):
            x = block_cls(trunk, name=f"block_{i}")(x, attn_fn)
        x = nn.LayerNorm(dtype=jnp.float32, name="ln_f")(x[:, 0])
        return nn.Dense(cfg.num_classes, dtype=jnp.float32, name="head")(x)
