// Async host-op engine: tensor queue + background thread + handle manager.
//
// Reference parity (SURVEY.md §2.1, §3.2):
//   * bluefog/common/tensor_queue.{h,cc} — mutex-protected FIFO between
//     frontend threads and the engine thread;
//   * bluefog/common/operations.cc BackgroundThreadLoop/RunLoopOnce — drain
//     the queue, execute, fire callbacks;
//   * bluefog/torch/handle_manager.{h,cc} — handle → status table with
//     PollHandle / WaitAndClear.
//
// On TPU the device-side collectives are compiled into the XLA program (the
// negotiation phase is unnecessary under SPMD — every rank runs the same
// program in the same order by construction), so this engine carries the
// *host* async work instead: checkpoint IO, DCN staging transfers between
// slices, timeline/metric flushes, prefetch.  Callbacks are C function
// pointers; from Python they are ctypes trampolines (ctypes re-acquires the
// GIL on the engine thread, so Python callbacks are safe).

#include "bf_runtime.h"

#include <chrono>
#include <condition_variable>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>

namespace {

struct OpEntry {
  int handle;
  std::string op;
  std::string name;
  bf_callback cb;
  void* arg;
};

// Handle → status.  kPending marks in-flight ops.
constexpr int kPending = INT32_MIN;

class Engine {
 public:
  // Start/Shutdown are serialized on lifecycle_mu_ (held across the join):
  // a Start racing an in-progress Shutdown must block until the old Loop
  // thread has fully exited, else resetting shutdown_ would strand that
  // thread on queue_cv_ forever and Shutdown's join would never return.
  int Start() {
    std::lock_guard<std::mutex> lc(lifecycle_mu_);
    std::lock_guard<std::mutex> lock(mu_);
    if (running_) return 0;
    shutdown_ = false;
    running_ = true;
    thread_ = std::thread(&Engine::Loop, this);
    return 0;
  }

  int Shutdown() {
    std::lock_guard<std::mutex> lc(lifecycle_mu_);
    std::thread t;
    {
      std::lock_guard<std::mutex> lock(mu_);
      if (!running_) return 0;
      running_ = false;
      shutdown_ = true;
      t = std::move(thread_);
    }
    queue_cv_.notify_all();
    if (t.joinable()) t.join();
    return 0;
  }

  bool Running() {
    std::lock_guard<std::mutex> lock(mu_);
    return running_;
  }

  int Enqueue(const char* op, const char* name, bf_callback cb, void* arg) {
    std::lock_guard<std::mutex> lock(mu_);
    if (!running_ || shutdown_) return -1;
    int handle = next_handle_++;
    status_[handle] = kPending;
    queue_.push_back(OpEntry{handle, op ? op : "", name ? name : "", cb, arg});
    queue_cv_.notify_one();
    return handle;
  }

  int Poll(int handle) {
    std::lock_guard<std::mutex> lock(mu_);
    auto it = status_.find(handle);
    if (it == status_.end()) return -1;
    return it->second == kPending ? 0 : 1;
  }

  int Wait(int handle, int timeout_ms, int* status_out) {
    std::unique_lock<std::mutex> lock(mu_);
    auto deadline = std::chrono::steady_clock::now() +
                    std::chrono::milliseconds(timeout_ms < 0 ? 0 : timeout_ms);
    for (;;) {
      auto it = status_.find(handle);
      if (it == status_.end()) return -1;
      if (it->second != kPending) {
        if (status_out != nullptr) *status_out = it->second;
        return 0;
      }
      if (timeout_ms < 0) {
        done_cv_.wait(lock);
      } else if (done_cv_.wait_until(lock, deadline) ==
                 std::cv_status::timeout) {
        return -2;
      }
    }
  }

  void Clear(int handle) {
    std::lock_guard<std::mutex> lock(mu_);
    auto it = status_.find(handle);
    if (it != status_.end() && it->second != kPending) status_.erase(it);
  }

  int WaitAll(int timeout_ms) {
    std::unique_lock<std::mutex> lock(mu_);
    auto deadline = std::chrono::steady_clock::now() +
                    std::chrono::milliseconds(timeout_ms < 0 ? 0 : timeout_ms);
    for (;;) {
      if (PendingLocked() == 0) return 0;
      if (timeout_ms < 0) {
        done_cv_.wait(lock);
      } else if (done_cv_.wait_until(lock, deadline) ==
                 std::cv_status::timeout) {
        return -2;
      }
    }
  }

  int PendingCount() {
    std::lock_guard<std::mutex> lock(mu_);
    return PendingLocked();
  }

 private:
  // Pending = queued + currently executing (both still hold kPending status).
  int PendingLocked() {
    int n = 0;
    for (const auto& kv : status_) {
      if (kv.second == kPending) ++n;
    }
    return n;
  }

  // RunLoopOnce, looped: pop → timeline span → execute → mark done.
  void Loop() {
    std::unique_lock<std::mutex> lock(mu_);
    for (;;) {
      queue_cv_.wait(lock, [this] { return shutdown_ || !queue_.empty(); });
      if (queue_.empty() && shutdown_) break;  // drain before exit
      OpEntry entry = std::move(queue_.front());
      queue_.pop_front();
      lock.unlock();

      std::string span = entry.op + "/" + entry.name;
      bf_timeline_async_begin(span.c_str(), "host_op", entry.handle);
      int status = 0;
      if (entry.cb != nullptr) status = entry.cb(entry.arg);
      bf_timeline_async_end(span.c_str(), "host_op", entry.handle);

      lock.lock();
      status_[entry.handle] = status == kPending ? kPending + 1 : status;
      done_cv_.notify_all();
    }
  }

  std::mutex lifecycle_mu_;
  std::mutex mu_;
  std::condition_variable queue_cv_;
  std::condition_variable done_cv_;
  std::deque<OpEntry> queue_;
  std::unordered_map<int, int> status_;
  std::thread thread_;
  int next_handle_ = 0;
  bool running_ = false;
  bool shutdown_ = false;
};

Engine& GetEngine() {
  static Engine* e = new Engine();
  return *e;
}

}  // namespace

extern "C" {

int bf_engine_start() { return GetEngine().Start(); }
int bf_engine_shutdown() { return GetEngine().Shutdown(); }
int bf_engine_running() { return GetEngine().Running() ? 1 : 0; }

int bf_enqueue(const char* op, const char* name, bf_callback cb, void* arg) {
  return GetEngine().Enqueue(op, name, cb, arg);
}

int bf_poll(int handle) { return GetEngine().Poll(handle); }
int bf_wait(int handle, int timeout_ms, int* status_out) {
  return GetEngine().Wait(handle, timeout_ms, status_out);
}
void bf_clear(int handle) { GetEngine().Clear(handle); }
int bf_wait_all(int timeout_ms) { return GetEngine().WaitAll(timeout_ms); }
int bf_pending_count() { return GetEngine().PendingCount(); }

}  // extern "C"
