// TFRecord container support: CRC32C (Castagnoli, software 8-slice) and a
// record-framing indexer.
//
// TPU-native equivalent of the reference examples' input path (the reference
// delegates to torch DataLoader workers — SURVEY.md §2.2 "Examples"); here
// the hot byte-level work (checksums, framing scans over multi-GB shards)
// is native while decode/batching policy stays in Python
// (bluefog_tpu/data/tfrecord.py).
//
// TFRecord framing (little-endian):
//   uint64 length | uint32 masked_crc32c(length) | byte data[length]
//   | uint32 masked_crc32c(data)
// masked = ((crc >> 15) | (crc << 17)) + 0xa282ead8.

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <vector>

namespace {

struct CrcTables {
  uint32_t t[8][256];
  CrcTables() {
    const uint32_t poly = 0x82F63B78u;  // reflected Castagnoli
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t crc = i;
      for (int k = 0; k < 8; ++k)
        crc = (crc & 1) ? (crc >> 1) ^ poly : crc >> 1;
      t[0][i] = crc;
    }
    for (uint32_t i = 0; i < 256; ++i)
      for (int s = 1; s < 8; ++s)
        t[s][i] = (t[s - 1][i] >> 8) ^ t[0][t[s - 1][i] & 0xFF];
  }
};

const uint32_t (*tables())[256] {
  // function-local static: C++11 guarantees thread-safe one-time init —
  // concurrent first calls from writer/indexer threads are well-defined
  static const CrcTables kTables;
  return kTables.t;
}

uint32_t crc32c_impl(const uint8_t* p, int64_t n, uint32_t crc) {
  const uint32_t (*g_table)[256] = tables();
  crc = ~crc;
  while (n >= 8) {
    uint64_t chunk;
    std::memcpy(&chunk, p, 8);
    chunk ^= crc;  // little-endian: low 4 bytes fold into the crc
    crc = g_table[7][chunk & 0xFF] ^ g_table[6][(chunk >> 8) & 0xFF] ^
          g_table[5][(chunk >> 16) & 0xFF] ^ g_table[4][(chunk >> 24) & 0xFF] ^
          g_table[3][(chunk >> 32) & 0xFF] ^ g_table[2][(chunk >> 40) & 0xFF] ^
          g_table[1][(chunk >> 48) & 0xFF] ^ g_table[0][(chunk >> 56) & 0xFF];
    p += 8;
    n -= 8;
  }
  while (n-- > 0) crc = (crc >> 8) ^ g_table[0][(crc ^ *p++) & 0xFF];
  return ~crc;
}

inline uint32_t masked(uint32_t crc) {
  return (((crc >> 15) | (crc << 17)) + 0xa282ead8u);
}

}  // namespace

extern "C" {

// Plain CRC32C of a buffer (used by the Python writer / verifier).
uint32_t bf_crc32c(const void* data, int64_t len) {
  return crc32c_impl(static_cast<const uint8_t*>(data), len, 0);
}

// Scan a TFRecord file's framing.  Fills up to `max_records` (payload offset,
// payload length) pairs; pass max_records = 0 to just count.  verify != 0
// additionally checks both checksums per record (slower; reads payloads).
// Returns the total number of records in the file, or:
//   -1  cannot open file
//   -2  truncated / malformed framing
//   -3  checksum mismatch (verify only); *bad_record holds its index
int64_t bf_tfrecord_index(const char* path, int64_t* offsets,
                          int64_t* lengths, int64_t max_records, int verify,
                          int64_t* bad_record) {
  std::FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  // File size once up front: every record's length field is validated
  // against it BEFORE any allocation or seek, so a corrupt/crafted length
  // yields -2 instead of bad_alloc/negative seeks (the framing guarantees
  // payload + 4-byte footer fit inside the file).
  std::fseek(f, 0, SEEK_END);
  const int64_t file_size = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  int64_t count = 0;
  std::vector<uint8_t> buf;
  for (;;) {
    uint8_t header[12];
    size_t got = std::fread(header, 1, 12, f);
    if (got == 0) break;  // clean EOF
    if (got != 12) { std::fclose(f); return -2; }
    uint64_t len;
    uint32_t len_crc;
    std::memcpy(&len, header, 8);
    std::memcpy(&len_crc, header + 8, 4);
    if (verify && masked(bf_crc32c(header, 8)) != len_crc) {
      if (bad_record) *bad_record = count;
      std::fclose(f);
      return -3;
    }
    const int64_t payload_off = std::ftell(f);
    if (len > static_cast<uint64_t>(file_size) ||
        payload_off + static_cast<int64_t>(len) + 4 > file_size) {
      std::fclose(f);
      return -2;
    }
    if (verify) {
      buf.resize(len);
      if (len > 0 && std::fread(buf.data(), 1, len, f) != len) {
        std::fclose(f);
        return -2;
      }
      uint8_t footer[4];
      if (std::fread(footer, 1, 4, f) != 4) { std::fclose(f); return -2; }
      uint32_t data_crc;
      std::memcpy(&data_crc, footer, 4);
      if (masked(bf_crc32c(buf.data(), len)) != data_crc) {
        if (bad_record) *bad_record = count;
        std::fclose(f);
        return -3;
      }
    } else if (std::fseek(f, static_cast<long>(len) + 4, SEEK_CUR) != 0) {
      std::fclose(f);
      return -2;
    }
    if (count < max_records) {
      offsets[count] = payload_off;
      lengths[count] = static_cast<int64_t>(len);
    }
    ++count;
  }
  std::fclose(f);
  return count;
}

}  // extern "C"
