// Passive-target window table: host-memory landing buffers for one-sided ops.
//
// Reference parity (upstream-relative, SURVEY.md §2.1/§3.4):
//   * bluefog/torch/mpi_win_ops.cc WinTorchStorageManager — per-tensor self
//     buffer + one landing buffer per in-neighbor, backed by MPI_Win memory;
//   * bluefog/common/mpi_controller.cc WinPut/WinAccumulate/WinUpdate —
//     MPI_Put/MPI_Accumulate land with NO receiver involvement; the receiver
//     merges whatever has arrived whenever it chooses.
//
// This is the host half of the TPU build's window story.  Device-side
// (intra-slice) one-sided transfers ride Pallas async remote DMA
// (ops/pallas_gossip.py); across processes/slices the transport is the
// coordination service or DCN, and THIS table is the landing zone each
// process exposes.  Ranks running at different speeds deposit into and
// consume from these buffers with no rendezvous — the property the SPMD
// ppermute path cannot express (VERDICT r1, missing #1).
//
// Concurrency design:
//   * per-slot mutex, held only for the memcpy/add — writers never wait for
//     readers to *run*, only for a bounded copy (MPI implementations
//     serialize accumulates on the target window the same way);
//   * deposits carry a version count; readers see how many deposits landed
//     since their last consume (staleness is observable, as with
//     MPI_Win_flush bookkeeping);
//   * consume=1 zero-fills after read — push-sum mass is consumed exactly
//     once even when reader and writers race (swap under the slot lock).
//
// Dtypes: f32 / f64 accumulate natively.  Low-precision tensors convert on
// the Python side (same disposition as the reference's half.h custom-sum).

#include "bf_runtime.h"

#include <cstring>
#include <memory>
#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

struct Slot {
  std::mutex mu;
  std::vector<unsigned char> buf;
  long long deposits = 0;  // total deposits ever (version)
  long long fresh = 0;     // deposits since last consume
};

struct Window {
  int dtype;          // 0 = f32, 1 = f64
  long long n_elems;
  size_t nbytes;
  std::mutex self_mu;
  std::vector<unsigned char> self_buf;
  std::vector<std::unique_ptr<Slot>> slots;
};

std::mutex g_table_mu;
std::unordered_map<std::string, std::shared_ptr<Window>> g_table;

std::shared_ptr<Window> Find(const char* name) {
  std::lock_guard<std::mutex> lock(g_table_mu);
  auto it = g_table.find(name ? name : "");
  return it == g_table.end() ? nullptr : it->second;
}

size_t ElemSize(int dtype) { return dtype == 1 ? 8 : 4; }

template <typename T>
void AddInto(unsigned char* dst, const unsigned char* src, long long n) {
  T* d = reinterpret_cast<T*>(dst);
  const T* s = reinterpret_cast<const T*>(src);
  for (long long i = 0; i < n; ++i) d[i] += s[i];
}

}  // namespace

extern "C" {

int bf_win_create(const char* name, int n_slots, long long n_elems,
                  int dtype) {
  if (name == nullptr || n_slots < 0 || n_elems <= 0 ||
      (dtype != 0 && dtype != 1)) {
    return -1;
  }
  auto w = std::make_shared<Window>();
  w->dtype = dtype;
  w->n_elems = n_elems;
  w->nbytes = static_cast<size_t>(n_elems) * ElemSize(dtype);
  w->self_buf.assign(w->nbytes, 0);
  w->slots.reserve(n_slots);
  for (int k = 0; k < n_slots; ++k) {
    auto s = std::make_unique<Slot>();
    s->buf.assign(w->nbytes, 0);
    w->slots.push_back(std::move(s));
  }
  std::lock_guard<std::mutex> lock(g_table_mu);
  if (g_table.count(name)) return -2;  // already exists
  g_table.emplace(name, std::move(w));
  return 0;
}

int bf_win_exists(const char* name) { return Find(name) ? 1 : 0; }

int bf_win_free(const char* name) {
  std::lock_guard<std::mutex> lock(g_table_mu);
  return g_table.erase(name ? name : "") ? 0 : -1;
}

void bf_win_free_all() {
  std::lock_guard<std::mutex> lock(g_table_mu);
  g_table.clear();
}

// Deposit into a landing slot.  accumulate=0 replaces (MPI_Put), =1 adds
// (MPI_Accumulate with MPI_SUM).  Returns the slot's new version, <0 error.
long long bf_win_deposit(const char* name, int slot, const void* data,
                         long long n_elems, int accumulate) {
  auto w = Find(name);
  if (!w || slot < 0 || slot >= static_cast<int>(w->slots.size()) ||
      n_elems != w->n_elems || data == nullptr) {
    return -1;
  }
  Slot& s = *w->slots[slot];
  std::lock_guard<std::mutex> lock(s.mu);
  const unsigned char* src = static_cast<const unsigned char*>(data);
  if (accumulate) {
    if (w->dtype == 1) {
      AddInto<double>(s.buf.data(), src, n_elems);
    } else {
      AddInto<float>(s.buf.data(), src, n_elems);
    }
  } else {
    std::memcpy(s.buf.data(), src, w->nbytes);
  }
  ++s.deposits;
  ++s.fresh;
  return s.deposits;
}

// Read a landing slot into out.  consume=1 zero-fills after the read (and
// resets the freshness counter) so accumulated push-sum mass is consumed
// exactly once.  Returns the number of deposits since the last consuming
// read (0 = nothing new landed; the caller decides how to treat staleness),
// <0 on error.
long long bf_win_read(const char* name, int slot, void* out, long long n_elems,
                      int consume) {
  auto w = Find(name);
  if (!w || slot < 0 || slot >= static_cast<int>(w->slots.size()) ||
      n_elems != w->n_elems || out == nullptr) {
    return -1;
  }
  Slot& s = *w->slots[slot];
  std::lock_guard<std::mutex> lock(s.mu);
  std::memcpy(out, s.buf.data(), w->nbytes);
  long long fresh = s.fresh;
  if (consume) {
    std::memset(s.buf.data(), 0, w->nbytes);
    s.fresh = 0;
  }
  return fresh;
}

int bf_win_set_self(const char* name, const void* data, long long n_elems) {
  auto w = Find(name);
  if (!w || n_elems != w->n_elems || data == nullptr) return -1;
  std::lock_guard<std::mutex> lock(w->self_mu);
  std::memcpy(w->self_buf.data(), data, w->nbytes);
  return 0;
}

int bf_win_read_self(const char* name, void* out, long long n_elems) {
  auto w = Find(name);
  if (!w || n_elems != w->n_elems || out == nullptr) return -1;
  std::lock_guard<std::mutex> lock(w->self_mu);
  std::memcpy(out, w->self_buf.data(), w->nbytes);
  return 0;
}

int bf_win_num_slots(const char* name) {
  auto w = Find(name);
  return w ? static_cast<int>(w->slots.size()) : -1;
}

}  // extern "C"
