// Passive-target window table: host-memory landing buffers for one-sided ops.
//
// Reference parity (upstream-relative, SURVEY.md §2.1/§3.4):
//   * bluefog/torch/mpi_win_ops.cc WinTorchStorageManager — per-tensor self
//     buffer + one landing buffer per in-neighbor, backed by MPI_Win memory;
//   * bluefog/common/mpi_controller.cc WinPut/WinAccumulate/WinUpdate —
//     MPI_Put/MPI_Accumulate land with NO receiver involvement; the receiver
//     merges whatever has arrived whenever it chooses.
//
// This is the host half of the TPU build's window story.  Device-side
// (intra-slice) one-sided transfers ride Pallas async remote DMA
// (ops/pallas_gossip.py); across processes on a host the transport is THIS
// table backed by named POSIX shared memory, and across machines it is the
// coordination service or DCN.  Ranks running at different speeds deposit
// into and consume from these buffers with no rendezvous — the property the
// SPMD ppermute path cannot express (VERDICT r1 missing #1, r3 missing #1).
//
// Memory design — ONE segment layout for every backing:
//   [WinHdr | SlotHdr x n_slots | self_buf | slot_buf x n_slots]
//   * process-local windows (bf_win_create) place it in an anonymous
//     private mapping — the round-1..3 rank-*thread* model;
//   * cross-process windows (bf_win_create_shm / bf_win_attach_shm) place
//     the SAME layout in a named shm object (/dev/shm), so a deposit from
//     another OS process lands in the owner's window with no receiver
//     involvement — the MPI_Put-across-process-boundaries semantic.
//
// Concurrency design:
//   * per-slot PROCESS-SHARED ROBUST pthread mutex living inside the
//     segment, held only for the memcpy/add — writers never wait for
//     readers to *run*, only for a bounded copy (MPI implementations
//     serialize accumulates on the target window the same way).  Robustness:
//     if a depositing process dies holding a slot lock, the next locker gets
//     EOWNERDEAD, marks the mutex consistent, and proceeds (the MPI
//     failure-semantics analog; the torn payload, if any, is bounded to one
//     slot and surfaced by the deposit counter);
//   * deposits carry a version count; readers see how many deposits landed
//     since their last consume (staleness is observable, as with
//     MPI_Win_flush bookkeeping);
//   * consume=1 zero-fills after read — push-sum mass is consumed exactly
//     once even when reader and writers race (swap under the slot lock);
//   * the owner publishes the segment by storing a magic word LAST
//     (release); attachers spin until they observe it (acquire), so a
//     concurrent create/attach race never sees half-initialized mutexes.
//
// Dtypes: f32 / f64 accumulate natively.  Low-precision tensors convert on
// the Python side (same disposition as the reference's half.h custom-sum).

#include "bf_runtime.h"

#include <errno.h>
#include <fcntl.h>
#include <pthread.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <cstdio>
#include <cstring>
#include <memory>
#include <mutex>
#include <string>
#include <unordered_map>

namespace {

constexpr unsigned long long kMagic = 0x62667769'6e646f77ULL;  // "bfwindow"

struct SlotHdr {
  pthread_mutex_t mu;
  long long deposits;  // total deposits ever (version)
  long long fresh;     // deposits since last consume
};

struct WinHdr {
  unsigned long long magic;  // set LAST (release) by the initializer
  int dtype;                 // 0 = f32, 1 = f64
  int n_slots;
  long long n_elems;
  long long nbytes;          // per buffer
  pthread_mutex_t self_mu;
};

size_t ElemSize(int dtype) { return dtype == 1 ? 8 : 4; }

size_t SegmentSize(int n_slots, long long nbytes) {
  return sizeof(WinHdr) + static_cast<size_t>(n_slots) * sizeof(SlotHdr) +
         static_cast<size_t>(n_slots + 1) * static_cast<size_t>(nbytes);
}

SlotHdr* Slots(WinHdr* h) { return reinterpret_cast<SlotHdr*>(h + 1); }

unsigned char* SelfBuf(WinHdr* h) {
  return reinterpret_cast<unsigned char*>(Slots(h) + h->n_slots);
}

unsigned char* SlotBuf(WinHdr* h, int k) {
  return SelfBuf(h) + static_cast<size_t>(k + 1) * h->nbytes;
}

// EOWNERDEAD: a process died holding the lock; mark consistent and proceed
// (our critical sections are idempotent-enough copies — at worst one torn
// deposit, observable through the version counter).
int LockMu(pthread_mutex_t* mu) {
  int rc = pthread_mutex_lock(mu);
  if (rc == EOWNERDEAD) {
    pthread_mutex_consistent(mu);
    rc = 0;
  }
  return rc;
}

void InitHdr(WinHdr* h, int n_slots, long long n_elems, int dtype,
             bool pshared) {
  h->dtype = dtype;
  h->n_slots = n_slots;
  h->n_elems = n_elems;
  h->nbytes = static_cast<long long>(n_elems * ElemSize(dtype));
  pthread_mutexattr_t at;
  pthread_mutexattr_init(&at);
  if (pshared) {
    pthread_mutexattr_setpshared(&at, PTHREAD_PROCESS_SHARED);
    pthread_mutexattr_setrobust(&at, PTHREAD_MUTEX_ROBUST);
  }
  pthread_mutex_init(&h->self_mu, &at);
  SlotHdr* slots = Slots(h);
  for (int k = 0; k < n_slots; ++k) {
    pthread_mutex_init(&slots[k].mu, &at);
    slots[k].deposits = 0;
    slots[k].fresh = 0;
  }
  pthread_mutexattr_destroy(&at);
  // buffers are already zero (fresh anonymous mapping / ftruncate'd shm)
  __atomic_store_n(&h->magic, kMagic, __ATOMIC_RELEASE);
}

struct Window {
  WinHdr* hdr = nullptr;
  size_t len = 0;
  bool owner = false;       // unlink/destroy on free
  std::string shm_name;     // empty = anonymous (process-local)

  ~Window() {
    if (hdr == nullptr) return;
    munmap(hdr, len);
    if (owner && !shm_name.empty()) shm_unlink(shm_name.c_str());
  }
};

std::mutex g_table_mu;
std::unordered_map<std::string, std::shared_ptr<Window>> g_table;

std::shared_ptr<Window> Find(const char* name) {
  std::lock_guard<std::mutex> lock(g_table_mu);
  auto it = g_table.find(name ? name : "");
  return it == g_table.end() ? nullptr : it->second;
}

// shm object name: namespaced by uid so two users on a host cannot collide,
// '/'-free (POSIX requires exactly one leading slash).  The escape is
// injective ('_' -> '_u', '/' -> '_s') so distinct window names can never
// map to one shm object ("a/b" vs "a_b").  Names longer than NAME_MAX keep
// a readable prefix and replace the tail with a 64-bit FNV-1a digest of the
// FULL escaped name — a plain truncation would map every long per-rank
// window ("<long job name>:0", ":1", ...) onto ONE segment, silently
// crossing their deposits.
std::string ShmName(const char* name) {
  std::string s = "/bfwin_" + std::to_string(getuid()) + "_";
  for (const char* p = name; *p; ++p) {
    if (*p == '_') {
      s += "_u";
    } else if (*p == '/') {
      s += "_s";
    } else {
      s.push_back(*p);
    }
  }
  if (s.size() > 250) {
    unsigned long long h = 1469598103934665603ULL;  // FNV-1a 64
    for (char c : s) {
      h ^= static_cast<unsigned char>(c);
      h *= 1099511628211ULL;
    }
    char digest[20];
    snprintf(digest, sizeof(digest), "_h%016llx", h);
    s.resize(250 - 18);
    s += digest;
  }
  return s;
}

template <typename T>
void AddInto(unsigned char* dst, const unsigned char* src, long long n) {
  T* d = reinterpret_cast<T*>(dst);
  const T* s = reinterpret_cast<const T*>(src);
  for (long long i = 0; i < n; ++i) d[i] += s[i];
}

int Register(const char* name, std::shared_ptr<Window> w) {
  std::lock_guard<std::mutex> lock(g_table_mu);
  if (g_table.count(name)) return -2;  // already exists in this process
  g_table.emplace(name, std::move(w));
  return 0;
}

}  // namespace

extern "C" {

// Process-local window (rank-thread model): anonymous mapping, same layout.
int bf_win_create(const char* name, int n_slots, long long n_elems,
                  int dtype) {
  if (name == nullptr || n_slots < 0 || n_elems <= 0 ||
      (dtype != 0 && dtype != 1)) {
    return -1;
  }
  size_t len = SegmentSize(n_slots, n_elems * ElemSize(dtype));
  void* map = mmap(nullptr, len, PROT_READ | PROT_WRITE,
                   MAP_PRIVATE | MAP_ANONYMOUS, -1, 0);
  if (map == MAP_FAILED) return -1;
  auto w = std::make_shared<Window>();
  w->hdr = static_cast<WinHdr*>(map);
  w->len = len;
  w->owner = true;
  InitHdr(w->hdr, n_slots, n_elems, dtype, /*pshared=*/false);
  return Register(name, std::move(w));
}

// Cross-process window: named shm segment, process-shared robust mutexes.
// The caller is the OWNER (this rank's landing zone); peers attach.
// Returns 0, -2 if the shm object already exists (stale from a crashed run
// — clean with bf_win_shm_unlink — or a live duplicate), -1 on error.
int bf_win_create_shm(const char* name, int n_slots, long long n_elems,
                      int dtype) {
  if (name == nullptr || n_slots < 0 || n_elems <= 0 ||
      (dtype != 0 && dtype != 1)) {
    return -1;
  }
  std::string sname = ShmName(name);
  int fd = shm_open(sname.c_str(), O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return errno == EEXIST ? -2 : -1;
  size_t len = SegmentSize(n_slots, n_elems * ElemSize(dtype));
  if (ftruncate(fd, static_cast<off_t>(len)) != 0) {
    close(fd);
    shm_unlink(sname.c_str());
    return -1;
  }
  void* map =
      mmap(nullptr, len, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (map == MAP_FAILED) {
    shm_unlink(sname.c_str());
    return -1;
  }
  auto w = std::make_shared<Window>();
  w->hdr = static_cast<WinHdr*>(map);
  w->len = len;
  w->owner = true;
  w->shm_name = sname;
  InitHdr(w->hdr, n_slots, n_elems, dtype, /*pshared=*/true);
  // on Register failure the moved-in Window's dtor both unmaps and (owner)
  // unlinks — a second unlink here could delete a segment some other
  // process legitimately re-created in between
  return Register(name, std::move(w));
}

// Attach a peer's shm window for depositing.  Spins up to timeout_ms for
// the owner to create AND publish (magic) the segment — creation order
// between processes is thereby free.  Returns 0, -1 on timeout/error, -3 on
// a malformed segment (size/magic mismatch).
int bf_win_attach_shm(const char* name, int timeout_ms) {
  if (name == nullptr) return -1;
  std::string sname = ShmName(name);
  const int step_us = 2000;
  long long waited_us = 0;
  int fd = -1;
  struct stat st;
  for (;;) {
    fd = shm_open(sname.c_str(), O_RDWR, 0600);
    if (fd >= 0 && fstat(fd, &st) == 0 && st.st_size >
        static_cast<off_t>(sizeof(WinHdr))) {
      break;  // owner has ftruncate'd to full size
    }
    if (fd >= 0) close(fd);
    fd = -1;
    if (waited_us / 1000 >= timeout_ms) return -1;
    usleep(step_us);
    waited_us += step_us;
  }
  size_t len = static_cast<size_t>(st.st_size);
  void* map = mmap(nullptr, len, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (map == MAP_FAILED) return -1;
  WinHdr* h = static_cast<WinHdr*>(map);
  while (__atomic_load_n(&h->magic, __ATOMIC_ACQUIRE) != kMagic) {
    if (waited_us / 1000 >= timeout_ms) {
      munmap(map, len);
      return -1;
    }
    usleep(step_us);
    waited_us += step_us;
  }
  if (SegmentSize(h->n_slots, h->nbytes) != len) {
    munmap(map, len);
    return -3;
  }
  auto w = std::make_shared<Window>();
  w->hdr = h;
  w->len = len;
  w->owner = false;  // peers never unlink
  w->shm_name = sname;
  return Register(name, std::move(w));
}

// Remove a (possibly stale) shm object by window name without mapping it.
// Returns 0 if unlinked, 1 if it did not exist, -1 on error.
int bf_win_shm_unlink(const char* name) {
  if (name == nullptr) return -1;
  if (shm_unlink(ShmName(name).c_str()) == 0) return 0;
  return errno == ENOENT ? 1 : -1;
}

int bf_win_exists(const char* name) { return Find(name) ? 1 : 0; }

// Window geometry for attachers: fills n_slots/n_elems/dtype, returns 0.
int bf_win_info(const char* name, int* n_slots, long long* n_elems,
                int* dtype) {
  auto w = Find(name);
  if (!w) return -1;
  if (n_slots) *n_slots = w->hdr->n_slots;
  if (n_elems) *n_elems = w->hdr->n_elems;
  if (dtype) *dtype = w->hdr->dtype;
  return 0;
}

int bf_win_free(const char* name) {
  std::lock_guard<std::mutex> lock(g_table_mu);
  return g_table.erase(name ? name : "") ? 0 : -1;
}

void bf_win_free_all() {
  std::lock_guard<std::mutex> lock(g_table_mu);
  g_table.clear();
}

// Deposit into a landing slot.  accumulate=0 replaces (MPI_Put), =1 adds
// (MPI_Accumulate with MPI_SUM).  Returns the slot's new version, <0 error.
long long bf_win_deposit(const char* name, int slot, const void* data,
                         long long n_elems, int accumulate) {
  auto w = Find(name);
  if (!w || slot < 0 || slot >= w->hdr->n_slots ||
      n_elems != w->hdr->n_elems || data == nullptr) {
    return -1;
  }
  WinHdr* h = w->hdr;
  SlotHdr& s = Slots(h)[slot];
  if (LockMu(&s.mu) != 0) return -1;
  const unsigned char* src = static_cast<const unsigned char*>(data);
  unsigned char* dst = SlotBuf(h, slot);
  if (accumulate) {
    if (h->dtype == 1) {
      AddInto<double>(dst, src, n_elems);
    } else {
      AddInto<float>(dst, src, n_elems);
    }
  } else {
    std::memcpy(dst, src, static_cast<size_t>(h->nbytes));
  }
  ++s.deposits;
  ++s.fresh;
  long long v = s.deposits;
  pthread_mutex_unlock(&s.mu);
  return v;
}

// Read a landing slot into out.  consume=1 zero-fills after the read (and
// resets the freshness counter) so accumulated push-sum mass is consumed
// exactly once.  Returns the number of deposits since the last consuming
// read (0 = nothing new landed; the caller decides how to treat staleness),
// <0 on error.
long long bf_win_read(const char* name, int slot, void* out, long long n_elems,
                      int consume) {
  auto w = Find(name);
  if (!w || slot < 0 || slot >= w->hdr->n_slots ||
      n_elems != w->hdr->n_elems || out == nullptr) {
    return -1;
  }
  WinHdr* h = w->hdr;
  SlotHdr& s = Slots(h)[slot];
  if (LockMu(&s.mu) != 0) return -1;
  unsigned char* buf = SlotBuf(h, slot);
  std::memcpy(out, buf, static_cast<size_t>(h->nbytes));
  long long fresh = s.fresh;
  if (consume) {
    std::memset(buf, 0, static_cast<size_t>(h->nbytes));
    s.fresh = 0;
  }
  pthread_mutex_unlock(&s.mu);
  return fresh;
}

int bf_win_set_self(const char* name, const void* data, long long n_elems) {
  auto w = Find(name);
  if (!w || n_elems != w->hdr->n_elems || data == nullptr) return -1;
  WinHdr* h = w->hdr;
  if (LockMu(&h->self_mu) != 0) return -1;
  std::memcpy(SelfBuf(h), data, static_cast<size_t>(h->nbytes));
  pthread_mutex_unlock(&h->self_mu);
  return 0;
}

int bf_win_read_self(const char* name, void* out, long long n_elems) {
  auto w = Find(name);
  if (!w || n_elems != w->hdr->n_elems || out == nullptr) return -1;
  WinHdr* h = w->hdr;
  if (LockMu(&h->self_mu) != 0) return -1;
  std::memcpy(out, SelfBuf(h), static_cast<size_t>(h->nbytes));
  pthread_mutex_unlock(&h->self_mu);
  return 0;
}

int bf_win_num_slots(const char* name) {
  auto w = Find(name);
  return w ? w->hdr->n_slots : -1;
}

}  // extern "C"
