// bluefog_tpu native host runtime — public C API.
//
// TPU-native equivalent of the reference's C++ core (upstream-relative:
// bluefog/common/{operations,tensor_queue,timeline,logging}.cc and
// bluefog/torch/handle_manager.cc — SURVEY.md §2.1).  On TPU the *device*
// dataflow lives inside XLA (async dispatch subsumes the reference's
// negotiation phase), so what remains genuinely host-native is:
//
//   * an async op engine: a mutex-protected FIFO drained by a background
//     thread, firing enqueued host callbacks (checkpoint IO, cross-slice DCN
//     staging, metric flushes) off the critical path;
//   * a handle manager with poll / wait-and-clear semantics (the reference's
//     nonblocking-op handle table);
//   * a chrome-trace timeline writer on its own thread;
//   * leveled logging controlled by BLUEFOG_TPU_LOG_LEVEL.
//
// Bound from Python via ctypes (no pybind11 in this image).

#ifndef BF_RUNTIME_H_
#define BF_RUNTIME_H_

#include <cstdint>

extern "C" {

// ---------------------------------------------------------------- logging --
// Levels: 0=trace 1=debug 2=info 3=warn 4=error 5=fatal(off).
int bf_log_level();
void bf_set_log_level(int level);
void bf_log(int level, const char* msg);

// --------------------------------------------------------------- timeline --
// Chrome trace-event JSON, written incrementally by a dedicated thread.
int bf_timeline_start(const char* path);   // 0 ok, <0 error
int bf_timeline_stop();                    // flush + close
int bf_timeline_active();
void bf_timeline_begin(const char* name, const char* cat, int64_t tid);
void bf_timeline_end(const char* name, const char* cat, int64_t tid);
void bf_timeline_instant(const char* name, const char* cat);
// Async-span helpers keyed by id (for overlapping ops, ph 'b'/'e').
void bf_timeline_async_begin(const char* name, const char* cat, int64_t id);
void bf_timeline_async_end(const char* name, const char* cat, int64_t id);

// ----------------------------------------------------------------- engine --
// Host callback executed on the engine thread; returns a status code
// (0 = OK; nonzero = op-defined error).
typedef int (*bf_callback)(void* arg);

int bf_engine_start();     // idempotent; spawns the background thread
int bf_engine_shutdown();  // drains the queue, joins the thread
int bf_engine_running();

// Enqueue a host op; returns a fresh handle (>=0), or -1 if not running.
int bf_enqueue(const char* op, const char* name, bf_callback cb, void* arg);

// Handle states: -1 = unknown handle, 0 = pending, 1 = done.
int bf_poll(int handle);
// Block until done (timeout_ms < 0 → forever).  Returns 0 and writes the
// callback's status to *status_out on success; -1 on unknown handle; -2 on
// timeout.  Keeping the op status out-of-band means callbacks may return any
// int without colliding with the sentinels.  Does NOT clear.
int bf_wait(int handle, int timeout_ms, int* status_out);
void bf_clear(int handle);      // forget a completed handle
int bf_wait_all(int timeout_ms);  // wait for every pending handle
int bf_pending_count();

// ---------------------------------------------------------------- windows --
// Passive-target landing buffers (windows.cc): the host-memory half of the
// one-sided window story.  A window owns a self buffer plus n_slots landing
// slots (one per in-neighbor, as in the reference's WinTorchStorageManager);
// writers deposit (put/accumulate) without any receiver involvement, and
// readers consume whenever they choose.  dtype: 0 = f32, 1 = f64.
int bf_win_create(const char* name, int n_slots, long long n_elems, int dtype);
// Cross-process variants: the segment lives in named POSIX shared memory
// (uid-namespaced), so a deposit from another OS process lands in the
// owner's window — the MPI_Put-across-process-boundaries semantic.  The
// creator owns (and unlinks on free); peers attach, spinning up to
// timeout_ms for the owner to publish.  bf_win_shm_unlink removes a stale
// segment (e.g. from a crashed run) by window name without mapping it.
int bf_win_create_shm(const char* name, int n_slots, long long n_elems,
                      int dtype);
int bf_win_attach_shm(const char* name, int timeout_ms);
int bf_win_shm_unlink(const char* name);
// Fills the window's geometry (any pointer may be NULL); -1 if unknown.
int bf_win_info(const char* name, int* n_slots, long long* n_elems,
                int* dtype);
int bf_win_exists(const char* name);
int bf_win_free(const char* name);
void bf_win_free_all();
// accumulate=0 replaces (MPI_Put), =1 adds (MPI_Accumulate MPI_SUM).
// Returns the slot's new deposit count, <0 on error.
long long bf_win_deposit(const char* name, int slot, const void* data,
                         long long n_elems, int accumulate);
// Returns deposits since the last consuming read (0 = stale); consume=1
// zero-fills after reading so accumulated mass is consumed exactly once.
long long bf_win_read(const char* name, int slot, void* out, long long n_elems,
                      int consume);
int bf_win_set_self(const char* name, const void* data, long long n_elems);
int bf_win_read_self(const char* name, void* out, long long n_elems);
int bf_win_num_slots(const char* name);

// --------------------------------------------------------------- tfrecord --
// CRC32C (Castagnoli) of a buffer; and a TFRecord-framing indexer that fills
// (payload offset, length) pairs for random access over on-disk shards.  See
// tfrecord.cc for return codes.
uint32_t bf_crc32c(const void* data, int64_t len);
int64_t bf_tfrecord_index(const char* path, int64_t* offsets,
                          int64_t* lengths, int64_t max_records, int verify,
                          int64_t* bad_record);

}  // extern "C"

#endif  // BF_RUNTIME_H_
