// Chrome-trace timeline writer on a dedicated thread.
//
// Reference parity: bluefog/common/timeline.{h,cc} — a writer thread consumes
// queued events and emits chrome://tracing JSON; events are recorded from the
// op engine at state transitions and from user span APIs (SURVEY.md §5).
// Same design here: record() is lock-cheap (mutex push onto a vector); the
// writer thread drains every ~100ms and appends serialized events to the file.
// The file is a valid trace-event JSON array; chrome/Perfetto also accept a
// truncated array if the process dies mid-run.

#include "bf_runtime.h"

#include <chrono>
#include <condition_variable>
#include <cstdio>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

using Clock = std::chrono::steady_clock;

struct Event {
  std::string name;
  std::string cat;
  char ph;         // 'B','E','i','b','e'
  int64_t ts_us;
  int64_t tid;     // thread id or async span id
};

class TimelineWriter {
 public:
  bool Start(const std::string& path) {
    std::lock_guard<std::mutex> lock(mu_);
    if (file_ != nullptr) return false;
    file_ = std::fopen(path.c_str(), "w");
    if (file_ == nullptr) return false;
    std::fputs("[\n", file_);
    first_ = true;
    t0_ = Clock::now();
    stop_ = false;
    thread_ = std::thread(&TimelineWriter::Loop, this);
    return true;
  }

  void Stop() {
    // Move the thread out under the lock: concurrent Stop calls must not
    // both join it (double-join would std::terminate).
    std::thread t;
    {
      std::lock_guard<std::mutex> lock(mu_);
      if (file_ == nullptr || stop_) return;
      stop_ = true;
      t = std::move(thread_);
    }
    cv_.notify_all();
    if (t.joinable()) t.join();
    std::lock_guard<std::mutex> lock(mu_);
    if (file_ == nullptr) return;
    Drain();
    std::fputs("\n]\n", file_);
    std::fclose(file_);
    file_ = nullptr;
  }

  bool Active() {
    std::lock_guard<std::mutex> lock(mu_);
    return file_ != nullptr;
  }

  void Record(const char* name, const char* cat, char ph, int64_t tid) {
    std::lock_guard<std::mutex> lock(mu_);
    if (file_ == nullptr) return;
    int64_t ts = std::chrono::duration_cast<std::chrono::microseconds>(
                     Clock::now() - t0_)
                     .count();
    pending_.push_back(Event{name ? name : "", cat ? cat : "", ph, ts, tid});
    if (pending_.size() >= 4096) cv_.notify_all();
  }

 private:
  void Loop() {
    std::unique_lock<std::mutex> lock(mu_);
    while (!stop_) {
      cv_.wait_for(lock, std::chrono::milliseconds(100));
      // Swap the backlog out and release mu_ before serializing/writing so
      // Record() never blocks on disk latency (the op-engine thread records
      // spans on its critical path).  file_/first_ are touched only by this
      // thread while it runs, and by Stop() strictly after joining it.
      std::vector<Event> batch;
      batch.swap(pending_);
      lock.unlock();
      WriteBatch(batch);
      lock.lock();
    }
  }

  // Requires mu_ held; only called from Stop() after the writer thread has
  // been joined (final flush).
  void Drain() {
    std::vector<Event> batch;
    batch.swap(pending_);
    WriteBatch(batch);
  }

  void WriteBatch(const std::vector<Event>& batch) {
    if (file_ == nullptr || batch.empty()) return;
    std::string out;
    out.reserve(batch.size() * 96);
    char buf[64];
    for (const Event& e : batch) {
      if (!first_) out += ",\n";
      first_ = false;
      out += "{\"name\":\"";
      AppendEscaped(&out, e.name);
      out += "\",\"cat\":\"";
      AppendEscaped(&out, e.cat);
      out += "\",\"ph\":\"";
      out += e.ph;
      out += "\",\"ts\":";
      std::snprintf(buf, sizeof(buf), "%lld", (long long)e.ts_us);
      out += buf;
      out += ",\"pid\":0";
      if (e.ph == 'b' || e.ph == 'e') {
        std::snprintf(buf, sizeof(buf), ",\"id\":%lld", (long long)e.tid);
        out += buf;
        out += ",\"tid\":0";
      } else {
        std::snprintf(buf, sizeof(buf), ",\"tid\":%lld", (long long)e.tid);
        out += buf;
      }
      if (e.ph == 'i') out += ",\"s\":\"p\"";
      out += "}";
    }
    std::fputs(out.c_str(), file_);
    std::fflush(file_);
  }

  static void AppendEscaped(std::string* out, const std::string& s) {
    for (char c : s) {
      if (c == '"' || c == '\\') {
        out->push_back('\\');
        out->push_back(c);
      } else if (static_cast<unsigned char>(c) >= 0x20) {
        out->push_back(c);
      }
    }
  }

  std::mutex mu_;
  std::condition_variable cv_;
  std::thread thread_;
  std::FILE* file_ = nullptr;
  bool first_ = true;
  bool stop_ = false;
  Clock::time_point t0_;
  std::vector<Event> pending_;
};

TimelineWriter& Writer() {
  static TimelineWriter* w = new TimelineWriter();
  return *w;
}

}  // namespace

extern "C" {

int bf_timeline_start(const char* path) {
  if (path == nullptr) return -1;
  return Writer().Start(path) ? 0 : -1;
}

int bf_timeline_stop() {
  Writer().Stop();
  return 0;
}

int bf_timeline_active() { return Writer().Active() ? 1 : 0; }

void bf_timeline_begin(const char* name, const char* cat, int64_t tid) {
  Writer().Record(name, cat, 'B', tid);
}

void bf_timeline_end(const char* name, const char* cat, int64_t tid) {
  Writer().Record(name, cat, 'E', tid);
}

void bf_timeline_instant(const char* name, const char* cat) {
  Writer().Record(name, cat, 'i', 0);
}

void bf_timeline_async_begin(const char* name, const char* cat, int64_t id) {
  Writer().Record(name, cat, 'b', id);
}

void bf_timeline_async_end(const char* name, const char* cat, int64_t id) {
  Writer().Record(name, cat, 'e', id);
}

}  // extern "C"
