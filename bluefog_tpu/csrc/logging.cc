// Leveled logging (reference parity: bluefog/common/logging.{h,cc} —
// BFLOG macros + BLUEFOG_LOG_LEVEL env; SURVEY.md §2.1, §5).

#include "bf_runtime.h"

#include <atomic>
#include <cctype>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <ctime>
#include <mutex>
#include <string>

namespace {

const char* kLevelNames[] = {"TRACE", "DEBUG", "INFO", "WARN", "ERROR", "OFF"};

// Case-insensitive, matching the Python logger's accepted level names
// (bluefog_tpu/utils/logging.py); "fatal" disables everything we emit
// (we log nothing above error), same as "off".
int LevelFromEnv() {
  const char* env = std::getenv("BLUEFOG_TPU_LOG_LEVEL");
  if (env == nullptr) return 3;  // default: warn
  std::string s(env);
  for (char& c : s) c = static_cast<char>(std::tolower(c));
  if (s == "trace") return 0;
  if (s == "debug") return 1;
  if (s == "info") return 2;
  if (s == "warn" || s == "warning") return 3;
  if (s == "error") return 4;
  if (s == "fatal" || s == "off") return 5;
  char* end = nullptr;
  long v = std::strtol(env, &end, 10);
  if (end != env && v >= 0 && v <= 5) return static_cast<int>(v);
  return 3;
}

std::atomic<int> g_level{LevelFromEnv()};
std::mutex g_io_mutex;

}  // namespace

extern "C" {

int bf_log_level() { return g_level.load(std::memory_order_relaxed); }

void bf_set_log_level(int level) {
  if (level < 0) level = 0;
  if (level > 5) level = 5;
  g_level.store(level, std::memory_order_relaxed);
}

void bf_log(int level, const char* msg) {
  if (level < bf_log_level() || level > 4 || msg == nullptr) return;
  std::timespec ts{};
  std::timespec_get(&ts, TIME_UTC);
  std::tm tm_buf{};
  gmtime_r(&ts.tv_sec, &tm_buf);
  std::lock_guard<std::mutex> lock(g_io_mutex);
  std::fprintf(stderr, "[%02d:%02d:%02d.%03ld][BF][%s] %s\n", tm_buf.tm_hour,
               tm_buf.tm_min, tm_buf.tm_sec, ts.tv_nsec / 1000000,
               kLevelNames[level], msg);
}

}  // extern "C"
