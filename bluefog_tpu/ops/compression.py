"""Compressed decentralized gossip — CHOCO-Gossip on the ppermute fabric.

The reference ships no communication compression (its wire is full-precision
MPI/NCCL buffers; SURVEY.md §2.4), so this module is beyond-reference
surface: CHOCO-Gossip/CHOCO-SGD (Koloskova, Stich & Jaggi, ICML 2019,
arXiv:1902.00340) — the standard algorithm for *compressed* gossip
averaging that still converges to exact consensus.  Plain gossip with
naively compressed payloads does NOT converge (compression noise
accumulates); CHOCO fixes that by gossiping compressed *innovations*
against mirror copies every rank keeps of its neighbors' public state:

    d_i      = x_i − x̂_i                 (innovation vs own public copy)
    q_i      = C(d_i)                     (compressed; this rides the wire)
    x̂_j     += q_j   for j ∈ {i} ∪ in-neighbors   (all mirrors advance)
    x_i     += γ · Σ_j w_ij (x̂_j − x̂_i)          (mix the public copies)

Exact consensus requires a SYMMETRIC doubly-stochastic mixing matrix (ring,
grid, full — not the directed exp2 graph) and γ ∈ (0, 1] sized to the
compression quality; the compressor must be a contraction in expectation:
``E‖C(x) − x‖² ≤ (1 − δ)‖x‖²`` with δ = the kept fraction.

TPU-first wire format: every payload has a STATIC shape (k values per
leaf), so the whole round jits into the same ``lax.ppermute`` fabric as
uncompressed gossip.  The HOST transport twin of these operators — same
top-k value+index format and the same ``_kept`` arithmetic, numpy instead
of jax so socket threads never trace — lives in
:mod:`bluefog_tpu.runtime.wire_codec` and compresses the cross-host DCN
deposit stream (``runtime/window_server.py``); a lockstep test
(``tests/test_window_transport.py``) keeps the two in agreement.  ``random_block_k`` uses a **shared-seed mask**: all
ranks derive the same slice offset from the round counter, so the wire
carries k values and ZERO index bytes — the receiver reconstructs placement
from the seed.  ``top_k`` is data-dependent, so its payload ships indices
alongside values (int32 per kept value).
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from bluefog_tpu.metrics import comm as _mt
from bluefog_tpu.ops.collectives import _acc_dtype, _rank_weights
from bluefog_tpu.topology.schedule import GossipSchedule

__all__ = [
    "Compressor", "identity", "random_block_k", "top_k",
    "ChocoState", "choco_init", "choco_gossip",
    "hierarchical_choco_gossip",
]


class Compressor(NamedTuple):
    """Leaf-wise compression operator with static-shape payloads.

    ``compress(leaf, key) -> payload`` (a pytree of arrays whose shapes
    depend only on ``leaf.shape``); ``decompress(payload, key, like) ->
    dense array of like.shape``.  ``key`` is identical on every rank for a
    given (round, leaf) — shared-seed compressors use it to avoid shipping
    indices; data-dependent ones ignore it.  ``wire_ratio(leaf)`` estimates
    payload bytes / dense bytes for the census.
    """

    name: str
    compress: Callable[[jnp.ndarray, jnp.ndarray], Any]
    decompress: Callable[[Any, jnp.ndarray, jnp.ndarray], jnp.ndarray]
    wire_ratio: Callable[[Any], float]
    delta: float = 1.0  # contraction quality: E||C(x)-x||^2 <= (1-delta)||x||^2


def identity() -> Compressor:
    """No compression (δ = 1): CHOCO degenerates to exact gossip in one
    mirror round — the parity baseline for tests."""
    return Compressor(
        name="identity",
        compress=lambda leaf, key: leaf,
        decompress=lambda payload, key, like: payload,
        wire_ratio=lambda leaf: 1.0,
        delta=1.0,
    )


def _kept(n: int, ratio: float) -> int:
    return max(1, min(n, int(round(ratio * n))))


def random_block_k(ratio: float) -> Compressor:
    """Keep a contiguous block of ⌈ratio·n⌉ coordinates at a shared-seed
    random offset (wrap-around).

    Every coordinate is kept with probability k/n over the random offset, so
    the operator is a δ = k/n contraction in expectation — the CHOCO
    requirement — at O(k) compute (one dynamic slice; no sort, unlike
    coordinate-sampled random-k) and a wire of exactly k values, no indices
    (both sides recompute the offset from the shared key).
    """
    if not 0.0 < ratio <= 1.0:
        raise ValueError(f"ratio must be in (0, 1], got {ratio}")

    def compress(leaf, key):
        flat = leaf.reshape(-1)
        n = flat.size
        k = _kept(n, ratio)
        start = jax.random.randint(key, (), 0, n)
        # mod-index gather (the same indexing decompress scatters with):
        # O(k) transient — doubling the leaf to express the wrap-around
        # would allocate 2x the largest parameter every round
        idx = (start + jnp.arange(k)) % n
        return flat[idx]

    def decompress(payload, key, like):
        flat = jnp.zeros(int(np.prod(like.shape)), payload.dtype)
        n = flat.size
        k = payload.shape[0]
        start = jax.random.randint(key, (), 0, n)
        idx = (start + jnp.arange(k)) % n
        return flat.at[idx].set(payload).reshape(like.shape)

    return Compressor("random_block_k", compress, decompress,
                      lambda leaf: _kept(leaf.size, ratio) / leaf.size,
                      delta=ratio)


def top_k(ratio: float) -> Compressor:
    """Keep the ⌈ratio·n⌉ largest-magnitude coordinates (δ ≥ k/n — top-k is
    at least as contractive as random-k).  Data-dependent, so the wire
    carries int32 indices alongside the values."""
    if not 0.0 < ratio <= 1.0:
        raise ValueError(f"ratio must be in (0, 1], got {ratio}")

    def compress(leaf, key):
        flat = leaf.reshape(-1)
        k = _kept(flat.size, ratio)
        _, idx = lax.top_k(jnp.abs(flat.astype(jnp.float32)), k)
        return {"vals": flat[idx], "idx": idx.astype(jnp.int32)}

    def decompress(payload, key, like):
        flat = jnp.zeros(int(np.prod(like.shape)), payload["vals"].dtype)
        return (flat.at[payload["idx"]].set(payload["vals"])
                .reshape(like.shape))

    def ratio_fn(leaf):
        k = _kept(leaf.size, ratio)
        return k * (leaf.dtype.itemsize + 4) / (leaf.size * leaf.dtype.itemsize)

    return Compressor("top_k", compress, decompress, ratio_fn, delta=ratio)


class ChocoState(NamedTuple):
    """Mirror copies + round counter, carried across gossip rounds."""

    xhat_self: Any   # pytree like x: this rank's public copy
    xhat_nbrs: Any   # pytree with leading dim K: mirror of slot k's source
    round: jnp.ndarray  # int32: drives the shared-seed masks


def choco_init(x, schedule: GossipSchedule) -> ChocoState:
    """Zero mirrors (the algorithm's x̂⁰ = 0 initialization)."""
    zeros = jax.tree_util.tree_map(jnp.zeros_like, x)
    k = schedule.num_slots
    nbrs = jax.tree_util.tree_map(
        lambda t: jnp.zeros((k,) + t.shape, t.dtype), x)
    return ChocoState(zeros, nbrs, jnp.zeros((), jnp.int32))


def choco_gossip(x, state: ChocoState, schedule: GossipSchedule,
                 axis_name: str, *, compressor: Compressor,
                 gamma: float = 1.0, key=None):
    """One CHOCO-Gossip round.  Returns ``(x_new, state_new)``.

    The mask key for (round, leaf) is identical on every rank —
    ``fold_in(key, round)`` then ``fold_in(·, leaf_index)`` — which is what
    lets shared-seed compressors ship value-only payloads.  Payload arrays
    ride the same per-slot ``lax.ppermute`` as uncompressed gossip, so XLA
    overlaps them with surrounding compute identically.
    """
    if key is None:
        key = jax.random.PRNGKey(0)
    key_r = jax.random.fold_in(key, state.round)

    leaves, treedef = jax.tree_util.tree_flatten(x)
    hat_self = jax.tree_util.tree_flatten(state.xhat_self)[0]
    hat_nbrs = jax.tree_util.tree_flatten(state.xhat_nbrs)[0]

    new_x, new_self, new_nbrs = [], [], []
    for li, (leaf, hs, hn) in enumerate(zip(leaves, hat_self, hat_nbrs)):
        lkey = jax.random.fold_in(key_r, li)
        acc = _acc_dtype(leaf)
        payload = compressor.compress((leaf - hs).astype(leaf.dtype), lkey)
        hs2 = hs + compressor.decompress(payload, lkey, leaf)
        # only the received weights enter the mixing term: under the
        # required double stochasticity wsum == 1 - self_weight, so the
        # self weight is implicit in `mix - wsum * hs2`
        _self_w, recv_w = _rank_weights(schedule, axis_name, None, None, acc)
        mix = jnp.zeros(leaf.shape, acc)
        wsum = jnp.zeros((), acc)
        hn2 = []
        for k, perm in enumerate(schedule.perms):
            with jax.named_scope(f"bf.choco.slot{k}"):
                recv = jax.tree_util.tree_map(
                    lambda t: lax.ppermute(t, axis_name, perm), payload)
                hk = hn[k] + compressor.decompress(recv, lkey, leaf)
                hn2.append(hk)
                mix = mix + recv_w[k] * hk.astype(acc)
                wsum = wsum + recv_w[k]
        x2 = (leaf.astype(acc)
              + gamma * (mix - wsum * hs2.astype(acc))).astype(leaf.dtype)
        new_x.append(x2)
        new_self.append(hs2)
        new_nbrs.append(jnp.stack(hn2))

    unf = lambda ls: jax.tree_util.tree_unflatten(treedef, ls)
    x_out = unf(new_x)
    # wire accounting: the payload per slot is each leaf's COMPRESSED
    # innovation (wire_ratio x dense bytes — static per trace); the
    # achieved compression ratio is exported as a gauge so the operator
    # sees what fraction of the dense volume actually hits the wire
    dense = sum(l.size * l.dtype.itemsize for l in leaves)
    wire = sum(compressor.wire_ratio(l) * l.size * l.dtype.itemsize
               for l in leaves)
    if dense:
        _mt.set("bf_compression_ratio", wire / dense,
                compressor=compressor.name)
    x_out = _mt.record_collective(
        x_out, op="choco_gossip",
        bytes_per_round=wire * len(schedule.perms),
        messages_per_round=len(leaves) * len(schedule.perms),
        schedule=schedule.name, backend="xla",
        extra={"compressor": compressor.name})
    return x_out, ChocoState(unf(new_self), unf(new_nbrs),
                             state.round + 1)


def hierarchical_choco_gossip(x, state: ChocoState, machine_schedule,
                              machine_axis: str, local_axis: str, *,
                              compressor: Compressor, gamma: float = 1.0,
                              key=None):
    """Hierarchical compressed gossip: EXACT average inside a machine
    (``pmean`` over the local/ICI axis), CHOCO across machines.

    This is where compression earns its keep: the cross-machine hop rides
    DCN, whose bandwidth is a fraction of ICI's — the reference's
    hierarchical mode (SURVEY.md §2.4) sends full-precision buffers there.
    After the local pmean every rank of a machine holds the identical
    value, so all local ranks advance identical mirror copies and the
    machine behaves as one CHOCO node (no extra synchronization needed).
    Returns ``(x_new, state_new)`` with ``x_new`` identical across each
    machine's local ranks.
    """
    x = jax.tree_util.tree_map(lambda t: lax.pmean(t, local_axis), x)
    return choco_gossip(x, state, machine_schedule, machine_axis,
                        compressor=compressor, gamma=gamma, key=key)
