"""One-sided window ops — the TPU-native answer to MPI RMA.

Reference parity (upstream-relative; names confirmed in BASELINE.json):
``bluefog/torch/mpi_win_ops.{py,cc}`` + ``MPIController::Win*`` in
``bluefog/common/mpi_controller.cc``.  The reference allocates, per registered
tensor, one *self* buffer plus one buffer per in-neighbor backed by
``MPI_Win`` memory; ``win_put``/``win_accumulate`` write into the
destination's buffer without receiver involvement, and ``win_update`` forms a
weighted average of self + neighbor buffers.  Push-sum / gradient-tracking /
exact-diffusion algorithms are built on these (BASELINE.json configs[2,3]).

Design here: a window is a **functional state** (:class:`WindowState`, a
pytree) threaded through the training step.

- Portable backend (this module): the one-sided *dataflow* is expressed with
  ``lax.ppermute`` into per-slot buffers.  Execution is synchronous inside the
  SPMD program (both sides' programs contain the permute — exactly like the
  reference's NCCL backend, which emulates windows with paired
  ``ncclSend``/``ncclRecv``; SURVEY.md §2.4), but the *semantics* are
  one-sided: the destination's values are not consumed until ``win_update``,
  and puts/accumulates from different steps interleave freely.
- TPU backend (``bluefog_tpu.ops.pallas_gossip.deliver_pallas``, routed by
  ``backend='auto'|'pallas'``): within a slice the same state transitions
  run as Pallas async remote DMA (``pltpu.make_async_remote_copy``),
  making the transfer genuinely one-sided at the hardware level.
- Host runtime (``bluefog_tpu.runtime.async_windows`` + the shm/TCP
  transports): the genuinely *asynchronous* execution model — ranks at
  independent rates, deposits crossing thread/process/host boundaries with
  no receiver involvement.

All ops are jit-compatible and pytree-polymorphic.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
from flax import struct
from jax import lax

from bluefog_tpu.blackbox import recorder as _bb
from bluefog_tpu.metrics import comm as _mt
from bluefog_tpu.topology.graphs import Topology
from bluefog_tpu.topology.schedule import GossipSchedule, build_schedule
from bluefog_tpu.utils import timeline as _tl

__all__ = [
    "WindowSpec",
    "WindowState",
    "win_create",
    "win_partition",
    "win_free",
    "win_put",
    "win_get",
    "win_accumulate",
    "win_update",
    "win_update_then_collect",
    "win_sync",
    "win_associated_p",
]


def _as_schedule(s) -> GossipSchedule:
    if isinstance(s, GossipSchedule):
        return s
    if isinstance(s, Topology):
        return build_schedule(s)
    raise TypeError(f"expected Topology or GossipSchedule, got {type(s)}")


class WindowSpec(struct.PyTreeNode):
    """Static window metadata (hashable side of the state).

    ``partition``: the window buffers' declared sharding — a canonical
    ``((leaf_name, PartitionSpec), ...)`` tuple covering ``self_buf``'s
    leaves, resolved from the ONE rule table when the window was created
    with ``win_create(rule_table=)`` (the unified-sharding contract: a
    window buffer is partitioned exactly like the leaf it windows; the
    tuple form keeps the static metadata hashable for jit).  ``None``
    means undeclared (legacy/replicated); a declaration that DISAGREES
    with the live rule table is what the BF-SHD002 lint flags."""

    schedule: GossipSchedule = struct.field(pytree_node=False)
    name: str = struct.field(pytree_node=False, default="win")
    partition: Any = struct.field(pytree_node=False, default=None)


class WindowState(struct.PyTreeNode):
    """Per-rank window memory, as seen inside ``shard_map``.

    Attributes:
      self_buf: pytree — this rank's published value (what peers ``win_get``).
      peer_bufs: matching pytree with a leading ``(K,)`` slot axis — the
        landing buffers for in-edges, one per schedule slot (reference: one
        buffer per in-neighbor).
      spec: static metadata.
      assoc_self / assoc_peers: the **associated push-sum scalar** ``p`` and
        its landing slots — populated when the window was created with
        ``associated_p=True`` (the reference's win-ops-with-associated-p mode,
        SURVEY.md §2.1 ``mpi_win_ops.cc``): every put/accumulate/get moves the
        same weight fraction of ``p`` alongside the tensor, and updates merge
        it with the same weights, so ``self_buf / p`` debiases directed
        (column-substochastic) gossip.  ``None`` when the mode is off.
    """

    self_buf: Any
    peer_bufs: Any
    spec: WindowSpec = struct.field(pytree_node=False)
    assoc_self: Optional[jnp.ndarray] = None
    assoc_peers: Optional[jnp.ndarray] = None


def _slot_mask(sched: GossipSchedule, axis_name: str):
    """(K,) bool — which slots have a real in-edge at this rank."""
    i = lax.axis_index(axis_name)
    return jnp.asarray(sched.recv_src >= 0)[i]


def win_create(x, schedule, axis_name: str, *, name: str = "win",
               associated_p: bool = False, rule_table=None,
               partition=None) -> WindowState:
    """Allocate window buffers for tensor(-tree) ``x``.

    Peer slots are initialized with copies of ``x`` so that a ``win_update``
    before any communication returns ``x`` unchanged (matching the reference's
    WinCreate initialization).  Collective in the reference (all ranks must
    call it); here it is pure allocation.

    ``associated_p=True`` additionally carries the push-sum scalar: ``p``
    starts at 1 on every rank; every subsequent put/accumulate/get/update
    moves and merges it with the tensor's weights.  Read it with
    :func:`win_associated_p`; ``self_buf / p`` is the debiased value.  In
    this mode the landing slots start **empty** (zeros for both tensor and
    ``p``) so the (x, p) mass pairs stay consistent: all initial mass lives
    at self with weight 1.

    ``rule_table`` (a :class:`bluefog_tpu.sharding.RuleTable`): resolve
    and DECLARE the window buffers' partitioning from the one rule table
    — the same table that shards the parameters and optimizer state, so
    changing a rule re-shards the window consistently.  ``partition``
    (a matching spec pytree, or the canonical name->spec tuple) declares
    it explicitly instead; the BF-SHD002 lint flags a declaration that
    disagrees with the table.  Read back with :func:`win_partition`.
    """
    sched = _as_schedule(schedule)
    k = sched.num_slots
    if rule_table is not None and partition is not None:
        raise ValueError("pass rule_table OR partition, not both")
    if rule_table is not None:
        partition = rule_table.resolve_tree(x)
    if partition is not None and not isinstance(partition, tuple):
        from bluefog_tpu.sharding.rules import named_leaves as _nl

        from jax.sharding import PartitionSpec as _P

        partition = tuple(
            (n, s) for n, s in _nl(
                partition, is_leaf=lambda v: isinstance(v, _P)))

    def init_peers(leaf):
        if associated_p:
            return jnp.zeros((k,) + leaf.shape, leaf.dtype)
        return jnp.broadcast_to(leaf[None], (k,) + leaf.shape).astype(leaf.dtype)

    return WindowState(
        self_buf=jax.tree_util.tree_map(jnp.asarray, x),
        peer_bufs=jax.tree_util.tree_map(init_peers, x),
        spec=WindowSpec(schedule=sched, name=name, partition=partition),
        assoc_self=jnp.ones(()) if associated_p else None,
        assoc_peers=jnp.zeros((k,)) if associated_p else None,
    )


def win_partition(state: WindowState):
    """The window buffers' declared partitioning: ``{leaf_name:
    PartitionSpec}`` resolved from the rule table at :func:`win_create`
    time, or ``None`` when the window was created undeclared
    (legacy/replicated).  This is the readback the BF-SHD002 lint checks
    against the LIVE rule table — a window created under one table and
    gossiped under another is a silent wire-shape mismatch."""
    part = state.spec.partition
    if part is None:
        return None
    return dict(part)


def win_associated_p(state: WindowState) -> jnp.ndarray:
    """The window's associated push-sum scalar ``p`` (reference: the
    associated-p readback)."""
    if state.assoc_self is None:
        raise ValueError(
            f"window {state.spec.name!r} was created without associated_p")
    return state.assoc_self


def win_free(state: WindowState) -> None:
    """Parity no-op — functional state is freed by dropping the reference."""
    return None


def _deliver(state: WindowState, payload, axis_name: str, *, accumulate: bool,
             backend: str = "auto",
             assoc_payload=None, op_name: str = "bf.win_deliver") -> WindowState:
    sched = state.spec.schedule
    # per-op B/E runtime spans (identity without an active timeline): B once
    # the payload is live, E once the landing buffers materialize — the
    # reference's per-tensor stage events for the window family
    payload = _tl.device_stage(payload, op_name, phase="B",
                               category="window", axis_name=axis_name)
    # blackbox round markers for the window family (identity unless
    # BLUEFOG_TPU_BLACKBOX=jit at trace time)
    bb_cid = _bb.next_collective_id(op_name.replace("bf.", ""))
    bb_fields = {"op": op_name.replace("bf.", ""), "cid": bb_cid,
                 "window": state.spec.name,
                 "bytes": _mt.tree_bytes(payload)}
    payload = _bb.traced_event(payload, "collective_begin",
                               fields=bb_fields, axis_name=axis_name)
    # same routing policy as gossip (auto_gossip_backend's stated
    # conditions) — the window transport is the same fused RDMA kernel
    # family in 'put'/'acc' mode.  chunkable=False: the landing buffers are
    # persistent window state, so oversized payloads route to XLA here
    # instead of chunking (the gossip path chunks).
    from bluefog_tpu.ops import pallas_gossip

    backend = pallas_gossip.resolve_backend(backend, sched, payload,
                                            chunkable=False)
    mask = _slot_mask(sched, axis_name)

    def per_leaf(peers, leaf):
        new_slots = []
        for k, perm in enumerate(sched.perms):
            recvd = lax.ppermute(leaf, axis_name, perm)
            slot = peers[k] + recvd if accumulate else recvd
            # Slots with no in-edge this rank got zeros from the permute:
            # keep the old buffer there.
            new_slots.append(jnp.where(mask[k], slot, peers[k]))
        return jnp.stack(new_slots) if new_slots else peers

    new_assoc = state.assoc_peers
    if state.assoc_self is not None and assoc_payload is not None:
        # the associated scalar rides the portable path on every backend —
        # a () payload is latency noise next to the tensor transfer
        new_assoc = per_leaf(state.assoc_peers, assoc_payload)

    if backend == "pallas":
        # distinct collective_id per leaf (leaf kernels may overlap on
        # hardware; each needs its own barrier semaphore), and a distinct
        # NAME-derived base per window — two windows delivered in one
        # jitted program (e.g. gradient-tracking's x and y windows) must
        # not share semaphores either.  Windows own ids [2048, ...); gossip
        # owns [1024, 2048) — see ops/collectives.py.
        base = pallas_gossip.window_collective_id_base(state.spec.name)
        peer_leaves, treedef = jax.tree_util.tree_flatten(state.peer_bufs)
        if len(peer_leaves) > pallas_gossip.WINDOW_LEAF_CAP:
            raise ValueError(
                f"window {state.spec.name!r} has {len(peer_leaves)} leaves, "
                f"above the {pallas_gossip.WINDOW_LEAF_CAP}-leaf pallas cap "
                "(collective ids would bleed into the next window's bucket); "
                "use backend='xla' or fuse leaves")
        payload_leaves = treedef.flatten_up_to(payload)
        # trace-time lease record: the analysis audit sees this window's
        # id bucket next to every concurrent gossip/window lease in the
        # program (window buckets are disjoint by construction via the
        # CRC32 claim table; the lease makes that checkable, not assumed)
        from bluefog_tpu.analysis.registry import GLOBAL_LEASES

        GLOBAL_LEASES.lease(
            f"window:{state.spec.name}", base=base, used=len(peer_leaves),
            limit=base + pallas_gossip.WINDOW_LEAF_CAP, family="windows")
        outs = [
            pallas_gossip.deliver_pallas(
                leaf, peers, sched, axis_name, accumulate=accumulate,
                collective_id=base + idx,
            )
            for idx, (peers, leaf) in enumerate(zip(peer_leaves, payload_leaves))
        ]
        new_peers = jax.tree_util.tree_unflatten(treedef, outs)
    else:
        new_peers = jax.tree_util.tree_map(per_leaf, state.peer_bufs, payload)
    # wire accounting for the window family: every slot ships the full
    # payload tree (identity when metrics are off)
    new_peers = _mt.record_collective(
        new_peers, op=op_name.replace("bf.", ""),
        bytes_per_round=_mt.tree_bytes(payload) * sched.num_slots,
        messages_per_round=_mt.tree_leaf_count(payload) * sched.num_slots,
        schedule=sched.name, backend=backend,
        extra={"window": state.spec.name})
    new_peers = _bb.traced_event(new_peers, "collective_end",
                                 fields=bb_fields, axis_name=axis_name)
    new_peers = _tl.device_stage(new_peers, op_name, phase="E",
                                 category="window", axis_name=axis_name)
    return state.replace(peer_bufs=new_peers, assoc_peers=new_assoc)


def _weighted(dst_weight):
    """``leaf -> dst_weight * leaf`` with f32 arithmetic for low-precision
    leaves (push-sum fractions like 1/3 are not representable in bf16/f16 —
    the same concern the reference's fp16 custom MPI sum addresses,
    SURVEY.md §2.1 ``half.h``)."""

    def apply(leaf):
        acc = (jnp.float32 if leaf.dtype in (jnp.bfloat16, jnp.float16)
               else leaf.dtype)
        return (jnp.asarray(dst_weight, acc) * leaf.astype(acc)).astype(
            leaf.dtype)

    return apply


def _prepare_payload(state: WindowState, x, dst_weight):
    """Shared put/accumulate preamble: ``x=None`` ships the tracked
    ``self_buf`` (the associated-p mass-safe path); the associated scalar is
    weighted identically."""
    if x is not None and state.assoc_self is not None:
        # Shipping a tensor that is not the window's tracked state would
        # silently desynchronize the (x, p) push-sum recursion and bias
        # self_buf / p — a convergence bug with no visible symptom.  Force
        # callers through x=None (ships self_buf) or win_sync first.
        raise ValueError(
            f"window {state.spec.name!r} carries an associated push-sum "
            "scalar; pass x=None (ships self_buf) or win_sync(state, x) "
            "first so the (x, p) mass pair stays consistent")
    if x is None:
        x = state.self_buf
    payload = jax.tree_util.tree_map(_weighted(dst_weight), x)
    assoc = (None if state.assoc_self is None
             else _weighted(dst_weight)(state.assoc_self))
    return payload, assoc


def win_put(
    state: WindowState,
    x,
    axis_name: str,
    *,
    dst_weight=1.0,
    backend: str = "auto",
) -> WindowState:
    """Write ``dst_weight * x`` into every out-neighbor's landing buffer.

    ``dst_weight`` may be a traced scalar (push-sum sends ``1/(out_deg+1)``
    fractions — the reference's per-call ``dst_weights``).  The destination is
    not involved until it chooses to ``win_update``.  ``backend='pallas'``
    performs the transfer as a genuine one-sided RDMA on TPU slices.

    Associated-p windows: the scalar ``dst_weight * p`` ships alongside.
    Mass consistency requires the tensor shipped to be the window's tracked
    state — pass ``x=None`` (ships ``self_buf``) or ``win_sync`` the value in
    first; an explicit ``x`` on an associated-p window raises, because
    shipping an unrelated tensor silently desynchronizes the (x, p)
    recursions and biases ``self_buf / p``.
    """
    payload, assoc = _prepare_payload(state, x, dst_weight)
    return _deliver(state, payload, axis_name, accumulate=False,
                    backend=backend, assoc_payload=assoc,
                    op_name="bf.win_put")


def win_accumulate(
    state: WindowState,
    x,
    axis_name: str,
    *,
    dst_weight=1.0,
    backend: str = "auto",
) -> WindowState:
    """Like :func:`win_put` but adds into the destination buffer
    (``MPI_Accumulate(MPI_SUM)`` semantics).  The associated-p mass caveat in
    :func:`win_put` applies: pass ``x=None`` to ship ``self_buf``."""
    payload, assoc = _prepare_payload(state, x, dst_weight)
    return _deliver(state, payload, axis_name, accumulate=True,
                    backend=backend, assoc_payload=assoc,
                    op_name="bf.win_accumulate")


def win_get(state: WindowState, axis_name: str) -> WindowState:
    """Pull each in-neighbor's *published* value (their ``self_buf``) into the
    corresponding landing slot (one-sided read)."""
    return _deliver(state, state.self_buf, axis_name, accumulate=False,
                    assoc_payload=state.assoc_self, op_name="bf.win_get")


def win_update(
    state: WindowState,
    axis_name: str,
    *,
    self_weight=None,
    recv_weights=None,
):
    """Weighted-average self + landing buffers; publish and return the result.

    ``out = w_self * self_buf + sum_k w_k * peer_bufs[k]``, with weights from
    the window's topology by default (per-call overrides as in the reference).
    Returns ``(out, new_state)`` with ``self_buf = out``.
    """
    sched = state.spec.schedule
    i = lax.axis_index(axis_name)
    mask = _slot_mask(sched, axis_name)
    state = state.replace(self_buf=_tl.device_stage(
        state.self_buf, "bf.win_update", phase="B", category="window",
        axis_name=axis_name))

    def one(self_leaf, peers):
        acc_dt = jnp.float32 if self_leaf.dtype in (jnp.bfloat16, jnp.float16) else self_leaf.dtype
        if self_weight is None:
            w_self = jnp.asarray(sched.self_weights, acc_dt)[i]
        else:
            w_self = jnp.asarray(self_weight, acc_dt)
        if recv_weights is None:
            w_recv = jnp.asarray(sched.recv_weights, acc_dt)[i]
        else:
            w_recv = jnp.asarray(recv_weights, acc_dt)
        out = w_self * self_leaf.astype(acc_dt)
        for k in range(sched.num_slots):
            out = out + jnp.where(mask[k], w_recv[k], 0.0) * peers[k].astype(acc_dt)
        return out.astype(self_leaf.dtype)

    out = jax.tree_util.tree_map(one, state.self_buf, state.peer_bufs)
    # no wire transfer — count the merge rounds so deposit volume can be
    # read per consume (bytes/update = deposit bytes / update rounds)
    out = _mt.count(out, [("bf_window_update_rounds_total", 1.0)],
                    {"op": "win_update", "window": state.spec.name})
    out = _tl.device_stage(out, "bf.win_update", phase="E",
                           category="window", axis_name=axis_name)
    new_state = state.replace(self_buf=out)
    if state.assoc_self is not None:
        new_state = new_state.replace(
            assoc_self=one(state.assoc_self, state.assoc_peers))
    return out, new_state


def win_update_then_collect(state: WindowState, axis_name: str):
    """Sum-collect variant used by push-sum: ``out = self_buf + sum_k
    peer_bufs[k]`` over real slots, then **reset** the landing buffers to zero
    (accumulated mass must be consumed exactly once).  Returns
    ``(out, new_state)``.

    Mirrors the reference's ``win_update_then_collect`` (upstream —
    UNVERIFIED exact reset semantics; chosen to conserve push-sum mass).
    """
    sched = state.spec.schedule
    mask = _slot_mask(sched, axis_name)
    state = state.replace(self_buf=_tl.device_stage(
        state.self_buf, "bf.win_update_then_collect", phase="B",
        category="window", axis_name=axis_name))

    def one(self_leaf, peers):
        acc_dt = jnp.float32 if self_leaf.dtype in (jnp.bfloat16, jnp.float16) else self_leaf.dtype
        out = self_leaf.astype(acc_dt)
        for k in range(sched.num_slots):
            out = out + jnp.where(mask[k], 1.0, 0.0) * peers[k].astype(acc_dt)
        return out.astype(self_leaf.dtype)

    out = jax.tree_util.tree_map(one, state.self_buf, state.peer_bufs)
    out = _tl.device_stage(out, "bf.win_update_then_collect", phase="E",
                           category="window", axis_name=axis_name)
    zeroed = jax.tree_util.tree_map(jnp.zeros_like, state.peer_bufs)
    new_state = state.replace(self_buf=out, peer_bufs=zeroed)
    if state.assoc_self is not None:
        new_state = new_state.replace(
            assoc_self=one(state.assoc_self, state.assoc_peers),
            assoc_peers=jnp.zeros_like(state.assoc_peers))
    return out, new_state


def win_sync(state: WindowState, x=None) -> WindowState:
    """Publish a new local value without communicating (the reference's
    ``win_sync``-style refresh of the self window)."""
    if x is None:
        return state
    return state.replace(self_buf=jax.tree_util.tree_map(jnp.asarray, x))
