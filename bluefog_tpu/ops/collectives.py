"""Collective ops for decentralized training, as SPMD primitives.

Every function here is designed to be called *inside* a ``shard_map``-ed (or
``pmap``-ed) function body, with ``axis_name`` naming the gossip mesh axis.
They are pure, jit-compatible, and work on arbitrary pytrees.

Reference parity (upstream-relative; see SURVEY.md §2.2/§3):

===========================================  ===================================
reference (``bluefog/torch/mpi_ops.py``)     here
===========================================  ===================================
``allreduce(tensor, average=True)``          :func:`allreduce`
``broadcast(tensor, root_rank)``             :func:`broadcast`
``allgather(tensor)``                        :func:`allgather`
``neighbor_allreduce(t, self_weight,         :func:`neighbor_allreduce`
  src_weights, dst_weights)``                  (weights via schedule or
                                               per-call overrides)
dynamic per-call topology                    :func:`neighbor_allreduce_dynamic`
``neighbor_allgather(t)``                    :func:`neighbor_allgather`
``hierarchical_neighbor_allreduce(t)``       :func:`hierarchical_neighbor_allreduce`
``barrier()``                                :func:`barrier`
``pair_gossip(t, target_rank)``              :func:`pair_gossip`
===========================================  ===================================

The reference executes the weighted average on the host CPU after
``MPI_Neighbor_allgatherv`` (SURVEY.md §3.2); here the ``ppermute`` payloads
and the weighted sum are one fused XLA computation that overlaps with
surrounding compute — the background-thread/negotiation machinery of
``bluefog/common/operations.cc`` has no equivalent because XLA's static
schedule already guarantees every rank issues identical collectives in
identical order.
"""

from __future__ import annotations

import functools
import itertools
import warnings
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from bluefog_tpu.blackbox import recorder as _bb
from bluefog_tpu.metrics import comm as _mt
from bluefog_tpu.metrics import registry as _mreg
from bluefog_tpu.topology.graphs import Topology
from bluefog_tpu.topology.schedule import GossipSchedule, build_schedule
from bluefog_tpu.utils import timeline as _tl

__all__ = [
    "allreduce",
    "allgather",
    "broadcast",
    "barrier",
    "fuse_apply",
    "neighbor_allreduce",
    "sharded_neighbor_allreduce",
    "neighbor_allgather",
    "neighbor_allreduce_dynamic",
    "neighbor_allreduce_aperiodic",
    "hierarchical_neighbor_allreduce",
    "hierarchical_neighbor_allreduce_2d",
    "pair_gossip",
]


def fuse_apply(fn, x, *, threshold_bytes: int = 8 << 20):
    """Tensor fusion: run a tree-polymorphic collective on ONE flat buffer
    per dtype instead of per-leaf.

    The reference batches small tensors through a fusion buffer so each
    negotiation round issues one wire transfer (`bluefog/common/tensor_queue`
    fusion-buffer manager, SURVEY.md §2.1).  The XLA analog: a model like
    ResNet-50 has ~160 parameter leaves, and leaf-wise gossip emits ~160
    ``ppermute`` ops per schedule slot — each with its own latency.  Packing
    the tree into a single 1-D buffer per dtype turns that into one large
    bandwidth-bound transfer per slot, then splits back.

    Leaves at or above ``threshold_bytes`` ship unfused: a large tensor is
    already one bandwidth-bound transfer, so concatenating it buys no latency
    and costs a full transient copy of the leaf (concat + split) in HBM —
    the same reason the reference's fusion buffer has a size cutoff.  Set
    ``threshold_bytes=None`` to fuse everything.

    ``fn`` must be shape-polymorphic and leaf-wise (all collectives here
    are).  Leaves keep their dtypes: each dtype group is fused separately, so
    mixed bf16/f32 trees behave exactly as unfused.
    """
    leaves, treedef = jax.tree_util.tree_flatten(x)
    if len(leaves) <= 1:
        return fn(x)
    big = set()
    if threshold_bytes is not None:
        for i, leaf in enumerate(leaves):
            a = jnp.asarray(leaf)
            if a.size * a.dtype.itemsize >= threshold_bytes:
                big.add(i)
    groups: dict = {}  # dtype str -> small-leaf indices
    for i, leaf in enumerate(leaves):
        if i not in big:
            groups.setdefault(str(jnp.asarray(leaf).dtype), []).append(i)
    bufs = {
        dt: jnp.concatenate([jnp.asarray(leaves[i]).ravel() for i in idxs])
        for dt, idxs in groups.items()
    }
    # One fn call over {fused buffers} ∪ {large leaves}: fn is leaf-wise, so
    # large leaves ride the same collective unfused, with no extra copy.
    out_all = fn({"fused": bufs,
                  "big": {str(i): leaves[i] for i in sorted(big)}})
    out_bufs, out_big = out_all["fused"], out_all["big"]
    out = [None] * len(leaves)
    for i in big:
        out[i] = out_big[str(i)]
    for dt, idxs in groups.items():
        buf, off = out_bufs[dt], 0
        for i in idxs:
            sz = int(np.prod(jnp.shape(leaves[i]), dtype=np.int64))
            out[i] = buf[off:off + sz].reshape(jnp.shape(leaves[i]))
            off += sz
    return jax.tree_util.tree_unflatten(treedef, out)


def axis_size(axis_name) -> int:
    """Size of a named mesh axis, as a trace-time Python int.

    ``jax.lax.axis_size`` only exists in newer jax releases; on older ones
    the pre-API idiom ``psum(1, axis)`` folds to the same constant at
    trace time.
    """
    size = getattr(lax, "axis_size", None)
    if size is not None:
        return size(axis_name)
    return lax.psum(1, axis_name)


# one group token per neighbor_allreduce_dynamic call site: the switch's
# branches are mutually exclusive at runtime, so their (identical) id
# leases must not be audited against each other — but two DIFFERENT
# dynamic calls in one program still must not share ids
_dynamic_group_counter = itertools.count()


def _as_schedule(s) -> GossipSchedule:
    if isinstance(s, GossipSchedule):
        return s
    if isinstance(s, Topology):
        return build_schedule(s)
    raise TypeError(f"expected Topology or GossipSchedule, got {type(s)}")


def _rank_weights(
    schedule: GossipSchedule,
    axis_name: str,
    self_weight,
    recv_weights,
    dtype,
):
    """Per-rank (self_w, recv_w[K]) as traced scalars, f32 accumulate dtype."""
    i = lax.axis_index(axis_name)
    if self_weight is None:
        self_w = jnp.asarray(schedule.self_weights, dtype=dtype)[i]
    else:
        self_w = jnp.asarray(self_weight, dtype=dtype)
    if recv_weights is None:
        recv_w = jnp.asarray(schedule.recv_weights, dtype=dtype)[i]
    else:
        recv_w = jnp.asarray(recv_weights, dtype=dtype)
    return self_w, recv_w


def _acc_dtype(x) -> jnp.dtype:
    # Accumulate gossip averages in f32 when inputs are low-precision: the
    # mixing weights (1/3, 1/5, ...) are not representable in bf16 and the
    # repeated averaging is exactly the kind of op that drifts.
    if x.dtype in (jnp.bfloat16, jnp.float16):
        return jnp.float32
    return x.dtype


def neighbor_allreduce(
    x,
    schedule,
    axis_name: str,
    *,
    self_weight=None,
    recv_weights=None,
    send_weights=None,
    backend: str = "auto",
    collective_id_base: int = 1024,
    collective_id_limit: Optional[int] = None,
    collective_id_group: Optional[str] = None,
):
    """Weighted average with in-neighbors: ``out_i = w_ii x_i + sum_k w_ik x_k``.

    Args:
      x: array or pytree; each rank's local value.
      schedule: :class:`GossipSchedule` (or a :class:`Topology`, lowered on the
        fly — prefer pre-building at setup time).
      axis_name: the gossip mesh axis.
      self_weight / recv_weights: optional per-call traced overrides (scalar /
        ``(num_slots,)``), the analog of the reference's per-call
        ``self_weight=/src_weights=`` arguments.  Because only *weights* change
        (the ppermute pattern is static), overriding them does not recompile.
      send_weights: optional per-call SENDER-side scaling, the analog of the
        reference's ``dst_weights=`` (each rank scales what it ships per out
        slot before the transfer): ``(num_slots,)`` traced — slot ``k``'s
        payload leaves this rank as ``send_weights[k] * x`` — or a
        ``(size, num_slots)`` table, from which each rank takes its own row.
        The receiver's ``recv_weights`` then apply on top, exactly as
        upstream composes ``src_weights`` x ``dst_weights``.  Sender-side
        scaling is an XLA-path feature: ``backend='auto'`` quietly keeps
        XLA, and forcing ``backend='pallas'`` with it raises (the fused
        kernel folds weights on the arrival path only).

    Lowering: one ``lax.ppermute`` per schedule slot (a single ICI rotation
    for circulant graphs) + fused multiply-adds; or the fused RDMA kernel
    (:mod:`bluefog_tpu.ops.pallas_gossip`), which folds the weighted
    reduction into the arrival path.  ``backend``: ``'xla'`` and
    ``'pallas'`` force a path; ``'auto'`` selects per call under the stated
    conditions of :func:`bluefog_tpu.ops.pallas_gossip.auto_gossip_backend`
    (real TPU slice, multi-device, circulant schedule — else XLA).  On the
    pallas path, leaves beyond the per-invocation VMEM cap are split into
    cap-sized chunks (one kernel each), so fused optimizer buffers ride the
    RDMA kernels by default.

    ``collective_id_base`` / ``collective_id_limit``: the half-open id
    range ``[base, limit)`` this call's pallas kernels enumerate
    barrier-semaphore ids from (gossip owns [1024, 2048); ``limit=None``
    declares the whole tail up to 2048).  A program that issues SEVERAL
    pallas gossip calls over trees with no data dependency between them
    (e.g. gradient tracking's y-mix and params-mix) must give each call a
    DISJOINT range — devices may be skewed across the calls' kernels, and
    sharing a barrier semaphore would let one call's handshake absorb
    another's signals.  The chunk plan is validated against the CALLER'S
    ``limit``, not just the family bound, so an oversized tree cannot
    silently bleed into a sibling's ids; on ``backend='auto'`` an
    over-limit plan falls back to XLA (slower, correct) while a forced
    ``'pallas'`` raises.  Each pallas call records a
    :class:`~bluefog_tpu.analysis.registry.CollectiveIdLease` at trace
    time, so ``bluefog_tpu.analysis`` can audit the traced program for
    overlaps.  The audit is CONSERVATIVE — it sees leases, not data
    dependence, so it flags every same-family overlap as if the kernels
    could run concurrently.  ``collective_id_group`` is the sanctioned
    suppression: give the same group string to call sites that can never
    be in flight together — the branches of one ``lax.switch``
    (``neighbor_allreduce_dynamic`` does this itself), or sequential
    calls chained by data dependence (the output of one feeding the
    input of the next) — and the audit will not flag them against each
    other.  Calls with NO data dependency between them (e.g. gradient
    tracking's y-mix and params-mix) must instead use disjoint ranges.
    """
    sched = _as_schedule(schedule)

    from bluefog_tpu.ops import pallas_gossip

    requested_backend = backend
    if send_weights is not None and backend == "pallas":
        raise NotImplementedError(
            "backend='pallas' cannot honor send_weights: the fused RDMA "
            "kernel folds weights on the ARRIVAL path only.  Use "
            "backend='xla' (same math), or fold the sender scaling into "
            "recv_weights when it is uniform per slot")
    if send_weights is not None and backend == "auto":
        backend = "xla"  # sender-side scaling is an XLA-path feature
    else:
        backend = pallas_gossip.resolve_backend(backend, sched, x)
    # runtime per-round spans (B once inputs are live, E once the weighted
    # merge materializes; per-rank lanes) — identity unless a timeline is
    # active at trace time.  The reference emits the analogous per-tensor
    # enqueue/execute stage events from operations.cc (SURVEY.md §5).
    x = _tl.device_stage(x, "bf.neighbor_allreduce", phase="B",
                         axis_name=axis_name)
    # blackbox flight-recorder round markers (identity unless
    # BLUEFOG_TPU_BLACKBOX=jit at trace time): a begin without a matching
    # end in a hang dump names the exact round this rank wedged in.  The
    # cid is a trace-time call-site id, identical across SPMD processes,
    # so bfblackbox-tpu can align ranks on (step, cid).
    bb_cid = _bb.next_collective_id("neighbor_allreduce")
    bb_fields = {"op": "neighbor_allreduce", "cid": bb_cid,
                 "schedule": sched.name, "bytes": _mt.tree_bytes(x)}
    x = _bb.traced_event(x, "collective_begin", fields=bb_fields,
                         axis_name=axis_name)

    if backend == "pallas":
        # distinct collective_id per kernel invocation: DEVICES may be
        # skewed in time (device A already in chunk k+1's kernel while B is
        # still in chunk k), so sharing one barrier semaphore would let one
        # kernel's handshake absorb another's signals.  Gossip owns ids
        # [1024, 2048); the window transport owns [2048, ...)
        # (ops/windows.py), so the two kernel families can never share a
        # barrier semaphore inside one program.  Aggregate VMEM stays
        # bounded regardless of chunk count: a TensorCore executes one
        # Mosaic kernel at a time, so at most (num_slots+2) cap-sized
        # copies are ever resident.
        #
        # Leaves larger than the per-invocation cap (the kernel keeps
        # (num_slots+2) whole-payload copies resident in VMEM) are CHUNKED
        # into cap-sized pieces rather than routed to XLA: this is what
        # makes the RDMA kernels the real default under fuse_apply's
        # one-flat-buffer-per-dtype optimizer trees, and it preserves the
        # kernel's advantage — every received chunk accumulates in VMEM on
        # arrival instead of materializing in HBM like a ppermute output.
        leaves, treedef = jax.tree_util.tree_flatten(x)
        limit = pallas_gossip.auto_max_bytes()
        n_invocations = sum(
            pallas_gossip.leaf_chunk_count(leaf, limit) for leaf in leaves)
        id_limit = 2048 if collective_id_limit is None else collective_id_limit
        if not 1024 <= collective_id_base < 2048:
            raise ValueError(
                f"collective_id_base {collective_id_base} outside the "
                "gossip id range [1024, 2048)")
        if not collective_id_base < id_limit <= 2048:
            raise ValueError(
                f"collective_id_limit {id_limit} must lie in "
                f"({collective_id_base}, 2048]")
        if collective_id_base + n_invocations > id_limit:
            if requested_backend == "pallas":
                raise ValueError(
                    f"pallas gossip needs {n_invocations} kernel "
                    f"invocations ({len(leaves)} leaves after chunking) "
                    f"from base {collective_id_base}, exceeding this "
                    f"call's collective-id limit {id_limit}; fuse the "
                    "tree first (fuse_apply), raise "
                    "BLUEFOG_TPU_PALLAS_MAX_BYTES, or widen the caller's "
                    "id lease")
            # backend='auto': an over-limit chunk plan takes the (slower,
            # always-correct) XLA path instead of hard-failing a run that
            # the pre-chunking code would have completed — but audibly:
            # the performance cliff must be visible to the user (warning
            # dedup keeps this to once per call site)
            warnings.warn(
                f"neighbor_allreduce backend='auto': chunk plan needs "
                f"{n_invocations} pallas kernel ids from base "
                f"{collective_id_base}, exceeding the call's id limit "
                f"{id_limit}; falling back to the XLA path (correct but "
                "slower — no fused RDMA kernels). Fuse the tree "
                "(fuse_apply), raise BLUEFOG_TPU_PALLAS_MAX_BYTES, or "
                "widen the caller's id lease.",
                stacklevel=3)
            backend = "xla"
        else:
            from bluefog_tpu.analysis.registry import GLOBAL_LEASES

            GLOBAL_LEASES.lease(
                f"neighbor_allreduce[{sched.name}]@{collective_id_base}",
                base=collective_id_base, used=n_invocations,
                limit=id_limit, family="gossip",
                exclusive_group=collective_id_group)

    if backend == "pallas":
        cid = collective_id_base
        outs = []
        for leaf in leaves:
            n_chunks = pallas_gossip.leaf_chunk_count(leaf, limit)
            if n_chunks == 1:
                outs.append(pallas_gossip.neighbor_allreduce_pallas(
                    leaf, sched, axis_name,
                    self_weight=self_weight, recv_weights=recv_weights,
                    collective_id=cid))
                cid += 1
                continue
            flat = leaf.reshape(-1)
            chunk_outs = []
            for piece in jnp.array_split(flat, n_chunks):
                chunk_outs.append(pallas_gossip.neighbor_allreduce_pallas(
                    piece, sched, axis_name,
                    self_weight=self_weight, recv_weights=recv_weights,
                    collective_id=cid))
                cid += 1
            outs.append(jnp.concatenate(chunk_outs).reshape(leaf.shape))
        out = jax.tree_util.tree_unflatten(treedef, outs)
        # per-round wire accounting (identity when metrics are off): each
        # kernel invocation performs one transfer per schedule slot of its
        # chunk; bytes = what this rank ships per round
        out = _mt.record_collective(
            out, op="neighbor_allreduce",
            bytes_per_round=_mt.tree_bytes(x) * sched.num_slots,
            messages_per_round=n_invocations * sched.num_slots,
            schedule=sched.name, backend="pallas", chunks=n_invocations)
        out = _bb.traced_event(out, "collective_end", fields=bb_fields,
                               axis_name=axis_name)
        return _tl.device_stage(out, "bf.neighbor_allreduce", phase="E",
                                axis_name=axis_name)

    send_w = (None if send_weights is None
              else jnp.asarray(send_weights, jnp.float32))
    if send_w is not None and send_w.ndim == 2:
        # (size, num_slots) table: take this rank's row
        send_w = send_w[lax.axis_index(axis_name)]

    def one(leaf):
        acc_dt = _acc_dtype(leaf)
        self_w, recv_w = _rank_weights(sched, axis_name, self_weight, recv_weights, acc_dt)
        out = self_w * leaf.astype(acc_dt)
        for k, perm in enumerate(sched.perms):
            # named_scope: per-slot attribution in jax.profiler/Perfetto
            # device traces (free — trace-time metadata only)
            with jax.named_scope(f"bf.neighbor_allreduce.slot{k}"):
                shipped = (leaf if send_w is None
                           else (send_w[k].astype(acc_dt)
                                 * leaf.astype(acc_dt)).astype(leaf.dtype))
                recvd = lax.ppermute(shipped, axis_name, perm)
                out = out + recv_w[k] * recvd.astype(acc_dt)
        return out.astype(leaf.dtype)

    out = jax.tree_util.tree_map(one, x)
    # one ppermute per slot per leaf; every slot ships the full tree
    out = _mt.record_collective(
        out, op="neighbor_allreduce",
        bytes_per_round=_mt.tree_bytes(x) * sched.num_slots,
        messages_per_round=_mt.tree_leaf_count(x) * sched.num_slots,
        schedule=sched.name, backend="xla")
    out = _bb.traced_event(out, "collective_end", fields=bb_fields,
                           axis_name=axis_name)
    return _tl.device_stage(out, "bf.neighbor_allreduce", phase="E",
                            axis_name=axis_name)


def sharded_neighbor_allreduce(
    x,
    schedule,
    axis_name: str,
    *,
    rule_table=None,
    specs=None,
    inner_axes=None,
    **kwargs,
):
    """Gossip-of-meshes :func:`neighbor_allreduce`: the gossip step of a
    hybrid ``(bf, fsdp/tp)`` mesh, where every leaf of ``x`` is a LOCAL
    SHARD and each inner-mesh coordinate exchanges only its own shard
    with the same coordinate on neighbor meshes.

    Call inside ``shard_map`` over the hybrid mesh.  Because gossip is
    element-wise, shard-locality needs no extra collectives — the
    ``ppermute`` over ``axis_name`` already moves only the local shard;
    what this wrapper adds is the RULE-TABLE contract and its
    enforcement:

    - ``rule_table`` (a :class:`bluefog_tpu.sharding.RuleTable`) or a
      pre-resolved ``specs`` pytree declares every leaf's partitioning —
      the same single source of truth that shards the parameters,
      optimizer state, and window buffers.  A leaf whose spec mentions
      ``axis_name`` raises: sharding the gossip axis would mix
      *different* model coordinates across ranks, which is never what a
      decentralized-DP outer loop means.
    - **No gather on the hot path** is a checked property, not a hope:
      the BF-SHD lint pass traces this function over a hybrid mesh and
      walks the jaxpr for ``all_gather``/``all_to_all`` over the inner
      axes (BF-SHD003).
    - Per-execution wire accounting: ``bf_sharded_bytes_total`` (shard
      bytes this rank ships per round) and
      ``bf_gather_bytes_saved_total`` (what gather-then-gossip would
      have added), labelled with the joined inner axes.

    ``inner_axes``: ``{axis: size}`` of the inner mesh (used for the
    savings accounting and axis validation); remaining ``kwargs`` pass
    through to :func:`neighbor_allreduce`.
    """
    from bluefog_tpu.sharding.mesh import shard_size_ratio
    from bluefog_tpu.sharding.rules import (RuleTable as _RuleTable,
                                            spec_mentions as _spec_mentions)

    if rule_table is not None and specs is not None:
        raise ValueError("pass rule_table OR specs, not both")
    if isinstance(rule_table, _RuleTable):
        specs = rule_table.resolve_tree(x)
    elif rule_table is not None and specs is None:
        specs = rule_table  # duck-typed: an already-resolved spec tree
    if specs is None:
        raise ValueError(
            "sharded_neighbor_allreduce needs the rule table (or its "
            "resolved specs) — the single-source-of-truth contract; use "
            "plain neighbor_allreduce for unsharded trees")

    from jax.sharding import PartitionSpec as _P

    spec_leaves = jax.tree_util.tree_leaves(
        specs, is_leaf=lambda s: isinstance(s, _P))
    leaves = jax.tree_util.tree_leaves(x)
    if len(spec_leaves) != len(leaves):
        raise ValueError(f"spec tree has {len(spec_leaves)} leaves, "
                         f"x has {len(leaves)}")
    axes = dict(inner_axes or {})
    for spec in spec_leaves:
        if _spec_mentions(spec, axis_name):
            raise ValueError(
                f"spec {spec} shards a leaf over the GOSSIP axis "
                f"{axis_name!r}; gossip mixes same-coordinate "
                "elements across ranks — shard over inner axes only")

    sched = _as_schedule(schedule)
    out = neighbor_allreduce(x, sched, axis_name, **kwargs)

    # what this rank ships is already shard-local (leaf shapes here are
    # the local shards); the gather-then-gossip wire would ship each
    # leaf's full size instead
    shard_bytes = _mt.tree_bytes(x) * sched.num_slots
    saved = 0
    for leaf, spec in zip(leaves, spec_leaves):
        size = getattr(leaf, "size", None)
        dtype = getattr(leaf, "dtype", None)
        if size is None or dtype is None:
            continue
        ratio = shard_size_ratio(spec, axes)
        saved += int(size) * int(dtype.itemsize) * (ratio - 1)
    axis_label = "+".join(sorted(axes)) if axes else ""
    counters = [("bf_sharded_bytes_total", float(shard_bytes))]
    if saved:
        counters.append(
            ("bf_gather_bytes_saved_total", float(saved * sched.num_slots)))
    return _mt.count(out, counters,
                     labels={"leaf": "<spmd>", "axis": axis_label})


def neighbor_allreduce_dynamic(
    x,
    schedules: Sequence,
    step,
    axis_name: str,
    *,
    backend: str = "auto",
    collective_id_base: int = 1024,
    collective_id_limit: Optional[int] = None,
):
    """Time-varying gossip: applies ``schedules[step % len(schedules)]``.

    ``step`` may be a traced integer (e.g. the optimizer step counter): the
    period's schedules are compiled once into a ``lax.switch`` — this is the
    recompilation-free answer to the reference's per-call ``src_weights``
    dynamic-topology API (SURVEY.md §7 hard-part #2).  The switch branches
    are mutually exclusive, so they may share ``collective_id_base``; their
    id leases carry a shared ``collective_id_group`` so the analysis audit
    knows not to flag them against each other.
    """
    scheds = [_as_schedule(s) for s in schedules]
    if len(scheds) == 1:
        return neighbor_allreduce(x, scheds[0], axis_name, backend=backend,
                                  collective_id_base=collective_id_base,
                                  collective_id_limit=collective_id_limit)
    group = f"bf.dynamic_switch.{next(_dynamic_group_counter)}"
    branches = [
        functools.partial(neighbor_allreduce, schedule=s, axis_name=axis_name,
                          backend=backend,
                          collective_id_base=collective_id_base,
                          collective_id_limit=collective_id_limit,
                          collective_id_group=group)
        for s in scheds
    ]
    # Timeline spans are hoisted OUTSIDE the switch: an ordered io_callback
    # inside a branch threads an effect token through the branch signature
    # and XLA's sharding propagation CHECK-fails (hard process abort) on the
    # extra entry parameter.  Exactly one branch runs per step, so one outer
    # B/E pair carries the same information.
    x = _tl.device_stage(x, "bf.neighbor_allreduce", phase="B",
                         axis_name=axis_name)
    # Blackbox round markers, hoisted like spans/metrics (one begin/end
    # per step, outside the switch), with the TRACED step recorded so the
    # cross-rank merge aligns rounds on real step numbers.
    bb_cid = _bb.next_collective_id("neighbor_allreduce_dynamic")
    bb_fields = {"op": "neighbor_allreduce_dynamic", "cid": bb_cid,
                 "schedule": f"dynamic[{len(scheds)}]",
                 "bytes": _mt.tree_bytes(x)}
    bb_step = {"step": jnp.asarray(step, jnp.float32)}
    x = _bb.traced_event(x, "collective_begin", fields=bb_fields,
                         traced=bb_step, axis_name=axis_name)
    # Metrics follow the same hoisting rule as timeline spans: the inner
    # neighbor_allreduce records are suppressed inside the switch (exactly
    # one branch runs per step) and ONE outer record carries the taken
    # branch's cost, selected by the traced phase index — so the counter
    # reflects the actual schedule of every step without per-branch
    # callbacks.
    idx = jnp.asarray(step) % len(scheds)
    with _tl.suppress_device_stage(), _mt.suppress_comm_metrics(), \
            _bb.suppress_blackbox():
        out = lax.switch(idx, branches, x)
    if _mreg.current() is not None:
        from bluefog_tpu.ops import pallas_gossip

        payload = _mt.tree_bytes(x)
        leaves = _mt.tree_leaf_count(x)
        # label the RESOLVED transport, not the literal 'auto' (which is
        # never an actual wire) — resolution depends only on environment
        # + schedule shape, and a dynamic period's schedules resolve
        # uniformly in practice, so the first phase's answer stands for
        # the period
        resolved = pallas_gossip.resolve_backend(backend, scheds[0], x)
        out = _mt.record_collective(
            out, op="neighbor_allreduce_dynamic",
            bytes_per_round=jnp.asarray(
                [payload * s.num_slots for s in scheds], jnp.float32)[idx],
            messages_per_round=jnp.asarray(
                [leaves * s.num_slots for s in scheds], jnp.float32)[idx],
            schedule=f"dynamic[{len(scheds)}]", backend=resolved)
    out = _bb.traced_event(out, "collective_end", fields=bb_fields,
                           traced=bb_step, axis_name=axis_name)
    return _tl.device_stage(out, "bf.neighbor_allreduce", phase="E",
                            axis_name=axis_name)


def neighbor_allreduce_aperiodic(x, mixing_matrix, axis_name: str,
                                 max_rotations: Optional[int] = None):
    """Gossip with an **arbitrary per-call topology** in one compile:
    ``out_i = sum_j W[i, j] x_j`` for any row-stochastic ``W`` within the
    full graph — the TPU answer to the reference's per-call
    ``self_weight=/src_weights=`` arguments when the *edge set* (not just
    the weights) changes every step (``bluefog/torch/mpi_ops.py``;
    SURVEY.md §7 hard-part #2).

    How (default, ``max_rotations=None``): any directed graph on ``n``
    ranks decomposes into the ``n-1`` circulant rotations.  Each rotation's
    ``ppermute`` is compiled once (static pattern); which rotations
    actually run is decided at **runtime** by a ``lax.cond`` on whether any
    edge of that rotation carries nonzero weight — changing ``W`` between
    calls re-selects rotations and re-weights edges with zero
    recompilation, and unused rotations cost nothing (the cond executes
    only the taken branch).  A one-peer dynamic exp2 step therefore pays
    for exactly one ICI rotation, not ``n-1``.

    **Degree-capped form** (``max_rotations=D``): the full decomposition
    emits ``n-1`` conditional ppermutes — a program-size/compile-time cost
    that grows linearly with the mesh (127 at a v5p-128 target).  With a
    cap, the program instead materializes ``D`` rotation slots whose shifts
    are selected at RUNTIME (the active rotations of ``W``, lowest shift
    first), each executed as a conditional power-of-two ppermute chain
    (``ceil(log2 n)`` static ppermutes per slot, only the set bits of the
    shift taken) — ``D * ceil(log2 n)`` ppermutes total, e.g. 21 instead of
    127 for ``D=3, n=128``.  Dynamic graphs are typically degree-bounded
    (one-peer: 1 rotation/step; static exp2: log2 n), so ``D`` small is the
    common case.  Contract: if ``W`` activates MORE than ``D`` rotations,
    every output is poisoned with NaN (fail-loud — silently dropping edges
    would corrupt the consensus direction instead).

    Args:
      x: array or pytree; each rank's local value.
      mixing_matrix: ``(n, n)`` array, ``W[i, j]`` = the weight rank ``i``
        applies to rank ``j``'s value (``W[i, i]`` the self weight).  Must be
        **replicated** across ranks (pass it with a ``P()`` spec): the
        rotation-used predicates must agree on every rank or the program
        deadlocks, exactly as mismatched ``src_weights`` deadlock the
        reference's MPI negotiation.
      max_rotations: program-size cap ``D`` (see above), or None for the
        full ``n-1``-rotation decomposition.

    See :func:`bluefog_tpu.topology.dynamic.one_peer_exp2_mixing_matrix` for
    a jittable step->W builder.
    """
    n = axis_size(axis_name)
    i = lax.axis_index(axis_name)
    W = jnp.asarray(mixing_matrix, jnp.float32)
    if W.shape != (n, n):
        raise ValueError(f"mixing_matrix shape {W.shape} != ({n}, {n})")
    rows = jnp.arange(n)

    if max_rotations is not None:
        return _aperiodic_capped(x, W, axis_name, n, i, rows,
                                 int(max_rotations))

    def one(leaf):
        acc_dt = _acc_dtype(leaf)
        out = W[i, i].astype(acc_dt) * leaf.astype(acc_dt)
        for s in range(1, n):
            srcs = (rows - s) % n
            rot_w = W[rows, srcs]          # (n,) rotation-s edge weights
            used = jnp.any(rot_w != 0.0)   # replicated: same on all ranks
            perm = [(a, (a + s) % n) for a in range(n)]

            def fold(o):
                recvd = lax.ppermute(leaf, axis_name, perm)
                return o + rot_w[i].astype(acc_dt) * recvd.astype(acc_dt)

            out = lax.cond(used, fold, lambda o: o, out)
        return out.astype(leaf.dtype)

    out = jax.tree_util.tree_map(one, x)
    if _mreg.current() is not None:
        # data-dependent cost: only ACTIVE rotations run their ppermute —
        # the traced active count rides the record as an operand, so the
        # counter reflects each call's actual edge set
        shifts_all = jnp.arange(1, n)
        srcs_all = (rows[None, :] - shifts_all[:, None]) % n
        active = jnp.sum(jnp.any(W[rows[None, :], srcs_all] != 0.0,
                                 axis=1)).astype(jnp.float32)
        out = _mt.record_collective(
            out, op="neighbor_allreduce_aperiodic",
            bytes_per_round=active * _mt.tree_bytes(x),
            messages_per_round=active * _mt.tree_leaf_count(x),
            schedule=f"aperiodic[n={n}]", backend="xla")
    return out


def _aperiodic_capped(x, W, axis_name: str, n: int, i, rows, cap: int):
    """Degree-capped aperiodic gossip body: ``cap`` runtime-shift rotation
    slots, each a conditional power-of-two ppermute chain."""
    if cap < 1:
        raise ValueError(f"max_rotations must be >= 1, got {cap}")
    cap = min(cap, n - 1)  # only n-1 distinct rotations exist
    # per-rotation activity, computed once for the whole tree (replicated
    # on every rank, as the predicates must be)
    shifts_all = jnp.arange(1, n)                        # (n-1,)
    srcs_all = (rows[None, :] - shifts_all[:, None]) % n  # (n-1, n)
    rot_w_all = W[rows[None, :], srcs_all]               # (n-1, n)
    used = jnp.any(rot_w_all != 0.0, axis=1)             # (n-1,)
    used_count = used.sum()
    # the first `cap` ACTIVE shifts, lowest first (stable argsort of the
    # inactive mask); slots beyond the active count are disabled
    order = jnp.argsort(~used, stable=True)[:cap]
    sel_shift = shifts_all[order]                        # (cap,) runtime
    sel_active = used[order]
    overflow = used_count > cap

    # power-of-two ppermute chain: shift s executes only its set bits
    pows = []
    p = 1
    while p < n:
        pows.append(p)
        p *= 2

    def one(leaf):
        acc_dt = _acc_dtype(leaf)
        out = W[i, i].astype(acc_dt) * leaf.astype(acc_dt)
        for d in range(cap):
            shift = sel_shift[d]

            def fold(o, shift=shift):
                rot = leaf
                for pk in pows:
                    perm = [(a, (a + pk) % n) for a in range(n)]
                    bit = (shift // pk) % 2 == 1

                    def hop(r, perm=perm):
                        return lax.ppermute(r, axis_name, perm)

                    rot = lax.cond(bit, hop, lambda r: r, rot)
                # this rank's weight for the arriving value: W[i, i-shift]
                w = W[i, (i - shift) % n]
                return o + w.astype(acc_dt) * rot.astype(acc_dt)

            out = lax.cond(sel_active[d], fold, lambda o: o, out)
        # exceeding the cap must be LOUD inside jit: poison, don't drop
        out = jnp.where(overflow, jnp.full_like(out, jnp.nan), out)
        return out.astype(leaf.dtype)

    out = jax.tree_util.tree_map(one, x)
    if _mreg.current() is not None:
        # each active slot hops once per SET BIT of its runtime shift
        popcount = sum(((sel_shift // p) % 2 for p in pows),
                       start=jnp.zeros_like(sel_shift))
        hops = jnp.sum(jnp.where(sel_active, popcount, 0)).astype(
            jnp.float32)
        out = _mt.record_collective(
            out, op="neighbor_allreduce_aperiodic",
            bytes_per_round=hops * _mt.tree_bytes(x),
            messages_per_round=hops * _mt.tree_leaf_count(x),
            schedule=f"aperiodic[n={n},cap={cap}]", backend="xla")
    return out


def neighbor_allgather(x, schedule, axis_name: str):
    """Collect in-neighbor tensors.

    Returns ``(slots, mask)`` where ``slots`` has shape ``(K, *x.shape)`` —
    slot ``k`` holds the payload from the rank feeding this rank's slot ``k``
    (``schedule.recv_src``) — and ``mask`` is a ``(K,)`` bool validity mask.

    SPMD deviation from the reference: ``bf.neighbor_allgather`` returns a
    ragged concatenation sized by the rank's in-degree; XLA requires static
    uniform shapes, so irregular graphs are padded to ``K = num_slots`` with
    the mask marking real entries.  For regular graphs ``mask`` is all-True
    and ``slots`` is exactly the reference's output (stacked, slot order =
    ``recv_src`` order).
    """
    sched = _as_schedule(schedule)
    i = lax.axis_index(axis_name)
    parts = []
    for perm in sched.perms:
        parts.append(lax.ppermute(x, axis_name, perm))
    slots = jnp.stack(parts) if parts else jnp.zeros((0,) + x.shape, x.dtype)
    mask = jnp.asarray(sched.recv_src >= 0)[i]
    return slots, mask


def allreduce(x, axis_name: str, *, average: bool = True):
    """Global sum (or mean, the reference default) over the gossip axis."""

    def one(leaf):
        s = lax.psum(leaf, axis_name)
        if average:
            n = axis_size(axis_name)
            s = (s.astype(_acc_dtype(leaf)) / n).astype(leaf.dtype)
        return s

    bb_cid = _bb.next_collective_id("allreduce")
    bb_fields = {"op": "allreduce", "cid": bb_cid,
                 "bytes": _mt.tree_bytes(x)}
    x = _bb.traced_event(x, "collective_begin", fields=bb_fields,
                         axis_name=axis_name)
    out = jax.tree_util.tree_map(one, x)
    out = _mt.record_collective(
        out, op="allreduce", bytes_per_round=_mt.tree_bytes(x),
        messages_per_round=_mt.tree_leaf_count(x), backend="xla")
    return _bb.traced_event(out, "collective_end", fields=bb_fields,
                            axis_name=axis_name)


def allgather(x, axis_name: str, *, axis: int = 0, tiled: bool = False):
    """Gather every rank's tensor; concatenated along ``axis`` when ``tiled``
    (the reference concatenates along dim 0), stacked otherwise."""
    return jax.tree_util.tree_map(
        lambda leaf: lax.all_gather(leaf, axis_name, axis=axis, tiled=tiled), x
    )


def broadcast(x, root_rank: int, axis_name: str):
    """Every rank gets ``root_rank``'s value.

    Lowered as a masked ``psum`` — on ICI this is a single optimized reduction
    rather than a host-coordinated tree as in the reference's MPI path.
    """
    i = lax.axis_index(axis_name)

    def one(leaf):
        contrib = jnp.where(i == root_rank, leaf, jnp.zeros_like(leaf))
        # psum promotes bool to int32; restore the input dtype (per-dtype
        # parity with the reference's typed entry points, SURVEY.md §2.1)
        return lax.psum(contrib, axis_name).astype(leaf.dtype)

    return jax.tree_util.tree_map(one, x)


def barrier(axis_name: str):
    """Synchronization point for API parity (``bf.barrier``).  SPMD programs
    are implicitly ordered by their collectives; this issues a trivial psum so
    the host can block on its completion."""
    return lax.psum(jnp.zeros((), jnp.float32), axis_name)


def pair_gossip(x, axis_name: str, *, perm, self_weight=0.5):
    """Average with a single partner: ``out = w x + (1-w) x_partner``.

    Mirrors the reference's ``pair_gossip(tensor, target_rank)`` (upstream,
    UNVERIFIED name — see SURVEY.md §2.2).  SPMD deviation: the reference's
    per-process ``target_rank`` argument becomes the full pairing ``perm`` —
    a list of ``(src, dst)`` pairs covering every participating rank (all
    ranks must agree on the pairing, which the reference leaves implicit).
    Ranks absent from ``perm``'s destinations keep their own value.
    """
    got = lax.ppermute(x, axis_name, perm)
    w = jnp.asarray(self_weight, _acc_dtype(x))
    # Ranks not named as a destination receive zeros; they keep their own value.
    dsts = sorted(d for _, d in perm)
    i = lax.axis_index(axis_name)
    is_dst = jnp.isin(i, jnp.asarray(dsts))
    mixed = (w * x.astype(w.dtype) + (1 - w) * got.astype(w.dtype)).astype(x.dtype)
    return jnp.where(is_dst, mixed, x)


def hierarchical_neighbor_allreduce(
    x,
    machine_schedule,
    axis_name: str,
    *,
    local_size: int,
    self_weight=None,
    recv_weights=None,
):
    """Intra-machine exact average, then machine-level gossip.

    The reference's ``hierarchical_neighbor_allreduce`` (confirmed in
    BASELINE.json): ranks on one machine first average exactly (reference:
    local-communicator allreduce; here: ``psum`` over ``axis_index_groups``
    riding intra-slice ICI), then machines gossip along ``machine_schedule``
    with every local rank exchanging with its counterpart on the peer machine
    (reference: cross-communicator neighbor collective; here the machine-graph
    permutation is expanded to a rank-level ppermute).  All local ranks end
    with identical values, as upstream guarantees.

    ``machine_schedule`` is a schedule/topology over ``n_machines =
    axis_size / local_size`` nodes.
    """
    x = _tl.device_stage(x, "bf.hierarchical_neighbor_allreduce", phase="B",
                         axis_name=axis_name)
    msched = _as_schedule(machine_schedule)
    bb_cid = _bb.next_collective_id("hierarchical_neighbor_allreduce")
    bb_fields = {"op": "hierarchical_neighbor_allreduce", "cid": bb_cid,
                 "schedule": msched.name, "bytes": _mt.tree_bytes(x)}
    x = _bb.traced_event(x, "collective_begin", fields=bb_fields,
                         axis_name=axis_name)
    n_machines = msched.size
    groups = [list(range(m * local_size, (m + 1) * local_size)) for m in range(n_machines)]

    # Expand machine-level matchings to rank-level: each local rank talks to
    # the same local rank on the peer machine (pure ICI/DCN-parallel lanes).
    rank_perms = []
    for perm in msched.perms:
        rp = []
        for (src_m, dst_m) in perm:
            for l in range(local_size):
                rp.append((src_m * local_size + l, dst_m * local_size + l))
        rank_perms.append(tuple(rp))

    i = lax.axis_index(axis_name)
    machine = i // local_size

    def one(leaf):
        acc_dt = _acc_dtype(leaf)
        local_avg = (lax.psum(leaf, axis_name, axis_index_groups=groups).astype(acc_dt)
                     / local_size)
        if self_weight is None:
            self_w = jnp.asarray(msched.self_weights, acc_dt)[machine]
        else:
            self_w = jnp.asarray(self_weight, acc_dt)
        if recv_weights is None:
            recv_w = jnp.asarray(msched.recv_weights, acc_dt)[machine]
        else:
            recv_w = jnp.asarray(recv_weights, acc_dt)
        out = self_w * local_avg
        for k, rp in enumerate(rank_perms):
            with jax.named_scope(f"bf.hierarchical.machine_slot{k}"):
                recvd = lax.ppermute(local_avg.astype(leaf.dtype), axis_name, rp)
                out = out + recv_w[k] * recvd.astype(acc_dt)
        return out.astype(leaf.dtype)

    out = jax.tree_util.tree_map(one, x)
    # accounted: the machine-hop ppermutes (every local lane ships the
    # local average per machine slot); the intra-machine psum is ICI-local
    out = _mt.record_collective(
        out, op="hierarchical_neighbor_allreduce",
        bytes_per_round=_mt.tree_bytes(x) * len(rank_perms),
        messages_per_round=_mt.tree_leaf_count(x) * len(rank_perms),
        schedule=msched.name, backend="xla")
    out = _bb.traced_event(out, "collective_end", fields=bb_fields,
                           axis_name=axis_name)
    return _tl.device_stage(out, "bf.hierarchical_neighbor_allreduce",
                            phase="E", axis_name=axis_name)


def hierarchical_neighbor_allreduce_2d(
    x,
    machine_schedule,
    *,
    machine_axis: str,
    local_axis: str,
    self_weight=None,
    recv_weights=None,
):
    """Hierarchical gossip over a two-level ``(machine, local)`` mesh.

    The multi-slice deployment form of :func:`hierarchical_neighbor_allreduce`:
    instead of one flat mesh axis with ``axis_index_groups``, the mesh is
    ``Mesh(devices.reshape(n_machines, local_size), (machine_axis,
    local_axis))`` — in a real multi-slice/multi-pod job the outer axis maps
    onto DCN and the inner axis onto each slice's ICI (reference analog: the
    cross vs local MPI communicators of ``bluefog/common/mpi_context.cc``,
    SURVEY.md §2.4).  The local exact average is a ``pmean`` riding ICI; the
    machine gossip is a ``ppermute`` *over the machine axis itself*, so every
    local lane crosses DCN in parallel and the counterpart-lane pairing of
    the flat path holds by construction.
    """
    # lane id = linearized (machine, local) rank, matching the flat path
    x = _tl.device_stage(x, "bf.hierarchical_neighbor_allreduce_2d", phase="B",
                         axis_name=(machine_axis, local_axis))
    msched = _as_schedule(machine_schedule)
    bb_cid = _bb.next_collective_id("hierarchical_neighbor_allreduce_2d")
    bb_fields = {"op": "hierarchical_neighbor_allreduce_2d", "cid": bb_cid,
                 "schedule": msched.name, "bytes": _mt.tree_bytes(x)}
    x = _bb.traced_event(x, "collective_begin", fields=bb_fields,
                         axis_name=(machine_axis, local_axis))

    def one(leaf):
        acc_dt = _acc_dtype(leaf)
        local_avg = lax.pmean(leaf.astype(acc_dt), local_axis)
        m = lax.axis_index(machine_axis)
        if self_weight is None:
            self_w = jnp.asarray(msched.self_weights, acc_dt)[m]
        else:
            self_w = jnp.asarray(self_weight, acc_dt)
        if recv_weights is None:
            recv_w = jnp.asarray(msched.recv_weights, acc_dt)[m]
        else:
            recv_w = jnp.asarray(recv_weights, acc_dt)
        out = self_w * local_avg
        for k, perm in enumerate(msched.perms):
            with jax.named_scope(f"bf.hierarchical2d.machine_slot{k}"):
                recvd = lax.ppermute(local_avg.astype(leaf.dtype),
                                     machine_axis, perm)
                out = out + recv_w[k] * recvd.astype(acc_dt)
        return out.astype(leaf.dtype)

    out = jax.tree_util.tree_map(one, x)
    out = _mt.record_collective(
        out, op="hierarchical_neighbor_allreduce_2d",
        bytes_per_round=_mt.tree_bytes(x) * len(msched.perms),
        messages_per_round=_mt.tree_leaf_count(x) * len(msched.perms),
        schedule=msched.name, backend="xla")
    out = _bb.traced_event(out, "collective_end", fields=bb_fields,
                           axis_name=(machine_axis, local_axis))
    return _tl.device_stage(out, "bf.hierarchical_neighbor_allreduce_2d",
                            phase="E", axis_name=(machine_axis, local_axis))
