"""Pallas TPU kernels: gossip exchange and one-sided delivery via inter-chip
RDMA (``pltpu.make_async_remote_copy``).

This is the genuinely *native* layer of the build (SURVEY.md §7 "one-sided
layer"): the TPU equivalent of the reference's MPI RMA machinery
(``MPIController::WinPut/WinAccumulate/WinUpdate`` over ``MPI_Win`` memory,
``bluefog/common/mpi_controller.cc``, upstream-relative) and of its NCCL
send/recv emulation (``nccl_controller.cc``).

Two kernels, both restricted to **circulant schedules** (every standard
topology: ring, exponential-2, symmetric-exp, one-peer phases — each slot is
a uniform shift ``i -> i+s``, i.e. one ICI rotation):

- :func:`neighbor_allreduce_pallas` — fused gossip: per slot, RDMA the local
  tensor into the in-neighbor slot buffer of ``rank+s`` while accumulating
  arrived slots into ``w_self*x + sum_k w_k*recv_k``.  Against the XLA
  lowering (ppermute + adds) this fuses the weighted reduction into the
  arrival path — one VMEM pass instead of ppermute-materialize-then-add.
- :func:`deliver_pallas` — the ``win_put``/``win_accumulate`` transport:
  RDMA payloads into per-slot landing buffers (the reference's per-neighbor
  ``MPI_Win`` memory) without touching them on the compute path; the receiver
  consumes them only at ``win_update``.

Synchronization protocol (per kernel invocation, SPMD-symmetric):
1. barrier handshake with in/out-neighbors via the global barrier semaphore —
   guarantees the remote landing buffers are live before any RDMA starts
   (the reference gets this from ``MPI_Win_create``'s collective epoch);
2. per-slot RDMA start; sender tracks ``send_sem``, the in-flight data
   signals the *receiver's* ``recv_sem`` on arrival;
3. ``wait_recv`` per slot before accumulating (gossip) or storing (deliver).

Use on real multi-chip slices; single-chip and CPU meshes route to the XLA
path automatically (``backend='auto'``).
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from bluefog_tpu.topology.schedule import GossipSchedule

__all__ = [
    "is_pallas_supported",
    "circulant_shifts",
    "auto_gossip_backend",
    "auto_max_bytes",
    "leaf_wire_bytes",
    "leaf_chunk_count",
    "neighbor_allreduce_pallas",
    "deliver_pallas",
    "DEFAULT_AUTO_MAX_BYTES",
]

_LANES = 128
_SUBLANES = 8

# Per-kernel-invocation payload cap in on-wire bytes (bf16 leaves ship as
# bf16, the rest as f32).  The kernel holds (num_slots+2) whole-payload
# copies in VMEM at once, so a single invocation must stay small; the GOSSIP
# op layer CHUNKS any larger leaf into <=cap pieces (one kernel per chunk,
# distinct collective ids) instead of falling back to XLA — that keeps every
# received payload accumulating in VMEM on arrival, never landing in HBM,
# which is the kernel's whole advantage over ppermute-materialize-then-add
# (saves ~2*num_slots HBM passes over the buffer per gossip; the per-chunk
# barrier handshake costs microseconds against that).  The WINDOW deliver
# path cannot chunk (its landing buffers are persistent window state), so
# for it this value remains a routing cutoff: bigger payloads take XLA.
# Override with BLUEFOG_TPU_PALLAS_MAX_BYTES.
DEFAULT_AUTO_MAX_BYTES = 4 << 20


def auto_max_bytes() -> int:
    """The effective per-invocation payload cap (env-overridable).  A
    non-positive override means "never use the kernels": auto routes to
    XLA (the pre-chunking de facto meaning of ``MAX_BYTES=0``), and a
    *forced* ``backend='pallas'`` raises in :func:`leaf_chunk_count`."""
    import os

    return int(os.environ.get("BLUEFOG_TPU_PALLAS_MAX_BYTES",
                              DEFAULT_AUTO_MAX_BYTES))


def leaf_wire_bytes(leaf) -> int:
    """On-wire byte size of one leaf (bf16 ships as bf16, the rest as f32)."""
    dt = _wire_dtype(getattr(leaf, "dtype", jnp.float32))
    return (int(np.prod(jnp.shape(leaf), dtype=np.int64))
            * np.dtype(dt).itemsize)


def leaf_chunk_count(leaf, limit: Optional[int] = None) -> int:
    """How many kernel invocations the gossip op layer will split ``leaf``
    into (1 = unchunked)."""
    limit = auto_max_bytes() if limit is None else limit
    if limit <= 0:
        raise ValueError(
            "BLUEFOG_TPU_PALLAS_MAX_BYTES must be positive to run the "
            f"pallas backend (got {limit}); a non-positive cap only makes "
            "sense as 'never use the kernels', which backend='auto' "
            "honors by routing to XLA")
    return max(1, -(-leaf_wire_bytes(leaf) // limit))


def on_tpu_platform() -> bool:
    """THE platform predicate for every pallas-transport gate (auto routing
    and :func:`is_pallas_supported` both call this — one predicate, one
    answer).  True on a real TPU backend, whether reached directly
    (``'tpu'``) or through the axon relay (``'axon'``); either name may show
    up as the backend name or the device platform depending on the relay, so
    both are consulted."""
    try:
        names = {jax.default_backend(), jax.devices()[0].platform}
    except Exception:
        return False
    return bool(names & {"tpu", "axon"})


def auto_gossip_backend(sched: GossipSchedule, x, *,
                        chunkable: bool = True) -> str:
    """Resolve ``backend='auto'`` for a gossip call: ``'pallas'`` or ``'xla'``.

    The stated conditions under which auto selects the RDMA kernels — ALL
    must hold:

    1. a real TPU backend (:func:`on_tpu_platform`) — CPU test meshes
       always take XLA (the non-interpret kernel cannot run there);
    2. multi-device mesh (``sched.size > 1``) — nothing to exchange on one
       chip;
    3. a circulant schedule (every slot one uniform ICI rotation — all
       standard topologies; irregular graphs take XLA);
    4. ``chunkable=False`` only (the window deliver path): every leaf at
       most the size cutoff (see :data:`DEFAULT_AUTO_MAX_BYTES`).  Gossip
       callers (``chunkable=True``, the default) have no size condition —
       the op layer splits oversized leaves into cutoff-sized chunks, so
       the fused-optimizer buffers (``fuse_apply``'s one-flat-buffer-per-
       dtype trees, far beyond the cutoff for any real model) ride the
       RDMA kernels BY DEFAULT rather than quietly falling back to XLA;
    5. not disabled via ``BLUEFOG_TPU_PALLAS_GOSSIP=0`` (the kill switch if
       a deployment's kernels misbehave).
    """
    import os

    if os.environ.get("BLUEFOG_TPU_PALLAS_GOSSIP", "1") in ("0", "off"):
        return "xla"
    if sched.size <= 1 or not circulant_shifts(sched):
        return "xla"  # non-circulant (None) or zero slots (()): both XLA
    if not on_tpu_platform():
        return "xla"
    leaves = jax.tree_util.tree_leaves(x)
    if not leaves:
        return "xla"
    limit = auto_max_bytes()
    if limit <= 0:
        return "xla"  # explicit "never use the kernels" override
    if not chunkable and max(leaf_wire_bytes(l) for l in leaves) > limit:
        return "xla"
    return "pallas"


def resolve_backend(backend: str, sched: GossipSchedule, x, *,
                    chunkable: bool = True) -> str:
    """Shared backend resolution for every transport that can ride the RDMA
    kernels (gossip and the window deliver path): validate the name and
    resolve ``'auto'`` through :func:`auto_gossip_backend`.  Window callers
    pass ``chunkable=False`` (persistent landing buffers cannot chunk)."""
    if backend not in ("auto", "xla", "pallas"):
        raise ValueError(
            f"unknown backend {backend!r}; expected 'auto', 'xla', or "
            "'pallas'")
    if backend == "auto":
        return auto_gossip_backend(sched, x, chunkable=chunkable)
    return backend


def interpret_requested() -> bool:
    """``BLUEFOG_TPU_PALLAS_INTERPRET=1`` runs every pallas-backend op
    through TPU-interpret emulation — the full op layers (gossip pytree
    dispatch, window deliver with collective-id bases and masks) execute
    their REAL pallas branch on a CPU mesh in CI, not just the bare
    kernels the dedicated kernel tests cover.  Never set in production
    (emulation is orders of magnitude slower).  Kernel entry points
    resolve this themselves when ``interpret`` is left at None."""
    import os

    return os.environ.get("BLUEFOG_TPU_PALLAS_INTERPRET") == "1"


# The interpret machinery models barrier semaphores with int16 ids; the
# name-derived window bases (up to ~2^30) overflow it.  Under EMULATION
# ONLY, ids are remapped through a trace-time table assigning compact
# sequential ids — collision-free by construction (a raw modulo would fold
# distinct windows onto one semaphore, the exact hazard the bases exist to
# prevent).  Hardware keeps the full id space.
_interpret_ids: dict = {}


def _interpret_collective_id(cid: int) -> int:
    return _interpret_ids.setdefault(cid, 1 + len(_interpret_ids))


# CRC32 bucket -> window name that claimed it.  Two window names hashing to
# the same bucket would silently share barrier semaphores inside one jitted
# program — the exact hazard the name-derived base exists to prevent — so the
# first claimant owns the bucket and any later colliding name raises.
WINDOW_LEAF_CAP = 1024  # collective ids per window; bases are spaced this far
_claimed_bases: dict = {}


def window_collective_id_base(name: str) -> int:
    """Deterministic per-window collective-id base.  Two windows delivered
    in ONE jitted program must not share barrier semaphores, so each
    window's leaf kernels enumerate from a name-derived base: 2048 + a CRC32
    bucket spaced :data:`WINDOW_LEAF_CAP` apart (the per-call leaf cap).
    Stable across processes (CRC32, not Python hash) as SPMD requires.

    Bucket collisions (distinct names, same CRC32 bucket) raise rather than
    silently sharing semaphores; rename one window to resolve.
    """
    import zlib

    bucket = zlib.crc32(name.encode()) % (1 << 20)
    owner = _claimed_bases.setdefault(bucket, name)
    if owner != name:
        raise ValueError(
            f"window name {name!r} collides with existing window {owner!r} "
            f"in collective-id bucket {bucket} (CRC32 % 2^20); the two would "
            "share barrier semaphores if delivered in one program — rename "
            "one of them (or win_free the other first if it no longer "
            "exists)")
    return 2048 + bucket * WINDOW_LEAF_CAP


def release_window_collective_id(name: str) -> None:
    """Release ``name``'s collective-id bucket (call when the window is
    freed): the semaphore-sharing hazard only exists between windows
    delivered in one program, so a FREED window must not poison its bucket
    for the rest of a long-lived process (per-experiment window names would
    otherwise accumulate spurious collisions)."""
    import zlib

    bucket = zlib.crc32(name.encode()) % (1 << 20)
    if _claimed_bases.get(bucket) == name:
        del _claimed_bases[bucket]


def circulant_shifts(sched: GossipSchedule) -> Optional[Tuple[int, ...]]:
    """Per-slot uniform shifts, or None if the schedule is not circulant."""
    if not sched.is_circulant:
        return None
    shifts = []
    for perm in sched.perms:
        (src0, dst0) = perm[0]
        shifts.append((dst0 - src0) % sched.size)
    return tuple(shifts)


def is_pallas_supported(sched: GossipSchedule) -> bool:
    """True when the schedule can ride the RDMA kernels (circulant, at least
    one slot, more than one device) and we are on a real TPU backend (the
    shared :func:`on_tpu_platform` predicate — never disagrees with
    ``'auto'`` routing about the same schedule)."""
    if sched.size <= 1 or not circulant_shifts(sched):
        return False
    return on_tpu_platform()


def _wire_dtype(dtype) -> jnp.dtype:
    """On-wire dtype for a leaf: bf16 leaves ship as bf16 (HALF the ICI
    bytes — the dominant cost of a gossip step on real hardware), everything
    else as f32.  Reduction precision per kernel: the GOSSIP kernel's
    weighted sum runs in f32 regardless of wire (the XLA path's
    ``_acc_dtype`` discipline); the deliver kernel's ``acc`` mode adds in
    the wire dtype, exactly matching the portable window path's leaf-dtype
    slot adds (``ops/windows.py`` ``peers[k] + recvd``)."""
    return jnp.bfloat16 if dtype == jnp.bfloat16 else jnp.float32


def _pad_to_tiles(flat: jnp.ndarray) -> Tuple[jnp.ndarray, int]:
    """Pad a flat vector to a tile-aligned (R, 128) 2-D block (min sublane
    count is dtype-dependent: 8 for f32, 16 for bf16)."""
    n = flat.shape[0]
    sublanes = _SUBLANES * (4 // max(flat.dtype.itemsize, 1))
    per_tile = sublanes * _LANES
    padded = int(np.ceil(max(n, 1) / per_tile)) * per_tile
    flat = jnp.pad(flat, (0, padded - n))
    return flat.reshape(padded // _LANES, _LANES), n


def _make_exchange_kernel(shifts: Sequence[int], size: int, axis_name: str,
                          mode: str, num_slots: int):
    """Build the shared RDMA exchange kernel body.

    mode: 'gossip'  -> out = sw*x + sum_k rw[k]*recv_k
          'put'     -> out_bufs[k] = recv_k (masked by mask[k])
          'acc'     -> out_bufs[k] = old_bufs[k] + recv_k (masked)
    """
    from jax.experimental import pallas as pl  # deferred: TPU-only path
    from jax.experimental.pallas import tpu as pltpu

    n_shifts = len(shifts)

    if mode == "gossip":
        def kernel(x_ref, sw_ref, rw_ref, out_ref, comm_buf, send_sem, recv_sem):
            my = lax.axis_index(axis_name)
            barrier = pltpu.get_barrier_semaphore()
            # handshake: signal each IN-neighbor (my-s) that my landing
            # buffers are live; the n_shifts signals I then wait for come
            # from my OUT-neighbors (my+s) — exactly my RDMA targets — so
            # no RDMA starts before its destination buffer exists
            for s in shifts:
                pltpu.semaphore_signal(
                    barrier, inc=1,
                    device_id=lax.rem(my - s + size, size),
                    device_id_type=pltpu.DeviceIdType.LOGICAL,
                )
            pltpu.semaphore_wait(barrier, n_shifts)

            rdmas = []
            for k, s in enumerate(shifts):
                rdma = pltpu.make_async_remote_copy(
                    src_ref=x_ref,
                    dst_ref=comm_buf.at[k],
                    send_sem=send_sem.at[k],
                    recv_sem=recv_sem.at[k],
                    device_id=lax.rem(my + s, size),
                    device_id_type=pltpu.DeviceIdType.LOGICAL,
                )
                rdma.start()
                rdmas.append(rdma)

            # accumulate in f32 whatever the wire dtype (bf16 wires halve
            # ICI bytes; the reduction still runs at f32, matching the XLA
            # path's _acc_dtype discipline)
            acc = sw_ref[0, 0] * x_ref[:].astype(jnp.float32)
            for k, rdma in enumerate(rdmas):
                rdma.wait_recv()
                acc = acc + rw_ref[0, k] * comm_buf[k].astype(jnp.float32)
            out_ref[:] = acc.astype(out_ref.dtype)
            for rdma in rdmas:
                rdma.wait_send()
        return kernel

    def kernel(x_ref, bufs_ref, mask_ref, out_bufs_ref, send_sem, recv_sem):
        my = lax.axis_index(axis_name)
        barrier = pltpu.get_barrier_semaphore()
        # signal in-neighbors; wait for out-neighbors (RDMA targets) — see
        # the gossip kernel's handshake comment
        for s in shifts:
            pltpu.semaphore_signal(
                barrier, inc=1,
                device_id=lax.rem(my - s + size, size),
                device_id_type=pltpu.DeviceIdType.LOGICAL,
            )
        pltpu.semaphore_wait(barrier, n_shifts)

        rdmas = []
        for k, s in enumerate(shifts):
            rdma = pltpu.make_async_remote_copy(
                src_ref=x_ref,
                dst_ref=out_bufs_ref.at[k],
                send_sem=send_sem.at[k],
                recv_sem=recv_sem.at[k],
                device_id=lax.rem(my + s, size),
                device_id_type=pltpu.DeviceIdType.LOGICAL,
            )
            rdma.start()
            rdmas.append(rdma)
        for k, rdma in enumerate(rdmas):
            rdma.wait_recv()
            landed = out_bufs_ref[k]
            old = bufs_ref[k]
            keep = mask_ref[0, k]
            if mode == "acc":
                new = old + landed
            else:
                new = landed
            out_bufs_ref[k] = jnp.where(keep > 0, new, old)
        for rdma in rdmas:
            rdma.wait_send()
    return kernel


def neighbor_allreduce_pallas(
    x: jnp.ndarray,
    sched: GossipSchedule,
    axis_name: str,
    *,
    self_weight=None,
    recv_weights=None,
    collective_id: int = 7,
    interpret: Optional[bool] = None,
):
    """Fused RDMA gossip step for one array (any shape/dtype; internally a
    padded tile-aligned (R,128) block in the wire dtype — bf16 for bf16
    leaves, halving ICI bytes; f32 otherwise; accumulation is f32 either
    way).  Call inside ``shard_map``; circulant schedules only — gate with
    :func:`is_pallas_supported`."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    shifts = circulant_shifts(sched)
    if shifts is None:
        raise ValueError("pallas gossip requires a circulant schedule")
    if interpret is None:
        interpret = interpret_requested()
    if interpret:
        collective_id = _interpret_collective_id(collective_id)
    if not shifts:
        # 0-slot schedule (no edges — e.g. identity mixing): nothing to
        # exchange, and a grid-free kernel with zero receive buffers cannot
        # lower; the gossip degenerates to the self-weighted term.
        i0 = lax.axis_index(axis_name)
        sw0 = (jnp.asarray(sched.self_weights, jnp.float32)[i0]
               if self_weight is None
               else jnp.asarray(self_weight, jnp.float32))
        return (sw0 * x.astype(jnp.float32)).astype(x.dtype)
    n = sched.size
    i = lax.axis_index(axis_name)

    orig_dtype = x.dtype
    wire = _wire_dtype(orig_dtype)
    flat = x.astype(wire).reshape(-1)
    block, true_len = _pad_to_tiles(flat)

    sw = (jnp.asarray(sched.self_weights, jnp.float32)[i]
          if self_weight is None else jnp.asarray(self_weight, jnp.float32))
    rw = (jnp.asarray(sched.recv_weights, jnp.float32)[i]
          if recv_weights is None else jnp.asarray(recv_weights, jnp.float32))
    sw = sw.reshape(1, 1)
    rw = rw.reshape(1, -1)

    kernel = _make_exchange_kernel(shifts, n, axis_name, "gossip", sched.num_slots)
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(block.shape, wire),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1), memory_space=pltpu.SMEM),
            pl.BlockSpec((1, len(shifts)), memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((len(shifts),) + block.shape, wire),
            pltpu.SemaphoreType.DMA((len(shifts),)),
            pltpu.SemaphoreType.DMA((len(shifts),)),
        ],
        compiler_params=pltpu.CompilerParams(
            has_side_effects=True, collective_id=collective_id,
        ),
        interpret=pltpu.InterpretParams() if interpret else False,
    )(block, sw, rw)
    return out.reshape(-1)[:true_len].reshape(x.shape).astype(orig_dtype)


def deliver_pallas(
    payload: jnp.ndarray,
    bufs: jnp.ndarray,
    sched: GossipSchedule,
    axis_name: str,
    *,
    accumulate: bool,
    collective_id: int = 8,
    interpret: Optional[bool] = None,
):
    """RDMA transport for ``win_put``/``win_accumulate``: sends ``payload`` to
    every out-neighbor's landing slot; returns the updated ``(K, ...)`` slot
    buffers for this rank.  Circulant schedules only."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    shifts = circulant_shifts(sched)
    if shifts is None:
        raise ValueError("pallas deliver requires a circulant schedule")
    if interpret is None:
        interpret = interpret_requested()
    if interpret:
        collective_id = _interpret_collective_id(collective_id)
    if not shifts:
        # 0-slot schedule: no out-neighbors, nothing lands — the slot
        # buffers are unchanged (a zero-receive grid-free kernel cannot
        # lower; same degenerate case as neighbor_allreduce_pallas).
        return bufs
    n = sched.size
    i = lax.axis_index(axis_name)

    orig_dtype = payload.dtype
    wire = _wire_dtype(orig_dtype)
    flat = payload.astype(wire).reshape(-1)
    block, true_len = _pad_to_tiles(flat)
    k_slots = len(shifts)
    bufs_f = bufs.astype(wire).reshape(k_slots, -1)
    bufs_block = jnp.pad(
        bufs_f, ((0, 0), (0, block.size - bufs_f.shape[1]))
    ).reshape((k_slots,) + block.shape)

    mask = jnp.asarray(sched.recv_src >= 0, jnp.int32)[i].reshape(1, -1)

    kernel = _make_exchange_kernel(
        shifts, n, axis_name, "acc" if accumulate else "put", sched.num_slots
    )
    out_bufs = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(bufs_block.shape, wire),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec((1, k_slots), memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.SemaphoreType.DMA((k_slots,)),
            pltpu.SemaphoreType.DMA((k_slots,)),
        ],
        compiler_params=pltpu.CompilerParams(
            has_side_effects=True, collective_id=collective_id,
        ),
        interpret=pltpu.InterpretParams() if interpret else False,
    )(block, bufs_block, mask)
    return (out_bufs.reshape(k_slots, -1)[:, : bufs_f.shape[1]]
            .reshape(bufs.shape).astype(orig_dtype))
