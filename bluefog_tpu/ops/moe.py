"""Mixture-of-experts routing + expert parallelism (GShard/Switch style).

No counterpart exists in the reference (SURVEY.md §2.3: EP absent — Bluefog
predates MoE).  The TPU build adds it as the fourth parallelism axis: experts
are sharded over an ``'ep'`` mesh axis and tokens reach their expert via a
pair of ``lax.all_to_all`` hops — the canonical TPU MoE dataflow (dense
einsum dispatch, static capacity, no dynamic shapes, everything MXU-tiled).

Pieces:

- :func:`switch_router` — top-1 (Switch) routing with a static per-shard
  capacity: returns dense dispatch/combine tensors.
- :func:`expert_parallel_ffn` — dispatch → all_to_all → local expert FFNs →
  reverse all_to_all → combine, inside ``shard_map``.

Gradient convention: normalize the per-rank loss by the GLOBAL token count
(``local_sum / total_tokens``) so the per-rank loss seeds sum to the true
global objective.  Then raw ``jax.grad`` inside ``shard_map`` is exact for
the **ep-sharded expert parameters** (the ``all_to_all`` transposes route
cotangents back without scaling).  **Replicated parameters** (router,
embeddings, attention, …) receive only the local tokens' contribution on
each rank — ``lax.psum`` their grads over the ep axis before the optimizer
update, or the nominally replicated copies silently diverge (see
tests/test_moe.py::test_expert_parallel_grads_match_reference and the
``gr = lax.psum(gr, "ep")`` step in ``__graft_entry__.dryrun_multichip``).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

__all__ = [
    "RouterOutput",
    "switch_router",
    "top2_router",
    "get_router",
    "expert_parallel_ffn",
    "moe_ffn_reference",
]


class RouterOutput(NamedTuple):
    """dispatch/combine: ``(T, E, C)``; aux: scalar load-balance loss;
    metrics: non-differentiated accounting dict —

    - ``dropped_frac``: fraction of routing ASSIGNMENTS (token-choice
      pairs; a top-2 token makes two) past expert capacity, hence dropped;
    - ``fully_dropped_frac``: fraction of TOKENS with every assignment
      dropped (the residual connection alone carries them);
    - ``expert_load``: ``(E,)`` fraction of assignments per expert.
    """

    dispatch: jnp.ndarray
    combine: jnp.ndarray
    aux: jnp.ndarray
    metrics: dict


def _router_probs(x, router_kernel, noise_rng, noise_scale):
    """Shared preamble: f32 logits (+ optional exploration noise) -> probs."""
    logits = x.astype(jnp.float32) @ router_kernel.astype(jnp.float32)
    if noise_rng is not None and noise_scale > 0:
        logits = logits + noise_scale * jax.random.normal(noise_rng,
                                                          logits.shape)
    return jax.nn.softmax(logits, axis=-1)


def _assign_slots(onehot, capacity: int, base=0.0):
    """Queue one routing choice into expert slots.

    ``base`` (scalar or ``(1, E)``) offsets each expert's queue start —
    top-2's second choices pass the expert's first-choice count so they
    queue behind ALL first choices.  Returns ``(keep, slot)``: the
    surviving ``(T, E)`` mask and the ``(T, E, C)`` dispatch one-hots.
    """
    pos = (base + jnp.cumsum(onehot, axis=0) - 1.0) * onehot
    keep = (pos < capacity) * onehot
    slot = keep[..., None] * jax.nn.one_hot(pos.astype(jnp.int32), capacity)
    return keep, slot


def _router_metrics(assigned, kept):
    """assigned/kept: (T, E) 0/1 masks of routed vs surviving slots."""
    total = jnp.maximum(jnp.sum(assigned), 1.0)
    kept_per_token = jnp.sum(kept, axis=-1)
    routed_per_token = jnp.sum(assigned, axis=-1)
    fully_dropped = (routed_per_token > 0) & (kept_per_token == 0)
    return {
        "dropped_frac": lax.stop_gradient(1.0 - jnp.sum(kept) / total),
        "fully_dropped_frac": lax.stop_gradient(
            jnp.mean(fully_dropped.astype(jnp.float32))),
        "expert_load": lax.stop_gradient(jnp.sum(assigned, axis=0) / total),
    }


def switch_router(x, router_kernel, *, num_experts: int, capacity: int,
                  noise_rng=None, noise_scale: float = 0.0) -> RouterOutput:
    """Top-1 (Switch) routing with static capacity.

    Args:
      x: ``(T, D)`` tokens (local shard).
      router_kernel: ``(D, E)`` router weights (replicated).
      capacity: max tokens per expert **per shard**; overflow tokens are
        dropped (their combine weights are zero — the residual connection
        carries them, as in Switch) and counted in ``metrics``.
      noise_rng/noise_scale: optional jitter for load-balancing exploration.
    """
    probs = _router_probs(x, router_kernel, noise_rng, noise_scale)  # (T, E)
    expert = jnp.argmax(probs, axis=-1)                   # (T,)
    onehot = jax.nn.one_hot(expert, num_experts)          # (T, E)
    keep, dispatch = _assign_slots(onehot, capacity)      # (T,E), (T,E,C)
    gate = jnp.sum(probs * onehot, axis=-1, keepdims=True)      # (T, 1)
    combine = dispatch * gate[..., None]

    # Switch aux loss: E * sum_e fraction_tokens_e * mean_prob_e
    frac = jnp.mean(onehot, axis=0)
    mean_prob = jnp.mean(probs, axis=0)
    aux = num_experts * jnp.sum(frac * mean_prob)
    return RouterOutput(dispatch, combine, aux,
                        _router_metrics(onehot, keep))


def top2_router(x, router_kernel, *, num_experts: int, capacity: int,
                noise_rng=None, noise_scale: float = 0.0) -> RouterOutput:
    """Top-2 (GShard) routing with static capacity.

    Each token is routed to its two highest-probability experts with gates
    renormalized over the pair (``g_i = p_i / (p_1 + p_2)``).  Capacity
    accounting is GShard's: an expert's second-choice tokens queue BEHIND
    all of its first-choice tokens, so second choices are the first to drop
    under pressure.  The aux loss is the standard Switch/GShard
    load-balance term over FIRST choices (``E * sum_e frac1_e *
    mean_prob_e`` — differentiable through ``mean_prob``).
    """
    if num_experts < 2:
        # with E=1 the second argmax collapses onto the first: every token
        # is dispatched twice to the same expert, consuming two capacity
        # slots and silently halving effective capacity — reject loudly
        raise ValueError(
            f"top2_router requires num_experts >= 2, got {num_experts}; "
            "with a single expert the second choice duplicates the first "
            "(capacity silently halves) — use switch_router / router='top1'")
    probs = _router_probs(x, router_kernel, noise_rng, noise_scale)  # (T, E)
    e1 = jnp.argmax(probs, axis=-1)
    oh1 = jax.nn.one_hot(e1, num_experts)
    e2 = jnp.argmax(probs * (1.0 - oh1), axis=-1)
    oh2 = jax.nn.one_hot(e2, num_experts)
    g1 = jnp.sum(probs * oh1, axis=-1)
    g2 = jnp.sum(probs * oh2, axis=-1)
    denom = g1 + g2 + 1e-9
    g1n, g2n = g1 / denom, g2 / denom

    keep1, slot1 = _assign_slots(oh1, capacity)
    count1 = jnp.sum(oh1, axis=0, keepdims=True)                # (1, E)
    # second choices queue behind ALL first choices of that expert (when
    # first choices overflow, no slots remain for seconds — exact either way)
    keep2, slot2 = _assign_slots(oh2, capacity, base=count1)
    dispatch = slot1 + slot2                                    # (T, E, C)
    combine = (slot1 * g1n[:, None, None] + slot2 * g2n[:, None, None])

    frac1 = jnp.mean(oh1, axis=0)
    mean_prob = jnp.mean(probs, axis=0)
    aux = num_experts * jnp.sum(frac1 * mean_prob)
    return RouterOutput(dispatch, combine, aux,
                        _router_metrics(oh1 + oh2, keep1 + keep2))


def get_router(name: str):
    """``'top1'`` -> :func:`switch_router`, ``'top2'`` ->
    :func:`top2_router`."""
    try:
        return {"top1": switch_router, "top2": top2_router}[name]
    except KeyError:
        raise ValueError(f"unknown router {name!r}; expected 'top1' or "
                         "'top2'") from None


def _local_ffn(expert_inputs, wi, wo):
    """(El, S, D) x (El, D, H) x (El, H, D) -> (El, S, D)."""
    h = jnp.einsum("esd,edh->esh", expert_inputs, wi)
    h = jax.nn.gelu(h)
    return jnp.einsum("esh,ehd->esd", h, wo)


def expert_parallel_ffn(x, router_kernel, wi_local, wo_local, *,
                        ep_axis: str = "ep", num_experts: int,
                        capacity: int, router: str = "top1",
                        noise_rng=None, noise_scale: float = 0.0):
    """MoE FFN with experts sharded over ``ep_axis``; call inside
    ``shard_map`` with tokens batch-sharded over the same axis.

    Args:
      x: ``(T_local, D)`` this shard's tokens.
      wi_local / wo_local: ``(E // ep, D, H)`` / ``(E // ep, H, D)`` — this
        shard's experts.
      router: ``'top1'`` (Switch) or ``'top2'`` (GShard; remember to size
        ``capacity`` for two assignments per token).

    Returns:
      ``(y, aux, metrics)``: ``(T_local, D)`` expert outputs (zero for
      dropped tokens — add the residual outside), the local aux loss, and
      the router's drop/load accounting (:class:`RouterOutput` metrics).
    """
    ep = lax.psum(1, ep_axis)
    local_e = wi_local.shape[0]
    dispatch, combine, aux, metrics = get_router(router)(
        x, router_kernel, num_experts=num_experts, capacity=capacity,
        noise_rng=noise_rng, noise_scale=noise_scale)

    # (T, E, C) x (T, D) -> (E, C, D): expert-major send buffer.  Global
    # expert e = s * (E//ep) + j lives on ep-shard s.
    sends = jnp.einsum("tec,td->ecd", dispatch.astype(x.dtype), x)
    sends = sends.reshape((ep, local_e) + sends.shape[1:])     # (ep, El, C, D)
    # all_to_all(split 0, concat 0): chunk s goes to shard s; afterwards
    # axis 0 indexes the SOURCE shard (verified semantics — tests/test_moe.py)
    recvd = lax.all_to_all(sends, ep_axis, split_axis=0, concat_axis=0)
    inputs = recvd.transpose(1, 0, 2, 3).reshape(
        local_e, ep * capacity, x.shape[-1])                   # (El, ep*C, D)

    outputs = _local_ffn(inputs, wi_local, wo_local)           # (El, ep*C, D)

    # reverse route: chunk s of the capacity axis belongs to source shard s
    outputs = outputs.reshape(local_e, ep, capacity, x.shape[-1])
    outputs = outputs.transpose(1, 0, 2, 3)                    # (ep, El, C, D)
    back = lax.all_to_all(outputs, ep_axis, split_axis=0, concat_axis=0)
    expert_outputs = back.reshape(num_experts, capacity, x.shape[-1])

    y = jnp.einsum("tec,ecd->td", combine.astype(x.dtype), expert_outputs)
    return y, aux, metrics


def moe_ffn_reference(x, router_kernel, wi, wo, *, num_experts: int,
                      capacity: int, router: str = "top1"):
    """Unsharded reference: all experts local (for tests and 1-chip runs)."""
    dispatch, combine, aux, metrics = get_router(router)(
        x, router_kernel, num_experts=num_experts, capacity=capacity)
    inputs = jnp.einsum("tec,td->ecd", dispatch.astype(x.dtype), x)
    outputs = _local_ffn(inputs, wi, wo)
    y = jnp.einsum("tec,ecd->td", combine.astype(x.dtype), outputs)
    return y, aux, metrics
