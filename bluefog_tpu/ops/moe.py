"""Mixture-of-experts routing + expert parallelism (GShard/Switch style).

No counterpart exists in the reference (SURVEY.md §2.3: EP absent — Bluefog
predates MoE).  The TPU build adds it as the fourth parallelism axis: experts
are sharded over an ``'ep'`` mesh axis and tokens reach their expert via a
pair of ``lax.all_to_all`` hops — the canonical TPU MoE dataflow (dense
einsum dispatch, static capacity, no dynamic shapes, everything MXU-tiled).

Pieces:

- :func:`switch_router` — top-1 (Switch) routing with a static per-shard
  capacity: returns dense dispatch/combine tensors.
- :func:`expert_parallel_ffn` — dispatch → all_to_all → local expert FFNs →
  reverse all_to_all → combine, inside ``shard_map``.

Gradient convention: normalize the per-rank loss by the GLOBAL token count
(``local_sum / total_tokens``) so the per-rank loss seeds sum to the true
global objective.  Then raw ``jax.grad`` inside ``shard_map`` is exact for
the **ep-sharded expert parameters** (the ``all_to_all`` transposes route
cotangents back without scaling).  **Replicated parameters** (router,
embeddings, attention, …) receive only the local tokens' contribution on
each rank — ``lax.psum`` their grads over the ep axis before the optimizer
update, or the nominally replicated copies silently diverge (see
tests/test_moe.py::test_expert_parallel_grads_match_reference and the
``gr = lax.psum(gr, "ep")`` step in ``__graft_entry__.dryrun_multichip``).
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

__all__ = [
    "switch_router",
    "expert_parallel_ffn",
    "moe_ffn_reference",
]


def switch_router(x, router_kernel, *, num_experts: int, capacity: int,
                  noise_rng=None, noise_scale: float = 0.0):
    """Top-1 routing with static capacity.

    Args:
      x: ``(T, D)`` tokens (local shard).
      router_kernel: ``(D, E)`` router weights (replicated).
      capacity: max tokens per expert **per shard**; overflow tokens are
        dropped (their combine weights are zero — the residual connection
        carries them, as in Switch).
      noise_rng/noise_scale: optional jitter for load-balancing exploration.

    Returns:
      ``(dispatch, combine, aux)`` — dispatch ``(T, E, C)`` one-hot float,
      combine ``(T, E, C)`` = dispatch * router prob, and ``aux`` the Switch
      load-balancing loss (scalar, local shard).
    """
    T = x.shape[0]
    logits = x.astype(jnp.float32) @ router_kernel.astype(jnp.float32)
    if noise_rng is not None and noise_scale > 0:
        logits = logits + noise_scale * jax.random.normal(noise_rng,
                                                          logits.shape)
    probs = jax.nn.softmax(logits, axis=-1)               # (T, E)
    expert = jnp.argmax(probs, axis=-1)                   # (T,)
    onehot = jax.nn.one_hot(expert, num_experts)          # (T, E)

    # position of each token within its expert's queue (0-indexed)
    pos = jnp.cumsum(onehot, axis=0) * onehot - onehot    # (T, E)
    keep = (pos < capacity) * onehot                      # (T, E)
    dispatch = keep[..., None] * jax.nn.one_hot(
        pos.astype(jnp.int32), capacity)                  # (T, E, C)
    gate = jnp.sum(probs * onehot, axis=-1, keepdims=True)      # (T, 1)
    combine = dispatch * gate[..., None]

    # Switch aux loss: E * sum_e fraction_tokens_e * mean_prob_e
    frac = jnp.mean(onehot, axis=0)
    mean_prob = jnp.mean(probs, axis=0)
    aux = num_experts * jnp.sum(frac * mean_prob)
    return dispatch, combine, aux


def _local_ffn(expert_inputs, wi, wo):
    """(El, S, D) x (El, D, H) x (El, H, D) -> (El, S, D)."""
    h = jnp.einsum("esd,edh->esh", expert_inputs, wi)
    h = jax.nn.gelu(h)
    return jnp.einsum("esh,ehd->esd", h, wo)


def expert_parallel_ffn(x, router_kernel, wi_local, wo_local, *,
                        ep_axis: str = "ep", num_experts: int,
                        capacity: int, noise_rng=None,
                        noise_scale: float = 0.0):
    """Switch-MoE FFN with experts sharded over ``ep_axis``; call inside
    ``shard_map`` with tokens batch-sharded over the same axis.

    Args:
      x: ``(T_local, D)`` this shard's tokens.
      wi_local / wo_local: ``(E // ep, D, H)`` / ``(E // ep, H, D)`` — this
        shard's experts.

    Returns:
      ``(y, aux)``: ``(T_local, D)`` expert outputs (zero for dropped
      tokens — add the residual outside) and the local aux loss.
    """
    ep = lax.psum(1, ep_axis)
    local_e = wi_local.shape[0]
    dispatch, combine, aux = switch_router(
        x, router_kernel, num_experts=num_experts, capacity=capacity,
        noise_rng=noise_rng, noise_scale=noise_scale)

    # (T, E, C) x (T, D) -> (E, C, D): expert-major send buffer.  Global
    # expert e = s * (E//ep) + j lives on ep-shard s.
    sends = jnp.einsum("tec,td->ecd", dispatch.astype(x.dtype), x)
    sends = sends.reshape((ep, local_e) + sends.shape[1:])     # (ep, El, C, D)
    # all_to_all(split 0, concat 0): chunk s goes to shard s; afterwards
    # axis 0 indexes the SOURCE shard (verified semantics — tests/test_moe.py)
    recvd = lax.all_to_all(sends, ep_axis, split_axis=0, concat_axis=0)
    inputs = recvd.transpose(1, 0, 2, 3).reshape(
        local_e, ep * capacity, x.shape[-1])                   # (El, ep*C, D)

    outputs = _local_ffn(inputs, wi_local, wo_local)           # (El, ep*C, D)

    # reverse route: chunk s of the capacity axis belongs to source shard s
    outputs = outputs.reshape(local_e, ep, capacity, x.shape[-1])
    outputs = outputs.transpose(1, 0, 2, 3)                    # (ep, El, C, D)
    back = lax.all_to_all(outputs, ep_axis, split_axis=0, concat_axis=0)
    expert_outputs = back.reshape(num_experts, capacity, x.shape[-1])

    y = jnp.einsum("tec,ecd->td", combine.astype(x.dtype), expert_outputs)
    return y, aux


def moe_ffn_reference(x, router_kernel, wi, wo, *, num_experts: int,
                      capacity: int):
    """Unsharded reference: all experts local (for tests and 1-chip runs)."""
    dispatch, combine, aux = switch_router(
        x, router_kernel, num_experts=num_experts, capacity=capacity)
    inputs = jnp.einsum("tec,td->ecd", dispatch.astype(x.dtype), x)
    outputs = _local_ffn(inputs, wi, wo)
    y = jnp.einsum("tec,ecd->td", combine.astype(x.dtype), outputs)
    return y, aux
