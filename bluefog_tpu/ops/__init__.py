"""In-SPMD collective primitives (call these inside ``shard_map``/``pjit``).

These are the TPU-native lowering of the reference's communication layer
(``bluefog/torch/mpi_ops.py`` + ``bluefog/common/mpi_controller.cc``,
upstream-relative): neighbor collectives become ``lax.ppermute`` matchings
along the ICI mesh, dense collectives become ``lax.psum``/``all_gather``, and
the weighted combination fuses into the surrounding XLA program instead of
running on the host as in the reference (SURVEY.md §3.2 "HOT CPU" note).
"""

from bluefog_tpu.ops.collectives import (
    allreduce,
    allgather,
    broadcast,
    barrier,
    neighbor_allreduce,
    neighbor_allgather,
    neighbor_allreduce_dynamic,
    neighbor_allreduce_aperiodic,
    fuse_apply,
    hierarchical_neighbor_allreduce,
    hierarchical_neighbor_allreduce_2d,
    pair_gossip,
)
from bluefog_tpu.ops.windows import (
    WindowSpec,
    WindowState,
    win_create,
    win_free,
    win_put,
    win_get,
    win_accumulate,
    win_update,
    win_update_then_collect,
    win_sync,
    win_associated_p,
)
from bluefog_tpu.ops.ring_attention import (
    ring_attention,
    all_to_all_attention,
    local_attention,
    zigzag_shard,
    zigzag_unshard,
)
from bluefog_tpu.ops.moe import (
    RouterOutput,
    switch_router,
    top2_router,
    get_router,
    expert_parallel_ffn,
    moe_ffn_reference,
)
from bluefog_tpu.ops.compression import (
    Compressor,
    identity,
    random_block_k,
    top_k,
    ChocoState,
    choco_init,
    choco_gossip,
    hierarchical_choco_gossip,
)
